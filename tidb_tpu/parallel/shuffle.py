"""Worker-to-worker DCN shuffle service: the cross-host data plane.

Reference: ExchangeSender/ExchangeReceiver with HashPartition over
MPPDataPacket tunnels (pkg/planner/core/physical_plans.go:1706,
unistore cophandler/mpp_exec.go:597,711) — MPP peers exchange
hash-partitioned chunks DIRECTLY; the coordinator only orchestrates.
PR 1's scheduler staged every inter-host byte through the coordinator
(fine for partial-agg shapes, the wrong cost model for shuffle joins
where neither side is small — ROADMAP; Flare arXiv:1703.08219 and
"Enhancing Computation Pushdown" arXiv:2312.15405 reach the same
conclusion for cloud OLAP pushdown).

This module generalizes the intra-host ICI collectives
(parallel/exchange.py hash_repartition / partition_of with the
`_mix_hash` finalizer) to the DCN tier so the two compose
hierarchically: within a host, rows move over the device mesh's
all_to_all; between hosts, the SAME hash (int keys run the identical
64-bit mix) routes binary columnar frames (parallel/wire.py) over
engine-RPC tunnels (server/engine_rpc.py `shuffle_push` frames). The
producer hashes whole key COLUMNS as numpy arrays and np.takes each
column by partition — HostColumn in, HostColumn out, no Python row
tuples on the hot path; the JSON row-packet codec of PR 3 survives
only as the mixed-version / `shuffle_codec=json` fallback
(partition_rows + _send_stream below).

Pieces, worker side:
- ShuffleStore  — receiver state per (stage, attempt): packet streams
  keyed (side, sender) with per-(fragment, partition, attempt) fences.
  A packet from a superseded attempt is dropped (the stage restarted on
  a survivor set); a duplicate sequence number within an attempt is
  dropped (a retransmit after an ack loss) — the exactly-once
  FragmentLedger discipline (dxf/framework.fence_accepts) applied to
  the data plane, so a re-dispatched fragment never double-delivers.
- PeerTunnel    — sender per peer: a bounded-bytes in-flight window
  (producers block when the window fills: backpressure, counted as
  tunnel stalls), a background sender thread, reconnect + retransmit
  on transport loss (receiver-side dedupe makes retransmit safe).
- ShuffleWorker — one dispatched shuffle task: execute producer side
  plans (SPMD on the local mesh), bucketize rows by key, push
  partitions to peers, wait for the peers' pushes, substitute the
  received partitions for the plan's ShuffleRead leaves, execute the
  consumer plan, reply to the coordinator.

Coordinator-side stage orchestration (tunnel wiring, whole-stage retry
onto the survivor set after a peer death) lives in parallel/dcn.py.

Failpoint sites: shuffle/open, shuffle/recv, shuffle/recv-ack-lost
(server/engine_rpc.py), shuffle/produce, shuffle/push,
shuffle/push-lost, shuffle/wait, shuffle/consume (worker, here) and
shuffle/stage, shuffle/stage-retry (coordinator, parallel/dcn.py).
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import json
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.obs import profiler as topsql
from tidb_tpu.utils import racecheck
from tidb_tpu.utils.failpoint import inject
from tidb_tpu.utils.metrics import REGISTRY

#: receiver cap on concurrently-buffered stages (a runaway backstop,
#: not a working set: completed stages are discarded by run_task as
#: soon as their partition is consumed, so only in-flight queries
#: occupy the window)
_MAX_STAGES = 64

#: default tunnel flow-control window (bytes in flight per peer) and
#: packet granularity; the coordinator can override per stage
DEFAULT_INFLIGHT_BYTES = 4 << 20
DEFAULT_PACKET_ROWS = 2048
#: pipelined producer sub-slices per side (Scan.frag arithmetic):
#: chunk k of n re-frags (i, m) -> (i + k*m, n*m), so encode+push+peer
#: decode of chunk k overlap the device produce of chunk k+1 — the
#: exchange tail after the LAST produce shrinks to one chunk. 2 is the
#: measured sweet spot on CPU dryruns (higher counts starve the
#: shipper thread of the GIL during the rapid-fire sub-dispatches);
#: raise it on real hardware where produce is device-bound.
DEFAULT_PRODUCE_CHUNKS = 2
#: transport retries per packet before the peer is declared dead
PUSH_RETRIES = 3
#: staged-batch nonces for every ShuffleWorker in this process
#: (disjoint from dcn.py's 1<<20 and streamed.py's ranges): shared so
#: two in-process workers can never mint the same nonce — non-keyed
#: Staged plans fingerprint on the nonce alone
_STAGE_NONCES = itertools.count(1 << 24)


# -- telemetry (tidbtpu_shuffle_*) ------------------------------------------


def _c_bytes():
    return REGISTRY.counter(
        "tidbtpu_shuffle_bytes_total",
        "row-packet bytes pushed over worker-to-worker tunnels",
        labels=("src", "dst"),
    )


def _c_rows():
    return REGISTRY.counter(
        "tidbtpu_shuffle_rows_total",
        "rows pushed over worker-to-worker tunnels",
        labels=("src", "dst"),
    )


def _c_stalls():
    return REGISTRY.counter(
        "tidbtpu_shuffle_tunnel_stalls",
        "sends that blocked on the per-peer in-flight byte window",
        labels=("dst",),
    )


def _c_retransmits():
    return REGISTRY.counter(
        "tidbtpu_shuffle_retransmits",
        "packets retransmitted after a tunnel transport loss",
    )


def _c_stale():
    return REGISTRY.counter(
        "tidbtpu_shuffle_stale_dropped",
        "packets fenced out for carrying a superseded stage attempt",
    )


def _c_dups():
    return REGISTRY.counter(
        "tidbtpu_shuffle_duplicates_dropped",
        "duplicate-sequence packets dropped by the receiver dedupe",
    )


def _c_codec_bytes():
    return REGISTRY.counter(
        "tidbtpu_shuffle_codec_bytes",
        "shuffle packet bytes encoded, by wire codec",
        labels=("codec",),
    )


def _c_encode_seconds():
    return REGISTRY.counter(
        "tidbtpu_shuffle_encode_seconds",
        "producer-side packet encode time, by wire codec",
        labels=("codec",),
    )


def _c_decode_seconds():
    return REGISTRY.counter(
        "tidbtpu_shuffle_decode_seconds",
        "receiver-side packet decode time, by wire codec",
        labels=("codec",),
    )


def _c_wait_idle_seconds():
    return REGISTRY.counter(
        "tidbtpu_shuffle_wait_idle_seconds",
        "seconds consumers spent blocked in ShuffleStore waits with "
        "no stream work left to overlap (the barrier cost pipelining "
        "attacks)",
    )


def _c_decode_on_arrival_seconds():
    return REGISTRY.counter(
        "tidbtpu_shuffle_decode_on_arrival_seconds",
        "binary frame decode time spent in the push handler as frames "
        "land (overlapping the producers still in flight), after the "
        "header-only fence check admitted the frame",
    )


def _h_ttff():
    return REGISTRY.histogram(
        "tidbtpu_shuffle_time_to_first_frame_seconds",
        "stage-open to first data frame per (side, sender) stream — "
        "low when producers ship chunk-granularly instead of after the "
        "whole side materializes",
    )


def _c_filter_built():
    return REGISTRY.counter(
        "tidbtpu_shuffle_filter_built_total",
        "runtime filters built from probe-cached build sides, by kind "
        "(bloom / inlist — ISSUE 19 sideways information passing)",
        labels=("kind",),
    )


def _c_filter_bytes():
    return REGISTRY.counter(
        "tidbtpu_shuffle_filter_bytes",
        "runtime filter payload bytes shipped coordinator-ward in "
        "probe replies (the build+ship cost side of the rf cost model)",
    )


def _c_filter_dropped():
    return REGISTRY.counter(
        "tidbtpu_shuffle_filter_dropped_rows_total",
        "probe-side rows dropped by a runtime filter BEFORE "
        "partitioning and encoding (never shipped, never staged)",
    )


def _g_stages_buffered():
    return REGISTRY.gauge(
        "tidbtpu_shuffle_stages_buffered",
        "shuffle stages concurrently buffered in this worker's store — "
        "the serving tier's per-worker concurrency signal (each "
        "in-flight query contributes its own sid-keyed stage)",
    )


# -- host-side hash partitioning --------------------------------------------
#
# The same 64-bit finalizer as parallel/exchange._mix_hash so the two
# shuffle tiers compose: numpy int64 arithmetic has the identical
# wraparound-multiply and arithmetic-shift semantics as the jnp version
# (parity is unit-tested in tests/test_shuffle.py).

_MIX1 = np.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as signed
_MIX2 = np.int64(-4658895280553007687)  # 0xBF58476D1CE4E5B9 as signed


def mix_hash_np(x: np.ndarray) -> np.ndarray:
    """exchange._mix_hash over a host numpy int64 array."""
    with np.errstate(over="ignore"):
        h = x.astype(np.int64) * _MIX1
        h = h ^ (h >> 29)
        h = h * _MIX2
        h = h ^ (h >> 32)
    return h & np.int64(0x7FFFFFFFFFFFFFFF)


def _key_to_int(v) -> Optional[int]:
    """Stable int64 image of one key value, identical across worker
    processes (python hash() is salted per process and MUST not be
    used here — two producers disagreeing on a partition would split a
    join key across hosts). None stays None (NULL keys colocate on
    partition 0, like exchange.partition_of)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, float):
        if v == 0.0:
            v = 0.0  # -0.0 == 0.0 must land together
        if float(v).is_integer() and abs(v) < 2 ** 62:
            return int(v)  # decimal keys decode to integral floats
        (bits,) = struct.unpack("<q", struct.pack("<d", float(v)))
        return bits
    if isinstance(v, str):
        d = hashlib.blake2b(v.encode(), digest_size=8).digest()
        return int.from_bytes(d, "little", signed=True)
    d = hashlib.blake2b(repr(v).encode(), digest_size=8).digest()
    return int.from_bytes(d, "little", signed=True)


def partition_rows(
    rows: List[tuple], key_idx: int, n: int
) -> List[List[tuple]]:
    """Split materialized rows into n hash partitions on column
    `key_idx`. Equal keys always land in one partition; NULL keys all
    go to partition 0 (one group / never match in joins, but must
    colocate) — the host tier of exchange.partition_of."""
    ints = [_key_to_int(r[key_idx]) for r in rows]
    out: List[List[tuple]] = [[] for _ in range(n)]
    if not rows:
        return out
    arr = np.array([0 if i is None else i for i in ints], dtype=np.int64)
    parts = mix_hash_np(arr) % np.int64(n)
    for r, i, p in zip(rows, ints, parts):
        out[0 if i is None else int(p)].append(r)
    return out


# -- receiver: the tunnel endpoint ------------------------------------------


class ShuffleWaitTimeout(TimeoutError):
    def __init__(self, missing: List[str]):
        super().__init__(f"shuffle wait timed out; missing {missing}")
        self.missing = missing


class WaitInterrupted(Exception):
    """wait_side's abort() callback fired: the caller's own producer
    ship failed while the consumer was already waiting (the pipelined
    task overlaps the two), so the wait must hand control back for the
    ship error to surface instead of idling to the stage deadline."""


class _Stream:
    """One (side, sender) packet stream within a stage attempt."""

    __slots__ = ("seqs", "nseq")

    def __init__(self):
        self.seqs: Dict[int, list] = {}
        self.nseq: Optional[int] = None

    def complete(self) -> bool:
        return self.nseq is not None and len(self.seqs) >= self.nseq


class _Stage:
    __slots__ = (
        "attempt", "m", "streams", "waiters", "opened_at", "ttff",
        "vocab",
    )

    def __init__(self, attempt: int, m: int):
        self.attempt = attempt
        self.m = m
        self.streams: Dict[Tuple[int, int], _Stream] = {}
        #: consumer threads blocked in wait() on this stage — never
        #: evict under a waiter's feet
        self.waiters = 0
        self.opened_at = time.monotonic()
        #: (side, sender) -> seconds from stage open to the stream's
        #: first data frame (the pipelining signal: chunk-granular
        #: producers push early, whole-side producers push late)
        self.ttff: Dict[Tuple[int, int], float] = {}
        #: (side, colname) -> running union of string-dictionary
        #: entries, folded in as columnar frames LAND — by the time a
        #: side completes, its unified stage dictionary is one sort
        #: away instead of a full re-scan of every buffered chunk
        self.vocab: Dict[Tuple[int, str], set] = {}


class ShuffleStore:
    """Worker-side receive buffer for pushed shuffle partitions.

    Fencing rules (the FragmentLedger pattern on the data plane):
    - a packet whose attempt is OLDER than the stage's current attempt
      is dropped (the coordinator restarted the stage on a survivor
      set; the old partition map no longer applies);
    - a packet whose attempt is NEWER resets the stage (pushes from a
      fast peer may precede this worker's own task dispatch);
    - within an attempt, a duplicate (side, sender, seq) is dropped —
      retransmits after an ack loss land exactly once.

    Per-QUERY isolation under the concurrent serving tier (PR 8
    audit): stages key on the coordinator's sid, which embeds a
    strictly-unique qid (serving.QidAllocator) under a per-coordinator
    uuid prefix — two concurrent queries (even the same SQL from two
    sessions) can never share a stage record, so a frame admits into
    exactly the stage its producer was dispatched for. The eviction
    window keeps actively-waited stages pinned (waiters counter), so K
    concurrent queries occupy K stage records and complete
    independently; tests/test_race.py hammers K distinct concurrent
    queries through one in-process fleet asserting per-query parity
    and zero stale/duplicate admits.
    """

    #: poisoned-sid memory (cancelled queries): bounded — sids are
    #: strictly unique, so an aged-out entry can only matter if a peer
    #: still pushes a >256-queries-old cancelled stage, which the
    #: eviction window then bounds anyway
    _POISON_CAP = 256

    def __init__(self):
        self._cv = racecheck.make_condition("shuffle.store")
        self._stages: "collections.OrderedDict[str, _Stage]" = (
            collections.OrderedDict()
        )
        self._poisoned: "collections.OrderedDict[str, bool]" = (
            collections.OrderedDict()
        )

    def poison(self, sid: str) -> None:
        """Cancel one stage FOR GOOD: drop its buffered frames and
        refuse to recreate its record — in-flight frames from peers
        that have not yet observed the cancellation land as fenced
        stale drops instead of resurrecting an orphan stage, so the
        buffered-stages gauge returns to zero immediately (the
        fleet-cancellation abort path)."""
        with self._cv:
            self._stages.pop(sid, None)
            self._poisoned[sid] = True
            while len(self._poisoned) > self._POISON_CAP:
                self._poisoned.popitem(last=False)
            _g_stages_buffered().set(len(self._stages))
            self._cv.notify_all()

    def buffered_stages(self) -> int:
        with self._cv:
            return len(self._stages)

    def _stage(self, sid: str, attempt: int, m: int) -> Optional[_Stage]:
        """Stage record for (sid, attempt), fencing stale attempts and
        poisoned (cancelled) sids. Caller holds the condition lock."""
        if sid in self._poisoned:
            return None  # callers count the drop (stale fence)
        st = self._stages.get(sid)
        if st is None or attempt > st.attempt:
            st = _Stage(attempt, m)
            self._stages[sid] = st
            if len(self._stages) > _MAX_STAGES:
                # evict oldest WAITER-FREE stages only: dropping a
                # stage whose consumer is blocked in wait() would fail
                # a query on healthy hosts. With every stage actively
                # waited the map simply grows past the cap (bounded by
                # the number of concurrent tasks).
                excess = len(self._stages) - _MAX_STAGES
                for old_sid in list(self._stages):
                    if excess <= 0:
                        break
                    if old_sid != sid and self._stages[old_sid].waiters == 0:
                        del self._stages[old_sid]
                        excess -= 1
        elif attempt < st.attempt:
            return None
        # LRU touch on EVERY access: an actively-receiving stage must
        # never age out under concurrent stages — only idle/orphan ones
        self._stages.move_to_end(sid)
        return st

    def open(self, sid: str, attempt: int, m: int) -> None:
        inject("shuffle/open")
        with self._cv:
            self._stage(sid, attempt, m)
            # set under the cv: outside it a lost update with a
            # concurrent open/discard leaves the gauge stale
            _g_stages_buffered().set(len(self._stages))

    def discard(self, sid: str) -> None:
        """Drop a stage's buffered rows (called once the consumer has
        read its partition — a retry would run under a NEW attempt,
        which resets the stage anyway, so nothing ever re-reads this
        data). Late peer pushes simply recreate an orphan record that
        ages out of the window."""
        with self._cv:
            self._stages.pop(sid, None)
            _g_stages_buffered().set(len(self._stages))

    def push(
        self,
        sid: str,
        attempt: int,
        m: int,
        side: int,
        sender: int,
        seq: int,
        payload,
        nseq: Optional[int] = None,
    ) -> bool:
        """Land one packet; returns False when fenced (stale attempt)
        or deduped (duplicate seq). `payload` is codec-shaped: a list
        of row tuples (JSON packets) or a decoded columnar HostBlock
        (binary frames) — the store buffers it opaquely and the
        consumer normalizes at staging time, so one stream can even mix
        codecs across senders (mixed-version peers). An EOF packet
        carries payload=None and nseq=<total data packets>."""
        with self._cv:
            st = self._stage(sid, attempt, m)
            if st is None:
                _c_stale().inc()
                return False
            stream = st.streams.setdefault((side, sender), _Stream())
            if payload is None:  # EOF marker — idempotent
                stream.nseq = int(nseq)
                self._cv.notify_all()
                return True
            if seq in stream.seqs:
                _c_dups().inc()
                return False
            stream.seqs[int(seq)] = payload
            if (side, sender) not in st.ttff:
                dt = time.monotonic() - st.opened_at
                st.ttff[(side, sender)] = dt
                _h_ttff().observe(dt)
            cols = getattr(payload, "columns", None)
            if cols is not None:
                # columnar frame: fold its (pruned) string dictionaries
                # into the side's running vocabulary NOW, while other
                # streams are still in flight — incremental staging
                # then unifies with one sort instead of re-walking
                # every buffered chunk after the wait
                for name, col in cols.items():
                    if col.dictionary is not None:
                        st.vocab.setdefault((side, name), set()).update(
                            col.dictionary.tolist()
                        )
            self._cv.notify_all()
            return True

    def admits(
        self, sid: str, attempt: int, side: int, sender: int, seq: int
    ) -> bool:
        """Header-only fence pre-check: would a data frame with this
        route land? False for a superseded attempt or a duplicate seq
        (counted like the push-time fences) — the receive handler asks
        this from decode_header output BEFORE spending decode work on
        the column payload. Purely advisory: push() re-applies the
        fences authoritatively, so a race between two identical
        retransmits still lands exactly once."""
        with self._cv:
            if sid in self._poisoned:
                _c_stale().inc()  # cancelled stage: drop before decode
                return False
            st = self._stages.get(sid)
            if st is None or attempt > st.attempt:
                return True  # new stage / newer attempt: will reset
            if attempt < st.attempt:
                _c_stale().inc()
                return False
            stream = st.streams.get((side, sender))
            if stream is not None and seq in stream.seqs:
                _c_dups().inc()
                return False
            return True

    @staticmethod
    def _senders_of(senders, side, m) -> List[int]:
        """Expected sender set for one side: all m peers unless the
        stage declared otherwise (a "local"-mode side only ever has
        its own host's stream; a broadcast side still has all m)."""
        if senders is None:
            return list(range(m))
        return list(senders.get(side, range(m)))

    def wait(
        self,
        sid: str,
        attempt: int,
        n_sides: int,
        m: int,
        timeout_s: float,
        abort=None,
        senders=None,
    ) -> Dict[int, list]:
        """Block until every (side, sender) stream of the attempt is
        complete; returns side -> payload chunks ordered (sender, seq)
        — a deterministic concatenation order, so per-partition
        execution is reproducible across retries. Raises
        ShuffleWaitTimeout with the missing senders (the coordinator's
        death-suspect list). ``senders`` optionally narrows the
        expected sender set per side (local-mode DAG edges)."""
        inject("shuffle/wait")
        deadline = time.monotonic() + timeout_s

        def missing() -> List[str]:
            st = self._stages.get(sid)
            out = []
            for side in range(n_sides):
                for sender in self._senders_of(senders, side, m):
                    stream = (
                        st.streams.get((side, sender))
                        if st is not None and st.attempt == attempt
                        else None
                    )
                    if stream is None or not stream.complete():
                        out.append(f"side{side}/sender{sender}")
            return out

        with self._cv:
            # pin the stage for the duration of the wait: eviction
            # skips stages with active waiters. pin is None when this
            # attempt is already superseded (the wait can only time
            # out); identity-compare on release — a newer attempt may
            # have replaced the record mid-wait.
            pin = self._stage(sid, attempt, m)
            if pin is not None:
                pin.waiters += 1
            try:
                while True:
                    gone = missing()
                    if not gone:
                        break
                    if abort is not None and abort():
                        # same contract as wait_side: a truthy abort
                        # hands control back (a raising abort — the
                        # fleet-cancel check — propagates directly)
                        raise WaitInterrupted()
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise ShuffleWaitTimeout(gone)
                    self._cv.wait(min(left, 0.25))
            finally:
                if pin is not None and self._stages.get(sid) is pin:
                    pin.waiters -= 1
            st = self._stages[sid]
            out: Dict[int, list] = {}
            for side in range(n_sides):
                chunks: list = []
                for sender in self._senders_of(senders, side, m):
                    stream = st.streams[(side, sender)]
                    for seq in range(stream.nseq):
                        chunks.append(stream.seqs[seq])
                out[side] = chunks
            return out

    def _side_complete(self, st: Optional[_Stage], attempt, side, m,
                       senders=None):
        if st is None or st.attempt != attempt:
            return False
        for sender in self._senders_of(senders, side, m):
            stream = st.streams.get((side, sender))
            if stream is None or not stream.complete():
                return False
        return True

    def wait_side(
        self,
        sid: str,
        attempt: int,
        pending: List[int],
        m: int,
        deadline: float,
        abort=None,
        senders=None,
    ) -> Tuple[int, list, Dict[str, set]]:
        """Block until ANY side in ``pending`` has all m streams
        complete; returns (side, payload chunks ordered (sender, seq),
        that side's running string vocabularies) — the pipelined
        consumer stages each side the moment it finishes while the
        other side is still in flight, instead of barriering on the
        whole stage like wait(). ``deadline`` is absolute
        (time.monotonic); on expiry raises ShuffleWaitTimeout naming
        every missing stream across the still-pending sides."""
        inject("shuffle/wait")
        with self._cv:
            pin = self._stage(sid, attempt, m)
            if pin is not None:
                pin.waiters += 1
            try:
                while True:
                    st = self._stages.get(sid)
                    for side in pending:
                        if self._side_complete(
                            st, attempt, side, m, senders
                        ):
                            chunks: list = []
                            for sender in self._senders_of(
                                senders, side, m
                            ):
                                stream = st.streams[(side, sender)]
                                for seq in range(stream.nseq):
                                    chunks.append(stream.seqs[seq])
                            vocab = {
                                name: set(v)
                                for (s, name), v in st.vocab.items()
                                if s == side
                            }
                            return side, chunks, vocab
                    if abort is not None and abort():
                        raise WaitInterrupted()
                    left = deadline - time.monotonic()
                    if left <= 0:
                        missing = []
                        for side in pending:
                            for sender in self._senders_of(
                                senders, side, m
                            ):
                                stream = (
                                    st.streams.get((side, sender))
                                    if st is not None
                                    and st.attempt == attempt
                                    else None
                                )
                                if stream is None or not stream.complete():
                                    missing.append(
                                        f"side{side}/sender{sender}"
                                    )
                        raise ShuffleWaitTimeout(missing)
                    self._cv.wait(min(left, 0.25))
            finally:
                if pin is not None and self._stages.get(sid) is pin:
                    pin.waiters -= 1

    def max_ttff(self, sid: str) -> float:
        """Largest stream time-to-first-frame of the stage (0.0 when
        nothing landed) — the straggler signal run_task reports."""
        with self._cv:
            st = self._stages.get(sid)
            if st is None or not st.ttff:
                return 0.0
            return max(st.ttff.values())


# -- sender: per-peer tunnel with flow control ------------------------------


class PeerDeadError(ConnectionError):
    """A tunnel gave up on its peer. `fatal` distinguishes an engine-
    side rejection or encoding error (retrying a HEALTHY peer cannot
    fix it — must surface, not retry) from a transport loss (the peer
    is a death suspect and the stage should retry on survivors)."""

    def __init__(self, address: str, cause: Exception, fatal: bool = False):
        super().__init__(f"shuffle peer {address} unreachable: {cause}")
        self.address = address
        self.cause = cause
        self.fatal = fatal


class PeerTunnel:
    """One worker-to-worker tunnel: a background sender thread drains a
    queue of packets over an EngineClient connection; producers block
    when queued-plus-unacked bytes exceed the window (backpressure —
    counted as tunnel stalls). Transport loss reconnects and
    retransmits the packet (the receiver's seq dedupe makes this safe);
    PUSH_RETRIES consecutive failures declare the peer dead."""

    def __init__(
        self,
        host: str,
        port: int,
        secret: Optional[str],
        src: str,
        max_inflight_bytes: int = DEFAULT_INFLIGHT_BYTES,
        timeout_s: float = 30.0,
        batch_packets: int = 64,
    ):
        self.host, self.port, self.secret = host, port, secret
        self.address = f"{host}:{port}"
        self.src = src
        # packets pipelined onto the wire per ack round trip (the
        # byte window bounds the data volume); 1 = strict stop-and-
        # wait, the pre-pipelining wire discipline the pipeline=off
        # escape hatch preserves
        self.batch_packets = max(int(batch_packets), 1)
        self.max_inflight = int(max_inflight_bytes)
        self.timeout_s = timeout_s
        self.bytes_sent = 0
        self.rows_sent = 0
        self.frames_sent = 0
        self.stalls = 0
        #: cumulative seconds producers spent blocked on this tunnel's
        #: byte window (backpressure stall WALL, not just a count —
        #: information_schema.cluster_links reads this per link)
        self.stall_s = 0.0
        #: individual stall windows as (wall_t0, dur_s) — the timeline
        #: tracer's per-link backpressure events (obs/timeline.py).
        #: Bounded; appended only when a stall actually happened, so
        #: the un-stalled hot path never touches it.
        self.stall_windows: List[Tuple[float, float]] = []
        self.retransmits = 0
        self._cv = racecheck.make_condition("shuffle.tunnel")
        self._q: "collections.deque" = collections.deque()
        self._inflight = 0
        self._dead: Optional[Exception] = None
        self._dead_fatal = False
        self._closing = False
        self._client = None
        self._codec: Optional[str] = None
        self._neg_lock = racecheck.make_lock("shuffle.negotiate")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"shuffle-tx-{self.address}"
        )
        self._thread.start()

    def negotiated_codec(self, preferred: str = "binary") -> str:
        """The wire codec this tunnel may use: "binary" when the peer's
        handshake advertises a compatible wire version, else "json"
        (mixed-version peers keep interoperating through the row-packet
        fallback). Negotiated once per tunnel over a throwaway ping
        connection (the sender thread owns the data connection); an
        unreachable peer answers `preferred` — the first real send will
        surface the death through the normal suspect machinery."""
        if preferred != "binary":
            return "json"
        with self._neg_lock:
            if self._codec is None:
                from tidb_tpu.parallel.wire import WIRE_VERSION
                from tidb_tpu.server.engine_rpc import EngineClient

                try:
                    # lock-blocking-ok: the one-shot negotiation probe
                    # deliberately holds the per-tunnel lock across its
                    # throwaway handshake so racing producers get ONE
                    # answer; the lock is tunnel-private and leaf-level
                    c = EngineClient(
                        self.host, self.port, secret=self.secret,
                        timeout_s=min(self.timeout_s, 10.0),
                    )
                    try:
                        # the connect-time handshake already cached the
                        # peer's advertised wire version
                        peer_wire = int(c.server_wire)
                    finally:
                        c.close()
                    # EXACT version match: decode_frame rejects any
                    # other version, so a skewed peer must degrade to
                    # the JSON fallback, not trade unreadable frames
                    self._codec = (
                        "binary" if peer_wire == WIRE_VERSION else "json"
                    )
                except Exception:
                    self._codec = preferred
            return self._codec

    # -- producer side -------------------------------------------------
    def send(self, packet, nbytes: int, nrows: int) -> None:
        """Enqueue one packet: pre-encoded bytes (the hot path — the
        producer serialized it once and the bytes cross the wire
        verbatim) or a plain dict (tests/tools)."""
        with self._cv:
            stalled = False
            stall_t0 = 0.0
            stall_wall0 = 0.0
            while (
                self._dead is None
                and self._inflight + nbytes > self.max_inflight
                and self._inflight > 0
            ):
                if not stalled:
                    stalled = True
                    stall_t0 = time.perf_counter()
                    stall_wall0 = time.time()
                    self.stalls += 1
                    _c_stalls().labels(dst=self.address).inc()
                self._cv.wait(0.05)
            if stalled:
                dt = time.perf_counter() - stall_t0
                self.stall_s += dt
                if len(self.stall_windows) < 256:
                    self.stall_windows.append((stall_wall0, dt))
                from tidb_tpu.obs.flight import _c_link_stall_seconds

                _c_link_stall_seconds().labels(
                    src=self.src, dst=self.address
                ).inc(dt)
            if self._dead is not None:
                raise PeerDeadError(
                    self.address, self._dead, fatal=self._dead_fatal
                )
            self._inflight += nbytes
            self._q.append((packet, nbytes, nrows))
            self._cv.notify_all()

    def flush(self) -> None:
        """Block until every queued packet is acked; raises if the peer
        died mid-stream."""
        with self._cv:
            while self._dead is None and (self._q or self._inflight):
                self._cv.wait(0.05)
            if self._dead is not None:
                raise PeerDeadError(
                    self.address, self._dead, fatal=self._dead_fatal
                )

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        self._thread.join(timeout=10)
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass

    # -- sender thread -------------------------------------------------
    def _connect(self):
        from tidb_tpu.server.engine_rpc import EngineClient

        if self._client is None or self._client._dead:
            self._client = EngineClient(
                self.host, self.port, secret=self.secret,
                timeout_s=self.timeout_s,
            )
        return self._client

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closing and self._dead is None:
                    self._cv.wait(0.05)
                if self._dead is not None or (self._closing and not self._q):
                    return
                # take a RUN of pre-encoded packets and pipeline them
                # onto the wire in ONE write + one in-order ack read —
                # a synchronous round trip per packet made the ack
                # latency the dominant serial tail of a push stream.
                # Packets stay queued until acked (retransmit fodder);
                # plain-dict packets (tests/tools) go one at a time.
                batch = []
                encoded = isinstance(self._q[0][0], (bytes, bytearray))
                for item in self._q:
                    if len(batch) >= self.batch_packets:
                        break
                    if isinstance(
                        item[0], (bytes, bytearray)
                    ) != encoded:
                        break
                    batch.append(item)
                    if not encoded:
                        break
            err: Optional[Exception] = None
            fatal = False
            for attempt in range(PUSH_RETRIES):
                try:
                    for _packet, _nb, _nr in batch:
                        inject("shuffle/push")
                        if inject("shuffle/push-lost"):
                            raise ConnectionError(
                                "failpoint: push lost in transit"
                            )
                    client = self._connect()
                    if encoded:
                        # hot path: pre-encoded at enqueue, sent as-is
                        client.shuffle_push_encoded_many(
                            [bytes(p) for p, _nb, _nr in batch]
                        )
                    else:
                        client.shuffle_push(batch[0][0])
                    err = None
                    break
                except (RuntimeError, ValueError, TypeError) as e:
                    # engine-side rejection or an encoding error — NOT
                    # a transport loss: retrying a healthy peer cannot
                    # fix it, and reporting the peer as a death suspect
                    # would send the coordinator chasing a ghost
                    err, fatal = e, True
                    break
                except Exception as e:
                    err = e
                    if self._client is not None:
                        try:
                            self._client.close()
                        except Exception:
                            pass
                        self._client = None
                    if attempt + 1 < PUSH_RETRIES:
                        # the whole unacked batch retransmits; the
                        # receiver's header dedupe lands each exactly
                        # once
                        self.retransmits += len(batch)
                        _c_retransmits().inc(len(batch))
                        from tidb_tpu.obs.flight import _c_link_retransmits

                        _c_link_retransmits().labels(
                            src=self.src, dst=self.address
                        ).inc(len(batch))
                        time.sleep(0.05 * (attempt + 1))
            with self._cv:
                nbytes_acked = nrows_acked = 0
                for _packet, nbytes, nrows in batch:
                    self._q.popleft()
                    self._inflight -= nbytes
                    nbytes_acked += nbytes
                    nrows_acked += nrows
                if err is not None:
                    self._dead = err
                    self._dead_fatal = fatal
                else:
                    self.bytes_sent += nbytes_acked
                    self.rows_sent += nrows_acked
                    self.frames_sent += len(batch)
                    _c_bytes().labels(src=self.src, dst=self.address).inc(
                        nbytes_acked
                    )
                    _c_rows().labels(src=self.src, dst=self.address).inc(
                        nrows_acked
                    )
                    # per-link health family (information_schema.
                    # cluster_links; counters ship to the coordinator
                    # via the piggybacked registry deltas)
                    from tidb_tpu.obs.flight import (
                        _c_link_bytes,
                        _c_link_frames,
                    )

                    _c_link_bytes().labels(
                        src=self.src, dst=self.address
                    ).inc(nbytes_acked)
                    _c_link_frames().labels(
                        src=self.src, dst=self.address
                    ).inc(len(batch))
                self._cv.notify_all()


# -- the dispatched shuffle task --------------------------------------------


def _payload_rows(p) -> int:
    """Row count of one buffered shuffle payload — a columnar
    HostBlock on the binary path, a plain row list on the JSON
    fallback (the per-partition received-rows accounting feeding the
    skew ratio)."""
    n = getattr(p, "nrows", None)
    return int(n) if n is not None else len(p)


class ShuffleAbort(RuntimeError):
    """Retryable stage failure a worker reports to the coordinator:
    dead peers during push, or producers that never delivered before
    the wait deadline. The coordinator verifies the suspects, then
    re-runs the WHOLE stage (new attempt) on the survivor set."""

    def __init__(self, reason: str, suspects: List[str]):
        super().__init__(f"{reason}; suspects={suspects}")
        self.reason = reason
        self.suspects = suspects


def _substitute_reads(plan, staged_by_tag):
    """Replace every ShuffleRead leaf with its Staged partition batch."""
    import dataclasses

    from tidb_tpu.planner import logical as L

    if isinstance(plan, L.ShuffleRead):
        return staged_by_tag[plan.tag]
    kw = {}
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is not None:
            kw[attr] = _substitute_reads(c, staged_by_tag)
    ch = getattr(plan, "children", None)
    if ch:
        kw["children"] = [_substitute_reads(c, staged_by_tag) for c in ch]
    return dataclasses.replace(plan, **kw) if kw else plan


def _slice_producer(plan, k: int, n_chunks: int):
    """Sub-slice a producer side plan for chunk-granular execution:
    the host's fragment scan ``frag=(i, m)`` (rows i::m) becomes
    ``frag=(i + k*m, n_chunks*m)`` — the k-th of n_chunks disjoint
    sub-slices whose union is exactly the host's slice, pure index
    arithmetic through the existing frag machinery. Returns None when
    the plan is not row-sliceable (anything beyond a scan/filter/
    project chain, or no single frag'd scan): aggregates, sorts and
    joins compute over the WHOLE slice and must not be re-run per
    sub-slice."""
    import dataclasses

    from tidb_tpu.planner import logical as L

    scans = []

    def sliceable(p) -> bool:
        if isinstance(p, L.Scan):
            scans.append(p)
            return p.frag is not None
        if isinstance(p, (L.Selection, L.Projection)):
            return sliceable(p.child)
        return False

    if not sliceable(plan) or len(scans) != 1:
        return None
    i, m = scans[0].frag

    def rewrite(p):
        if isinstance(p, L.Scan):
            return dataclasses.replace(
                p, frag=(i + k * m, n_chunks * m)
            )
        return dataclasses.replace(p, child=rewrite(p.child))

    return rewrite(plan)


def _shuffle_read_tags(plan) -> Dict[int, object]:
    """tag -> ShuffleRead node (the consumer's exchange leaves)."""
    from tidb_tpu.planner import logical as L

    out: Dict[int, object] = {}

    def walk(p):
        if isinstance(p, L.ShuffleRead):
            out[p.tag] = p
            return
        for attr in ("child", "left", "right"):
            c = getattr(p, attr, None)
            if c is not None:
                walk(c)
        for c in getattr(p, "children", []) or []:
            walk(c)

    walk(plan)
    return out


def stage_rows_as_batch(schema, rows: List[tuple], nonce: int, key=None):
    """Materialized rows -> a Staged device batch under `schema` (the
    receiving side of any host-level exchange; shared with the
    coordinator's final stage in parallel/dcn.py). With ``key`` the
    staged batch is a runtime input, so repeated final stages of one
    plan shape reuse the compiled program (L.Staged.key)."""
    from tidb_tpu.chunk import (
        HostBlock,
        block_to_batch,
        column_from_values,
        pad_capacity,
    )
    from tidb_tpu.planner import logical as L

    cols = {}
    dicts = {}
    for i, oc in enumerate(schema.cols):
        hc = column_from_values([r[i] for r in rows], oc.type)
        cols[oc.internal] = hc
        if hc.dictionary is not None:
            dicts[oc.internal] = hc.dictionary
    block = HostBlock(cols, len(rows))
    batch = block_to_batch(block, pad_capacity(max(len(rows), 1)))
    return L.Staged(
        schema, batch=batch, dicts=dicts, nonce=nonce, key=key
    )


def stage_payloads_as_batch(schema, payloads: list, nonce: int, key=None):
    """Received shuffle payload chunks -> a Staged device batch by
    COLUMN CONCATENATION: binary frames arrive as decoded HostBlocks
    whose columns concatenate directly (string dictionaries unified
    into one sorted stage-local table, codes re-keyed — join keys
    comparable across senders and sides); JSON row packets take the
    column_from_values slow path per chunk. No per-row Python loop
    touches columnar chunks."""
    from tidb_tpu.chunk import (
        HostBlock,
        block_to_batch,
        column_from_values,
        concat_host_columns,
        pad_capacity,
    )
    from tidb_tpu.planner import logical as L

    per_col: Dict[str, list] = {oc.internal: [] for oc in schema.cols}
    total = 0
    for pl in payloads:
        if isinstance(pl, HostBlock):
            for oc in schema.cols:
                per_col[oc.internal].append(pl.columns[oc.internal])
            total += pl.nrows
        else:  # JSON row packet — the declared fallback's row loop
            for i, oc in enumerate(schema.cols):
                per_col[oc.internal].append(
                    column_from_values([r[i] for r in pl], oc.type)
                )
            total += len(pl)
    cols = {}
    dicts = {}
    for oc in schema.cols:
        hc = concat_host_columns(oc.type, per_col[oc.internal])
        cols[oc.internal] = hc
        if hc.dictionary is not None:
            dicts[oc.internal] = hc.dictionary
    block = HostBlock(cols, total)
    batch = block_to_batch(block, pad_capacity(max(total, 1)))
    return L.Staged(
        schema, batch=batch, dicts=dicts, nonce=nonce, key=key
    )


def stage_payloads_incremental(
    schema, payloads: list, nonce: int, vocab=None, key=None
):
    """Received shuffle payload chunks -> a Staged device batch with
    each output column WRITTEN ONCE (ROADMAP PR 4 item a): the final
    buffers are allocated at tile capacity up front (row counts are
    known from the received frames) and every chunk writes its slice
    directly — no concat-then-pad double copy, no np.concatenate.
    String dictionaries come pre-unioned from the store's running
    per-side vocabularies (``vocab``, folded in as frames ARRIVED), so
    staging sorts once and remaps codes per chunk. JSON row packets
    (mixed-codec peers) normalize per chunk through column_from_values
    — the declared fallback's slow path — contributing their own
    dictionary entries to the union."""
    from tidb_tpu.chunk import (
        HostBlock,
        HostColumn,
        batch_from_padded,
        column_from_values,
        pad_capacity,
    )
    from tidb_tpu.dtypes import Kind
    from tidb_tpu.planner import logical as L

    vocab = {k: set(v) for k, v in (vocab or {}).items()}
    blocks: list = []
    for pl in payloads:
        if isinstance(pl, HostBlock):
            # fold any dictionary entries the running vocab missed
            # (payloads landed via ShuffleStore.push already folded
            # theirs on arrival — these unions are then no-ops over
            # the per-chunk pruned dictionaries, not a row-data scan)
            for cname, col in pl.columns.items():
                if col.dictionary is not None:
                    vocab.setdefault(cname, set()).update(
                        col.dictionary.tolist()
                    )
            blocks.append(pl)
            continue
        cols = {}
        for i, oc in enumerate(schema.cols):
            hc = column_from_values([r[i] for r in pl], oc.type)
            cols[oc.internal] = hc
            if hc.dictionary is not None:
                vocab.setdefault(oc.internal, set()).update(
                    hc.dictionary.tolist()
                )
        blocks.append(HostBlock(cols, len(pl)))
    total = sum(b.nrows for b in blocks)
    cap = pad_capacity(max(total, 1))
    out_cols = {}
    dicts = {}
    for oc in schema.cols:
        name = oc.internal
        valid = np.zeros(cap, dtype=bool)
        if oc.type.kind == Kind.STRING:
            unified = np.array(
                sorted(str(v) for v in vocab.get(name, set())),
                dtype=object,
            )
            lut = {v: i for i, v in enumerate(unified.tolist())}
            data = np.zeros(cap, dtype=np.int32)
            off = 0
            for b in blocks:
                c, n = b.columns[name], b.nrows
                if n:
                    cvalid = np.asarray(c.valid, dtype=bool)
                    if c.dictionary is not None and len(c.dictionary):
                        mapping = np.array(
                            [lut[str(v)] for v in c.dictionary.tolist()],
                            dtype=np.int32,
                        )
                        codes = mapping[
                            np.clip(
                                np.asarray(c.data), 0,
                                len(c.dictionary) - 1,
                            )
                        ]
                    else:
                        codes = np.zeros(n, dtype=np.int32)
                    data[off : off + n] = np.where(cvalid, codes, 0)
                    valid[off : off + n] = cvalid
                off += n
            out_cols[name] = HostColumn(oc.type, data, valid, unified)
            dicts[name] = unified
            continue
        dtype = oc.type.np_dtype
        data = np.zeros(cap, dtype=dtype)
        off = 0
        for b in blocks:
            c, n = b.columns[name], b.nrows
            if n:
                data[off : off + n] = np.asarray(c.data, dtype=dtype)
                valid[off : off + n] = np.asarray(c.valid, dtype=bool)
            off += n
        out_cols[name] = HostColumn(oc.type, data, valid)
    batch = batch_from_padded(out_cols, total)
    return L.Staged(
        schema, batch=batch, dicts=dicts, nonce=nonce, key=key
    )


class ShuffleWorker:
    """Executes one dispatched shuffle task on a worker host. One
    instance per EngineServer; holds the receive store (tunnel
    endpoint) the server's `shuffle_push` frames land in."""

    def __init__(self, catalog, self_address: str = "?", mesh_devices=None,
                 delta_state=None):
        self.catalog = catalog
        self.store = ShuffleStore()
        self.self_address = self_address
        self.mesh_devices = mesh_devices
        # HTAP delta replica state of the owning EngineServer (None on
        # shared-catalog servers): producer plans resolve their routed
        # snapshot against it (storage/delta.py prepare_worker_plan)
        self.delta_state = delta_state
        # PROCESS-wide nonce stream (disjoint from dcn.py's and
        # streamed.py's): nonce-staged plans fingerprint on the nonce
        # alone, so two in-process workers minting from per-instance
        # counters would collide in any process-scoped cache
        self._nonce = _STAGE_NONCES
        # executors persist across tasks so producer plans compile once
        # per (plan, slice) instead of once per dispatch; their plan
        # caches are not thread-safe, so executor phases serialize on
        # this lock (tunnel pushes and the store wait still overlap)
        self._exec_lock = racecheck.make_rlock("shuffle.exec")
        self._producer_exec = None
        self._consumer_exec = None
        # shuffle-DAG held state: (coord, qid, attempt, stage, tag) ->
        # HostBlock. tag=None entries are CONSUMER outputs held between
        # stages (stage N's partition feeds stage N+1's StageInput);
        # tag>=0 entries are range-side produce blocks cached by the
        # sampling round so the stage round ships without re-executing
        # the producer. Pruned when a newer attempt's stage-0 task
        # arrives, when the last stage releases, on cancel, and by the
        # bounded-cap backstop.
        self._held_lock = racecheck.make_lock("shuffle.held")
        self._held: "collections.OrderedDict" = collections.OrderedDict()

    _HELD_CAP = 128

    def _held_put(self, coord, qid, attempt, stage, tag, block) -> None:
        with self._held_lock:
            self._held[(coord, qid, int(attempt), int(stage), tag)] = block
            while len(self._held) > self._HELD_CAP:
                self._held.popitem(last=False)

    def _held_get(self, coord, qid, attempt, stage, tag):
        """Peek (entries live until release/prune: the sampling round
        and the stage round both read the same cached block)."""
        with self._held_lock:
            return self._held.get(
                (coord, qid, int(attempt), int(stage), tag)
            )

    def _held_prune(self, coord, qid, before_attempt=None) -> None:
        """Drop held state for one query — everything (release /
        cancel), or only attempts older than ``before_attempt`` (a
        retried DAG restarts from stage 0; the superseded attempt's
        partitions must not satisfy the new attempt's StageInputs)."""
        with self._held_lock:
            for k in list(self._held):
                if k[0] != coord or k[1] != qid:
                    continue
                if before_attempt is None or k[2] < int(before_attempt):
                    del self._held[k]

    def held_count(self) -> int:
        """Held DAG blocks on this worker (engine_status introspection;
        must drain to zero after a completed or cancelled DAG — the
        chaos harness's held-leak invariant)."""
        with self._held_lock:
            return len(self._held)

    def _side_input_block(self, spec, side, plan, cancel_check=None):
        """The producer input of one DAG side as a complete HostBlock:
        a StageInput leaf reads the held output of an earlier stage
        (missing = this worker restarted mid-DAG -> retryable abort),
        a leaf plan prefers the sampling round's cached produce and
        executes the plan otherwise."""
        from tidb_tpu.chunk import batch_to_block
        from tidb_tpu.planner import logical as L
        from tidb_tpu.planner.physical import PhysicalExecutor

        coord, qid = spec.get("coord"), spec.get("qid")
        attempt, stage = int(spec["attempt"]), int(spec.get("stage", 0))
        tag = int(side["tag"])
        if isinstance(plan, L.StageInput):
            # the mid-DAG re-staging seam (and the worker-kill-between-
            # stages chaos site): stage N's held partition becomes
            # stage N+1's already-sliced producer input — no re-scan
            inject("shuffle/stage-input")
            blk = self._held_get(coord, qid, attempt, plan.stage, None)
            if blk is None:
                raise ShuffleAbort(
                    f"held output of stage {plan.stage} missing "
                    f"(worker restarted mid-DAG?)", [],
                )
            return blk
        blk = self._held_get(coord, qid, attempt, stage, tag)
        if blk is not None:
            return blk
        if cancel_check is not None:
            cancel_check()
        with self._exec_lock:
            if self._producer_exec is None:
                self._producer_exec = PhysicalExecutor(
                    self.catalog, mesh_devices=self.mesh_devices
                )
            batch, dicts = self._run_producer(
                self._producer_exec, plan, side.get("_snap_hook"),
                bool(side.get("_snap_merged")),
            )
            types = {c.internal: c.type for c in plan.schema.cols}
            return batch_to_block(batch, types, dicts)

    def _apply_snap(self, spec, side, plan, pins):
        """Apply the dispatch's routed snapshot to one producer side:
        pin the base versions, rewrite the plan to merge this replica's
        buffered deltas, and stash the resolver hook on the side spec
        for the run sites. No-op without a snapshot."""
        snap = spec.get("snap")
        if not snap:
            return plan
        from tidb_tpu.storage import delta as _delta

        plan2, hook, stats = _delta.prepare_worker_plan(
            self.catalog, self.delta_state, plan, snap, pins
        )
        side["_snap_hook"] = hook
        side["_snap_merged"] = stats is not None
        return plan2

    def _run_producer(self, exec_, plan, hook, merged):
        """One producer-plan execution under the exec lock with the
        snapshot resolver installed. Delta-merged plans mix sharded
        scans with replicated Staged leaves — they run on a plain
        (single-device) executor; the SPMD mesh program is a scan
        throughput optimization, not a correctness requirement."""
        from tidb_tpu.planner.physical import PhysicalExecutor

        with self._exec_lock:
            if merged and self.mesh_devices:
                if getattr(self, "_producer_plain", None) is None:
                    self._producer_plain = PhysicalExecutor(self.catalog)
                exec_ = self._producer_plain
            if hook is not None:
                exec_.table_hook = hook
            try:
                return exec_.run(plan)
            finally:
                exec_.table_hook = None

    def run_sample(self, spec: dict, cancel_check=None) -> dict:
        """Boundary-sampling round of a range exchange stage: produce
        (or read) this worker's side input, CACHE it for the stage
        round (the produce runs once, not twice), and return a
        deterministic sample of the partition key for the
        coordinator-merged quantile cut."""
        from tidb_tpu.parallel.wire import sample_range_keys
        from tidb_tpu.planner.ir import plan_from_ir

        inject("shuffle/sample")
        side = spec["side"]
        plan = plan_from_ir(side["plan"])
        pins: list = []
        try:
            plan = self._apply_snap(spec, side, plan, pins)
            blk = self._side_input_block(spec, side, plan, cancel_check)
        finally:
            for t, v in pins:
                t.unpin(v)
        from tidb_tpu.planner import logical as L

        if not isinstance(plan, L.StageInput):
            self._held_put(
                spec.get("coord"), spec.get("qid"), spec["attempt"],
                spec.get("stage", 0), int(side["tag"]), blk,
            )
        samples = sample_range_keys(
            blk, side["key"], int(spec.get("sample_k") or 64),
            int(spec.get("sample_seed") or 0), int(spec["part"]),
        )
        return {"samples": samples, "rows": blk.nrows}

    def run_probe(self, spec: dict, cancel_check=None) -> dict:
        """AQE skew/cardinality probe of one hash stage (parallel/
        aqe.py): produce (and CACHE, exactly like the range sampling
        round) every side's input, reply each side's EXACT
        per-partition row histogram plus its hottest key values — the
        coordinator sums histograms across producers, detects a
        partition over ``tidb_tpu_shuffle_skew_ratio`` x mean, and
        re-dispatches the stage salted (or broadcast-switched, when a
        side's observed total collapsed). The produce runs ONCE: the
        stage round's sides read the cached blocks through
        _side_input_block.

        Runtime filters (ISSUE 19): when the spec carries an ``rf``
        geometry request, build-flagged sides also reply a compact
        filter over their key domain (bloom / in-list / min-max) plus
        the exact distinct key count — harvested from the SAME keyed-
        int extraction the histogram and hot-key replies use
        (key_ints_valid: each cached block is hashed ONCE). A side
        flagged with a ``gcol`` group column replies its distinct
        group count (``gndv``) for the partial-agg-skip decision."""
        from tidb_tpu.dtypes import Kind
        from tidb_tpu.parallel.wire import (
            build_runtime_filter,
            hot_key_ints_from_ints,
            key_ints_valid,
            partition_histogram_from_ints,
            runtime_filter_nbytes,
        )
        from tidb_tpu.planner import logical as L
        from tidb_tpu.planner.ir import plan_from_ir

        inject("aqe/probe")
        m = int(spec["m"])
        rf_spec = spec.get("rf")
        out = []
        pins: list = []
        try:
            for side in spec["sides"]:
                if cancel_check is not None:
                    cancel_check()
                plan = plan_from_ir(side["plan"])
                plan = self._apply_snap(spec, side, plan, pins)
                blk = self._side_input_block(
                    spec, side, plan, cancel_check
                )
                if not isinstance(plan, L.StageInput):
                    self._held_put(
                        spec.get("coord"), spec.get("qid"),
                        spec["attempt"], spec.get("stage", 0),
                        int(side["tag"]), blk,
                    )
                ints, valid = key_ints_valid(blk, side["key"])
                ent = {
                    "tag": int(side["tag"]),
                    "rows": int(blk.nrows),
                    "part_rows": partition_histogram_from_ints(
                        ints, valid, m
                    ),
                    "hot": hot_key_ints_from_ints(ints, valid),
                }
                if rf_spec and side.get("rf_build"):
                    # min-max bounds are legal only where the key-int
                    # image IS the raw value in logical order
                    kkind = blk.columns[side["key"]].type.kind
                    rf = build_runtime_filter(
                        ints, valid, rf_spec,
                        minmax=kkind in (Kind.INT, Kind.BOOL),
                    )
                    ent["filter"] = rf
                    _c_filter_built().labels(kind=rf["kind"]).inc()
                    _c_filter_bytes().inc(runtime_filter_nbytes(rf))
                gcol = side.get("gcol")
                if gcol and gcol in blk.columns:
                    gints, gvalid = key_ints_valid(blk, gcol)
                    ent["gndv"] = int(len(np.unique(gints[gvalid])))
                out.append(ent)
        finally:
            for t, v in pins:
                t.unpin(v)
        return {"sides": out}

    def _apply_side_filter(self, blk, key, rf, stats, tlock):
        """Apply a broadcast runtime filter to one produced block
        BEFORE partitioning/encoding. The shuffle/filter-lost chaos
        site models a filter lost or corrupted between broadcast and
        application: the side degrades to unfiltered shipping — the
        filter is a pure bytes optimization, never a correctness
        dependency. Stats merge under ``tlock`` (shipper threads and
        the task thread share one stats dict)."""
        from tidb_tpu.parallel.wire import apply_runtime_filter_block

        inject("shuffle/filter")
        if inject("shuffle/filter-lost", False):
            with tlock:
                stats["rf_lost"] = int(stats.get("rf_lost", 0)) + 1
            return blk
        blk2, rows_in, dropped = apply_runtime_filter_block(
            blk, key, rf
        )
        with tlock:
            stats["rf_rows_in"] = (
                int(stats.get("rf_rows_in", 0)) + rows_in
            )
            stats["rf_dropped"] = (
                int(stats.get("rf_dropped", 0)) + dropped
            )
        if dropped:
            _c_filter_dropped().inc(dropped)
        return blk2

    def run_task(self, spec: dict, tracer=None, cancel_check=None) -> dict:
        """The worker half of one shuffle stage. Pipelined (the
        default, ``pipeline=True`` + binary codec): producer sides are
        shipped CHUNK-GRANULARLY on shipper threads — each produced
        block is sliced, hash-partitioned and frame-encoded per packet
        chunk so encode+push (and the peers' on-arrival decode) overlap
        the NEXT side's produce; the consumer then waits PER SIDE
        (ShuffleStore.wait_side) and stages each side the moment its
        streams complete, while the other side is still in flight,
        through the single-write incremental stager. Barrier mode
        (``pipeline=False`` escape hatch, or the JSON codec) keeps the
        four sequential phases of PR 4:

        1. open the receive store for (sid, attempt);
        2. run each producer side plan (this worker's fragment slice),
           bucketize its rows by the partition key, push every
           partition to its owning peer (self partitions short-circuit
           into the local store — no tunnel bytes);
        3. wait for all m producers' streams for OUR partition;
        4. substitute the received partitions for the consumer plan's
           ShuffleRead leaves and execute it.

        Returns {"columns", "rows", "shuffle": {...stats}}; raises
        ShuffleAbort for retryable stage failures and whatever
        ``cancel_check`` raises (fleet-wide cancellation: the check is
        polled at every loop point — produce chunks, shipped
        sub-batches, store waits, consume — and a cancelled task
        poisons its stage so late peer frames cannot resurrect it)."""
        from tidb_tpu.chunk import materialize_rows
        from tidb_tpu.planner.ir import plan_from_ir
        from tidb_tpu.planner.physical import PhysicalExecutor
        from tidb_tpu.server.engine_rpc import QueryCancelled

        sid = spec["sid"]
        attempt = int(spec["attempt"])
        m = int(spec["m"])
        part = int(spec["part"])
        peers = [tuple(p) for p in spec["peers"]]
        secret = spec.get("secret")
        packet_rows = int(spec.get("packet_rows") or DEFAULT_PACKET_ROWS)
        inflight = int(
            spec.get("max_inflight_bytes") or DEFAULT_INFLIGHT_BYTES
        )
        wait_timeout = float(spec.get("wait_timeout_s") or 120.0)
        codec = str(spec.get("codec") or "binary")
        pipeline = (
            bool(spec.get("pipeline", True)) and codec == "binary"
        )
        produce_chunks = max(
            int(spec.get("produce_chunks") or DEFAULT_PRODUCE_CHUNKS), 1
        )
        # shuffle-DAG fields (absent = the single-stage shape): stage
        # index + chain length (telemetry), the exchange kind, range
        # boundaries, and whether this stage's output is HELD for the
        # next stage's StageInput instead of returned to the
        # coordinator
        stage_idx = int(spec.get("stage", 0))
        n_stages = int(spec.get("n_stages", 1))
        exchange = str(spec.get("exchange") or "hash")
        boundaries = spec.get("boundaries") or []
        hold_output = bool(spec.get("hold_output"))
        release_held = bool(spec.get("release_held"))
        coord, qid = spec.get("coord"), spec.get("qid")
        if stage_idx == 0:
            # a retried DAG restarts from stage 0 under a new attempt:
            # the superseded attempt's held partitions must not
            # satisfy the new attempt's StageInputs
            self._held_prune(coord, qid, before_attempt=int(attempt))
        ctx = f"q{spec.get('qid')}/p{part}"
        # fleet timeline capture (obs/timeline.py): when the dispatch
        # asks for it, work windows land in a per-task buffer the reply
        # ships back piggybacked — the coordinator merges them behind
        # the ledger fence and rebases through the handshake clock
        # offset, so a retried stage's events land exactly once
        buf = None
        ev_args = {
            "pipeline": pipeline, "stage": stage_idx,
            "exchange": exchange,
        }
        if spec.get("timeline"):
            from tidb_tpu.obs.timeline import TimelineBuffer

            buf = TimelineBuffer()

        def emit(name: str, t0_wall: float, dur_s: float) -> None:
            if buf is not None:
                buf.emit_event(
                    "shuffle", name, t0_wall, dur_s, track=ctx,
                    args=ev_args,
                )

        self.store.open(sid, attempt, m)
        with self._exec_lock:
            # producer executor: the per-host SPMD engine (scans run
            # over the local device mesh — ICI below, tunnels above)
            if self._producer_exec is None:
                self._producer_exec = PhysicalExecutor(
                    self.catalog, mesh_devices=self.mesh_devices
                )
            producer_exec = self._producer_exec
        tunnels: Dict[int, PeerTunnel] = {}
        tlock = racecheck.make_lock("shuffle.tunnels")  # create + stats
        # adaptive-stage marker (parallel/aqe.py): the coordinator's
        # taken decisions ride the task spec so a worker-side chaos
        # fault can target exactly the window between the re-plan
        # decision and the switched/salted stage's execution
        if spec.get("adaptive"):
            inject("aqe/switched-stage")
        stats = {
            "pushed_bytes": 0, "pushed_rows": 0, "local_rows": 0,
            "stalls": 0, "stall_s": 0.0, "retransmits": 0,
            "produced_rows": 0,
            "stage": stage_idx, "n_stages": n_stages,
            "exchange": exchange, "scan_rows": 0, "held_rows": 0,
            # AQE observability: per-side produced rows (the
            # cardinality feedback's exact actuals), rows this
            # partition RECEIVED (the skew ratio's numerator), and
            # the salt fan-out if this stage ran salted
            "side_rows": {}, "recv_rows": 0, "salted": 0,
            "per_peer": [], "codec": codec, "encode_s": 0.0,
            "pipeline": pipeline, "wait_idle_s": 0.0, "ttff_s": 0.0,
            # flight-recorder phase breakdown (obs/flight.py): engine
            # time below the exchange, total blocked-in-wait wall
            # (nonzero even when overlap hides it — wait_idle_s is the
            # NON-overlapped remainder), and partition staging time
            "produce_s": 0.0, "wait_s": 0.0, "stage_s": 0.0,
        }
        _nullspan = _NullSpan()

        def span(name):
            return tracer.span(name) if tracer is not None else _nullspan

        shippers: List[threading.Thread] = []
        ship_errs: List[Exception] = []
        staged: Dict[int, object] = {}
        snap_pins: List[tuple] = []

        def poll():
            """Wait-abort callback: raises on fleet cancellation, else
            reports whether a shipper failed (the WaitInterrupted
            hand-back)."""
            if cancel_check is not None:
                cancel_check()
            return bool(ship_errs)

        try:
            for side in spec["sides"]:
                if cancel_check is not None:
                    cancel_check()
                # Top SQL live phase (obs/profiler.py): the sampler
                # attributes this thread's instants to the shuffle
                # phase it is inside — a no-op when the engine-RPC
                # handler registered no task context
                topsql.set_task_phase("shuffle-produce")
                tag = int(side["tag"])
                plan = plan_from_ir(side["plan"])
                plan = self._apply_snap(spec, side, plan, snap_pins)
                schema_cols = list(plan.schema)
                inject("shuffle/produce")
                stats["scan_rows"] += self._plan_scan_rows(plan)
                mode = str(
                    side.get("mode")
                    or ("range" if exchange == "range" else "hash")
                )
                from tidb_tpu.planner import logical as _L

                salt = side.get("salt")
                if (
                    salt or mode != "hash"
                    or side.get("probed")
                    or isinstance(plan, _L.StageInput)
                ):
                    # DAG edge over a COMPLETE block: a held stage
                    # output (StageInput), a range side (the sampling
                    # round already produced and cached it), a salted
                    # or merely PROBED side (the skew probe cached the
                    # produce — a plain-hash outcome must still read
                    # the cache, not pay produce twice), or a
                    # broadcast/local edge — partitioned/copied whole,
                    # shipped through the columnar frame path
                    t_prod = time.perf_counter()
                    t_wall = time.time()
                    with span(f"{ctx}/produce#{tag}"):
                        blk = self._side_input_block(
                            spec, side, plan, cancel_check
                        )
                    dt_prod = time.perf_counter() - t_prod
                    stats["produce_s"] += dt_prod
                    emit(f"produce#{tag}", t_wall, dt_prod)
                    stats["produced_rows"] += blk.nrows
                    stats["side_rows"][str(tag)] = int(blk.nrows)
                    if side.get("rf") is not None:
                        # runtime filter over the complete block (the
                        # probe-cached / held / range side shape) —
                        # side_rows above stays the TRUE produce count
                        # (the cardinality feedback's actuals)
                        blk = self._apply_side_filter(
                            blk, side["key"], side["rf"], stats, tlock
                        )
                    t_push = time.perf_counter()
                    t_wall = time.time()
                    topsql.set_task_phase("shuffle-push")
                    with span(f"{ctx}/push#{tag}"):
                        if salt:
                            stats["salted"] = max(
                                stats["salted"],
                                int(salt.get("k", 0)),
                            )
                            self._ship_salted_side(
                                sid, attempt, m, tag, part, blk,
                                schema_cols, salt, side.get("key"),
                                peers, secret, tunnels, tlock,
                                packet_rows, inflight, stats,
                            )
                        else:
                            self._ship_block_side(
                                sid, attempt, m, tag, part, blk,
                                schema_cols, mode, boundaries,
                                side.get("key"), peers, secret,
                                tunnels, tlock, packet_rows, inflight,
                                stats,
                            )
                    emit(
                        f"push#{tag}", t_wall,
                        time.perf_counter() - t_push,
                    )
                    continue
                if codec == "json":
                    # shuffle-json-fallback: the row-packet escape
                    # hatch (shuffle_codec=json) materializes and
                    # partitions Python rows, like PR 3
                    t_prod = time.perf_counter()
                    t_wall = time.time()
                    with span(f"{ctx}/produce#{tag}"):
                        batch, dicts = self._run_producer(
                            producer_exec, plan,
                            side.get("_snap_hook"),
                            bool(side.get("_snap_merged")),
                        )
                    dt_prod = time.perf_counter() - t_prod
                    stats["produce_s"] += dt_prod
                    emit(f"produce#{tag}", t_wall, dt_prod)
                    with self._exec_lock:
                        rows = materialize_rows(batch, schema_cols, dicts)
                    key_idx = [c.internal for c in schema_cols].index(
                        side["key"]
                    )
                    stats["produced_rows"] += len(rows)
                    stats["side_rows"][str(tag)] = len(rows)
                    parts = partition_rows(rows, key_idx, m)
                    t_push = time.perf_counter()
                    t_wall = time.time()
                    topsql.set_task_phase("shuffle-push")
                    with span(f"{ctx}/push#{tag}"):
                        for dest, prows in enumerate(parts):
                            self._send_stream(
                                sid, attempt, m, tag, part, dest, prows,
                                peers, secret, tunnels, tlock,
                                packet_rows, inflight, stats,
                            )
                    emit(
                        f"push#{tag}", t_wall,
                        time.perf_counter() - t_push,
                    )
                    continue
                # binary hot path: keep the engine's own columnar
                # layout end to end — hash the key COLUMN (bit-identical
                # to exchange._mix_hash), np.take each column by
                # partition, frame-encode straight from HostColumn
                from tidb_tpu.chunk import batch_to_block, take_block
                from tidb_tpu.parallel.wire import partition_block

                types = {c.internal: c.type for c in schema_cols}
                if pipeline:
                    # shipper thread fed by a queue of produced
                    # sub-batches: d2h fetch + partition + encode +
                    # push of everything enqueued overlaps BOTH the
                    # same side's next produce chunk and the next
                    # side's produce (and the peers' on-arrival decode
                    # of what we push)
                    import queue as _queue

                    sq: "_queue.Queue" = _queue.Queue()
                    with tlock:
                        stats["_live_shippers"] = (
                            stats.get("_live_shippers", 0) + 1
                        )
                    th = threading.Thread(
                        target=self._ship_side_stream,
                        args=(
                            sid, attempt, m, tag, part, sq,
                            side["key"], schema_cols, peers, secret,
                            tunnels, tlock, packet_rows, inflight,
                            stats, ship_errs, buf, ctx, ev_args,
                            cancel_check,
                            # shipper threads inherit the task's Top
                            # SQL digest (their samples charge the
                            # same statement, phase shuffle-push)
                            topsql.current_digest(),
                            # broadcast runtime filter (None = off):
                            # applied per produced sub-block before
                            # partition/encode
                            side.get("rf"),
                        ),
                        daemon=True,
                        name=f"shuffle-ship-{sid}-s{tag}",
                    )
                    th.start()
                    shippers.append(th)
                    # chunk-granular produce: the side executes as
                    # produce_chunks disjoint frag sub-slices when the
                    # plan is row-sliceable, so push starts after ONE
                    # chunk instead of after the whole side
                    subplans = None
                    if produce_chunks > 1 and not side.get(
                        "_snap_merged"
                    ):
                        # a delta-merged side already carries its frag
                        # slice inside the UnionAll — sub-slicing the
                        # base scan again would desync it from the
                        # staged insert slice
                        cand = [
                            _slice_producer(plan, k, produce_chunks)
                            for k in range(produce_chunks)
                        ]
                        if all(c is not None for c in cand):
                            subplans = cand
                    for sp in (subplans or [plan]):
                        if cancel_check is not None:
                            cancel_check()
                        t_prod = time.perf_counter()
                        t_wall = time.time()
                        with span(f"{ctx}/produce#{tag}"):
                            batch, dicts = self._run_producer(
                                producer_exec, sp,
                                side.get("_snap_hook"),
                                bool(side.get("_snap_merged")),
                            )
                        dt_prod = time.perf_counter() - t_prod
                        stats["produce_s"] += dt_prod
                        emit(f"produce#{tag}", t_wall, dt_prod)
                        sq.put((batch, types, dicts))
                    sq.put(None)  # side EOF sentinel
                    continue
                t_prod = time.perf_counter()
                t_wall = time.time()
                with span(f"{ctx}/produce#{tag}"):
                    batch, dicts = self._run_producer(
                        producer_exec, plan, side.get("_snap_hook"),
                        bool(side.get("_snap_merged")),
                    )
                dt_prod = time.perf_counter() - t_prod
                stats["produce_s"] += dt_prod
                emit(f"produce#{tag}", t_wall, dt_prod)
                block = batch_to_block(batch, types, dicts)
                stats["produced_rows"] += block.nrows
                stats["side_rows"][str(tag)] = int(block.nrows)
                if side.get("rf") is not None:
                    block = self._apply_side_filter(
                        block, side["key"], side["rf"], stats, tlock
                    )
                idxs = partition_block(block, side["key"], m)
                t_push = time.perf_counter()
                t_wall = time.time()
                topsql.set_task_phase("shuffle-push")
                with span(f"{ctx}/push#{tag}"):
                    for dest, idx in enumerate(idxs):
                        self._ship_partition(
                            sid, attempt, m, tag, part, dest,
                            take_block(block, idx), schema_cols, peers,
                            secret, tunnels, tlock, packet_rows,
                            inflight, stats,
                        )
                emit(f"push#{tag}", t_wall, time.perf_counter() - t_push)
            consumer = plan_from_ir(spec["consumer"])
            reads = _shuffle_read_tags(consumer)
            # per-side expected sender sets: a "local" DAG edge only
            # ever has this host's own stream (nothing crosses the
            # wire), every other mode expects all m producers
            senders = {
                int(s["tag"]): (
                    [part]
                    if str(s.get("mode") or "") == "local"
                    else list(range(m))
                )
                for s in spec["sides"]
            }
            if not pipeline:
                # barrier shape: every push acked before the wait
                # opens (shipper threads exist only in pipelined mode,
                # so there are no ship_errs to consult here). Local
                # work is done once the last partition is enqueued, so
                # BOTH the flush block (waiting for peer acks) and the
                # store wait are exchange idle.
                t0 = time.perf_counter()
                t_wall = time.time()
                topsql.set_task_phase("shuffle-wait")
                for t in tunnels.values():
                    t.flush()
                with span(f"{ctx}/wait"):
                    by_side = self.store.wait(
                        sid, attempt, len(spec["sides"]), m,
                        wait_timeout, abort=poll, senders=senders,
                    )
                idle = time.perf_counter() - t0
                emit("wait", t_wall, idle)
                stats["wait_idle_s"] += idle
                stats["wait_s"] += idle
                _c_wait_idle_seconds().inc(idle)
            else:
                # pipelined: the wait/stage loop starts while our OWN
                # shippers are still draining — a side whose streams
                # are all EOF stages (including its h2d move) while the
                # other side is still in flight AND while our outbound
                # tail is still crossing the tunnels. abort() hands
                # control back within a poll tick if a shipper fails,
                # so a dead peer surfaces promptly, not at the wait
                # deadline.
                pending = sorted(int(s["tag"]) for s in spec["sides"])
                waited = 0.0
                while pending:
                    t0 = time.perf_counter()
                    t_wall = time.time()
                    # the timeout budget charges WAITING only: per-side
                    # staging between waits must not burn it (barrier
                    # mode charged wait_timeout purely to its one wait)
                    deadline = time.monotonic() + max(
                        wait_timeout - waited, 0.0
                    )
                    topsql.set_task_phase("shuffle-wait")
                    with span(f"{ctx}/wait"):
                        done, chunks, vocab = self.store.wait_side(
                            sid, attempt, pending, m, deadline,
                            abort=poll, senders=senders,
                        )
                    t1 = time.perf_counter()
                    emit("wait", t_wall, t1 - t0)
                    waited += t1 - t0
                    stats["wait_s"] += t1 - t0
                    # idle = blocked time with our own shippers already
                    # drained (wait wall that overlaps our outbound
                    # push is pipeline WORKING, not idling)
                    with tlock:
                        ship_done = stats.get("_ship_done")
                    idle = (
                        max(0.0, t1 - max(t0, ship_done))
                        if ship_done is not None else 0.0
                    )
                    stats["wait_idle_s"] += idle
                    _c_wait_idle_seconds().inc(idle)
                    pending.remove(done)
                    stats["recv_rows"] += sum(
                        _payload_rows(c) for c in chunks
                    )
                    node = reads.get(done)
                    if node is not None:
                        t_stage = time.perf_counter()
                        t_wall = time.time()
                        topsql.set_task_phase("shuffle-stage")
                        with span(f"{ctx}/stage#{done}"):
                            staged[done] = stage_payloads_incremental(
                                node.schema, chunks,
                                next(self._nonce), vocab=vocab,
                                key=f"shuffle#{done}",
                            )
                        dt_stage = time.perf_counter() - t_stage
                        emit(f"stage#{done}", t_wall, dt_stage)
                        stats["stage_s"] += dt_stage
                for th in shippers:
                    th.join()
                if ship_errs:
                    raise ship_errs[0]
                for t in tunnels.values():
                    t.flush()
        except WaitInterrupted:
            # a shipper failed while we were waiting: surface ITS
            # error with the same taxonomy as the in-try raises (a
            # raise from an except clause skips sibling handlers)
            for th in shippers:
                th.join(timeout=30)
            self.store.discard(sid)
            err = ship_errs[0] if ship_errs else None
            if isinstance(err, QueryCancelled):
                # a cancelled shipper: poison like the direct-cancel
                # path (this raise skips the sibling handlers below)
                self.store.poison(sid)
                self._held_prune(coord, qid)
                raise err
            if isinstance(err, PeerDeadError):
                if err.fatal:
                    raise RuntimeError(
                        f"shuffle push to {err.address} rejected: "
                        f"{err.cause}"
                    ) from err
                raise ShuffleAbort("push failed", [err.address]) from err
            raise err if err is not None else ShuffleAbort(
                "ship interrupted", []
            )
        except ShuffleWaitTimeout as e:
            # missing "sideS/senderJ" -> suspect peer address J
            suspects = sorted(
                {
                    "%s:%s" % peers[int(s.rsplit("sender", 1)[1])]
                    for s in e.missing
                }
            )
            self.store.discard(sid)  # a retry runs under a new attempt
            raise ShuffleAbort("wait timed out", suspects) from e
        except PeerDeadError as e:
            if e.fatal:
                # engine-side rejection/encoding error: surface the
                # REAL cause as a non-retryable engine error
                raise RuntimeError(
                    f"shuffle push to {e.address} rejected: {e.cause}"
                ) from e
            raise ShuffleAbort("push failed", [e.address]) from e
        except QueryCancelled:
            # fleet-wide cancellation reached this task: free the
            # stage's buffers and POISON the sid — frames still in
            # flight from peers that have not seen the cancel land as
            # stale drops instead of resurrecting an orphan record —
            # and drop the query's held DAG blocks
            self.store.poison(sid)
            self._held_prune(coord, qid)
            raise
        finally:
            # release the routed snapshot's base-version pins: GC may
            # collect superseded versions once no dispatch reads them
            for t, v in snap_pins:
                t.unpin(v)
            for th in shippers:
                # an error can escape while shippers run: never close
                # tunnels under an active sender
                th.join(timeout=30)
            for t in tunnels.values():
                t.close()
            # authoritative push stats come from the tunnels (only
            # ACKED packets count — an aborted stream's queued bytes
            # never crossed the link)
            for t in tunnels.values():
                stats["pushed_bytes"] += t.bytes_sent
                stats["pushed_rows"] += t.rows_sent
                stats["stalls"] += t.stalls
                stats["stall_s"] += t.stall_s
                stats["retransmits"] += t.retransmits
                if buf is not None:
                    # backpressure stall windows per link — where a
                    # producer stood blocked on a peer's in-flight
                    # byte window, on the merged fleet timeline
                    for w0, wdur in t.stall_windows:
                        buf.emit_event(
                            "stall", f"stall->{t.address}", w0, wdur,
                            track=ctx, args={"dst": t.address},
                        )
                stats["per_peer"].append(
                    {
                        "dst": t.address, "bytes": t.bytes_sent,
                        "rows": t.rows_sent, "frames": t.frames_sent,
                        "stalls": t.stalls,
                        "stall_s": round(t.stall_s, 6),
                        "retransmits": t.retransmits,
                        "codec": t._codec or stats["codec"],
                    }
                )
        stats["ttff_s"] = self.store.max_ttff(sid)
        stats.pop("_live_shippers", None)
        stats.pop("_ship_done", None)
        # the waits copied the rows out: free the buffered packets NOW
        # so the store holds only in-flight stages, not consumed ones
        self.store.discard(sid)

        if pipeline:
            for tag, node in reads.items():
                if tag not in staged:  # a read with no producer side
                    staged[tag] = stage_payloads_incremental(
                        node.schema, [], next(self._nonce),
                        key=f"shuffle#{tag}",
                    )
        else:
            # barrier escape hatch: the PR 4 stage end to end — bulk
            # concat staging under a fresh nonce (no compiled-consumer
            # reuse; the keyed staged input is incremental-mode
            # machinery)
            t_stage = time.perf_counter()
            t_wall = time.time()
            topsql.set_task_phase("shuffle-stage")
            stats["recv_rows"] += sum(
                _payload_rows(c)
                for payloads in by_side.values() for c in payloads
            )
            staged = {
                tag: stage_payloads_as_batch(
                    node.schema, by_side.get(tag, []),
                    next(self._nonce),
                )
                for tag, node in reads.items()
            }
            dt_stage = time.perf_counter() - t_stage
            emit("stage", t_wall, dt_stage)
            stats["stage_s"] += dt_stage
        inject("shuffle/consume")
        if cancel_check is not None:
            cancel_check()
        topsql.set_task_phase("execute")
        with span(f"{ctx}/consume"), self._exec_lock:
            # consumer executes single-device: its sources are Staged
            # partition batches, not mesh-sharded scans
            if self._consumer_exec is None:
                self._consumer_exec = PhysicalExecutor(self.catalog)
            out, out_dicts = self._consumer_exec.run(
                _substitute_reads(consumer, staged)
            )
            if hold_output:
                # mid-DAG stage: the partition output stays HERE as
                # the next stage's StageInput — nothing but stats
                # returns to the coordinator
                from tidb_tpu.chunk import batch_to_block

                types = {
                    c.internal: c.type for c in consumer.schema.cols
                }
                blk = batch_to_block(out, types, out_dicts)
                self._held_put(
                    coord, qid, attempt, stage_idx, None, blk
                )
                stats["held_rows"] = blk.nrows
                out_rows = []
            else:
                out_rows = materialize_rows(
                    out, list(consumer.schema), out_dicts
                )
        if release_held:
            # last DAG stage done: free every held block of this query
            self._held_prune(coord, qid)
        return {
            "columns": [c.name for c in consumer.schema],
            "rows": out_rows,
            "shuffle": stats,
            # piggybacked timeline events (None when capture is off):
            # the reply ships them, the coordinator merges them behind
            # the exactly-once ledger fence
            "events": buf.events if buf is not None else None,
        }

    def _tunnel_for(
        self, dest, peers, sender, secret, tunnels, tlock, inflight,
        batch_packets: int = 64,
    ) -> PeerTunnel:
        # check-and-create under the shared tunnel lock: the task
        # thread ships complete blocks (probed/held/range sides) WHILE
        # shipper threads stream pipelined sides to the same dests — a
        # racing duplicate PeerTunnel would be overwritten in the dict
        # and its tx thread leak past the task's close
        with tlock:
            if dest not in tunnels:
                host, port = peers[dest]
                # src labeled with THIS worker's dial address
                # (peers[sender]) so tidbtpu_shuffle_bytes_total
                # {src,dst} uses one identity space — a host's inbound
                # and outbound series correlate
                tunnels[dest] = PeerTunnel(
                    host, port, secret,
                    src="%s:%s" % tuple(peers[sender]),
                    max_inflight_bytes=inflight,
                    batch_packets=batch_packets,
                )
            return tunnels[dest]

    def _ship_side_stream(
        self, sid, attempt, m, side, sender, sq, key, schema_cols,
        peers, secret, tunnels, tlock, packet_rows, inflight, stats,
        errs, buf=None, ctx="", ev_args=None, cancel_check=None,
        topsql_digest=None, rf=None,
    ) -> None:
        """Pipelined producer ship (one side, run on a shipper thread,
        fed produced sub-batches through queue ``sq`` until the None
        sentinel): each sub-batch is fetched device->host HERE — the
        d2h move overlaps the next produce chunk — then its partition
        map is computed once and the block walked in packet chunks:
        each chunk is split by destination, frame-encoded and enqueued
        IMMEDIATELY, so every peer's first frame leaves after one
        chunk instead of after the whole side (low time-to-first-frame)
        and destinations interleave fairly. Sequence numbers run
        continuously across sub-batches; EOFs close each stream with
        the true frame count once the sentinel arrives. The whole-side
        row materialization of the barrier path never happens here
        (lint-enforced by check_shuffle_hotpath.py). Self partitions
        land HostBlocks in the local store chunk by chunk; a
        mixed-version peer that negotiated down gets per-chunk JSON
        row packets. Errors land in ``errs`` for the task thread."""
        from tidb_tpu.chunk import (
            batch_to_block,
            block_to_rows,
            slice_block,
            take_block,
        )
        from tidb_tpu.parallel.wire import encode_frame, partition_map

        # shipper threads carry the task's statement digest so the Top
        # SQL sampler attributes their encode/push CPU (and tunnel
        # backpressure stalls) to the same query, phase shuffle-push
        _ts_prev = None
        if topsql_digest:
            _ts_prev = topsql.begin_task(
                "shuffle", digest=topsql_digest, phase="shuffle-push"
            )
        try:
            seqs = [0] * m
            local_rows = 0
            encode_s = 0.0
            produced = 0
            # chunks of packet_rows*m keep per-destination frames near
            # packet_rows rows — framing (and per-frame dictionary/
            # header overhead) comparable to the barrier producer
            step = max(int(packet_rows) * max(m, 1), 1)
            while True:
                item = sq.get()
                if item is None:
                    break
                t_ship0 = time.perf_counter()
                t_ship_wall = time.time()
                batch, types, dicts = item
                block = batch_to_block(batch, types, dicts)
                produced += block.nrows
                if rf is not None:
                    # runtime filter per produced sub-block: dropped
                    # rows are never partitioned, encoded or shipped
                    # (``produced`` above stays the true produce count)
                    block = self._apply_side_filter(
                        block, key, rf, stats, tlock
                    )
                pmap = partition_map(block, key, m)
                for a in range(0, block.nrows, step):
                    if cancel_check is not None:
                        # fleet cancellation: the shipper stops mid-
                        # side — its error lands in ``errs`` and the
                        # waiting consumer's abort poll hands control
                        # back within a tick
                        cancel_check()
                    chunk = slice_block(block, a, a + step)
                    cmap = pmap[a : a + step]
                    for dest in range(m):
                        idx = np.nonzero(cmap == dest)[0]
                        if not len(idx):
                            continue
                        sub = take_block(chunk, idx)
                        seq = seqs[dest]
                        seqs[dest] += 1
                        if dest == sender:
                            self.store.push(
                                sid, attempt, m, side, sender, seq, sub
                            )
                            local_rows += sub.nrows
                            continue
                        tun = self._tunnel_for(
                            dest, peers, secret=secret,
                            sender=sender, tunnels=tunnels,
                            tlock=tlock, inflight=inflight,
                        )
                        if tun.negotiated_codec("binary") != "binary":
                            packet = {
                                "sid": sid, "attempt": attempt, "m": m,
                                "side": side, "sender": sender,
                                "part": dest, "seq": seq,
                                "rows": block_to_rows(sub, schema_cols),
                            }
                            # shuffle-json-fallback: per-chunk row
                            # packet for a peer that negotiated down
                            t0 = time.perf_counter()
                            payload = json.dumps(
                                {"shuffle_push": packet}
                            ).encode()
                            dt = time.perf_counter() - t0
                            encode_s += dt
                            _c_encode_seconds().labels(
                                codec="json"
                            ).inc(dt)
                            _c_codec_bytes().labels(codec="json").inc(
                                len(payload)
                            )
                            tun.send(payload, len(payload), sub.nrows)
                            continue
                        t0 = time.perf_counter()
                        frame = encode_frame(
                            sid, attempt, m, side, sender, dest, seq,
                            sub, schema_cols,
                        )
                        dt = time.perf_counter() - t0
                        encode_s += dt
                        _c_encode_seconds().labels(codec="binary").inc(
                            dt
                        )
                        _c_codec_bytes().labels(codec="binary").inc(
                            len(frame)
                        )
                        tun.send(frame, len(frame), sub.nrows)
                if buf is not None:
                    # one push window per shipped sub-batch: d2h fetch
                    # + partition + encode + enqueue — on the timeline
                    # these windows interleave with the SAME side's
                    # next produce chunk, which is the overlap the
                    # pipelined stage claims
                    buf.emit_event(
                        "shuffle", f"push#{side}", t_ship_wall,
                        time.perf_counter() - t_ship0, track=ctx,
                        args=ev_args,
                    )
            for dest in range(m):
                if dest == sender:
                    self.store.push(
                        sid, attempt, m, side, sender, -1, None,
                        nseq=seqs[dest],
                    )
                    continue
                tun = self._tunnel_for(
                    dest, peers, secret=secret, sender=sender,
                    tunnels=tunnels, tlock=tlock, inflight=inflight,
                )
                if tun.negotiated_codec("binary") != "binary":
                    eof = {
                        "sid": sid, "attempt": attempt, "m": m,
                        "side": side, "sender": sender, "part": dest,
                        "seq": -1, "rows": None, "nseq": seqs[dest],
                    }
                    # shuffle-json-fallback: the row-codec EOF marker
                    payload = json.dumps({"shuffle_push": eof}).encode()
                    tun.send(payload, len(payload), 0)
                else:
                    eof = encode_frame(
                        sid, attempt, m, side, sender, dest, -1, None,
                        schema_cols, nseq=seqs[dest],
                    )
                    tun.send(eof, len(eof), 0)
            with tlock:
                stats["local_rows"] += local_rows
                stats["encode_s"] += encode_s
                stats["produced_rows"] += produced
                stats.setdefault("side_rows", {})[str(side)] = produced
        except Exception as e:
            errs.append(e)
        finally:
            if topsql_digest:
                topsql.end_task(_ts_prev)
            with tlock:
                stats["_live_shippers"] = (
                    stats.get("_live_shippers", 1) - 1
                )
                if stats["_live_shippers"] <= 0:
                    # all sides shipped: wait time past this point is
                    # TRUE consumer idle (nothing left to overlap)
                    stats["_ship_done"] = time.perf_counter()

    def _plan_scan_rows(self, plan) -> int:
        """Base-table rows this plan's scans will read, fragment
        slices honored — the per-host scan-work accounting the DAG A/B
        cites (a chained DAG slices EVERY side; the single-cut
        group-by re-scans unsliced join sides on every host)."""
        from tidb_tpu.planner import logical as L

        total = 0

        def walk(p):
            nonlocal total
            if isinstance(p, L.Scan):
                try:
                    nrows = int(self.catalog.table(p.db, p.table).nrows)
                except Exception:
                    return
                if p.frag is not None:
                    i, mm = p.frag
                    total += len(range(int(i), nrows, int(mm)))
                else:
                    total += nrows
                return
            for attr in ("child", "left", "right"):
                c = getattr(p, attr, None)
                if c is not None:
                    walk(c)
            for c in getattr(p, "children", []) or []:
                walk(c)

        walk(plan)
        return total

    def _ship_block_side(
        self, sid, attempt, m, side, sender, block, schema_cols, mode,
        boundaries, key, peers, secret, tunnels, tlock, packet_rows,
        inflight, stats,
    ) -> None:
        """Ship one COMPLETE columnar side under a DAG edge mode:

        - "local": no exchange at all — the producing host is the
          owning partition (the broadcast join's probe side; zero
          tunnel bytes);
        - "broadcast": the whole side goes to EVERY peer (the small
          join side of a broadcast edge);
        - "range": rows route by sampled key-range boundaries
          (wire.range_partition_map — distributed ORDER BY);
        - "hash": key-hash routing (a held StageInput re-exchange).

        Everything rides the existing columnar frame path
        (_ship_partition: per-chunk binary frames, JSON only for a
        peer that negotiated down)."""
        from tidb_tpu.chunk import take_block
        from tidb_tpu.parallel.wire import (
            partition_block,
            range_partition_map,
        )

        if mode == "local":
            # dest == sender: _ship_partition's self-push path lands
            # the block in the local store with the EOF discipline —
            # ONE definition of the self-push protocol
            self._ship_partition(
                sid, attempt, m, side, sender, sender, block,
                schema_cols, peers, secret, tunnels, tlock,
                packet_rows, inflight, stats,
            )
            return
        if mode == "broadcast":
            for dest in range(m):
                self._ship_partition(
                    sid, attempt, m, side, sender, dest, block,
                    schema_cols, peers, secret, tunnels, tlock,
                    packet_rows, inflight, stats,
                )
            return
        if mode == "range":
            pmap = range_partition_map(block, key, boundaries)
            idxs = [
                np.nonzero(pmap == d)[0] for d in range(m)
            ]
        else:
            idxs = partition_block(block, key, m)
        for dest, idx in enumerate(idxs):
            self._ship_partition(
                sid, attempt, m, side, sender, dest,
                take_block(block, idx), schema_cols, peers, secret,
                tunnels, tlock, packet_rows, inflight, stats,
            )

    def _ship_salted_side(
        self, sid, attempt, m, side, sender, block, schema_cols, salt,
        key, peers, secret, tunnels, tlock, packet_rows, inflight,
        stats,
    ) -> None:
        """Ship one COMPLETE columnar side under a salt spec
        (``{"keys": [key_ints], "k": K, "role": ...}``): the hot
        partition's keys route across their K-wide salted target set
        instead of one home partition.

        - role "split" (the skewed side): each hot-key row goes to ONE
          salted target, round-robin (staggered by sender so m
          producers don't all start on lane 0) — the hot partition's
          work spreads K ways, every row still lands exactly once;
        - role "replicate" (a join's other side): each hot-key row is
          COPIED to all K targets, so every salted lane can match its
          share of the split side (the broadcast-of-hot-keys half of
          skew-salted joins). Unflagged rows keep the plain hash map
          either way."""
        from tidb_tpu.chunk import take_block
        from tidb_tpu.parallel.wire import (
            salted_partition_assign,
            salted_split_map,
        )

        if str(salt.get("role") or "split") == "split":
            pmap = salted_split_map(block, key, m, salt, lane0=sender)
            idxs = [np.nonzero(pmap == d)[0] for d in range(m)]
        else:
            base, flagged, k = salted_partition_assign(
                block, key, m, salt
            )
            idxs = []
            for dest in range(m):
                sel = [np.nonzero((base == dest) & ~flagged)[0]]
                for j in range(k):
                    sel.append(np.nonzero(
                        flagged & ((base + j) % m == dest)
                    )[0])
                idxs.append(np.sort(np.concatenate(sel)))
        for dest, idx in enumerate(idxs):
            self._ship_partition(
                sid, attempt, m, side, sender, dest,
                take_block(block, idx), schema_cols, peers, secret,
                tunnels, tlock, packet_rows, inflight, stats,
            )

    def _ship_partition(
        self, sid, attempt, m, side, sender, dest, block, schema_cols,
        peers, secret, tunnels, tlock, packet_rows, inflight, stats,
    ) -> None:
        """Ship one columnar partition: binary frames seq 0..k-1 then
        the EOF frame, each encoded ONCE here in the producer (the
        encoded bytes size the flow-control window, cross the wire
        verbatim after the tunnel's byte-level id/auth splice, and an
        encoding error fails HERE as a non-retryable engine error, not
        a fake peer death). Self partitions land the HostBlock in the
        local store with NO serialization at all; a mixed-version peer
        whose tunnel negotiates down gets the JSON row packets."""
        from tidb_tpu.chunk import block_to_rows, slice_block
        from tidb_tpu.parallel.wire import encode_frame

        if dest == sender:
            if block.nrows:
                self.store.push(sid, attempt, m, side, sender, 0, block)
                stats["local_rows"] += block.nrows
            self.store.push(
                sid, attempt, m, side, sender, -1, None,
                nseq=1 if block.nrows else 0,
            )
            return
        # barrier escape hatch: strict stop-and-wait acks, the
        # pre-pipelining wire discipline
        tun = self._tunnel_for(
            dest, peers, secret=secret, sender=sender, tunnels=tunnels,
            tlock=tlock, inflight=inflight, batch_packets=1,
        )
        if tun.negotiated_codec("binary") != "binary":
            self._send_stream(
                sid, attempt, m, side, sender, dest,
                block_to_rows(block, schema_cols), peers, secret,
                tunnels, tlock, packet_rows, inflight, stats,
            )
            return
        nchunks = (block.nrows + packet_rows - 1) // packet_rows
        for seq in range(nchunks):
            sub = slice_block(
                block, seq * packet_rows, (seq + 1) * packet_rows
            )
            t0 = time.perf_counter()
            frame = encode_frame(
                sid, attempt, m, side, sender, dest, seq, sub,
                schema_cols,
            )
            dt = time.perf_counter() - t0
            stats["encode_s"] += dt
            _c_encode_seconds().labels(codec="binary").inc(dt)
            _c_codec_bytes().labels(codec="binary").inc(len(frame))
            tun.send(frame, len(frame), sub.nrows)
        eof = encode_frame(
            sid, attempt, m, side, sender, dest, -1, None, schema_cols,
            nseq=nchunks,
        )
        tun.send(eof, len(eof), 0)

    def _send_stream(
        self, sid, attempt, m, side, sender, dest, rows, peers, secret,
        tunnels, tlock, packet_rows, inflight, stats,
    ) -> None:
        """Ship one (side, partition) ROW stream — the JSON fallback
        codec (shuffle_codec=json, or a peer that negotiated down):
        data packets seq 0..k-1 then the EOF marker. Self partitions
        land directly in the local store (no tunnel, no DCN bytes)."""
        local = dest == sender
        if not local:
            # json fallback codec keeps the PR 3 wire discipline:
            # stop-and-wait acks, one packet per round trip
            self._tunnel_for(
                dest, peers, secret=secret, sender=sender,
                tunnels=tunnels, tlock=tlock, inflight=inflight,
                batch_packets=1,
            )
        chunks = [
            rows[a : a + packet_rows]
            for a in range(0, len(rows), packet_rows)
        ]
        for seq, chunk in enumerate(chunks):
            if local:
                self.store.push(
                    sid, attempt, m, side, sender, seq, chunk
                )
                stats["local_rows"] += len(chunk)
                continue
            packet = {
                "sid": sid, "attempt": attempt, "m": m, "side": side,
                "sender": sender, "part": dest, "seq": seq, "rows": chunk,
            }
            # shuffle-json-fallback: serialized ONCE, here in the
            # producer — the bytes size the flow-control window and
            # cross the wire verbatim (wire.splice_id_auth stamps
            # id/auth at the byte level); an unserializable value fails
            # HERE as a non-retryable engine error, not a fake peer
            # death
            t0 = time.perf_counter()
            payload = json.dumps({"shuffle_push": packet}).encode()
            dt = time.perf_counter() - t0
            stats["encode_s"] += dt
            _c_encode_seconds().labels(codec="json").inc(dt)
            _c_codec_bytes().labels(codec="json").inc(len(payload))
            tunnels[dest].send(payload, len(payload), len(chunk))
        if local:
            self.store.push(
                sid, attempt, m, side, sender, -1, None, nseq=len(chunks)
            )
        else:
            eof = {
                "sid": sid, "attempt": attempt, "m": m, "side": side,
                "sender": sender, "part": dest, "seq": -1, "rows": None,
                "nseq": len(chunks),
            }
            # shuffle-json-fallback: the row-codec EOF marker
            payload = json.dumps({"shuffle_push": eof}).encode()
            tunnels[dest].send(payload, len(payload), 0)


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
