"""Worker-to-worker DCN shuffle service: the cross-host data plane.

Reference: ExchangeSender/ExchangeReceiver with HashPartition over
MPPDataPacket tunnels (pkg/planner/core/physical_plans.go:1706,
unistore cophandler/mpp_exec.go:597,711) — MPP peers exchange
hash-partitioned chunks DIRECTLY; the coordinator only orchestrates.
PR 1's scheduler staged every inter-host byte through the coordinator
(fine for partial-agg shapes, the wrong cost model for shuffle joins
where neither side is small — ROADMAP; Flare arXiv:1703.08219 and
"Enhancing Computation Pushdown" arXiv:2312.15405 reach the same
conclusion for cloud OLAP pushdown).

This module generalizes the intra-host ICI collectives
(parallel/exchange.py hash_repartition / partition_of with the
`_mix_hash` finalizer) to the DCN tier so the two compose
hierarchically: within a host, rows move over the device mesh's
all_to_all; between hosts, the SAME hash (int keys run the identical
64-bit mix) routes binary columnar frames (parallel/wire.py) over
engine-RPC tunnels (server/engine_rpc.py `shuffle_push` frames). The
producer hashes whole key COLUMNS as numpy arrays and np.takes each
column by partition — HostColumn in, HostColumn out, no Python row
tuples on the hot path; the JSON row-packet codec of PR 3 survives
only as the mixed-version / `shuffle_codec=json` fallback
(partition_rows + _send_stream below).

Pieces, worker side:
- ShuffleStore  — receiver state per (stage, attempt): packet streams
  keyed (side, sender) with per-(fragment, partition, attempt) fences.
  A packet from a superseded attempt is dropped (the stage restarted on
  a survivor set); a duplicate sequence number within an attempt is
  dropped (a retransmit after an ack loss) — the exactly-once
  FragmentLedger discipline (dxf/framework.fence_accepts) applied to
  the data plane, so a re-dispatched fragment never double-delivers.
- PeerTunnel    — sender per peer: a bounded-bytes in-flight window
  (producers block when the window fills: backpressure, counted as
  tunnel stalls), a background sender thread, reconnect + retransmit
  on transport loss (receiver-side dedupe makes retransmit safe).
- ShuffleWorker — one dispatched shuffle task: execute producer side
  plans (SPMD on the local mesh), bucketize rows by key, push
  partitions to peers, wait for the peers' pushes, substitute the
  received partitions for the plan's ShuffleRead leaves, execute the
  consumer plan, reply to the coordinator.

Coordinator-side stage orchestration (tunnel wiring, whole-stage retry
onto the survivor set after a peer death) lives in parallel/dcn.py.

Failpoint sites: shuffle/open, shuffle/recv, shuffle/recv-ack-lost
(server/engine_rpc.py), shuffle/produce, shuffle/push,
shuffle/push-lost, shuffle/wait, shuffle/consume (worker, here) and
shuffle/stage, shuffle/stage-retry (coordinator, parallel/dcn.py).
"""

from __future__ import annotations

import collections
import hashlib
import json
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.utils.failpoint import inject
from tidb_tpu.utils.metrics import REGISTRY

#: receiver cap on concurrently-buffered stages (a runaway backstop,
#: not a working set: completed stages are discarded by run_task as
#: soon as their partition is consumed, so only in-flight queries
#: occupy the window)
_MAX_STAGES = 64

#: default tunnel flow-control window (bytes in flight per peer) and
#: packet granularity; the coordinator can override per stage
DEFAULT_INFLIGHT_BYTES = 4 << 20
DEFAULT_PACKET_ROWS = 2048
#: transport retries per packet before the peer is declared dead
PUSH_RETRIES = 3


# -- telemetry (tidbtpu_shuffle_*) ------------------------------------------


def _c_bytes():
    return REGISTRY.counter(
        "tidbtpu_shuffle_bytes_total",
        "row-packet bytes pushed over worker-to-worker tunnels",
        labels=("src", "dst"),
    )


def _c_rows():
    return REGISTRY.counter(
        "tidbtpu_shuffle_rows_total",
        "rows pushed over worker-to-worker tunnels",
        labels=("src", "dst"),
    )


def _c_stalls():
    return REGISTRY.counter(
        "tidbtpu_shuffle_tunnel_stalls",
        "sends that blocked on the per-peer in-flight byte window",
        labels=("dst",),
    )


def _c_retransmits():
    return REGISTRY.counter(
        "tidbtpu_shuffle_retransmits",
        "packets retransmitted after a tunnel transport loss",
    )


def _c_stale():
    return REGISTRY.counter(
        "tidbtpu_shuffle_stale_dropped",
        "packets fenced out for carrying a superseded stage attempt",
    )


def _c_dups():
    return REGISTRY.counter(
        "tidbtpu_shuffle_duplicates_dropped",
        "duplicate-sequence packets dropped by the receiver dedupe",
    )


def _c_codec_bytes():
    return REGISTRY.counter(
        "tidbtpu_shuffle_codec_bytes",
        "shuffle packet bytes encoded, by wire codec",
        labels=("codec",),
    )


def _c_encode_seconds():
    return REGISTRY.counter(
        "tidbtpu_shuffle_encode_seconds",
        "producer-side packet encode time, by wire codec",
        labels=("codec",),
    )


def _c_decode_seconds():
    return REGISTRY.counter(
        "tidbtpu_shuffle_decode_seconds",
        "receiver-side packet decode time, by wire codec",
        labels=("codec",),
    )


# -- host-side hash partitioning --------------------------------------------
#
# The same 64-bit finalizer as parallel/exchange._mix_hash so the two
# shuffle tiers compose: numpy int64 arithmetic has the identical
# wraparound-multiply and arithmetic-shift semantics as the jnp version
# (parity is unit-tested in tests/test_shuffle.py).

_MIX1 = np.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as signed
_MIX2 = np.int64(-4658895280553007687)  # 0xBF58476D1CE4E5B9 as signed


def mix_hash_np(x: np.ndarray) -> np.ndarray:
    """exchange._mix_hash over a host numpy int64 array."""
    with np.errstate(over="ignore"):
        h = x.astype(np.int64) * _MIX1
        h = h ^ (h >> 29)
        h = h * _MIX2
        h = h ^ (h >> 32)
    return h & np.int64(0x7FFFFFFFFFFFFFFF)


def _key_to_int(v) -> Optional[int]:
    """Stable int64 image of one key value, identical across worker
    processes (python hash() is salted per process and MUST not be
    used here — two producers disagreeing on a partition would split a
    join key across hosts). None stays None (NULL keys colocate on
    partition 0, like exchange.partition_of)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, float):
        if v == 0.0:
            v = 0.0  # -0.0 == 0.0 must land together
        if float(v).is_integer() and abs(v) < 2 ** 62:
            return int(v)  # decimal keys decode to integral floats
        (bits,) = struct.unpack("<q", struct.pack("<d", float(v)))
        return bits
    if isinstance(v, str):
        d = hashlib.blake2b(v.encode(), digest_size=8).digest()
        return int.from_bytes(d, "little", signed=True)
    d = hashlib.blake2b(repr(v).encode(), digest_size=8).digest()
    return int.from_bytes(d, "little", signed=True)


def partition_rows(
    rows: List[tuple], key_idx: int, n: int
) -> List[List[tuple]]:
    """Split materialized rows into n hash partitions on column
    `key_idx`. Equal keys always land in one partition; NULL keys all
    go to partition 0 (one group / never match in joins, but must
    colocate) — the host tier of exchange.partition_of."""
    ints = [_key_to_int(r[key_idx]) for r in rows]
    out: List[List[tuple]] = [[] for _ in range(n)]
    if not rows:
        return out
    arr = np.array([0 if i is None else i for i in ints], dtype=np.int64)
    parts = mix_hash_np(arr) % np.int64(n)
    for r, i, p in zip(rows, ints, parts):
        out[0 if i is None else int(p)].append(r)
    return out


# -- receiver: the tunnel endpoint ------------------------------------------


class ShuffleWaitTimeout(TimeoutError):
    def __init__(self, missing: List[str]):
        super().__init__(f"shuffle wait timed out; missing {missing}")
        self.missing = missing


class _Stream:
    """One (side, sender) packet stream within a stage attempt."""

    __slots__ = ("seqs", "nseq")

    def __init__(self):
        self.seqs: Dict[int, list] = {}
        self.nseq: Optional[int] = None

    def complete(self) -> bool:
        return self.nseq is not None and len(self.seqs) >= self.nseq


class _Stage:
    __slots__ = ("attempt", "m", "streams", "waiters")

    def __init__(self, attempt: int, m: int):
        self.attempt = attempt
        self.m = m
        self.streams: Dict[Tuple[int, int], _Stream] = {}
        #: consumer threads blocked in wait() on this stage — never
        #: evict under a waiter's feet
        self.waiters = 0


class ShuffleStore:
    """Worker-side receive buffer for pushed shuffle partitions.

    Fencing rules (the FragmentLedger pattern on the data plane):
    - a packet whose attempt is OLDER than the stage's current attempt
      is dropped (the coordinator restarted the stage on a survivor
      set; the old partition map no longer applies);
    - a packet whose attempt is NEWER resets the stage (pushes from a
      fast peer may precede this worker's own task dispatch);
    - within an attempt, a duplicate (side, sender, seq) is dropped —
      retransmits after an ack loss land exactly once.
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._stages: "collections.OrderedDict[str, _Stage]" = (
            collections.OrderedDict()
        )

    def _stage(self, sid: str, attempt: int, m: int) -> Optional[_Stage]:
        """Stage record for (sid, attempt), fencing stale attempts.
        Caller holds the condition lock."""
        st = self._stages.get(sid)
        if st is None or attempt > st.attempt:
            st = _Stage(attempt, m)
            self._stages[sid] = st
            if len(self._stages) > _MAX_STAGES:
                # evict oldest WAITER-FREE stages only: dropping a
                # stage whose consumer is blocked in wait() would fail
                # a query on healthy hosts. With every stage actively
                # waited the map simply grows past the cap (bounded by
                # the number of concurrent tasks).
                excess = len(self._stages) - _MAX_STAGES
                for old_sid in list(self._stages):
                    if excess <= 0:
                        break
                    if old_sid != sid and self._stages[old_sid].waiters == 0:
                        del self._stages[old_sid]
                        excess -= 1
        elif attempt < st.attempt:
            return None
        # LRU touch on EVERY access: an actively-receiving stage must
        # never age out under concurrent stages — only idle/orphan ones
        self._stages.move_to_end(sid)
        return st

    def open(self, sid: str, attempt: int, m: int) -> None:
        inject("shuffle/open")
        with self._cv:
            self._stage(sid, attempt, m)

    def discard(self, sid: str) -> None:
        """Drop a stage's buffered rows (called once the consumer has
        read its partition — a retry would run under a NEW attempt,
        which resets the stage anyway, so nothing ever re-reads this
        data). Late peer pushes simply recreate an orphan record that
        ages out of the window."""
        with self._cv:
            self._stages.pop(sid, None)

    def push(
        self,
        sid: str,
        attempt: int,
        m: int,
        side: int,
        sender: int,
        seq: int,
        payload,
        nseq: Optional[int] = None,
    ) -> bool:
        """Land one packet; returns False when fenced (stale attempt)
        or deduped (duplicate seq). `payload` is codec-shaped: a list
        of row tuples (JSON packets) or a decoded columnar HostBlock
        (binary frames) — the store buffers it opaquely and the
        consumer normalizes at staging time, so one stream can even mix
        codecs across senders (mixed-version peers). An EOF packet
        carries payload=None and nseq=<total data packets>."""
        with self._cv:
            st = self._stage(sid, attempt, m)
            if st is None:
                _c_stale().inc()
                return False
            stream = st.streams.setdefault((side, sender), _Stream())
            if payload is None:  # EOF marker — idempotent
                stream.nseq = int(nseq)
                self._cv.notify_all()
                return True
            if seq in stream.seqs:
                _c_dups().inc()
                return False
            stream.seqs[int(seq)] = payload
            self._cv.notify_all()
            return True

    def wait(
        self,
        sid: str,
        attempt: int,
        n_sides: int,
        m: int,
        timeout_s: float,
    ) -> Dict[int, list]:
        """Block until every (side, sender) stream of the attempt is
        complete; returns side -> payload chunks ordered (sender, seq)
        — a deterministic concatenation order, so per-partition
        execution is reproducible across retries. Raises
        ShuffleWaitTimeout with the missing senders (the coordinator's
        death-suspect list)."""
        inject("shuffle/wait")
        deadline = time.monotonic() + timeout_s

        def missing() -> List[str]:
            st = self._stages.get(sid)
            out = []
            for side in range(n_sides):
                for sender in range(m):
                    stream = (
                        st.streams.get((side, sender))
                        if st is not None and st.attempt == attempt
                        else None
                    )
                    if stream is None or not stream.complete():
                        out.append(f"side{side}/sender{sender}")
            return out

        with self._cv:
            # pin the stage for the duration of the wait: eviction
            # skips stages with active waiters. pin is None when this
            # attempt is already superseded (the wait can only time
            # out); identity-compare on release — a newer attempt may
            # have replaced the record mid-wait.
            pin = self._stage(sid, attempt, m)
            if pin is not None:
                pin.waiters += 1
            try:
                while True:
                    gone = missing()
                    if not gone:
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise ShuffleWaitTimeout(gone)
                    self._cv.wait(min(left, 0.25))
            finally:
                if pin is not None and self._stages.get(sid) is pin:
                    pin.waiters -= 1
            st = self._stages[sid]
            out: Dict[int, list] = {}
            for side in range(n_sides):
                chunks: list = []
                for sender in range(m):
                    stream = st.streams[(side, sender)]
                    for seq in range(stream.nseq):
                        chunks.append(stream.seqs[seq])
                out[side] = chunks
            return out


# -- sender: per-peer tunnel with flow control ------------------------------


class PeerDeadError(ConnectionError):
    """A tunnel gave up on its peer. `fatal` distinguishes an engine-
    side rejection or encoding error (retrying a HEALTHY peer cannot
    fix it — must surface, not retry) from a transport loss (the peer
    is a death suspect and the stage should retry on survivors)."""

    def __init__(self, address: str, cause: Exception, fatal: bool = False):
        super().__init__(f"shuffle peer {address} unreachable: {cause}")
        self.address = address
        self.cause = cause
        self.fatal = fatal


class PeerTunnel:
    """One worker-to-worker tunnel: a background sender thread drains a
    queue of packets over an EngineClient connection; producers block
    when queued-plus-unacked bytes exceed the window (backpressure —
    counted as tunnel stalls). Transport loss reconnects and
    retransmits the packet (the receiver's seq dedupe makes this safe);
    PUSH_RETRIES consecutive failures declare the peer dead."""

    def __init__(
        self,
        host: str,
        port: int,
        secret: Optional[str],
        src: str,
        max_inflight_bytes: int = DEFAULT_INFLIGHT_BYTES,
        timeout_s: float = 30.0,
    ):
        self.host, self.port, self.secret = host, port, secret
        self.address = f"{host}:{port}"
        self.src = src
        self.max_inflight = int(max_inflight_bytes)
        self.timeout_s = timeout_s
        self.bytes_sent = 0
        self.rows_sent = 0
        self.stalls = 0
        self.retransmits = 0
        self._cv = threading.Condition()
        self._q: "collections.deque" = collections.deque()
        self._inflight = 0
        self._dead: Optional[Exception] = None
        self._dead_fatal = False
        self._closing = False
        self._client = None
        self._codec: Optional[str] = None
        self._neg_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"shuffle-tx-{self.address}"
        )
        self._thread.start()

    def negotiated_codec(self, preferred: str = "binary") -> str:
        """The wire codec this tunnel may use: "binary" when the peer's
        handshake advertises a compatible wire version, else "json"
        (mixed-version peers keep interoperating through the row-packet
        fallback). Negotiated once per tunnel over a throwaway ping
        connection (the sender thread owns the data connection); an
        unreachable peer answers `preferred` — the first real send will
        surface the death through the normal suspect machinery."""
        if preferred != "binary":
            return "json"
        with self._neg_lock:
            if self._codec is None:
                from tidb_tpu.parallel.wire import WIRE_VERSION
                from tidb_tpu.server.engine_rpc import EngineClient

                try:
                    c = EngineClient(
                        self.host, self.port, secret=self.secret,
                        timeout_s=min(self.timeout_s, 10.0),
                    )
                    try:
                        peer_wire = int(c._call({}).get("wire", 0))
                    finally:
                        c.close()
                    # EXACT version match: decode_frame rejects any
                    # other version, so a skewed peer must degrade to
                    # the JSON fallback, not trade unreadable frames
                    self._codec = (
                        "binary" if peer_wire == WIRE_VERSION else "json"
                    )
                except Exception:
                    self._codec = preferred
            return self._codec

    # -- producer side -------------------------------------------------
    def send(self, packet, nbytes: int, nrows: int) -> None:
        """Enqueue one packet: pre-encoded bytes (the hot path — the
        producer serialized it once and the bytes cross the wire
        verbatim) or a plain dict (tests/tools)."""
        with self._cv:
            stalled = False
            while (
                self._dead is None
                and self._inflight + nbytes > self.max_inflight
                and self._inflight > 0
            ):
                if not stalled:
                    stalled = True
                    self.stalls += 1
                    _c_stalls().labels(dst=self.address).inc()
                self._cv.wait(0.05)
            if self._dead is not None:
                raise PeerDeadError(
                    self.address, self._dead, fatal=self._dead_fatal
                )
            self._inflight += nbytes
            self._q.append((packet, nbytes, nrows))
            self._cv.notify_all()

    def flush(self) -> None:
        """Block until every queued packet is acked; raises if the peer
        died mid-stream."""
        with self._cv:
            while self._dead is None and (self._q or self._inflight):
                self._cv.wait(0.05)
            if self._dead is not None:
                raise PeerDeadError(
                    self.address, self._dead, fatal=self._dead_fatal
                )

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        self._thread.join(timeout=10)
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass

    # -- sender thread -------------------------------------------------
    def _connect(self):
        from tidb_tpu.server.engine_rpc import EngineClient

        if self._client is None or self._client._dead:
            self._client = EngineClient(
                self.host, self.port, secret=self.secret,
                timeout_s=self.timeout_s,
            )
        return self._client

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closing and self._dead is None:
                    self._cv.wait(0.05)
                if self._dead is not None or (self._closing and not self._q):
                    return
                packet, nbytes, nrows = self._q[0]
            err: Optional[Exception] = None
            fatal = False
            for attempt in range(PUSH_RETRIES):
                try:
                    inject("shuffle/push")
                    if inject("shuffle/push-lost"):
                        raise ConnectionError(
                            "failpoint: push lost in transit"
                        )
                    client = self._connect()
                    if isinstance(packet, (bytes, bytearray)):
                        # hot path: pre-encoded at enqueue, sent as-is
                        client.shuffle_push_encoded(bytes(packet))
                    else:
                        client.shuffle_push(packet)
                    err = None
                    break
                except (RuntimeError, ValueError, TypeError) as e:
                    # engine-side rejection or an encoding error — NOT
                    # a transport loss: retrying a healthy peer cannot
                    # fix it, and reporting the peer as a death suspect
                    # would send the coordinator chasing a ghost
                    err, fatal = e, True
                    break
                except Exception as e:
                    err = e
                    if self._client is not None:
                        try:
                            self._client.close()
                        except Exception:
                            pass
                        self._client = None
                    if attempt + 1 < PUSH_RETRIES:
                        self.retransmits += 1
                        _c_retransmits().inc()
                        time.sleep(0.05 * (attempt + 1))
            with self._cv:
                self._q.popleft()
                self._inflight -= nbytes
                if err is not None:
                    self._dead = err
                    self._dead_fatal = fatal
                else:
                    self.bytes_sent += nbytes
                    self.rows_sent += nrows
                    _c_bytes().labels(src=self.src, dst=self.address).inc(
                        nbytes
                    )
                    _c_rows().labels(src=self.src, dst=self.address).inc(
                        nrows
                    )
                self._cv.notify_all()


# -- the dispatched shuffle task --------------------------------------------


class ShuffleAbort(RuntimeError):
    """Retryable stage failure a worker reports to the coordinator:
    dead peers during push, or producers that never delivered before
    the wait deadline. The coordinator verifies the suspects, then
    re-runs the WHOLE stage (new attempt) on the survivor set."""

    def __init__(self, reason: str, suspects: List[str]):
        super().__init__(f"{reason}; suspects={suspects}")
        self.reason = reason
        self.suspects = suspects


def _substitute_reads(plan, staged_by_tag):
    """Replace every ShuffleRead leaf with its Staged partition batch."""
    import dataclasses

    from tidb_tpu.planner import logical as L

    if isinstance(plan, L.ShuffleRead):
        return staged_by_tag[plan.tag]
    kw = {}
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is not None:
            kw[attr] = _substitute_reads(c, staged_by_tag)
    ch = getattr(plan, "children", None)
    if ch:
        kw["children"] = [_substitute_reads(c, staged_by_tag) for c in ch]
    return dataclasses.replace(plan, **kw) if kw else plan


def _shuffle_read_tags(plan) -> Dict[int, object]:
    """tag -> ShuffleRead node (the consumer's exchange leaves)."""
    from tidb_tpu.planner import logical as L

    out: Dict[int, object] = {}

    def walk(p):
        if isinstance(p, L.ShuffleRead):
            out[p.tag] = p
            return
        for attr in ("child", "left", "right"):
            c = getattr(p, attr, None)
            if c is not None:
                walk(c)
        for c in getattr(p, "children", []) or []:
            walk(c)

    walk(plan)
    return out


def stage_rows_as_batch(schema, rows: List[tuple], nonce: int):
    """Materialized rows -> a Staged device batch under `schema` (the
    receiving side of any host-level exchange; shared with the
    coordinator's final stage in parallel/dcn.py)."""
    from tidb_tpu.chunk import (
        HostBlock,
        block_to_batch,
        column_from_values,
        pad_capacity,
    )
    from tidb_tpu.planner import logical as L

    cols = {}
    dicts = {}
    for i, oc in enumerate(schema.cols):
        hc = column_from_values([r[i] for r in rows], oc.type)
        cols[oc.internal] = hc
        if hc.dictionary is not None:
            dicts[oc.internal] = hc.dictionary
    block = HostBlock(cols, len(rows))
    batch = block_to_batch(block, pad_capacity(max(len(rows), 1)))
    return L.Staged(schema, batch=batch, dicts=dicts, nonce=nonce)


def stage_payloads_as_batch(schema, payloads: list, nonce: int):
    """Received shuffle payload chunks -> a Staged device batch by
    COLUMN CONCATENATION: binary frames arrive as decoded HostBlocks
    whose columns concatenate directly (string dictionaries unified
    into one sorted stage-local table, codes re-keyed — join keys
    comparable across senders and sides); JSON row packets take the
    column_from_values slow path per chunk. No per-row Python loop
    touches columnar chunks."""
    from tidb_tpu.chunk import (
        HostBlock,
        block_to_batch,
        column_from_values,
        concat_host_columns,
        pad_capacity,
    )
    from tidb_tpu.planner import logical as L

    per_col: Dict[str, list] = {oc.internal: [] for oc in schema.cols}
    total = 0
    for pl in payloads:
        if isinstance(pl, HostBlock):
            for oc in schema.cols:
                per_col[oc.internal].append(pl.columns[oc.internal])
            total += pl.nrows
        else:  # JSON row packet — the declared fallback's row loop
            for i, oc in enumerate(schema.cols):
                per_col[oc.internal].append(
                    column_from_values([r[i] for r in pl], oc.type)
                )
            total += len(pl)
    cols = {}
    dicts = {}
    for oc in schema.cols:
        hc = concat_host_columns(oc.type, per_col[oc.internal])
        cols[oc.internal] = hc
        if hc.dictionary is not None:
            dicts[oc.internal] = hc.dictionary
    block = HostBlock(cols, total)
    batch = block_to_batch(block, pad_capacity(max(total, 1)))
    return L.Staged(schema, batch=batch, dicts=dicts, nonce=nonce)


class ShuffleWorker:
    """Executes one dispatched shuffle task on a worker host. One
    instance per EngineServer; holds the receive store (tunnel
    endpoint) the server's `shuffle_push` frames land in."""

    def __init__(self, catalog, self_address: str = "?", mesh_devices=None):
        self.catalog = catalog
        self.store = ShuffleStore()
        self.self_address = self_address
        self.mesh_devices = mesh_devices
        import itertools

        self._nonce = itertools.count(1 << 24)  # disjoint from dcn.py's
        # executors persist across tasks so producer plans compile once
        # per (plan, slice) instead of once per dispatch; their plan
        # caches are not thread-safe, so executor phases serialize on
        # this lock (tunnel pushes and the store wait still overlap)
        self._exec_lock = threading.RLock()
        self._producer_exec = None
        self._consumer_exec = None

    def run_task(self, spec: dict, tracer=None) -> dict:
        """The worker half of one shuffle stage:

        1. open the receive store for (sid, attempt);
        2. run each producer side plan (this worker's fragment slice),
           bucketize its rows by the partition key, push every
           partition to its owning peer (self partitions short-circuit
           into the local store — no tunnel bytes);
        3. wait for all m producers' streams for OUR partition;
        4. substitute the received partitions for the consumer plan's
           ShuffleRead leaves and execute it.

        Returns {"columns", "rows", "shuffle": {...stats}}; raises
        ShuffleAbort for retryable stage failures."""
        from tidb_tpu.chunk import materialize_rows
        from tidb_tpu.planner.ir import plan_from_ir
        from tidb_tpu.planner.physical import PhysicalExecutor

        sid = spec["sid"]
        attempt = int(spec["attempt"])
        m = int(spec["m"])
        part = int(spec["part"])
        peers = [tuple(p) for p in spec["peers"]]
        secret = spec.get("secret")
        packet_rows = int(spec.get("packet_rows") or DEFAULT_PACKET_ROWS)
        inflight = int(
            spec.get("max_inflight_bytes") or DEFAULT_INFLIGHT_BYTES
        )
        wait_timeout = float(spec.get("wait_timeout_s") or 120.0)
        codec = str(spec.get("codec") or "binary")
        ctx = f"q{spec.get('qid')}/p{part}"

        self.store.open(sid, attempt, m)
        with self._exec_lock:
            # producer executor: the per-host SPMD engine (scans run
            # over the local device mesh — ICI below, tunnels above)
            if self._producer_exec is None:
                self._producer_exec = PhysicalExecutor(
                    self.catalog, mesh_devices=self.mesh_devices
                )
            producer_exec = self._producer_exec
        tunnels: Dict[int, PeerTunnel] = {}
        stats = {
            "pushed_bytes": 0, "pushed_rows": 0, "local_rows": 0,
            "stalls": 0, "retransmits": 0, "produced_rows": 0,
            "per_peer": [], "codec": codec, "encode_s": 0.0,
        }
        _nullspan = _NullSpan()

        def span(name):
            return tracer.span(name) if tracer is not None else _nullspan

        try:
            for side in spec["sides"]:
                tag = int(side["tag"])
                plan = plan_from_ir(side["plan"])
                schema_cols = list(plan.schema)
                inject("shuffle/produce")
                with span(f"{ctx}/produce#{tag}"), self._exec_lock:
                    batch, dicts = producer_exec.run(plan)
                if codec == "json":
                    # shuffle-json-fallback: the row-packet escape
                    # hatch (shuffle_codec=json) materializes and
                    # partitions Python rows, like PR 3
                    with self._exec_lock:
                        rows = materialize_rows(batch, schema_cols, dicts)
                    key_idx = [c.internal for c in schema_cols].index(
                        side["key"]
                    )
                    stats["produced_rows"] += len(rows)
                    parts = partition_rows(rows, key_idx, m)
                    with span(f"{ctx}/push#{tag}"):
                        for dest, prows in enumerate(parts):
                            self._send_stream(
                                sid, attempt, m, tag, part, dest, prows,
                                peers, secret, tunnels, packet_rows,
                                inflight, stats,
                            )
                    continue
                # binary hot path: keep the engine's own columnar
                # layout end to end — hash the key COLUMN (bit-identical
                # to exchange._mix_hash), np.take each column by
                # partition, frame-encode straight from HostColumn
                from tidb_tpu.chunk import batch_to_block, take_block
                from tidb_tpu.parallel.wire import partition_block

                types = {c.internal: c.type for c in schema_cols}
                block = batch_to_block(batch, types, dicts)
                stats["produced_rows"] += block.nrows
                idxs = partition_block(block, side["key"], m)
                with span(f"{ctx}/push#{tag}"):
                    for dest, idx in enumerate(idxs):
                        self._ship_partition(
                            sid, attempt, m, tag, part, dest,
                            take_block(block, idx), schema_cols, peers,
                            secret, tunnels, packet_rows, inflight,
                            stats,
                        )
            for t in tunnels.values():
                t.flush()
        except PeerDeadError as e:
            if e.fatal:
                # engine-side rejection/encoding error: surface the
                # REAL cause as a non-retryable engine error
                raise RuntimeError(
                    f"shuffle push to {e.address} rejected: {e.cause}"
                ) from e
            raise ShuffleAbort("push failed", [e.address]) from e
        finally:
            for t in tunnels.values():
                t.close()
            # authoritative push stats come from the tunnels (only
            # ACKED packets count — an aborted stream's queued bytes
            # never crossed the link)
            for t in tunnels.values():
                stats["pushed_bytes"] += t.bytes_sent
                stats["pushed_rows"] += t.rows_sent
                stats["stalls"] += t.stalls
                stats["retransmits"] += t.retransmits
                stats["per_peer"].append(
                    {
                        "dst": t.address, "bytes": t.bytes_sent,
                        "rows": t.rows_sent, "stalls": t.stalls,
                        "retransmits": t.retransmits,
                    }
                )

        n_sides = len(spec["sides"])
        try:
            with span(f"{ctx}/wait"):
                by_side = self.store.wait(
                    sid, attempt, n_sides, m, wait_timeout
                )
        except ShuffleWaitTimeout as e:
            # missing "sideS/senderJ" -> suspect peer address J
            suspects = sorted(
                {
                    "%s:%s" % peers[int(s.rsplit("sender", 1)[1])]
                    for s in e.missing
                }
            )
            self.store.discard(sid)  # a retry runs under a new attempt
            raise ShuffleAbort("wait timed out", suspects) from e
        # wait() copied the rows out: free the buffered packets NOW so
        # the store holds only in-flight stages, not consumed ones
        self.store.discard(sid)

        consumer = plan_from_ir(spec["consumer"])
        reads = _shuffle_read_tags(consumer)
        staged = {
            tag: stage_payloads_as_batch(
                node.schema, by_side.get(tag, []), next(self._nonce)
            )
            for tag, node in reads.items()
        }
        inject("shuffle/consume")
        with span(f"{ctx}/consume"), self._exec_lock:
            # consumer executes single-device: its sources are Staged
            # partition batches, not mesh-sharded scans
            if self._consumer_exec is None:
                self._consumer_exec = PhysicalExecutor(self.catalog)
            out, out_dicts = self._consumer_exec.run(
                _substitute_reads(consumer, staged)
            )
            out_rows = materialize_rows(
                out, list(consumer.schema), out_dicts
            )
        return {
            "columns": [c.name for c in consumer.schema],
            "rows": out_rows,
            "shuffle": stats,
        }

    def _tunnel_for(
        self, dest, peers, sender, secret, tunnels, inflight
    ) -> PeerTunnel:
        if dest not in tunnels:
            host, port = peers[dest]
            # src labeled with THIS worker's dial address (peers[sender])
            # so tidbtpu_shuffle_bytes_total{src,dst} uses one identity
            # space — a host's inbound and outbound series correlate
            tunnels[dest] = PeerTunnel(
                host, port, secret, src="%s:%s" % tuple(peers[sender]),
                max_inflight_bytes=inflight,
            )
        return tunnels[dest]

    def _ship_partition(
        self, sid, attempt, m, side, sender, dest, block, schema_cols,
        peers, secret, tunnels, packet_rows, inflight, stats,
    ) -> None:
        """Ship one columnar partition: binary frames seq 0..k-1 then
        the EOF frame, each encoded ONCE here in the producer (the
        encoded bytes size the flow-control window, cross the wire
        verbatim after the tunnel's byte-level id/auth splice, and an
        encoding error fails HERE as a non-retryable engine error, not
        a fake peer death). Self partitions land the HostBlock in the
        local store with NO serialization at all; a mixed-version peer
        whose tunnel negotiates down gets the JSON row packets."""
        from tidb_tpu.chunk import block_to_rows, slice_block
        from tidb_tpu.parallel.wire import encode_frame

        if dest == sender:
            if block.nrows:
                self.store.push(sid, attempt, m, side, sender, 0, block)
                stats["local_rows"] += block.nrows
            self.store.push(
                sid, attempt, m, side, sender, -1, None,
                nseq=1 if block.nrows else 0,
            )
            return
        tun = self._tunnel_for(
            dest, peers, secret=secret, sender=sender, tunnels=tunnels,
            inflight=inflight,
        )
        if tun.negotiated_codec("binary") != "binary":
            self._send_stream(
                sid, attempt, m, side, sender, dest,
                block_to_rows(block, schema_cols), peers, secret,
                tunnels, packet_rows, inflight, stats,
            )
            return
        nchunks = (block.nrows + packet_rows - 1) // packet_rows
        for seq in range(nchunks):
            sub = slice_block(
                block, seq * packet_rows, (seq + 1) * packet_rows
            )
            t0 = time.perf_counter()
            frame = encode_frame(
                sid, attempt, m, side, sender, dest, seq, sub,
                schema_cols,
            )
            dt = time.perf_counter() - t0
            stats["encode_s"] += dt
            _c_encode_seconds().labels(codec="binary").inc(dt)
            _c_codec_bytes().labels(codec="binary").inc(len(frame))
            tun.send(frame, len(frame), sub.nrows)
        eof = encode_frame(
            sid, attempt, m, side, sender, dest, -1, None, schema_cols,
            nseq=nchunks,
        )
        tun.send(eof, len(eof), 0)

    def _send_stream(
        self, sid, attempt, m, side, sender, dest, rows, peers, secret,
        tunnels, packet_rows, inflight, stats,
    ) -> None:
        """Ship one (side, partition) ROW stream — the JSON fallback
        codec (shuffle_codec=json, or a peer that negotiated down):
        data packets seq 0..k-1 then the EOF marker. Self partitions
        land directly in the local store (no tunnel, no DCN bytes)."""
        local = dest == sender
        if not local:
            self._tunnel_for(
                dest, peers, secret=secret, sender=sender,
                tunnels=tunnels, inflight=inflight,
            )
        chunks = [
            rows[a : a + packet_rows]
            for a in range(0, len(rows), packet_rows)
        ]
        for seq, chunk in enumerate(chunks):
            if local:
                self.store.push(
                    sid, attempt, m, side, sender, seq, chunk
                )
                stats["local_rows"] += len(chunk)
                continue
            packet = {
                "sid": sid, "attempt": attempt, "m": m, "side": side,
                "sender": sender, "part": dest, "seq": seq, "rows": chunk,
            }
            # shuffle-json-fallback: serialized ONCE, here in the
            # producer — the bytes size the flow-control window and
            # cross the wire verbatim (wire.splice_id_auth stamps
            # id/auth at the byte level); an unserializable value fails
            # HERE as a non-retryable engine error, not a fake peer
            # death
            t0 = time.perf_counter()
            payload = json.dumps({"shuffle_push": packet}).encode()
            dt = time.perf_counter() - t0
            stats["encode_s"] += dt
            _c_encode_seconds().labels(codec="json").inc(dt)
            _c_codec_bytes().labels(codec="json").inc(len(payload))
            tunnels[dest].send(payload, len(payload), len(chunk))
        if local:
            self.store.push(
                sid, attempt, m, side, sender, -1, None, nseq=len(chunks)
            )
        else:
            eof = {
                "sid": sid, "attempt": attempt, "m": m, "side": side,
                "sender": sender, "part": dest, "seq": -1, "rows": None,
                "nseq": len(chunks),
            }
            # shuffle-json-fallback: the row-codec EOF marker
            payload = json.dumps({"shuffle_push": eof}).encode()
            tunnels[dest].send(payload, len(payload), 0)


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
