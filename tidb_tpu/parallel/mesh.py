"""Device mesh helpers.

Reference: the MPP task topology — fragments dispatched per store with
exchange between them (pkg/planner/core/fragment.go:149, copr/mpp.go:93).
TPU-native: one 1-D logical mesh axis "d" over all chips; row partitions
of every table shard over "d" (the analog of Region-partitioned scans,
SURVEY.md §2.7), and exchange ops are XLA collectives over ICI.
Multi-host: the same mesh spans hosts via jax.distributed — collectives
ride ICI within a slice and DCN across, with no code change here.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tidb_tpu.chunk import Batch

AXIS = "d"


# -- jax API compat ---------------------------------------------------------
# `jax.shard_map` / `jax.sharding.reshard` are the modern spellings; the
# pinned jax (0.4.x) only has the experimental/constraint forms. One
# shim here so every SPMD call site (planner/physical.py, tests) works
# on both — without it the whole mesh mode dies with AttributeError.

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax<0.5: experimental form, whose replication checker predates
    # rules for `while` (the aggregation claim loop) — disable it; the
    # engine's out_specs declare the replication contract explicitly
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f=None, **kw):
        kw.setdefault("check_rep", False)
        if f is None:
            return _functools.partial(shard_map, **kw)
        return _shard_map_exp(f, **kw)


def reshard(a, sharding):
    """jax.sharding.reshard(a, s) on new jax; on old jax a sharding
    constraint under tracing and a device_put eagerly."""
    if hasattr(jax.sharding, "reshard"):
        return jax.sharding.reshard(a, sharding)
    from jax import core as _core

    if isinstance(a, _core.Tracer):
        return jax.lax.with_sharding_constraint(a, sharding)
    return jax.device_put(a, sharding)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return jax.make_mesh((n,), (AXIS,), devices=devs[:n])


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_count: Optional[int] = None,
) -> Mesh:
    """Bring up the cross-host runtime (DCN analog) and return the
    GLOBAL mesh spanning every process's devices.

    Reference: cross-store MPP dispatch (pkg/store/copr/mpp.go:93) +
    cluster membership via PD/etcd. JAX's multi-controller model
    replaces both: every host runs the same program, jax.distributed
    wires the processes together (coordinator = the PD analog), and
    collectives ride ICI within a slice / DCN across slices with no
    engine change — the mesh axis simply spans more devices.

    For CPU-based testing set JAX_PLATFORMS=cpu and
    xla_force_host_platform_device_count before calling; each process
    contributes its local devices to the global mesh.
    """
    if local_device_count is not None:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={local_device_count}"
            ).strip()
    try:
        # CPU dryruns need an inter-process collectives transport; jax
        # 0.4.x defaults to 'none' ("Multiprocess computations aren't
        # implemented on the CPU backend"). Newer jax picks gloo itself
        # and drops the knob — hence best-effort.
        import os

        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return make_mesh()


def batch_spec() -> P:
    return P(AXIS)


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Place a host-built global batch row-sharded over the mesh."""
    sharding = NamedSharding(mesh, P(AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def unshard_batch(batch: Batch) -> Batch:
    """Gather a sharded batch to host-replicated layout (materialization)."""
    return jax.tree.map(lambda x: np.asarray(x), batch)
