"""Device mesh helpers.

Reference: the MPP task topology — fragments dispatched per store with
exchange between them (pkg/planner/core/fragment.go:149, copr/mpp.go:93).
TPU-native: one 1-D logical mesh axis "d" over all chips; row partitions
of every table shard over "d" (the analog of Region-partitioned scans,
SURVEY.md §2.7), and exchange ops are XLA collectives over ICI.
Multi-host: the same mesh spans hosts via jax.distributed — collectives
ride ICI within a slice and DCN across, with no code change here.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tidb_tpu.chunk import Batch

AXIS = "d"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return jax.make_mesh((n,), (AXIS,), devices=devs[:n])


def batch_spec() -> P:
    return P(AXIS)


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Place a host-built global batch row-sharded over the mesh."""
    sharding = NamedSharding(mesh, P(AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def unshard_batch(batch: Batch) -> Batch:
    """Gather a sharded batch to host-replicated layout (materialization)."""
    return jax.tree.map(lambda x: np.asarray(x), batch)
