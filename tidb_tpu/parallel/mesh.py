"""Device mesh helpers.

Reference: the MPP task topology — fragments dispatched per store with
exchange between them (pkg/planner/core/fragment.go:149, copr/mpp.go:93).
TPU-native: one 1-D logical mesh axis "d" over all chips; row partitions
of every table shard over "d" (the analog of Region-partitioned scans,
SURVEY.md §2.7), and exchange ops are XLA collectives over ICI.
Multi-host: the same mesh spans hosts via jax.distributed — collectives
ride ICI within a slice and DCN across, with no code change here.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tidb_tpu.chunk import Batch

AXIS = "d"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return jax.make_mesh((n,), (AXIS,), devices=devs[:n])


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_count: Optional[int] = None,
) -> Mesh:
    """Bring up the cross-host runtime (DCN analog) and return the
    GLOBAL mesh spanning every process's devices.

    Reference: cross-store MPP dispatch (pkg/store/copr/mpp.go:93) +
    cluster membership via PD/etcd. JAX's multi-controller model
    replaces both: every host runs the same program, jax.distributed
    wires the processes together (coordinator = the PD analog), and
    collectives ride ICI within a slice / DCN across slices with no
    engine change — the mesh axis simply spans more devices.

    For CPU-based testing set JAX_PLATFORMS=cpu and
    xla_force_host_platform_device_count before calling; each process
    contributes its local devices to the global mesh.
    """
    if local_device_count is not None:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={local_device_count}"
            ).strip()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return make_mesh()


def batch_spec() -> P:
    return P(AXIS)


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Place a host-built global batch row-sharded over the mesh."""
    sharding = NamedSharding(mesh, P(AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def unshard_batch(batch: Batch) -> Batch:
    """Gather a sharded batch to host-replicated layout (materialization)."""
    return jax.tree.map(lambda x: np.asarray(x), batch)
