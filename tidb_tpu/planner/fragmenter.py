"""Cross-host MPP fragment planning over the serializable plan IR.

Reference: fragment cutting at exchange boundaries
(pkg/planner/core/fragment.go:47,149) and the partial/final aggregate
split the MPP engine runs across stores. Here the cut point is the
topmost Aggregate: everything below it (scans, filters, joins, the
PARTIAL aggregation) ships to worker hosts as ordinary plan IR with one
scan fragment-sliced per host; everything above it (final merge, HAVING,
projections, ORDER BY, LIMIT) runs on the coordinator's local engine
over a Staged batch built from the gathered partials. Partial-agg-
before-DCN is the point: hosts reduce their slice to group rows before
anything crosses the inter-host link (SURVEY §2.8; the same byte-
minimizing shape as Enhancing Computation Pushdown, arxiv 2312.15405).

Within a host the fragment still executes on the host's own device mesh
(ICI all_to_all exchanges, parallel/exchange.py) — the hierarchical
shuffle: intra-host collectives below, host-staged exchange above.

The decomposition mirrors logical.py's _expand_distinct_aggs idiom:
  count -> partial count, final sum
  sum/min/max/first -> partial f, final f
  avg -> partial sum+count, final sums + a float64 division Projection
DISTINCT aggregates and shapes without a safely partitionable scan fall
back to whole-plan dispatch onto a single host (still correct — the
scheduler's retry/failover applies either way).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

from tidb_tpu.dtypes import INT64, FLOAT64, Kind
from tidb_tpu.expression.expr import ColumnRef, Func, Literal
from tidb_tpu.planner import logical as L
from tidb_tpu.planner.logical import OutCol, Schema
from tidb_tpu.planner.streamed import _replace_node


class Unschedulable(ValueError):
    """The plan cannot cross the engine seam at all (e.g. GROUP_CONCAT
    host-assisted shapes) — not even single-host dispatch applies."""


@dataclasses.dataclass
class FragmentPlan:
    """One query split into per-host fragments + a coordinator stage."""

    #: host-side plan template; per host the frag_scan gets its slice
    template: L.LogicalPlan
    #: the Scan inside `template` carrying the (idx, n) fragment slice
    frag_scan: L.Scan
    #: schema of the rows each host fragment returns (the exchange wire
    #: schema: group keys + partial aggregation columns)
    partial_schema: Schema
    #: staged-source plan node -> full coordinator plan (final agg merge
    #: + everything that was above the cut)
    final_builder: Callable[[L.LogicalPlan], L.LogicalPlan]

    def host_plan(self, idx: int, n_hosts: int) -> L.LogicalPlan:
        """The plan host `idx` of `n_hosts` executes: the template with
        the partitioned scan sliced to every n_hosts-th row. The slice
        is data-defined, not host-defined — a fragment re-dispatched to
        a survivor host computes the same rows."""
        sliced = dataclasses.replace(self.frag_scan, frag=(idx, n_hosts))
        return _replace_node(self.template, self.frag_scan, sliced)


# -- partitionable-scan discovery -------------------------------------------


def _candidate_scans(p: L.LogicalPlan, out: List[L.Scan]) -> None:
    """Scans that may be fragment-sliced: the path from the cut child
    down must cross only row-wise operators (Selection/Projection) and
    join sides whose rows are independently complete — both sides of an
    inner/cross join, only the probe (left/preserved) side of
    left/semi/anti/mark joins. Aggregates, windows, sorts, limits and
    UnionAll below the cut pin their subtree to whole-data execution."""
    if isinstance(p, L.Scan):
        if "_tidb_rowid" not in p.columns:
            out.append(p)
        return
    if isinstance(p, (L.Selection, L.Projection)):
        _candidate_scans(p.child, out)
        return
    if isinstance(p, L.JoinPlan):
        if p.kind in ("inner", "cross"):
            _candidate_scans(p.left, out)
            _candidate_scans(p.right, out)
        else:  # left/semi/anti/mark: only the preserved/probe side
            _candidate_scans(p.left, out)
        return
    # anything else (Aggregate, Window, Sort, Limit, UnionAll, Staged,
    # OneRow): no candidates beneath


def _pick_frag_scan(lower: L.LogicalPlan, catalog) -> Optional[L.Scan]:
    cands: List[L.Scan] = []
    _candidate_scans(lower, cands)
    if not cands:
        return None
    if catalog is None:
        return cands[0]

    def size(s: L.Scan) -> int:
        try:
            return int(catalog.table(s.db, s.table).nrows)
        except Exception:
            return 0

    # slice the fact side: the largest table dominates both scan bytes
    # and partial-agg work (batch_coprocessor.go balances by region
    # bytes the same way)
    return max(cands, key=size)


def plan_has_frag(p: L.LogicalPlan) -> bool:
    if isinstance(p, L.Scan):
        return p.frag is not None
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None and plan_has_frag(c):
            return True
    return any(plan_has_frag(c) for c in getattr(p, "children", []) or [])


# -- partial/final aggregate decomposition ----------------------------------


_COMBINABLE = ("count", "sum", "min", "max", "first", "avg")


def _decompose_aggs(agg: L.Aggregate):
    """(partial aggs+cols, final aggs, avg fixups) or None when a
    function does not decompose (the caller falls back to single-host).
    Types follow the binder's rules so the final stage's output schema
    is bit-identical to the original Aggregate's."""
    otypes = {c.internal: c.type for c in agg.schema.cols}
    partial: List[Tuple[str, str, object, bool]] = []
    pcols: List[OutCol] = []
    final: List[Tuple[str, str, object, bool]] = []
    avg_fix: List[Tuple[str, str, str, object]] = []
    for (name, func, arg, distinct) in agg.aggs:
        if distinct or func not in _COMBINABLE:
            return None
        pn = f"_dp{len(partial)}"
        if func == "count":
            partial.append((pn, "count", arg, False))
            pcols.append(OutCol(None, pn, pn, INT64))
            final.append((name, "sum", ColumnRef(INT64, pn), False))
        elif func in ("sum", "min", "max", "first"):
            t = otypes[name]
            partial.append((pn, func, arg, False))
            pcols.append(OutCol(None, pn, pn, t))
            final.append((name, func, ColumnRef(t, pn), False))
        else:  # avg: Σ(partial sums) / Σ(partial counts), like
            # _expand_distinct_aggs' stacked rewrite
            at = arg.type
            if at is not None and at.kind not in (
                Kind.INT, Kind.FLOAT, Kind.DECIMAL, Kind.BOOL
            ):
                return None
            scale = at.scale if at is not None and at.kind == Kind.DECIMAL else 0
            # DECIMAL partials ride the wire as RAW scaled-unit ints:
            # exact, and the final division can reproduce the engine's
            # avg bit-for-bit — s_f64 / (count * 10^scale)_f64, ONE
            # float division (apply_post_avg's association; dividing a
            # descaled sum by the count rounds differently in the last
            # ulp and breaks cross-host result parity)
            if at is None or at.kind in (Kind.BOOL, Kind.INT) or scale:
                ptype = INT64
            else:
                ptype = at
            cn = f"_dp{len(partial) + 1}"
            partial.append((pn, "sum", arg, False))
            partial.append((cn, "count", arg, False))
            pcols.append(OutCol(None, pn, pn, ptype))
            pcols.append(OutCol(None, cn, cn, INT64))
            fs, fc = f"_dfs{name}", f"_dfc{name}"
            final.append((fs, "sum", ColumnRef(ptype, pn), False))
            final.append((fc, "sum", ColumnRef(INT64, cn), False))
            avg_fix.append((name, fs, fc, ptype, scale))
    return partial, pcols, final, avg_fix


def _final_agg_plan(agg: L.Aggregate, source: L.LogicalPlan,
                    final, avg_fix) -> L.LogicalPlan:
    final_groups = [
        (n, ColumnRef(e.type, n)) for n, e in agg.group_exprs
    ]
    if not avg_fix:
        return L.Aggregate(agg.schema, source, final_groups, list(final))
    fix = {name: (fs, fc, pt, sc) for name, fs, fc, pt, sc in avg_fix}
    outer_cols = [OutCol(None, n, n, e.type) for n, e in final_groups]
    for (n, f, a, _d) in final:
        outer_cols.append(OutCol(None, n, n, INT64 if f == "count" else a.type))
    outer = L.Aggregate(Schema(outer_cols), source, final_groups, list(final))
    proj_exprs = []
    for oc in agg.schema.cols:
        if oc.internal in fix:
            fs, fc, pt, scale = fix[oc.internal]
            den = ColumnRef(INT64, fc)
            if scale:
                den = Func(
                    type=INT64, op="mul",
                    args=(den, Literal(type=INT64, value=10 ** scale)),
                )
            proj_exprs.append(
                (
                    oc.internal,
                    Func(
                        type=FLOAT64, op="div",
                        args=(ColumnRef(pt, fs), den),
                    ),
                )
            )
        else:
            proj_exprs.append(
                (oc.internal, ColumnRef(oc.type, oc.internal))
            )
    return L.Projection(agg.schema, outer, proj_exprs)


# -- the cut ----------------------------------------------------------------


def _peel_global_roots(plan: L.LogicalPlan):
    """Peel order-sensitive root operators (Limit/Sort, plus any
    row-wise nodes stacked above them) off the top of the plan: they
    re-run on the coordinator over the unioned per-host rows. Returns
    (peeled nodes root-first, remaining subtree). Shared by the
    staging and shuffle planners so their notion of a cuttable root
    never diverges."""

    def _chain_has_global(p) -> bool:
        while isinstance(p, (L.Projection, L.Selection)):
            p = p.child
        return isinstance(p, (L.Limit, L.Sort))

    peeled: List[L.LogicalPlan] = []
    lower = plan
    while isinstance(lower, (L.Limit, L.Sort)) or (
        isinstance(lower, (L.Projection, L.Selection))
        and _chain_has_global(lower.child)
    ):
        peeled.append(lower)
        lower = lower.child
    return peeled, lower


def _find_cut(plan: L.LogicalPlan):
    """Topmost Aggregate reachable from the root through single-child
    nodes, or None. The path nodes re-run unchanged on the coordinator."""
    p = plan
    while True:
        if isinstance(p, L.Aggregate):
            return p
        if isinstance(
            p, (L.Selection, L.Projection, L.Sort, L.Limit, L.Window)
        ):
            p = p.child
            continue
        return None


def split_plan(plan: L.LogicalPlan, catalog=None) -> Optional[FragmentPlan]:
    """Split a bound logical plan into per-host fragments + coordinator
    stage. Returns None when no safe split exists (caller dispatches the
    whole plan to one host). Raises Unschedulable for plans that cannot
    cross the engine seam at all."""
    agg = _find_cut(plan)
    if agg is not None and agg.gc_meta:
        raise Unschedulable(
            "GROUP_CONCAT plans execute host-assisted; they do not "
            "cross the engine boundary"
        )

    if agg is not None:
        dec = _decompose_aggs(agg)
        if dec is None:
            return None
        partial_aggs, pcols, final, avg_fix = dec
        frag_scan = _pick_frag_scan(agg.child, catalog)
        if frag_scan is None:
            return None
        group_cols = [
            OutCol(None, n, n, e.type) for n, e in agg.group_exprs
        ]
        partial_schema = Schema(group_cols + pcols)
        template = L.Aggregate(
            partial_schema, agg.child, list(agg.group_exprs), partial_aggs
        )

        def final_builder(source, _plan=plan, _agg=agg, _final=final,
                          _fix=avg_fix):
            merged = _final_agg_plan(_agg, source, _final, _fix)
            return _replace_node(_plan, _agg, merged)

        return FragmentPlan(template, frag_scan, partial_schema, final_builder)

    # no aggregate: peel order-sensitive root operators (and any
    # row-wise nodes stacked above them) to the coordinator, union the
    # per-host row fragments beneath them
    peeled, lower = _peel_global_roots(plan)
    frag_scan = _pick_frag_scan(lower, catalog)
    if frag_scan is None:
        return None

    def final_builder(source, _peeled=tuple(peeled)):
        out = source
        for node in reversed(_peeled):
            out = dataclasses.replace(node, child=out)
        return out

    return FragmentPlan(lower, frag_scan, lower.schema, final_builder)


# -- shuffle cuts (worker-to-worker exchange; parallel/shuffle.py) ----------


@dataclasses.dataclass
class ShuffleSide:
    """One producer side of a shuffle exchange: a plan every worker
    executes over its own fragment slice, whose output rows are hash-
    partitioned on `key` and pushed to the owning peers."""

    #: producer plan template; per worker the frag_scan gets its slice
    template: L.LogicalPlan
    #: the Scan inside `template` carrying the (idx, n) fragment slice
    frag_scan: L.Scan
    #: internal column name of the partition key in template.schema
    key: str
    #: which ShuffleRead leaf of the consumer this side feeds
    tag: int
    #: catalog row estimate of the sliced table (the cost-model input:
    #: tunnels only beat coordinator staging when the shuffled side is
    #: large — PERF_NOTES "Shuffle vs staging")
    est_rows: int = 0

    def host_plan(self, idx: int, n_hosts: int) -> L.LogicalPlan:
        sliced = dataclasses.replace(self.frag_scan, frag=(idx, n_hosts))
        return _replace_node(self.template, self.frag_scan, sliced)


@dataclasses.dataclass
class ShufflePlan:
    """One query cut at a worker-to-worker exchange: producer sides,
    the per-partition consumer plan (its ShuffleRead leaves stand for
    the received partitions), and the coordinator stage over the
    gathered per-partition results."""

    #: "join" (repartition join: both sides shuffled by the join key,
    #: executor/join.py runs per partition on the receiving host) or
    #: "groupby" (rows shuffled by group key; each partition owns
    #: COMPLETE groups, so the ORIGINAL aggregate — distinct included —
    #: runs per partition and its output is final, lifting the
    #: single-host fallback for high-cardinality/distinct aggregates)
    kind: str
    sides: List[ShuffleSide]
    #: per-partition worker plan with ShuffleRead(tag) exchange leaves
    consumer: L.LogicalPlan
    #: wire schema of the rows each partition's consumer returns
    partial_schema: Schema
    #: staged-source plan node -> full coordinator plan
    final_builder: Callable[[L.LogicalPlan], L.LogicalPlan]


#: join kinds whose semantics survive hash partitioning on the first
#: equi key: equal keys colocate, so inner/left matches and semi/anti
#: existence checks are complete per partition. Null-aware anti joins
#: need GLOBAL build-side-null knowledge (NULL build keys colocate on
#: partition 0 only) and mark joins need it three-valued — excluded.
_SHUFFLE_JOIN_KINDS = ("inner", "left", "semi", "anti")


def _find_shuffle_join(p: L.LogicalPlan):
    """Descend single-child row-wise nodes to the topmost JoinPlan;
    returns (path nodes root->join, join) — path re-runs unchanged on
    the consumer above the exchange. None join = no cut here."""
    path: List[L.LogicalPlan] = []
    while isinstance(p, (L.Selection, L.Projection)):
        path.append(p)
        p = p.child
    return path, (p if isinstance(p, L.JoinPlan) else None)


def _shuffle_key_of(expr, schema: Schema) -> Optional[str]:
    """The internal column a side can be hash-partitioned on, or None.
    Must be a bare column of the side's OUTPUT schema (the producer
    hashes whole key columns by VALUE — for strings via the dictionary
    entries, never the per-batch codes, so both sides of a join route
    equal keys identically; the receiver re-keys codes against a
    stage-local unified dictionary, parallel/shuffle.py
    stage_payloads_as_batch)."""
    if not isinstance(expr, ColumnRef):
        return None
    names = {c.internal for c in schema.cols}
    return expr.name if expr.name in names else None


def _est_rows(scan: L.Scan, catalog) -> int:
    try:
        return int(catalog.table(scan.db, scan.table).nrows)
    except Exception:
        return 0


def _wrap_path(path, inner: L.LogicalPlan) -> L.LogicalPlan:
    out = inner
    for node in reversed(path):
        out = dataclasses.replace(node, child=out)
    return out


def split_plan_shuffle(
    plan: L.LogicalPlan, catalog=None
) -> Optional[ShufflePlan]:
    """Cut a bound plan at a worker-to-worker shuffle exchange.

    Two shapes (repartition-join preferred — it ships pre-join rows
    once; the group-by cut re-scans unsliced join sides per host):

    1. repartition join — the topmost join under the aggregate cut (or
       under the peeled root operators) with a partitionable first equi
       key: BOTH sides fragment-slice their dominant scan, shuffle by
       the join key, and the join (plus the partial aggregate, when the
       topmost aggregate decomposes) runs per partition on the
       receiving worker;
    2. fragment-sliced GROUP BY — rows shuffled by the first group key,
       so every partition owns complete groups and the ORIGINAL
       aggregate (DISTINCT and other non-decomposable functions
       included) executes per partition with FINAL output.

    Returns None when neither applies (the caller falls back to the
    partial-agg staging cut or single-host dispatch). Raises
    Unschedulable for plans that cannot cross the engine seam."""
    agg = _find_cut(plan)
    if agg is not None and agg.gc_meta:
        raise Unschedulable(
            "GROUP_CONCAT plans execute host-assisted; they do not "
            "cross the engine boundary"
        )

    # ---- shape 1: repartition join ----
    if agg is not None:
        below = agg.child
        dec = _decompose_aggs(agg)
    else:
        # no aggregate: peel order-sensitive root operators (and
        # row-wise nodes stacked above them) to the coordinator
        peeled, below = _peel_global_roots(plan)
        dec = None

    path, jp = _find_shuffle_join(below)
    if (
        jp is not None
        and jp.kind in _SHUFFLE_JOIN_KINDS
        and not jp.null_aware
        and jp.equi_keys
        and (agg is None or dec is not None)
    ):
        le, re_ = jp.equi_keys[0]
        lkey = _shuffle_key_of(le, jp.left.schema)
        rkey = _shuffle_key_of(re_, jp.right.schema)
        lscan = _pick_frag_scan(jp.left, catalog)
        rscan = _pick_frag_scan(jp.right, catalog)
        if (
            lkey is not None and rkey is not None
            and lscan is not None and rscan is not None
        ):
            sides = [
                ShuffleSide(jp.left, lscan, lkey, 0,
                            _est_rows(lscan, catalog)),
                ShuffleSide(jp.right, rscan, rkey, 1,
                            _est_rows(rscan, catalog)),
            ]
            jp2 = dataclasses.replace(
                jp,
                left=L.ShuffleRead(jp.left.schema, tag=0),
                right=L.ShuffleRead(jp.right.schema, tag=1),
            )
            mid = _wrap_path(path, jp2)
            if agg is not None:
                partial_aggs, pcols, final, avg_fix = dec
                group_cols = [
                    OutCol(None, n, n, e.type) for n, e in agg.group_exprs
                ]
                partial_schema = Schema(group_cols + pcols)
                consumer = L.Aggregate(
                    partial_schema, mid, list(agg.group_exprs),
                    partial_aggs,
                )

                def final_builder(source, _plan=plan, _agg=agg,
                                  _final=final, _fix=avg_fix):
                    merged = _final_agg_plan(_agg, source, _final, _fix)
                    return _replace_node(_plan, _agg, merged)

                return ShufflePlan(
                    "join", sides, consumer, partial_schema, final_builder
                )

            def final_builder(source, _peeled=tuple(peeled)):
                out = source
                for node in reversed(_peeled):
                    out = dataclasses.replace(node, child=out)
                return out

            consumer = _wrap_path(path, jp2)
            return ShufflePlan(
                "join", sides, consumer, below.schema, final_builder
            )

    # ---- shape 2: fragment-sliced GROUP BY ----
    if agg is None or not agg.group_exprs:
        return None
    cut = _group_stack_cut(agg)
    if cut is None:
        return None
    cut_child, gkey = cut
    frag_scan = _pick_frag_scan(cut_child, catalog)
    if frag_scan is None:
        return None
    side = ShuffleSide(
        cut_child, frag_scan, gkey, 0, _est_rows(frag_scan, catalog)
    )
    consumer = _replace_node(
        agg, cut_child, L.ShuffleRead(cut_child.schema, tag=0)
    )

    def final_builder(source, _plan=plan, _agg=agg):
        return _replace_node(_plan, _agg, source)

    return ShufflePlan(
        "groupby", [side], consumer, agg.schema, final_builder
    )


def _group_stack_cut(agg: L.Aggregate):
    """Bottom of the aggregate stack under `agg` plus the raw-row
    column the stack's first group key resolves to: (cut child, key
    internal name) or None.

    DISTINCT aggregates expand into STACKED Aggregates (logical.py
    _expand_distinct_aggs: inner groups by keys + distinct arg), so
    the shuffle cut must sit below the WHOLE stack — rows hash-
    partitioned on the outermost group key make every level's groups
    complete per partition (deeper stacks group by supersets of the
    outer keys), and the original aggregate tree then executes per
    partition with FINAL output. The key must pass through the stack
    as a bare column (Projections may rename it; anything computed
    defeats row-level hashing)."""
    first = agg.group_exprs[0][1]
    if not isinstance(first, ColumnRef):
        return None
    kname = first.name  # in agg.child scope
    node = agg
    while True:
        path = []
        p = node.child
        while isinstance(p, (L.Selection, L.Projection)):
            path.append(p)
            p = p.child
        if not isinstance(p, L.Aggregate) or p.gc_meta:
            break
        # thread the key column down through the renames
        k = kname
        ok = True
        for q in path:
            if isinstance(q, L.Projection):
                e = dict(q.exprs).get(k)
                if e is None and q.additive:
                    continue
                if not isinstance(e, ColumnRef):
                    ok = False
                    break
                k = e.name
        if not ok:
            break
        e = {n: ge for n, ge in p.group_exprs}.get(k)
        if not isinstance(e, ColumnRef):
            break
        kname = e.name
        node = p
    cut_child = node.child
    if kname not in {c.internal for c in cut_child.schema.cols}:
        return None
    return cut_child, kname
