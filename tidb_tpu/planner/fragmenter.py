"""Cross-host MPP fragment planning over the serializable plan IR.

Reference: fragment cutting at exchange boundaries
(pkg/planner/core/fragment.go:47,149) and the partial/final aggregate
split the MPP engine runs across stores. Here the cut point is the
topmost Aggregate: everything below it (scans, filters, joins, the
PARTIAL aggregation) ships to worker hosts as ordinary plan IR with one
scan fragment-sliced per host; everything above it (final merge, HAVING,
projections, ORDER BY, LIMIT) runs on the coordinator's local engine
over a Staged batch built from the gathered partials. Partial-agg-
before-DCN is the point: hosts reduce their slice to group rows before
anything crosses the inter-host link (SURVEY §2.8; the same byte-
minimizing shape as Enhancing Computation Pushdown, arxiv 2312.15405).

Within a host the fragment still executes on the host's own device mesh
(ICI all_to_all exchanges, parallel/exchange.py) — the hierarchical
shuffle: intra-host collectives below, host-staged exchange above.

The decomposition mirrors logical.py's _expand_distinct_aggs idiom:
  count -> partial count, final sum
  sum/min/max/first -> partial f, final f
  avg -> partial sum+count, final sums + a float64 division Projection
DISTINCT aggregates and shapes without a safely partitionable scan fall
back to whole-plan dispatch onto a single host (still correct — the
scheduler's retry/failover applies either way).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

from tidb_tpu.dtypes import INT64, FLOAT64, Kind
from tidb_tpu.expression.expr import ColumnRef, Func, Literal
from tidb_tpu.planner import logical as L
from tidb_tpu.planner.logical import OutCol, Schema
from tidb_tpu.planner.streamed import _replace_node


class Unschedulable(ValueError):
    """The plan cannot cross the engine seam at all (e.g. GROUP_CONCAT
    host-assisted shapes) — not even single-host dispatch applies."""


@dataclasses.dataclass
class FragmentPlan:
    """One query split into per-host fragments + a coordinator stage."""

    #: host-side plan template; per host the frag_scan gets its slice
    template: L.LogicalPlan
    #: the Scan inside `template` carrying the (idx, n) fragment slice
    frag_scan: L.Scan
    #: schema of the rows each host fragment returns (the exchange wire
    #: schema: group keys + partial aggregation columns)
    partial_schema: Schema
    #: staged-source plan node -> full coordinator plan (final agg merge
    #: + everything that was above the cut)
    final_builder: Callable[[L.LogicalPlan], L.LogicalPlan]

    def host_plan(self, idx: int, n_hosts: int) -> L.LogicalPlan:
        """The plan host `idx` of `n_hosts` executes: the template with
        the partitioned scan sliced to every n_hosts-th row. The slice
        is data-defined, not host-defined — a fragment re-dispatched to
        a survivor host computes the same rows."""
        sliced = dataclasses.replace(self.frag_scan, frag=(idx, n_hosts))
        return _replace_node(self.template, self.frag_scan, sliced)


# -- partitionable-scan discovery -------------------------------------------


def _candidate_scans(p: L.LogicalPlan, out: List[L.Scan]) -> None:
    """Scans that may be fragment-sliced: the path from the cut child
    down must cross only row-wise operators (Selection/Projection) and
    join sides whose rows are independently complete — both sides of an
    inner/cross join, only the probe (left/preserved) side of
    left/semi/anti/mark joins. Aggregates, windows, sorts, limits and
    UnionAll below the cut pin their subtree to whole-data execution."""
    if isinstance(p, L.Scan):
        if "_tidb_rowid" not in p.columns:
            out.append(p)
        return
    if isinstance(p, (L.Selection, L.Projection)):
        _candidate_scans(p.child, out)
        return
    if isinstance(p, L.JoinPlan):
        if p.kind in ("inner", "cross"):
            _candidate_scans(p.left, out)
            _candidate_scans(p.right, out)
        else:  # left/semi/anti/mark: only the preserved/probe side
            _candidate_scans(p.left, out)
        return
    # anything else (Aggregate, Window, Sort, Limit, UnionAll, Staged,
    # OneRow): no candidates beneath


def _pick_frag_scan(lower: L.LogicalPlan, catalog) -> Optional[L.Scan]:
    cands: List[L.Scan] = []
    _candidate_scans(lower, cands)
    if not cands:
        return None
    if catalog is None:
        return cands[0]

    def size(s: L.Scan) -> int:
        try:
            return int(catalog.table(s.db, s.table).nrows)
        except Exception:
            return 0

    # slice the fact side: the largest table dominates both scan bytes
    # and partial-agg work (batch_coprocessor.go balances by region
    # bytes the same way)
    return max(cands, key=size)


def plan_has_frag(p: L.LogicalPlan) -> bool:
    if isinstance(p, L.Scan):
        return p.frag is not None
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None and plan_has_frag(c):
            return True
    return any(plan_has_frag(c) for c in getattr(p, "children", []) or [])


# -- partial/final aggregate decomposition ----------------------------------


_COMBINABLE = ("count", "sum", "min", "max", "first", "avg")


def _decompose_aggs(agg: L.Aggregate):
    """(partial aggs+cols, final aggs, avg fixups) or None when a
    function does not decompose (the caller falls back to single-host).
    Types follow the binder's rules so the final stage's output schema
    is bit-identical to the original Aggregate's."""
    otypes = {c.internal: c.type for c in agg.schema.cols}
    partial: List[Tuple[str, str, object, bool]] = []
    pcols: List[OutCol] = []
    final: List[Tuple[str, str, object, bool]] = []
    avg_fix: List[Tuple[str, str, str, object]] = []
    for (name, func, arg, distinct) in agg.aggs:
        if distinct or func not in _COMBINABLE:
            return None
        pn = f"_dp{len(partial)}"
        if func == "count":
            partial.append((pn, "count", arg, False))
            pcols.append(OutCol(None, pn, pn, INT64))
            final.append((name, "sum", ColumnRef(INT64, pn), False))
        elif func in ("sum", "min", "max", "first"):
            t = otypes[name]
            partial.append((pn, func, arg, False))
            pcols.append(OutCol(None, pn, pn, t))
            final.append((name, func, ColumnRef(t, pn), False))
        else:  # avg: Σ(partial sums) / Σ(partial counts), like
            # _expand_distinct_aggs' stacked rewrite
            at = arg.type
            if at is not None and at.kind not in (
                Kind.INT, Kind.FLOAT, Kind.DECIMAL, Kind.BOOL
            ):
                return None
            scale = at.scale if at is not None and at.kind == Kind.DECIMAL else 0
            # DECIMAL partials ride the wire as RAW scaled-unit ints:
            # exact, and the final division can reproduce the engine's
            # avg bit-for-bit — s_f64 / (count * 10^scale)_f64, ONE
            # float division (apply_post_avg's association; dividing a
            # descaled sum by the count rounds differently in the last
            # ulp and breaks cross-host result parity)
            if at is None or at.kind in (Kind.BOOL, Kind.INT) or scale:
                ptype = INT64
            else:
                ptype = at
            cn = f"_dp{len(partial) + 1}"
            partial.append((pn, "sum", arg, False))
            partial.append((cn, "count", arg, False))
            pcols.append(OutCol(None, pn, pn, ptype))
            pcols.append(OutCol(None, cn, cn, INT64))
            fs, fc = f"_dfs{name}", f"_dfc{name}"
            final.append((fs, "sum", ColumnRef(ptype, pn), False))
            final.append((fc, "sum", ColumnRef(INT64, cn), False))
            avg_fix.append((name, fs, fc, ptype, scale))
    return partial, pcols, final, avg_fix


def _final_agg_plan(agg: L.Aggregate, source: L.LogicalPlan,
                    final, avg_fix) -> L.LogicalPlan:
    final_groups = [
        (n, ColumnRef(e.type, n)) for n, e in agg.group_exprs
    ]
    if not avg_fix:
        return L.Aggregate(agg.schema, source, final_groups, list(final))
    fix = {name: (fs, fc, pt, sc) for name, fs, fc, pt, sc in avg_fix}
    outer_cols = [OutCol(None, n, n, e.type) for n, e in final_groups]
    for (n, f, a, _d) in final:
        outer_cols.append(OutCol(None, n, n, INT64 if f == "count" else a.type))
    outer = L.Aggregate(Schema(outer_cols), source, final_groups, list(final))
    proj_exprs = []
    for oc in agg.schema.cols:
        if oc.internal in fix:
            fs, fc, pt, scale = fix[oc.internal]
            den = ColumnRef(INT64, fc)
            if scale:
                den = Func(
                    type=INT64, op="mul",
                    args=(den, Literal(type=INT64, value=10 ** scale)),
                )
            proj_exprs.append(
                (
                    oc.internal,
                    Func(
                        type=FLOAT64, op="div",
                        args=(ColumnRef(pt, fs), den),
                    ),
                )
            )
        else:
            proj_exprs.append(
                (oc.internal, ColumnRef(oc.type, oc.internal))
            )
    return L.Projection(agg.schema, outer, proj_exprs)


# -- the cut ----------------------------------------------------------------


def _peel_global_roots(plan: L.LogicalPlan):
    """Peel order-sensitive root operators (Limit/Sort, plus any
    row-wise nodes stacked above them) off the top of the plan: they
    re-run on the coordinator over the unioned per-host rows. Returns
    (peeled nodes root-first, remaining subtree). Shared by the
    staging and shuffle planners so their notion of a cuttable root
    never diverges."""

    def _chain_has_global(p) -> bool:
        while isinstance(p, (L.Projection, L.Selection)):
            p = p.child
        return isinstance(p, (L.Limit, L.Sort))

    peeled: List[L.LogicalPlan] = []
    lower = plan
    while isinstance(lower, (L.Limit, L.Sort)) or (
        isinstance(lower, (L.Projection, L.Selection))
        and _chain_has_global(lower.child)
    ):
        peeled.append(lower)
        lower = lower.child
    return peeled, lower


def _find_cut(plan: L.LogicalPlan):
    """Topmost Aggregate reachable from the root through single-child
    nodes, or None. The path nodes re-run unchanged on the coordinator."""
    p = plan
    while True:
        if isinstance(p, L.Aggregate):
            return p
        if isinstance(
            p, (L.Selection, L.Projection, L.Sort, L.Limit, L.Window)
        ):
            p = p.child
            continue
        return None


def split_plan(plan: L.LogicalPlan, catalog=None) -> Optional[FragmentPlan]:
    """Split a bound logical plan into per-host fragments + coordinator
    stage. Returns None when no safe split exists (caller dispatches the
    whole plan to one host). Raises Unschedulable for plans that cannot
    cross the engine seam at all."""
    agg = _find_cut(plan)
    if agg is not None and agg.gc_meta:
        raise Unschedulable(
            "GROUP_CONCAT plans execute host-assisted; they do not "
            "cross the engine boundary"
        )

    if agg is not None:
        dec = _decompose_aggs(agg)
        if dec is None:
            return None
        partial_aggs, pcols, final, avg_fix = dec
        frag_scan = _pick_frag_scan(agg.child, catalog)
        if frag_scan is None:
            return None
        group_cols = [
            OutCol(None, n, n, e.type) for n, e in agg.group_exprs
        ]
        partial_schema = Schema(group_cols + pcols)
        template = L.Aggregate(
            partial_schema, agg.child, list(agg.group_exprs), partial_aggs
        )

        def final_builder(source, _plan=plan, _agg=agg, _final=final,
                          _fix=avg_fix):
            merged = _final_agg_plan(_agg, source, _final, _fix)
            return _replace_node(_plan, _agg, merged)

        return FragmentPlan(template, frag_scan, partial_schema, final_builder)

    # no aggregate: peel order-sensitive root operators (and any
    # row-wise nodes stacked above them) to the coordinator, union the
    # per-host row fragments beneath them
    peeled, lower = _peel_global_roots(plan)
    frag_scan = _pick_frag_scan(lower, catalog)
    if frag_scan is None:
        return None

    def final_builder(source, _peeled=tuple(peeled)):
        out = source
        for node in reversed(_peeled):
            out = dataclasses.replace(node, child=out)
        return out

    return FragmentPlan(lower, frag_scan, lower.schema, final_builder)


# -- shuffle cuts (worker-to-worker exchange; parallel/shuffle.py) ----------


@dataclasses.dataclass
class ShuffleSide:
    """One producer side of a shuffle exchange: a plan every worker
    executes over its own fragment slice, whose output rows are
    partitioned on `key` and pushed to the owning peers."""

    #: producer plan template; per worker the frag_scan gets its slice.
    #: A DAG re-staging side is an L.StageInput leaf instead (the
    #: worker's held output of an earlier stage IS the slice).
    template: L.LogicalPlan
    #: the Scan inside `template` carrying the (idx, n) fragment slice
    #: (None for StageInput sides — already partitioned)
    frag_scan: Optional[L.Scan]
    #: internal column name of the partition key in template.schema
    key: str
    #: which ShuffleRead leaf of the consumer this side feeds
    tag: int
    #: catalog row estimate of the sliced table (the cost-model input:
    #: tunnels only beat coordinator staging when the shuffled side is
    #: large — PERF_NOTES "Shuffle vs staging")
    est_rows: int = 0
    #: how this edge exchanges (the per-edge cost-model output):
    #: "hash" routes by key hash, "range" by sampled key-range
    #: boundaries, "broadcast" copies the whole side to every peer,
    #: "local" keeps the side on its producing host (the broadcast
    #: join's probe side — zero exchange bytes)
    mode: str = "hash"

    def host_plan(self, idx: int, n_hosts: int) -> L.LogicalPlan:
        if self.frag_scan is None:
            return self.template
        sliced = dataclasses.replace(self.frag_scan, frag=(idx, n_hosts))
        return _replace_node(self.template, self.frag_scan, sliced)


@dataclasses.dataclass
class ShufflePlan:
    """One query cut at a worker-to-worker exchange: producer sides,
    the per-partition consumer plan (its ShuffleRead leaves stand for
    the received partitions), and the coordinator stage over the
    gathered per-partition results."""

    #: "join" (repartition join: both sides shuffled by the join key,
    #: executor/join.py runs per partition on the receiving host) or
    #: "groupby" (rows shuffled by group key; each partition owns
    #: COMPLETE groups, so the ORIGINAL aggregate — distinct included —
    #: runs per partition and its output is final, lifting the
    #: single-host fallback for high-cardinality/distinct aggregates)
    kind: str
    sides: List[ShuffleSide]
    #: per-partition worker plan with ShuffleRead(tag) exchange leaves
    consumer: L.LogicalPlan
    #: wire schema of the rows each partition's consumer returns
    partial_schema: Schema
    #: staged-source plan node -> full coordinator plan
    final_builder: Callable[[L.LogicalPlan], L.LogicalPlan]
    #: join kind when kind == "join" (the broadcast-edge legality
    #: input for the adaptive switch — parallel/aqe.py); None for
    #: group-by cuts, which REQUIRE key-colocated partitions
    join_kind: Optional[str] = None


#: join kinds whose semantics survive hash partitioning on the first
#: equi key: equal keys colocate, so inner/left matches and semi/anti
#: existence checks are complete per partition. Null-aware anti joins
#: need GLOBAL build-side-null knowledge (NULL build keys colocate on
#: partition 0 only) and mark joins need it three-valued — excluded.
_SHUFFLE_JOIN_KINDS = ("inner", "left", "semi", "anti")


def _find_shuffle_join(p: L.LogicalPlan):
    """Descend single-child row-wise nodes to the topmost JoinPlan;
    returns (path nodes root->join, join) — path re-runs unchanged on
    the consumer above the exchange. None join = no cut here."""
    path: List[L.LogicalPlan] = []
    while isinstance(p, (L.Selection, L.Projection)):
        path.append(p)
        p = p.child
    return path, (p if isinstance(p, L.JoinPlan) else None)


def _shuffle_key_of(expr, schema: Schema) -> Optional[str]:
    """The internal column a side can be hash-partitioned on, or None.
    Must be a bare column of the side's OUTPUT schema (the producer
    hashes whole key columns by VALUE — for strings via the dictionary
    entries, never the per-batch codes, so both sides of a join route
    equal keys identically; the receiver re-keys codes against a
    stage-local unified dictionary, parallel/shuffle.py
    stage_payloads_as_batch)."""
    if not isinstance(expr, ColumnRef):
        return None
    names = {c.internal for c in schema.cols}
    return expr.name if expr.name in names else None


def _est_rows(scan: L.Scan, catalog) -> int:
    try:
        return int(catalog.table(scan.db, scan.table).nrows)
    except Exception:
        return 0


def _wrap_path(path, inner: L.LogicalPlan) -> L.LogicalPlan:
    out = inner
    for node in reversed(path):
        out = dataclasses.replace(node, child=out)
    return out


def split_plan_shuffle(
    plan: L.LogicalPlan, catalog=None
) -> Optional[ShufflePlan]:
    """Cut a bound plan at a worker-to-worker shuffle exchange.

    Two shapes (repartition-join preferred — it ships pre-join rows
    once; the group-by cut re-scans unsliced join sides per host):

    1. repartition join — the topmost join under the aggregate cut (or
       under the peeled root operators) with a partitionable first equi
       key: BOTH sides fragment-slice their dominant scan, shuffle by
       the join key, and the join (plus the partial aggregate, when the
       topmost aggregate decomposes) runs per partition on the
       receiving worker;
    2. fragment-sliced GROUP BY — rows shuffled by the first group key,
       so every partition owns complete groups and the ORIGINAL
       aggregate (DISTINCT and other non-decomposable functions
       included) executes per partition with FINAL output.

    Returns None when neither applies (the caller falls back to the
    partial-agg staging cut or single-host dispatch). Raises
    Unschedulable for plans that cannot cross the engine seam."""
    agg = _find_cut(plan)
    if agg is not None and agg.gc_meta:
        raise Unschedulable(
            "GROUP_CONCAT plans execute host-assisted; they do not "
            "cross the engine boundary"
        )

    # ---- shape 1: repartition join ----
    if agg is not None:
        below = agg.child
        dec = _decompose_aggs(agg)
    else:
        # no aggregate: peel order-sensitive root operators (and
        # row-wise nodes stacked above them) to the coordinator
        peeled, below = _peel_global_roots(plan)
        dec = None

    path, jp = _find_shuffle_join(below)
    if (
        jp is not None
        and jp.kind in _SHUFFLE_JOIN_KINDS
        and not jp.null_aware
        and jp.equi_keys
        and (agg is None or dec is not None)
    ):
        le, re_ = jp.equi_keys[0]
        lkey = _shuffle_key_of(le, jp.left.schema)
        rkey = _shuffle_key_of(re_, jp.right.schema)
        lscan = _pick_frag_scan(jp.left, catalog)
        rscan = _pick_frag_scan(jp.right, catalog)
        if (
            lkey is not None and rkey is not None
            and lscan is not None and rscan is not None
        ):
            sides = [
                ShuffleSide(jp.left, lscan, lkey, 0,
                            _est_rows(lscan, catalog)),
                ShuffleSide(jp.right, rscan, rkey, 1,
                            _est_rows(rscan, catalog)),
            ]
            jp2 = dataclasses.replace(
                jp,
                left=L.ShuffleRead(jp.left.schema, tag=0),
                right=L.ShuffleRead(jp.right.schema, tag=1),
            )
            mid = _wrap_path(path, jp2)
            if agg is not None:
                partial_aggs, pcols, final, avg_fix = dec
                group_cols = [
                    OutCol(None, n, n, e.type) for n, e in agg.group_exprs
                ]
                partial_schema = Schema(group_cols + pcols)
                consumer = L.Aggregate(
                    partial_schema, mid, list(agg.group_exprs),
                    partial_aggs,
                )

                def final_builder(source, _plan=plan, _agg=agg,
                                  _final=final, _fix=avg_fix):
                    merged = _final_agg_plan(_agg, source, _final, _fix)
                    return _replace_node(_plan, _agg, merged)

                return ShufflePlan(
                    "join", sides, consumer, partial_schema,
                    final_builder, join_kind=jp.kind,
                )

            def final_builder(source, _peeled=tuple(peeled)):
                out = source
                for node in reversed(_peeled):
                    out = dataclasses.replace(node, child=out)
                return out

            consumer = _wrap_path(path, jp2)
            return ShufflePlan(
                "join", sides, consumer, below.schema, final_builder,
                join_kind=jp.kind,
            )

    # ---- shape 2: fragment-sliced GROUP BY ----
    if agg is None or not agg.group_exprs:
        return None
    cut = _group_stack_cut(agg)
    if cut is None:
        return None
    cut_child, gkey = cut
    frag_scan = _pick_frag_scan(cut_child, catalog)
    if frag_scan is None:
        return None
    side = ShuffleSide(
        cut_child, frag_scan, gkey, 0, _est_rows(frag_scan, catalog)
    )
    consumer = _replace_node(
        agg, cut_child, L.ShuffleRead(cut_child.schema, tag=0)
    )

    def final_builder(source, _plan=plan, _agg=agg):
        return _replace_node(_plan, _agg, source)

    return ShufflePlan(
        "groupby", [side], consumer, agg.schema, final_builder
    )


# -- shuffle DAGs (multi-stage exchanges; parallel/dcn.py topo order) -------


#: range-partitionable first-sort-key kinds: values whose HostColumn
#: buffer order IS the sort order (ints, floats, scaled decimals, and
#: the temporal day/second encodings). Strings are excluded — collation
#: order lives in per-batch dictionaries, not a global comparable
#: domain, so a string-first-key ORDER BY keeps the coordinator sort.
_RANGE_KEY_KINDS = (
    Kind.INT, Kind.FLOAT, Kind.DECIMAL, Kind.BOOL,
    Kind.DATE, Kind.DATETIME, Kind.TIME,
)


@dataclasses.dataclass
class DagStage:
    """One exchange stage of a shuffle DAG: producer sides (leaf plans
    fragment-sliced per host, or StageInput re-stagings of the previous
    stage's held output), the exchange kind, and the per-partition
    consumer whose output this stage HOLDS for stage N+1 (or returns
    to the coordinator, for the last stage)."""

    #: "hash" (key-hash partitions) or "range" (sampled key-range
    #: boundaries; distributed ORDER BY)
    exchange: str
    sides: List[ShuffleSide]
    #: per-partition worker plan with ShuffleRead(tag) exchange leaves
    consumer: L.LogicalPlan
    #: join kind when this stage's consumer joins its sides (the
    #: broadcast-edge legality input: non-inner joins may broadcast
    #: only the non-preserved right side)
    join_kind: Optional[str] = None
    #: True when the consumer's correctness depends on key-colocated
    #: partitions (complete groups per partition) — such a stage must
    #: never trade its hash edges for broadcast/local ones
    requires_key_partition: bool = False
    #: range stages: first sort key direction (concat order) and the
    #: per-partition top-K pushed under the partition sort (None =
    #: unbounded)
    desc: bool = False
    limit: Optional[int] = None


@dataclasses.dataclass
class ShuffleDAG:
    """A query cut into a topo-ordered chain of exchange stages: the
    output partitions of stage N are held worker-side and become the
    fragment-sliced StageInput of stage N+1 — join feeding a
    DIFFERENT group-key shuffle no longer re-scans unsliced join sides
    per host, and ORDER BY / top-K distributes over a range exchange.
    ``merge`` decides the coordinator's final step:

    - {"kind": "plan"}: stage the last stage's rows and run
      final_builder's plan (the single-stage ShufflePlan discipline);
    - {"kind": "concat", ...}: the last stage was a range exchange —
      partitions are each sorted and ship at most K rows, so the
      coordinator CONCATENATES them in partition order (reversed for a
      descending first key), slices the global LIMIT/OFFSET, and runs
      only the row-wise ``above`` nodes — no global re-sort.
    """

    stages: List[DagStage]
    #: wire schema of the rows the LAST stage returns
    partial_schema: Schema
    #: staged-source plan node -> full coordinator plan (merge kind
    #: "plan" only)
    final_builder: Optional[Callable[[L.LogicalPlan], L.LogicalPlan]]
    #: {"kind": "plan"} or {"kind": "concat", "reverse": bool,
    #:  "limit": Optional[(count, offset)], "above": tuple of row-wise
    #:  plan nodes (root-first) re-run on the coordinator}
    merge: dict


def _decide_join_modes(
    sides: List[ShuffleSide], join_kind: str, broadcast_max_rows: int,
    ratio: float,
) -> str:
    """THE broadcast-vs-repartition decision core, shared by the DAG
    edge chooser and the single-stage adaptive switch. Mutates
    side.mode in place (including RESETTING a previously-broadcast
    pair back to hash — re-planning with observed counts must be able
    to flip either way). Returns "hash" or "broadcast"."""
    a, b = sides
    # reset first: a re-run with new estimates starts from the
    # repartition shape, not whatever the last run chose
    a.mode = b.mode = "hash"
    if broadcast_max_rows <= 0:
        return "hash"
    small, big = (a, b) if a.est_rows <= b.est_rows else (b, a)
    if small.est_rows <= 0 or big.est_rows <= 0:
        return "hash"
    if (
        small.est_rows > broadcast_max_rows
        or big.est_rows < ratio * small.est_rows
    ):
        return "hash"
    if join_kind != "inner" and small.tag != 1:
        return "hash"  # left/semi/anti preserve the LEFT side
    small.mode = "broadcast"
    big.mode = "local"
    return "broadcast"


def choose_edge_modes(
    stage: DagStage, broadcast_max_rows: int, ratio: float = 4.0
) -> str:
    """The per-edge half of the shuffle_mode cost model: given a
    two-sided hash join stage, decide whether the SMALL side should
    broadcast (every peer gets the whole side; the big side stays
    local and ships ZERO bytes) instead of hash-partitioning both.
    Broadcast wins when one side is small enough that copying it m
    ways costs less than repartitioning the big side; it is only legal
    when (a) the consumer does not require key-colocated partitions
    (a re-keyed next stage restores any grouping) and (b) for
    non-inner joins, the small side is the non-preserved RIGHT side.
    Mutates side.mode in place (idempotent under re-planning: a
    re-run with OBSERVED est_rows — AQE stage-boundary re-planning —
    may flip a previous choice either way); returns the chosen shape
    ("hash" or "broadcast") for telemetry."""
    if (
        stage.exchange != "hash"
        or stage.join_kind is None
        or stage.requires_key_partition
        or len(stage.sides) != 2
    ):
        return "hash"
    return _decide_join_modes(
        stage.sides, stage.join_kind, broadcast_max_rows, ratio
    )


def choose_shuffle_modes(
    sp: ShufflePlan, broadcast_max_rows: int, ratio: float = 4.0
) -> str:
    """The single-stage twin of choose_edge_modes: a repartition-join
    ShufflePlan whose small side fits under ``broadcast_max_rows``
    switches to broadcast small + local big (the adaptive
    broadcast-switch seam — a probe's observed produce counts, or a
    feedback-seeded estimate, lands here as updated est_rows).
    Group-by cuts require key-colocated partitions and never
    switch."""
    if (
        sp.kind != "join"
        or sp.join_kind is None
        or len(sp.sides) != 2
    ):
        return "hash"
    return _decide_join_modes(
        sp.sides, sp.join_kind, broadcast_max_rows, ratio
    )


def split_plan_shuffle_salted(
    plan: L.LogicalPlan, catalog=None
) -> Optional[ShufflePlan]:
    """The SALTED variant of the fragment-sliced GROUP BY cut: rows
    still shuffle by the first group key, but a salted hot key's
    group is SPLIT across K partitions — so the consumer must produce
    PARTIAL aggregates (the split_plan decomposition) and the
    coordinator's final stage re-merges the salted partials through
    the plain final-aggregate path. Returns None when the aggregate
    does not decompose (DISTINCT et al: a split group cannot merge)
    or the group key is not a bare column of the aggregate's input —
    the skew probe then skips salting rather than risking a wrong
    re-merge."""
    agg = _find_cut(plan)
    if agg is None or not agg.group_exprs or agg.gc_meta:
        return None
    dec = _decompose_aggs(agg)
    if dec is None:
        return None
    first = agg.group_exprs[0][1]
    if not isinstance(first, ColumnRef):
        return None
    key = first.name
    if key not in {c.internal for c in agg.child.schema.cols}:
        return None
    frag_scan = _pick_frag_scan(agg.child, catalog)
    if frag_scan is None:
        return None
    partial_aggs, pcols, final, avg_fix = dec
    group_cols = [
        OutCol(None, n, n, e.type) for n, e in agg.group_exprs
    ]
    partial_schema = Schema(group_cols + pcols)
    consumer = L.Aggregate(
        partial_schema,
        L.ShuffleRead(agg.child.schema, tag=0),
        list(agg.group_exprs), partial_aggs,
    )
    side = ShuffleSide(
        agg.child, frag_scan, key, 0, _est_rows(frag_scan, catalog)
    )

    def final_builder(source, _plan=plan, _agg=agg, _final=final,
                      _fix=avg_fix):
        merged = _final_agg_plan(_agg, source, _final, _fix)
        return _replace_node(_plan, _agg, merged)

    return ShufflePlan(
        "groupby", [side], consumer, partial_schema, final_builder
    )


def split_plan_shuffle_aggskip(
    plan: L.LogicalPlan, catalog=None
) -> Optional[ShufflePlan]:
    """The PARTIAL-AGG-SKIP variant of the repartition-join cut
    (parallel/aqe.py, the "Partial Partial Aggregates" decision): the
    same join shuffle, but each partition's consumer returns the RAW
    join rows — the coordinator's final stage runs the ORIGINAL
    aggregate over the staged rows. When the probe observes group
    cardinality approaching the row count, the per-partition partial
    aggregation compacts (nearly) nothing, so its hash-agg pass is
    pure overhead there; skipping it ships the same volume with one
    less pass. Returns None when the plan is not the join-under-
    aggregate shape. The first group key's producing side rides along
    as ``_aggskip_gcol``/``_aggskip_gtag`` (the probe measures that
    side's distinct group count — a LOWER bound on the join output's
    group NDV, so the skip only fires when even the bound is high)."""
    agg = _find_cut(plan)
    if agg is None or not agg.group_exprs or agg.gc_meta:
        return None
    path, jp = _find_shuffle_join(agg.child)
    if (
        jp is None or jp.kind not in _SHUFFLE_JOIN_KINDS
        or jp.null_aware or not jp.equi_keys
    ):
        return None
    le, re_ = jp.equi_keys[0]
    lkey = _shuffle_key_of(le, jp.left.schema)
    rkey = _shuffle_key_of(re_, jp.right.schema)
    lscan = _pick_frag_scan(jp.left, catalog)
    rscan = _pick_frag_scan(jp.right, catalog)
    if (
        lkey is None or rkey is None
        or lscan is None or rscan is None
    ):
        return None
    first = agg.group_exprs[0][1]
    if not isinstance(first, ColumnRef):
        return None
    gcol = first.name
    gtag = None
    if gcol in {c.internal for c in jp.left.schema.cols}:
        gtag = 0
    elif gcol in {c.internal for c in jp.right.schema.cols}:
        gtag = 1
    if gtag is None:
        return None
    sides = [
        ShuffleSide(jp.left, lscan, lkey, 0,
                    _est_rows(lscan, catalog)),
        ShuffleSide(jp.right, rscan, rkey, 1,
                    _est_rows(rscan, catalog)),
    ]
    jp2 = dataclasses.replace(
        jp,
        left=L.ShuffleRead(jp.left.schema, tag=0),
        right=L.ShuffleRead(jp.right.schema, tag=1),
    )
    mid = _wrap_path(path, jp2)

    def final_builder(source, _plan=plan, _agg=agg):
        return _replace_node(
            _plan, _agg, dataclasses.replace(_agg, child=source)
        )

    sp = ShufflePlan(
        "join", sides, mid, agg.child.schema, final_builder,
        join_kind=jp.kind,
    )
    sp._aggskip_gcol = gcol
    sp._aggskip_gtag = gtag
    return sp


def _parse_peeled(peeled):
    """Recognize a distributable ORDER BY root in the peeled node
    stack (root-first): ``[*above, Limit?, Sort]`` where ``above`` is
    row-wise only. Returns (above tuple, (count, offset) or None,
    Sort) or None when the stack has any other shape (the coordinator
    re-runs it over the unioned rows, as before)."""
    nodes = list(peeled)
    if not nodes or not isinstance(nodes[-1], L.Sort):
        return None
    sort = nodes.pop()
    limit = None
    if nodes and isinstance(nodes[-1], L.Limit):
        ln = nodes.pop()
        if ln.count is None:
            return None
        limit = (int(ln.count), int(ln.offset or 0))
    if any(
        not isinstance(nd, (L.Projection, L.Selection)) for nd in nodes
    ):
        return None
    return tuple(nodes), limit, sort


def _range_sort_key(sort: L.Sort, schema: Schema):
    """(key internal name, desc) when the first sort key is a bare
    range-partitionable column of ``schema``, else None."""
    if not sort.keys:
        return None
    e, desc = sort.keys[0]
    if not isinstance(e, ColumnRef):
        return None
    oc = next((c for c in schema.cols if c.internal == e.name), None)
    if oc is None or oc.type is None:
        return None
    if oc.type.kind not in _RANGE_KEY_KINDS:
        return None
    return e.name, bool(desc)


def _range_stage(prev_schema: Schema, source, sort: L.Sort, limit):
    """Build the range exchange stage: each partition owns one key
    range, runs the existing single-host sort (the TopN path when a
    LIMIT pushes K+offset under it — per-partition top-K) and the
    coordinator concatenates in partition order."""
    key_desc = _range_sort_key(sort, prev_schema)
    if key_desc is None:
        return None
    key, desc = key_desc
    sr = L.ShuffleRead(prev_schema, tag=0)
    sorted_p = dataclasses.replace(sort, schema=prev_schema, child=sr)
    k = None
    consumer: L.LogicalPlan = sorted_p
    if limit is not None:
        # push LIMIT under the range exchange: each partition ships at
        # most count+offset rows before the final concat (the global
        # offset cannot be split across partitions, so every partition
        # keeps its own first count+offset candidates)
        k = int(limit[0]) + int(limit[1])
        consumer = L.Limit(prev_schema, sorted_p, k, 0)
    side = ShuffleSide(source, None, key, 0, 0, mode="range")
    return DagStage(
        "range", [side], consumer, desc=desc, limit=k,
    )


def _only_rowwise_above(lower: L.LogicalPlan, target) -> bool:
    """True iff the single-child chain from ``lower`` down to
    ``target`` crosses only Selection/Projection nodes — the condition
    for folding those nodes into a per-partition stage consumer.
    Anything else (a Window between the ORDER BY and the aggregate
    computes over the WHOLE set, not per partition) must stay on the
    coordinator."""
    p = lower
    while p is not target:
        if not isinstance(p, (L.Selection, L.Projection)):
            return False
        p = p.child
    return True


def _find_windows(p: L.LogicalPlan, out: List[L.Window]) -> None:
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None:
            _find_windows(c, out)
    for c in getattr(p, "children", []) or []:
        _find_windows(c, out)
    if isinstance(p, L.Window):
        out.append(p)


def _window_stage(lower: L.LogicalPlan, catalog) -> Optional[DagStage]:
    """Distributed window functions: when ``lower`` is row-wise nodes
    over EXACTLY ONE Window whose first PARTITION BY key is a bare
    column of its child, hash-exchange the child rows by that key —
    every worker then owns COMPLETE window partitions (deeper
    partition keys are supersets of the first) and evaluates the
    ORIGINAL window (frames, running aggregates, lag/lead included)
    with final output, lifting the single-host fallback. Consumer
    output carries lower.schema (the row-wise nodes fold in)."""
    wins: List[L.Window] = []
    _find_windows(lower, wins)
    if len(wins) != 1:
        return None  # stacked OVER specs may disagree on keys
    win = wins[0]
    if not win.partition_exprs or not _only_rowwise_above(lower, win):
        return None
    pk = win.partition_exprs[0]
    if not isinstance(pk, ColumnRef):
        return None
    child_schema = win.child.schema
    if pk.name not in {c.internal for c in child_schema.cols}:
        return None
    frag_scan = _pick_frag_scan(win.child, catalog)
    if frag_scan is None:
        return None
    side = ShuffleSide(
        win.child, frag_scan, pk.name, 0,
        _est_rows(frag_scan, catalog),
    )
    consumer = _replace_node(
        lower, win.child, L.ShuffleRead(child_schema, tag=0)
    )
    return DagStage(
        "hash", [side], consumer, requires_key_partition=True,
    )


def _join_chain_stages(
    lower: L.LogicalPlan, catalog
) -> Optional[List[DagStage]]:
    """Left-deep join chain cut: when ``lower``'s topmost join's LEFT
    input is itself a qualifying shuffle join, stage 0 runs the nested
    join as an ordinary two-sided hash exchange (both scans
    fragment-sliced) and HOLDS its per-partition output; stage 1
    re-exchanges the held rows by the OUTER join key against the
    fragment-sliced outer right side. Keys must pass as bare columns
    (the held rows re-hash without compute) and both joins must be
    hash-partitionable kinds. Returns the two stages, or None."""
    path, jp = _find_shuffle_join(lower)
    if (
        jp is None or jp.kind not in _SHUFFLE_JOIN_KINDS
        or jp.null_aware or not jp.equi_keys
    ):
        return None
    ipath, ijp = _find_shuffle_join(jp.left)
    if (
        ijp is None or ijp.kind not in _SHUFFLE_JOIN_KINDS
        or ijp.null_aware or not ijp.equi_keys
    ):
        return None
    ile, ire = ijp.equi_keys[0]
    ilk = _shuffle_key_of(ile, ijp.left.schema)
    irk = _shuffle_key_of(ire, ijp.right.schema)
    ilscan = _pick_frag_scan(ijp.left, catalog)
    irscan = _pick_frag_scan(ijp.right, catalog)
    le, re_ = jp.equi_keys[0]
    mid_schema = jp.left.schema
    lkey = _shuffle_key_of(le, mid_schema)
    rkey = _shuffle_key_of(re_, jp.right.schema)
    rscan = _pick_frag_scan(jp.right, catalog)
    if None in (ilk, irk, ilscan, irscan, lkey, rkey, rscan):
        return None
    sides0 = [
        ShuffleSide(ijp.left, ilscan, ilk, 0,
                    _est_rows(ilscan, catalog)),
        ShuffleSide(ijp.right, irscan, irk, 1,
                    _est_rows(irscan, catalog)),
    ]
    ijp2 = dataclasses.replace(
        ijp,
        left=L.ShuffleRead(ijp.left.schema, tag=0),
        right=L.ShuffleRead(ijp.right.schema, tag=1),
    )
    mid = _wrap_path(ipath, ijp2)
    st0 = DagStage("hash", sides0, mid, join_kind=ijp.kind)
    # held-output estimate: the planner's join estimate — the static
    # baseline AQE's stage-boundary re-plan compares observed held
    # rows against before flipping the downstream edge
    try:
        from tidb_tpu.planner.cardinality import est_rows as _card_est

        held_est = int(_card_est(jp.left, catalog))
    except Exception:
        held_est = 0
    side_held = ShuffleSide(
        L.StageInput(mid_schema, stage=0), None, lkey, 0, held_est
    )
    side_right = ShuffleSide(
        jp.right, rscan, rkey, 1, _est_rows(rscan, catalog)
    )
    jp2 = dataclasses.replace(
        jp,
        left=L.ShuffleRead(mid_schema, tag=0),
        right=L.ShuffleRead(jp.right.schema, tag=1),
    )
    consumer = _wrap_path(path, jp2)
    st1 = DagStage(
        "hash", [side_held, side_right], consumer, join_kind=jp.kind
    )
    return [st0, st1]


def split_plan_dag(
    plan: L.LogicalPlan, catalog=None
) -> Optional[ShuffleDAG]:
    """Cut a bound plan into a DAG of worker-to-worker exchange
    stages. Shapes (deepest first):

    1. repartition join stage — both sides fragment-slice their
       dominant scan and exchange by the join key; when the first
       GROUP BY key IS a join key the original aggregate fuses into
       the join stage (complete groups per partition), otherwise a
       second hash stage re-exchanges the held join output by the
       group key (zero re-scan of either side);
    2. fragment-sliced GROUP BY stage (no suitable join) — the
       existing group-stack cut as stage 0, only used when a range
       stage rides above it;
    3. range ORDER BY stage — the peeled Sort (plus a pushed-down
       per-partition top-K for LIMIT) runs distributed over a
       range-partitioned exchange of the previous stage's held output
       (or of the fragment-sliced base scan when there is no deeper
       stage), merged by order-preserving concat.

    Returns None when no multi-stage (or range) shape applies — the
    caller falls back to the single-cut planners. Raises Unschedulable
    for plans that cannot cross the engine seam."""
    agg_probe = _find_cut(plan)
    if agg_probe is not None and agg_probe.gc_meta:
        raise Unschedulable(
            "GROUP_CONCAT plans execute host-assisted; they do not "
            "cross the engine boundary"
        )
    peeled, lower = _peel_global_roots(plan)
    rspec = _parse_peeled(peeled)
    agg = _find_cut(lower)
    stages: List[DagStage] = []
    fused = False  # the original aggregate already ran in a stage
    window_stage = False  # a distributed-window stage (no aggregate)

    if agg is not None and agg.group_exprs:
        # descend the WHOLE aggregate stack (DISTINCT aggregates
        # expand to stacked Aggregates — the shape whose single-cut
        # group-by re-scans unsliced join sides per host) to its
        # bottom and the raw-row column the outermost group key
        # resolves to; the join stage sits UNDER the stack
        cut = _group_stack_cut(agg)
        gkey = cut[1] if cut is not None else None
        cut_child = cut[0] if cut is not None else agg.child
        path, jp = _find_shuffle_join(cut_child)
        if (
            gkey is not None
            and jp is not None
            and jp.kind in _SHUFFLE_JOIN_KINDS
            and not jp.null_aware
            and jp.equi_keys
        ):
            le, re_ = jp.equi_keys[0]
            lkey = _shuffle_key_of(le, jp.left.schema)
            rkey = _shuffle_key_of(re_, jp.right.schema)
            lscan = _pick_frag_scan(jp.left, catalog)
            rscan = _pick_frag_scan(jp.right, catalog)
            if (
                lkey is not None and rkey is not None
                and lscan is not None and rscan is not None
            ):
                sides = [
                    ShuffleSide(jp.left, lscan, lkey, 0,
                                _est_rows(lscan, catalog)),
                    ShuffleSide(jp.right, rscan, rkey, 1,
                                _est_rows(rscan, catalog)),
                ]
                jp2 = dataclasses.replace(
                    jp,
                    left=L.ShuffleRead(jp.left.schema, tag=0),
                    right=L.ShuffleRead(jp.right.schema, tag=1),
                )
                mid = _wrap_path(path, jp2)
                if gkey in (lkey, rkey):
                    # join-key partitions colocate complete groups:
                    # the ORIGINAL aggregate stack fuses into the
                    # join stage (DISTINCT included — every level
                    # groups by a superset of the outer key)
                    core = _replace_node(agg, cut_child, mid)
                    stages.append(DagStage(
                        "hash", sides, core, join_kind=jp.kind,
                        requires_key_partition=True,
                    ))
                    fused = True
                else:
                    # stage 0: join only; stage 1 re-exchanges the
                    # HELD join output by the group key — no re-scan
                    # of either side (gkey is in cut_child's schema
                    # by _group_stack_cut's contract, and mid.schema
                    # == cut_child.schema)
                    stages.append(DagStage(
                        "hash", sides, mid, join_kind=jp.kind,
                    ))
                    side2 = ShuffleSide(
                        L.StageInput(mid.schema, stage=0), None,
                        gkey, 0, 0,
                    )
                    core = _replace_node(
                        agg, cut_child,
                        L.ShuffleRead(cut_child.schema, tag=0),
                    )
                    stages.append(DagStage(
                        "hash", [side2], core,
                        requires_key_partition=True,
                    ))
                    fused = True
        if not stages and rspec is not None and cut is not None:
            # no join stage: the group-stack cut as stage 0, worth a
            # DAG only because a range stage rides above it
            frag_scan = _pick_frag_scan(cut_child, catalog)
            if frag_scan is not None:
                side = ShuffleSide(
                    cut_child, frag_scan, gkey, 0,
                    _est_rows(frag_scan, catalog),
                )
                core = _replace_node(
                    agg, cut_child,
                    L.ShuffleRead(cut_child.schema, tag=0),
                )
                stages.append(DagStage(
                    "hash", [side], core,
                    requires_key_partition=True,
                ))
                fused = True

    # ---- distributed window stage (no aggregate below) ----
    if not stages and agg is None:
        ws = _window_stage(lower, catalog)
        if ws is not None:
            stages.append(ws)
            window_stage = True

    # ---- left-deep join chain (no aggregate): stage 0 exchanges the
    # nested join by its own key and HOLDS its output, stage 1
    # re-exchanges the held rows by the outer key against the
    # fragment-sliced outer side — the single-cut shape re-scans the
    # whole un-sliced nested side per host; the chain slices every
    # base scan exactly once. Stage 1 is a plain two-sided hash join
    # over an attempt-fenced StageInput, which is the seam AQE's
    # stage-boundary re-planning flips to broadcast when stage 0's
    # observed held rows collapse (parallel/dcn.py _run_dag). ----
    chain_stage = False
    chain_dec = None
    if not stages:
        chain_src = None
        if agg is None:
            chain_src = lower
        elif not agg.group_exprs and not agg.gc_meta:
            # a global (no-group-key) DECOMPOSABLE aggregate rides the
            # chain as a partial agg fused into the last stage; the
            # coordinator merges through the ordinary final-agg path
            chain_dec = _decompose_aggs(agg)
            if chain_dec is not None:
                chain_src = agg.child
        if chain_src is not None:
            chain = _join_chain_stages(chain_src, catalog)
            if chain is not None:
                stages.extend(chain)
                chain_stage = True

    # ---- range ORDER BY stage on top ----
    if rspec is not None and not chain_stage:
        above, limit, sort = rspec
        if stages:
            # re-wrap the last stage's consumer so its held output
            # carries the Sort child's schema (the row-wise nodes
            # between the Sort and the Aggregate fold into the
            # stage); only legal when that gap is purely row-wise —
            # a Window there computes over the WHOLE set, so the
            # coordinator keeps the sort (plan merge below)
            prev = stages[-1]
            if window_stage:
                wrapped = prev.consumer  # already carries lower.schema
            elif _only_rowwise_above(lower, agg):
                wrapped = _replace_node(lower, agg, prev.consumer)
            else:
                wrapped = None
            rs = _range_stage(
                lower.schema,
                L.StageInput(lower.schema, stage=len(stages) - 1),
                sort, limit,
            ) if wrapped is not None else None
            if rs is not None:
                stages[-1] = dataclasses.replace(prev, consumer=wrapped)
                stages.append(rs)
                out_cols = above[0].schema if above else sort.schema
                return ShuffleDAG(
                    stages, sort.schema, None,
                    {
                        "kind": "concat", "reverse": rs.desc,
                        "limit": limit, "above": tuple(above),
                        "columns": [c.name for c in out_cols.cols],
                    },
                )
        elif agg is None:
            frag_scan = _pick_frag_scan(lower, catalog)
            key_desc = _range_sort_key(sort, lower.schema)
            if frag_scan is not None and key_desc is not None:
                rs = _range_stage(lower.schema, lower, sort, limit)
                if rs is not None:
                    rs.sides[0] = dataclasses.replace(
                        rs.sides[0], frag_scan=frag_scan,
                        est_rows=_est_rows(frag_scan, catalog),
                    )
                    stages.append(rs)
                    out_cols = above[0].schema if above else sort.schema
                    return ShuffleDAG(
                        stages, sort.schema, None,
                        {
                            "kind": "concat", "reverse": rs.desc,
                            "limit": limit, "above": tuple(above),
                            "columns": [c.name for c in out_cols.cols],
                        },
                    )

    # ---- no range stage: a DAG is worth it when CHAINED, or when a
    # window stage lifts the single-host fallback outright ----
    if window_stage:
        def final_builder(source, _plan=plan, _lower=lower):
            return _replace_node(_plan, _lower, source)

        return ShuffleDAG(
            stages, lower.schema, final_builder, {"kind": "plan"},
        )
    if chain_stage:
        if chain_dec is not None:
            # fuse the partial half of the global aggregate into the
            # LAST chain stage's consumer; the coordinator's final
            # stage re-merges (split_plan's decomposition — also what
            # makes the chain safe under broadcast-switch: partials
            # re-aggregate regardless of which partition they ran on)
            partial_aggs, pcols, final, avg_fix = chain_dec
            last = stages[-1]
            partial_schema = Schema(list(pcols))
            consumer = L.Aggregate(
                partial_schema, last.consumer, [], partial_aggs
            )
            stages[-1] = dataclasses.replace(last, consumer=consumer)

            def final_builder(source, _plan=plan, _agg=agg,
                              _final=final, _fix=avg_fix):
                merged = _final_agg_plan(_agg, source, _final, _fix)
                return _replace_node(_plan, _agg, merged)

            return ShuffleDAG(
                stages, partial_schema, final_builder,
                {"kind": "plan"},
            )

        # coordinator re-runs the peeled root operators (ORDER BY /
        # LIMIT and row-wise nodes) over the unioned stage-1 rows —
        # the no-agg ShufflePlan discipline
        def final_builder(source, _peeled=tuple(peeled)):
            out = source
            for node in reversed(_peeled):
                out = dataclasses.replace(node, child=out)
            return out

        return ShuffleDAG(
            stages, lower.schema, final_builder, {"kind": "plan"},
        )
    if len(stages) < 2 or not fused:
        return None

    def final_builder(source, _plan=plan, _agg=agg):
        return _replace_node(_plan, _agg, source)

    return ShuffleDAG(
        stages, agg.schema, final_builder, {"kind": "plan"},
    )


def _group_stack_cut(agg: L.Aggregate):
    """Bottom of the aggregate stack under `agg` plus the raw-row
    column the stack's first group key resolves to: (cut child, key
    internal name) or None.

    DISTINCT aggregates expand into STACKED Aggregates (logical.py
    _expand_distinct_aggs: inner groups by keys + distinct arg), so
    the shuffle cut must sit below the WHOLE stack — rows hash-
    partitioned on the outermost group key make every level's groups
    complete per partition (deeper stacks group by supersets of the
    outer keys), and the original aggregate tree then executes per
    partition with FINAL output. The key must pass through the stack
    as a bare column (Projections may rename it; anything computed
    defeats row-level hashing)."""
    first = agg.group_exprs[0][1]
    if not isinstance(first, ColumnRef):
        return None
    kname = first.name  # in agg.child scope
    node = agg
    while True:
        path = []
        p = node.child
        while isinstance(p, (L.Selection, L.Projection)):
            path.append(p)
            p = p.child
        if not isinstance(p, L.Aggregate) or p.gc_meta:
            break
        # thread the key column down through the renames
        k = kname
        ok = True
        for q in path:
            if isinstance(q, L.Projection):
                e = dict(q.exprs).get(k)
                if e is None and q.additive:
                    continue
                if not isinstance(e, ColumnRef):
                    ok = False
                    break
                k = e.name
        if not ok:
            break
        e = {n: ge for n, ge in p.group_exprs}.get(k)
        if not isinstance(e, ColumnRef):
            break
        kname = e.name
        node = p
    cut_child = node.child
    if kname not in {c.internal for c in cut_child.schema.cols}:
        return None
    return cut_child, kname
