"""Host-assisted aggregation: GROUP_CONCAT.

String concatenation produces variable-length output — inherently host
work (the device engine's strings are fixed-width dictionary codes).
The reference runs GROUP_CONCAT row-at-a-time inside the engine
(pkg/executor/aggfuncs func_group_concat.go); here the heavy part —
scanning, filtering, projecting the agg inputs — still runs as one
fused device program, and only the per-group concatenation loop runs on
host over the (already reduced) projected columns. The aggregated
result is injected back into the plan as a Staged node (same mechanism
as streamed aggregation), so HAVING / ORDER BY / joins above the
aggregate execute normally on device.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.chunk import (
    HostBlock,
    HostColumn,
    batch_to_block,
    block_to_batch,
    encode_strings,
)
from tidb_tpu.dtypes import Kind, days_to_date
from tidb_tpu.planner import logical as L
from tidb_tpu.planner.streamed import _STAGED_NONCE, _replace_node, _children


def _find_gc_agg(plan) -> Optional[L.Aggregate]:
    found = None

    def walk(p):
        nonlocal found
        for c in _children(p):
            walk(c)
        if found is None and isinstance(p, L.Aggregate) and p.gc_meta:
            found = p

    walk(plan)
    return found


def _format_value(v, t) -> str:
    """MySQL string rendering of a value inside GROUP_CONCAT."""
    if t.kind == Kind.DATE:
        return days_to_date(int(v))
    if t.kind == Kind.DATETIME:
        from tidb_tpu.dtypes import micros_to_datetime

        return micros_to_datetime(int(v))
    if t.kind == Kind.TIME:
        from tidb_tpu.dtypes import micros_to_time

        return micros_to_time(int(v))
    if t.kind == Kind.DECIMAL:
        return f"{v:.{t.scale}f}"
    if t.kind == Kind.BOOL:
        return "1" if v else "0"
    if isinstance(v, float):
        import math

        if math.isfinite(v) and abs(v) < 1e15 and v == int(v):
            return str(int(v))
        return repr(v)
    return str(v)


def _json_cell(v, t):
    """SQL value -> JSON-embeddable python value (JSON_ARRAYAGG/
    JSON_OBJECTAGG rendering: numbers native, temporals as their MySQL
    strings, SQL NULL as JSON null; strings embed as JSON strings —
    documented divergence for JSON-typed columns, which MySQL nests as
    documents)."""
    if v is None:
        return None
    if t.kind in (Kind.DATE, Kind.DATETIME, Kind.TIME, Kind.DECIMAL):
        return _format_value(v, t)
    if isinstance(v, (bool, int, float)):
        return v
    return str(v)


def try_host_agg(executor, plan):
    """Execute `plan` when it contains a GROUP_CONCAT aggregate:
    device-run the aggregate's input projection, host-reduce the groups
    (all aggregates of the node in one pass), stage the result, re-run
    the remaining plan. Returns None when no GROUP_CONCAT is present."""
    agg = _find_gc_agg(plan)
    if agg is None:
        return None

    gc_meta = agg.gc_meta or {}

    # ---- 1. device-side projection of everything the reduction needs
    exprs: List[Tuple[str, object]] = []
    for n, e in agg.group_exprs:
        exprs.append((n, e))
    argname: Dict[int, str] = {}
    for i, (_n, _f, a, _d) in enumerate(agg.aggs):
        if a is not None:
            argname[i] = f"_x{i}"
            exprs.append((f"_x{i}", a))
    ordnames: Dict[str, List[Tuple[str, bool]]] = {}
    for name, (_sep, obs) in gc_meta.items():
        lst = []
        for j, (e, desc) in enumerate(obs):
            nm = f"_o_{name}_{j}"
            exprs.append((nm, e))
            lst.append((nm, desc))
        ordnames[name] = lst
    outc = [L.OutCol(None, nm, nm, e.type) for nm, e in exprs]
    sub = L.Projection(L.Schema(outc), agg.child, list(exprs))
    batch, dicts = executor.run(sub)
    types = {nm: e.type for nm, e in exprs}
    block = batch_to_block(batch, types, dicts)
    decoded = {nm: block.columns[nm].decode() for nm, _ in exprs}

    # ---- 2. host group-by reduction
    keys = [n for n, _ in agg.group_exprs]
    groups: Dict[tuple, int] = {}
    order: List[tuple] = []
    rows_of: List[List[int]] = []
    for r in range(block.nrows):
        k = tuple(decoded[n][r] for n in keys)
        gi = groups.get(k)
        if gi is None:
            gi = groups[k] = len(order)
            order.append(k)
            rows_of.append([])
        rows_of[gi].append(r)
    if not keys and not order:
        # scalar aggregate over empty input still yields one row
        order.append(())
        rows_of.append([])

    out_vals: Dict[str, List] = {n: [] for n in keys}
    for i, (name, _f, _a, _d) in enumerate(agg.aggs):
        out_vals[name] = []
    for gi, k in enumerate(order):
        for n, kv in zip(keys, k):
            out_vals[n].append(kv)
        rs = rows_of[gi]
        for i, (name, func, a, distinct) in enumerate(agg.aggs):
            if func == "count" and a is None:
                out_vals[name].append(len(rs))
                continue
            col = decoded[argname[i]]
            if func == "json_arrayagg":
                import json as _json

                # SQL NULLs become JSON nulls (MySQL keeps them)
                at = types[argname[i]]
                out_vals[name].append(
                    _json.dumps([_json_cell(col[r], at) for r in rs])
                    if rs else None
                )
                continue
            if func == "json_objectagg":
                import json as _json

                kcol_name = ordnames[name][0][0]
                kcol = decoded[kcol_name]
                at = types[argname[i]]
                obj = {}
                for r in rs:
                    if kcol[r] is None:
                        raise ValueError(
                            "JSON documents may not contain NULL member "
                            "names"
                        )
                    obj[str(kcol[r])] = _json_cell(col[r], at)
                out_vals[name].append(_json.dumps(obj) if rs else None)
                continue
            vals = [(col[r], r) for r in rs if col[r] is not None]
            if func == "group_concat":
                sep, _obs = gc_meta[name]
                obs = ordnames[name]
                if obs:
                    import functools

                    def cmp(x, y, _obs=obs):
                        for nm, desc in _obs:
                            ax, ay = decoded[nm][x[1]], decoded[nm][y[1]]
                            # MySQL sorts NULLs first ascending
                            kx = (ax is not None, ax)
                            ky = (ay is not None, ay)
                            if kx != ky:
                                lt = kx < ky
                                return (1 if desc else -1) if lt else (-1 if desc else 1)
                        return 0

                    vals = sorted(vals, key=functools.cmp_to_key(cmp))
                if distinct:
                    seen = set()
                    vals = [
                        v for v in vals
                        if not (v[0] in seen or seen.add(v[0]))
                    ]
                at = types[argname[i]]
                out_vals[name].append(
                    sep.join(_format_value(v, at) for v, _r in vals)
                    if vals
                    else None
                )
                continue
            vs = [v for v, _r in vals]
            if distinct:
                vs = list(dict.fromkeys(vs))
            if func == "count":
                out_vals[name].append(len(vs))
            elif not vs:
                out_vals[name].append(None)
            elif func == "sum":
                out_vals[name].append(sum(vs))
            elif func == "avg":
                out_vals[name].append(sum(vs) / len(vs))
            elif func == "min":
                out_vals[name].append(min(vs))
            elif func == "max":
                out_vals[name].append(max(vs))
            elif func == "first":
                out_vals[name].append(vs[0] if vs else None)
            else:
                raise NotImplementedError(f"host agg {func}")

    # ---- 3. stage the reduced table back onto the device
    cols: Dict[str, HostColumn] = {}
    sdicts = {}
    for c in agg.schema:
        vals = out_vals[c.internal]
        t = c.type
        if t.kind == Kind.STRING:
            hc = encode_strings([v for v in vals])
            hc = HostColumn(t, hc.data, hc.valid, hc.dictionary)
            sdicts[c.internal] = hc.dictionary
        else:
            valid = np.array([v is not None for v in vals], dtype=bool)
            if t.kind == Kind.DECIMAL:
                data = np.array(
                    [0 if v is None else int(round(v * 10**t.scale)) for v in vals],
                    dtype=np.int64,
                )
            else:
                data = np.array(
                    [0 if v is None else v for v in vals],
                    dtype=t.np_dtype,
                )
            hc = HostColumn(t, data, valid)
        cols[c.internal] = hc
    result = block_to_batch(HostBlock(cols, len(order)))

    _STAGED_NONCE[0] += 1
    staged = L.Staged(
        agg.schema, batch=result, dicts=sdicts, nonce=_STAGED_NONCE[0]
    )
    new_plan = staged if plan is agg else _replace_node(plan, agg, staged)
    return executor.run(new_plan)
