from tidb_tpu.planner.logical import build_select, build_query, PlanError  # noqa: F401
from tidb_tpu.planner import logical as nodes  # noqa: F401
