from tidb_tpu.planner.logical import build_select, PlanError  # noqa: F401
from tidb_tpu.planner import logical as nodes  # noqa: F401
