"""AST -> logical plan: name resolution, aggregate extraction, pushdown.

Reference: pkg/planner/core/logical_plan_builder.go (AST -> logical ops),
expression_rewriter.go (subqueries), and the fixed-order logical rule list
(optimizer.go:98-123). This builder applies the high-value rules inline:

- column pruning (columnPruner): scans read only referenced columns
- predicate pushdown (ppdSolver): WHERE conjuncts sink below joins to the
  side whose columns they reference; equi-conjuncts in ON become join keys
- projection elimination: additive projections keep base columns so ORDER
  BY can reference non-selected columns (MySQL scoping)

Internal column names are ``qualifier.column`` — unique across the plan,
used directly as device Batch column names.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from tidb_tpu.dtypes import BOOL, DATE, INT64, STRING, Kind, SQLType
from tidb_tpu.expression.expr import ColumnRef, Expr, Func, Literal
from tidb_tpu.parser import ast

# virtual row-handle column for multi-table DML (analog of _tidb_rowid):
# exposed only on scans whose alias is in the expose_rowid() scope's set
# (the DML's target tables), so joined read-only tables keep partition
# pruning / index-range access, and star expansion filters it by name
ROWID_NAME = "_tidb_rowid"
import contextlib as _contextlib
import contextvars as _contextvars

_EXPOSE_ROWID = _contextvars.ContextVar("expose_rowid", default=frozenset())


@_contextlib.contextmanager
def expose_rowid(aliases):
    tok = _EXPOSE_ROWID.set(frozenset(a.lower() for a in aliases))
    try:
        yield
    finally:
        _EXPOSE_ROWID.reset(tok)


class PlanError(ValueError):
    pass


@dataclasses.dataclass
class OutCol:
    """One column of a plan node's schema."""

    qualifier: Optional[str]  # table alias; None for computed columns
    name: str  # bare column name or output alias
    internal: str  # unique name used in device batches
    type: SQLType


class Schema:
    def __init__(self, cols: List[OutCol]):
        self.cols = cols

    def resolve(self, table: Optional[str], name: str) -> OutCol:
        name_l = name.lower()
        matches = [
            c
            for c in self.cols
            if c.name.lower() == name_l
            and (table is None or (c.qualifier or "").lower() == table.lower())
        ]
        if not matches:
            raise PlanError(f"unknown column {table + '.' if table else ''}{name}")
        if len(matches) > 1:
            # identical internal name means the same column seen twice
            if len({m.internal for m in matches}) > 1:
                raise PlanError(f"ambiguous column {name}")
        return matches[0]

    def types(self) -> Dict[str, SQLType]:
        return {c.internal: c.type for c in self.cols}

    def __iter__(self):
        return iter(self.cols)


class LayeredSchema(Schema):
    """MySQL ORDER BY scoping: select aliases shadow base columns of the
    same name; base columns remain reachable when no alias matches."""

    def __init__(self, *layers: Schema):
        super().__init__([c for l in layers for c in l.cols])
        self.layers = layers

    def resolve(self, table: Optional[str], name: str) -> OutCol:
        last_err = None
        for layer in self.layers:
            try:
                return layer.resolve(table, name)
            except PlanError as e:
                last_err = e
        raise last_err


# ---------------------------------------------------------------------------
# Logical operators
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LogicalPlan:
    schema: Schema


@dataclasses.dataclass
class OneRow(LogicalPlan):
    """Single-row, zero-column source — the dual table for tableless
    SELECTs (reference: TableDual plan)."""


@dataclasses.dataclass
class Scan(LogicalPlan):
    db: str
    table: str  # catalog table name
    alias: str  # qualifier
    columns: List[str]  # pruned, bare storage names (internal = alias.name)
    # cross-host fragment slice (planner/fragmenter.py): (idx, n) takes
    # every n-th row starting at idx of the version's block concatenation
    # — the per-host disjoint cover the DCN scheduler dispatches (the
    # region-partitioned MPP TableScan analog, pkg/store/copr/mpp.go:93).
    # None = whole-table scan.
    frag: Optional[Tuple[int, int]] = None


@dataclasses.dataclass
class Selection(LogicalPlan):
    child: LogicalPlan
    predicate: Expr  # bound


@dataclasses.dataclass
class Projection(LogicalPlan):
    child: LogicalPlan
    exprs: List[Tuple[str, Expr]]  # (internal out name, bound expr)
    additive: bool = False  # keep child columns too


@dataclasses.dataclass
class Aggregate(LogicalPlan):
    child: LogicalPlan
    group_exprs: List[Tuple[str, Expr]]  # (internal key name, bound expr)
    aggs: List[Tuple[str, str, Optional[Expr], bool]]  # (name, func, arg, distinct)
    # GROUP_CONCAT extras per agg name: (separator, ((bound expr, desc), ...)).
    # Presence of any entry routes the node through the host-assisted
    # aggregation stage (planner/hostagg.py) — string concatenation is
    # inherently host work (variable-length output).
    gc_meta: Optional[Dict[str, Tuple[str, tuple]]] = None


@dataclasses.dataclass
class JoinPlan(LogicalPlan):
    kind: str  # inner/left/semi/anti/cross
    left: LogicalPlan
    right: LogicalPlan
    # bound equi keys (left expr, right expr); may be empty for cross
    equi_keys: List[Tuple[Expr, Expr]]
    residual: Optional[Expr] = None
    null_aware: bool = False  # NOT IN semantics
    # cost-based mesh exchange choice ('left'/'right'/None): the named
    # side is estimated small enough to replicate (broadcast join)
    # instead of hash-repartitioning both sides. Set from ANALYZE stats
    # (cardinality.py); part of the plan fingerprint since it changes
    # the compiled exchange. Reference: broadcast-vs-shuffle MPP join in
    # pkg/planner/core/exhaust_physical_plans.go.
    broadcast: Optional[str] = None
    # mark join only: name of the boolean result column appended to the
    # probe schema (expression_rewriter.go LeftOuterSemiJoin analog)
    mark_name: Optional[str] = None


@dataclasses.dataclass
class Window(LogicalPlan):
    """One OVER spec; descs: (out name, func, bound arg, offset, running,
    frame) where frame is a (lo, hi) ROWS offset pair (None = unbounded
    side) or None for default framing."""

    child: LogicalPlan
    partition_exprs: List[Expr]
    order_exprs: List[Tuple[Expr, bool]]
    descs: List[Tuple[str, str, Optional[Expr], int, bool, Optional[tuple]]]


@dataclasses.dataclass
class Sort(LogicalPlan):
    child: LogicalPlan
    keys: List[Tuple[Expr, bool]]  # (bound expr, desc)


@dataclasses.dataclass
class Limit(LogicalPlan):
    child: LogicalPlan
    count: int
    offset: int = 0


@dataclasses.dataclass
class Staged(LogicalPlan):
    """A pre-computed device batch injected into a plan — the output of
    an out-of-band execution stage (streamed aggregation over a table
    too large for one device tile). The physical compiler treats it as
    a constant source; the nonce keeps plan-cache keys unique.

    With ``key`` set, the batch becomes a runtime INPUT instead of a
    baked constant, and the plan cache keys on (key, capacity, column
    dtypes, dictionary content hash) rather than the nonce — repeated
    executions of the same plan shape over fresh data (every shuffle
    stage's consumer) reuse one compiled program instead of paying a
    full XLA compile per stage. Dictionary content stays part of the
    cache key because string-key alignment bakes LUTs from it at
    compile time."""

    batch: object = None  # device Batch
    dicts: Optional[Dict] = None
    nonce: int = 0
    key: Optional[str] = None


@dataclasses.dataclass
class ShuffleRead(LogicalPlan):
    """Leaf standing for the receiving worker's shuffle partition of
    exchange side `tag` — the ExchangeReceiver of the cross-host
    shuffle service (parallel/shuffle.py). Serializable (unlike Staged:
    the node carries no data, only the wire schema); the worker
    substitutes a Staged batch built from its received partition before
    execution, so the physical compiler never sees it."""

    tag: int = 0


@dataclasses.dataclass
class StageInput(LogicalPlan):
    """Leaf standing for THIS worker's held output of an earlier
    shuffle-DAG stage (parallel/shuffle.py ShuffleWorker._held): the
    output partitions of stage N become the fragment-sliced producer
    input of stage N+1 — no re-scan, no re-exchange of what this host
    already owns. Serializable (the node carries only the wire schema
    and the source stage index); the worker substitutes the held
    HostBlock before execution, so like ShuffleRead the physical
    compiler never sees it."""

    stage: int = 0


@dataclasses.dataclass
class UnionAll(LogicalPlan):
    """Bag union by position; children are projections onto _u{i} names
    with casts to the common types (reference UnionExec,
    pkg/executor/unionexec)."""

    children: List[LogicalPlan] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# Expression binding (parser AST -> bound expression.Expr)
# ---------------------------------------------------------------------------


# Aggregate output columns use per-node-indexed names (_g0.., _a0..):
# deterministic across parses (the plan cache fingerprints plan reprs,
# pkg/planner/core/plan_cache.go analog) and collision-free because
# aggregate outputs are always re-projected before meeting another
# namespace (FROM-subqueries rename to alias.col; semi joins keep only
# probe columns).


class ExprBinder:
    """Lowers parser expression AST to bound expression trees against a
    schema. Aggregate calls and subqueries must have been rewritten out
    before binding (SelectBuilder does that)."""

    def __init__(self, schema: Schema, subquery_executor=None):
        self.schema = schema
        self.subquery_executor = subquery_executor

    def bind(self, e) -> Expr:
        from tidb_tpu.expression.expr import bind_expr

        lowered = self.lower(e)
        return bind_expr(lowered, self.schema.types())

    def lower(self, e) -> Expr:
        if isinstance(e, ast.Name):
            c = self.schema.resolve(e.table, e.column)
            return ColumnRef(name=c.internal)
        if isinstance(e, ast.Const):
            t = e.type_hint
            return Literal(
                type=t, value=e.value,
                param_slot=getattr(e, "param_index", None),
            )
        if isinstance(e, ast.Interval):
            raise PlanError("INTERVAL outside date arithmetic")
        if isinstance(e, ast.SubqueryExpr):
            if self.subquery_executor is None:
                raise PlanError("subquery not supported in this context")
            return self.subquery_executor(e)
        if isinstance(e, ast.AggCall):
            raise PlanError(
                f"aggregate {e.func}() not allowed here (no GROUP BY context)"
            )
        if isinstance(e, ast.Call):
            return self.lower_call(e)
        raise PlanError(f"cannot bind {e!r}")

    # name aliases normalized before compilation (reference: the alias
    # rows in pkg/expression/builtin.go funcs registry)
    _FN_ALIASES = {
        "substr": "substring",
        "mid": "substring",
        "ucase": "upper",
        "lcase": "lower",
        "character_length": "char_length",
        "ceiling": "ceil",
        "power": "pow",
        "dayofmonth": "day",
        "lengthb": "length",
        "adddate": "date_add",
        "subdate": "date_sub",
        "rlike": "regexp",
        "insert": "insert_str",
        "octet_length": "length",
        "utc_timestamp": "now",
        "curtime": "current_time",
        "lastday": "last_day",
        "localtime": "now",
        "sha": "sha1",
        "mid": "substring",
    }

    @staticmethod
    def _const_arg(x):
        """ast.Const of x, folding a leading unary minus; None if not
        constant (pre-bind normalization for const-only builtins)."""
        if isinstance(x, ast.Const):
            return x
        if (
            isinstance(x, ast.Call)
            and x.op == "neg"
            and len(x.args) == 1
            and isinstance(x.args[0], ast.Const)
            and isinstance(x.args[0].value, (int, float))
        ):
            return ast.Const(-x.args[0].value)
        return None

    def lower_call(self, e: ast.Call) -> Expr:
        op = self._FN_ALIASES.get(e.op, e.op)
        if op in ("conv", "char"):
            consts = [self._const_arg(a) for a in e.args]
            if any(c is None for c in consts):
                raise PlanError(f"{op.upper()} supports constant arguments only")
            e = ast.Call(op, consts)
        if op in ("date_add", "date_sub") and len(e.args) == 2 and not isinstance(
            e.args[1], ast.Interval
        ):
            # ADDDATE(d, n) / SUBDATE(d, n): bare N means N days
            e = ast.Call(op, [e.args[0], ast.Interval(e.args[1], "day")])
        if op == "strcmp" and len(e.args) == 2:
            # STRCMP(a, b) -> CASE WHEN a < b THEN -1 WHEN a = b THEN 0
            # ELSE 1 (NULL propagation via the comparisons)
            a, b = e.args
            return self.lower(
                ast.Call(
                    "case",
                    [
                        ast.Call("lt", [a, b]), ast.Const(-1),
                        ast.Call("eq", [a, b]), ast.Const(0),
                        ast.Const(1),
                    ],
                )
            )
        if op == "space" and len(e.args) == 1 and isinstance(e.args[0], ast.Const):
            if e.args[0].value is None:
                return self.lower(ast.Const(None))
            n = max(int(e.args[0].value), 0)
            return self.lower(ast.Const(" " * n))
        if op == "elt" and len(e.args) >= 2:
            # ELT(n, s1, s2, ...) -> CASE WHEN n=1 THEN s1 ... ELSE NULL
            n = e.args[0]
            args = []
            for i, sv in enumerate(e.args[1:], 1):
                args.extend([ast.Call("eq", [n, ast.Const(i)]), sv])
            args.append(ast.Const(None))
            return self.lower(ast.Call("case", args))
        if op in ("hex", "bin", "oct") and len(e.args) == 1:
            a0 = e.args[0]
            if isinstance(a0, ast.Const) and a0.value is None:
                return self.lower(ast.Const(None))
            if isinstance(a0, ast.Const) and isinstance(a0.value, int):
                fmt = {"hex": "X", "bin": "b", "oct": "o"}[op]
                v = a0.value
                if v < 0:  # MySQL: 64-bit two's complement
                    v &= (1 << 64) - 1
                return self.lower(ast.Const(format(v, fmt)))
            # column args resolve by type at compile (string -> byte-hex
            # transform, bounded int -> range LUT)
        if op == "conv" and len(e.args) == 3 and all(
            isinstance(a, ast.Const) for a in e.args
        ):
            v, fb, tb = (a.value for a in e.args)
            if v is None or fb is None or tb is None:
                return self.lower(ast.Const(None))
            try:
                n = int(str(v), int(fb))
            except (TypeError, ValueError):
                return self.lower(ast.Const(None))
            if n < 0:  # MySQL: 64-bit two's complement
                n &= (1 << 64) - 1
            digs = "0123456789abcdefghijklmnopqrstuvwxyz"
            tb = int(tb)
            out = ""
            m = n
            while True:
                out = digs[m % tb] + out
                m //= tb
                if m == 0:
                    break
            return self.lower(ast.Const(out.upper()))
        if op == "char" and all(isinstance(a, ast.Const) for a in e.args):
            if any(a.value is None for a in e.args):
                return self.lower(ast.Const(None))
            return self.lower(
                ast.Const("".join(chr(int(a.value)) for a in e.args))
            )
        if op in ("date_add", "date_sub"):
            base, iv = e.args
            assert isinstance(iv, ast.Interval)
            sign = 1 if op == "date_add" else -1
            months = self._interval_months(iv)
            if months is not None:
                # calendar-exact month/year arithmetic (MySQL clamps the
                # day-of-month; the reference does exact calendar math in
                # pkg/types/time.go AddDate) — fold on host for constant
                # dates, device kernel otherwise
                lowered = self.lower(base)
                if isinstance(lowered, Literal) and isinstance(lowered.value, int):
                    return Literal(
                        type=lowered.type or DATE,
                        value=_add_months_host(lowered.value, sign * months),
                    )
                return Func(
                    op="add_months",
                    args=(lowered, Literal(type=INT64, value=sign * months)),
                )
            us = self._interval_micros(iv)
            if us is not None:
                # sub-day units always promote the result to DATETIME
                sign2 = 1 if op == "date_add" else -1
                return Func(
                    op="add_us",
                    args=(self.lower(base), Literal(type=INT64, value=sign2 * us)),
                )
            days = self._interval_days(iv)
            return Func(
                op="add" if op == "date_add" else "sub",
                args=(self.lower(base), Literal(type=INT64, value=days)),
            )
        if op == "cast":
            return Func(op="cast", args=(self.lower(e.args[0]),), type=e.cast_type)
        if op == "if":
            if len(e.args) != 3:
                raise PlanError("IF takes 3 arguments")
            return Func(op="case", args=tuple(self.lower(a) for a in e.args))
        if op == "nullif":
            a, bb = (self.lower(x) for x in e.args)
            return Func(op="case", args=(Func(op="eq", args=(a, bb)), Literal(value=None), a))
        if op in (
            "eq", "ne", "lt", "le", "gt", "ge", "like", "in", "between",
        ) and any(
            isinstance(a, ast.Call) and a.op == "_collate_ci" for a in e.args
        ):
            # a CI-collated operand makes the whole COMPARISON case-
            # insensitive (MySQL collation coercion): fold ALL sides.
            # String literals lower-case at plan time (LIKE patterns and
            # IN lists must stay literals for the kernel LUTs).
            def _strip(x):
                return (
                    x.args[0]
                    if isinstance(x, ast.Call) and x.op == "_collate_ci"
                    else x
                )

            def _fold(a):
                low = self.lower(_strip(a))
                if isinstance(low, Literal) and isinstance(low.value, str):
                    return Literal(type=low.type, value=low.value.lower())
                return Func(op="lower", args=(low,))

            return Func(op=op, args=tuple(_fold(a) for a in e.args))
        if op == "rand":
            # DIVERGENCE (like uuid below): folds ONCE at plan time, so
            # every row of a statement sees the same value — per-row
            # volatile functions would defeat whole-plan compilation.
            # ORDER BY rand() therefore does not shuffle; a seed column
            # argument is not supported.
            import random as _random

            args_l = [self.lower(a) for a in e.args]
            rng = (
                _random.Random(args_l[0].value)
                if args_l and isinstance(args_l[0], Literal)
                else _random
            )
            from tidb_tpu.dtypes import FLOAT64 as _F64

            return Literal(type=_F64, value=rng.random())
        if op == "sleep":
            from tidb_tpu.utils.sqlkiller import interruptible_sleep

            a = self.lower(e.args[0])
            if isinstance(a, Literal) and isinstance(a.value, (int, float)):
                # killable: KILL QUERY / watchdogs abort a SLEEP mid-wait
                interruptible_sleep(min(max(float(a.value), 0.0), 300.0))
            return Literal(type=INT64, value=0)
        if op == "benchmark":
            # evaluated-for-timing in MySQL; here the whole plan is one
            # compiled program — accept and return the 0 contract
            return Literal(type=INT64, value=0)
        if op in ("uuid", "uuid_short"):
            # volatile generators fold at plan time: statements re-plan
            # per parse, so each STATEMENT gets a fresh value (per-ROW
            # uuids over a table would defeat dictionary coding — the
            # reference's per-row semantics are deliberately relaxed)
            import uuid as _uuid

            if op == "uuid":
                return Literal(type=STRING, value=str(_uuid.uuid4()))
            return Literal(
                type=INT64, value=_uuid.uuid4().int & ((1 << 62) - 1)
            )
        if op in ("format", "inet_ntoa", "export_set", "make_set"):
            # constant-foldable presentation builtins (value-dependent
            # string output cannot ride a static dictionary over columns)
            args_l = [self.lower(a) for a in e.args]
            if all(isinstance(a, Literal) for a in args_l):
                from tidb_tpu.expression.const_builtins import fold_const

                return Literal(
                    type=STRING, value=fold_const(op, [a.value for a in args_l])
                )
            raise PlanError(
                f"{op.upper()} supports constant arguments only (string "
                "results over columns need value-dependent dictionaries)"
            )
        if op in ("addtime", "subtime"):
            a0 = self.lower(e.args[0])
            a1 = self.lower(e.args[1])
            from tidb_tpu.dtypes import time_to_micros

            if isinstance(a0, Literal) and isinstance(a0.value, str):
                from tidb_tpu.dtypes import (
                    DATETIME as _DT, TIME as _TT, datetime_to_micros,
                )

                s0 = a0.value
                if " " in s0.strip() or "T" in s0:
                    a0 = Literal(
                        type=_DT, value=int(datetime_to_micros(s0))
                    )
                else:
                    a0 = Literal(type=_TT, value=int(time_to_micros(s0)))

            if isinstance(a1, Literal) and isinstance(a1.value, str):
                us = int(time_to_micros(a1.value))
            elif isinstance(a1, Literal) and a1.type is not None and a1.type.kind == Kind.TIME:
                us = int(a1.value)
            else:
                raise PlanError(
                    f"{op.upper()} needs a literal time as its second "
                    "argument"
                )
            if op == "subtime":
                us = -us
            return Func(
                op="add_us", args=(a0, Literal(type=INT64, value=us))
            )
        if op == "_collate_ci":
            # utf8mb4_general_ci ~ compare case-folded (explicit COLLATE)
            return Func(op="lower", args=(self.lower(e.args[0]),))
        if op == "_collate_bin":
            # explicit binary COLLATE: wrap in a passthrough whose
            # INFERRED type is collation-free STRING (bind_expr re-types
            # bare ColumnRefs from the schema, so a type-strip on the
            # ref itself would not survive binding)
            return Func(op="_force_bin", args=(self.lower(e.args[0]),))
        if op == "instr":
            s, sub = (self.lower(x) for x in e.args)
            return Func(op="locate", args=(s, sub))
        if op == "locate":
            sub, s = (self.lower(x) for x in e.args[:2])
            if len(e.args) > 2:
                raise PlanError("LOCATE with start position not supported")
            return Func(op="locate", args=(s, sub))
        if op == "concat_ws":
            # NULL arguments are skipped (not propagated), so this stays
            # a distinct op down to the kernel.
            return Func(op="concat_ws", args=tuple(self.lower(x) for x in e.args))
        if op == "date":
            # DATE(x): truncates DATETIME to its calendar day; identity on
            # DATE (kernel dispatches on the bound argument type)
            return Func(op="date_part_days", args=(self.lower(e.args[0]),))
        if op in ("curdate", "current_date"):
            import datetime

            from tidb_tpu.dtypes import DATE as _DATE, date_to_days

            return Literal(
                type=_DATE, value=int(date_to_days(datetime.date.today().isoformat()))
            )
        if op in ("now", "current_timestamp", "sysdate", "localtimestamp"):
            import datetime

            from tidb_tpu.dtypes import DATETIME as _DT, datetime_to_micros

            return Literal(
                type=_DT,
                value=int(
                    datetime_to_micros(
                        datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S")
                    )
                ),
            )
        if op in ("curtime", "current_time"):
            import datetime

            from tidb_tpu.dtypes import TIME as _TIME, time_to_micros

            return Literal(
                type=_TIME,
                value=int(
                    time_to_micros(datetime.datetime.now().strftime("%H:%M:%S"))
                ),
            )
        if op == "utc_date":
            import datetime

            from tidb_tpu.dtypes import DATE as _DATE, date_to_days

            return Literal(
                type=_DATE,
                value=int(date_to_days(
                    datetime.datetime.now(datetime.timezone.utc)
                    .date().isoformat()
                )),
            )
        if op == "utc_time":
            import datetime

            from tidb_tpu.dtypes import TIME as _TIME, time_to_micros

            return Literal(
                type=_TIME,
                value=int(time_to_micros(
                    datetime.datetime.now(datetime.timezone.utc)
                    .strftime("%H:%M:%S")
                )),
            )
        if op == "timestamp" and len(e.args) == 1:
            # TIMESTAMP(x): cast to DATETIME
            from tidb_tpu.dtypes import DATETIME as _DT

            return Func(
                op="cast", args=(self.lower(e.args[0]),), type=_DT
            )
        if op == "maketime" and len(e.args) == 3:
            consts = [self._const_arg(a) for a in e.args]
            if any(c is None for c in consts):
                raise PlanError("MAKETIME supports constant arguments only")
            from tidb_tpu.dtypes import TIME as _TIME

            h, m, sec = (int(c.value) for c in consts)
            sign = -1 if h < 0 else 1
            total = abs(h) * 3600 + m * 60 + sec
            return Literal(type=_TIME, value=sign * total * 1_000_000)
        if op == "get_format" and len(e.args) == 2:
            kind = str(getattr(e.args[0], "column", e.args[0])).lower()
            if isinstance(e.args[0], ast.Const):
                kind = str(e.args[0].value).lower()
            elif isinstance(e.args[0], ast.Name):
                kind = e.args[0].column.lower()
            loc = (
                str(e.args[1].value).lower()
                if isinstance(e.args[1], ast.Const) else "iso"
            )
            fmts = {
                ("date", "iso"): "%Y-%m-%d", ("date", "usa"): "%m.%d.%Y",
                ("date", "eur"): "%d.%m.%Y", ("date", "jis"): "%Y-%m-%d",
                ("date", "internal"): "%Y%m%d",
                ("time", "iso"): "%H:%i:%s", ("time", "usa"): "%h:%i:%s %p",
                ("time", "eur"): "%H.%i.%s", ("time", "jis"): "%H:%i:%s",
                ("time", "internal"): "%H%i%s",
                ("datetime", "iso"): "%Y-%m-%d %H:%i:%s",
                ("datetime", "usa"): "%Y-%m-%d %H.%i.%s",
                ("datetime", "eur"): "%Y-%m-%d %H.%i.%s",
                ("datetime", "jis"): "%Y-%m-%d %H:%i:%s",
                ("datetime", "internal"): "%Y%m%d%H%i%s",
            }
            from tidb_tpu.dtypes import STRING as _S

            v = fmts.get((kind, loc))
            return Literal(type=_S, value=v)
        if op == "to_seconds" and len(e.args) == 1:
            # TO_SECONDS(date) = TO_DAYS * 86400 (date-granular; the
            # DATETIME time-of-day component follows to_days semantics)
            return self.lower(
                ast.Call(
                    "add",
                    [
                        ast.Call(
                            "mul",
                            [ast.Call("to_days", [e.args[0]]),
                             ast.Const(86400)],
                        ),
                        ast.Const(0),
                    ],
                )
            )
        if op == "yearweek" and len(e.args) == 1:
            # YEARWEEK(d) = YEAR*100 + WEEK (mode-0 weeks; boundary
            # weeks where the week belongs to the adjacent year follow
            # WEEK()'s mode-0 result)
            return self.lower(
                ast.Call(
                    "add",
                    [
                        ast.Call("mul", [ast.Call("year", [e.args[0]]),
                                         ast.Const(100)]),
                        ast.Call("week", [e.args[0]]),
                    ],
                )
            )
        if op == "name_const" and len(e.args) == 2:
            return self.lower(e.args[1])
        if op == "time" and len(e.args) == 1:
            from tidb_tpu.dtypes import TIME as _T

            return Func(op="cast", args=(self.lower(e.args[0]),), type=_T)
        from tidb_tpu.expression.miscfuncs import CONST_FNS as _MISC

        if op in _MISC:
            # misc/info/legacy-crypto family (expression/miscfuncs.py):
            # const-folded like the rest of the connector-facing misc
            # functions below. Arguments lower first so nested foldable
            # calls (DECODE(ENCODE(x, p), p)) reduce to Literals.
            vals = []
            for a in e.args:
                c = self._const_arg(a)
                if c is not None:
                    vals.append(c.value)
                    continue
                low = self.lower(a)
                if isinstance(low, Literal):
                    vals.append(low.value)
                    continue
                raise PlanError(
                    f"{op.upper()} supports constant arguments only"
                )
            from tidb_tpu.dtypes import INT64 as _I64, STRING as _S

            fn, kind = _MISC[op]
            # every function in this family NULL-propagates (MySQL misc
            # semantics) — short-circuit so impls skip per-arg checks
            try:
                v = None if any(x is None for x in vals) else fn(*vals)
            except (TypeError, ValueError, ArithmeticError) as ex:
                raise PlanError(
                    f"Incorrect arguments to {op.upper()}: {ex}"
                )
            if kind == "int":
                return Literal(
                    type=_I64, value=None if v is None else int(v)
                )
            return Literal(type=_S, value=None if v is None else str(v))
        if op in ("format_bytes", "format_nano_time", "password"):
            c = self._const_arg(e.args[0]) if e.args else None
            if c is None:
                raise PlanError(f"{op.upper()} supports constant arguments only")
            from tidb_tpu.dtypes import STRING as _S

            v = c.value
            if v is None:
                return Literal(type=_S, value=None)
            if op == "password":
                # deprecated double-SHA1 (*hex) form
                import hashlib as _h

                d = _h.sha1(_h.sha1(str(v).encode()).digest()).hexdigest()
                return Literal(type=_S, value="*" + d.upper())
            units = (
                ["B", "KiB", "MiB", "GiB", "TiB", "PiB"]
                if op == "format_bytes"
                else ["ns", "µs", "ms", "s"]
            )
            step = 1024.0 if op == "format_bytes" else 1000.0
            x = float(v)
            i = 0
            while abs(x) >= step and i < len(units) - 1:
                x /= step
                i += 1
            return Literal(type=_S, value=f"{x:.2f} {units[i]}")
        if op in ("json_array", "json_object"):
            import json as _json

            consts = [self._const_arg(a) for a in e.args]
            if any(c is None for c in consts):
                raise PlanError(
                    f"{op.upper()} supports constant arguments only"
                )
            from tidb_tpu.dtypes import STRING as _S

            vs = [c.value for c in consts]
            if op == "json_array":
                return Literal(type=_S, value=_json.dumps(vs))
            if len(vs) % 2:
                raise PlanError("JSON_OBJECT needs key/value pairs")
            if any(vs[i] is None for i in range(0, len(vs), 2)):
                raise PlanError(
                    "JSON documents may not contain NULL member names"
                )
            return Literal(
                type=_S,
                value=_json.dumps(
                    {str(vs[i]): vs[i + 1] for i in range(0, len(vs), 2)}
                ),
            )
        if op in ("charset", "collation", "coercibility"):
            # pre-binding: argument types are unknown here; report the
            # connection charset like the reference does for the
            # overwhelmingly common string case (connector handshakes
            # SELECT these on literals)
            from tidb_tpu.dtypes import INT64 as _I64, STRING as _S

            a0 = e.args[0] if e.args else None
            is_num = isinstance(a0, ast.Const) and isinstance(
                a0.value, (int, float)
            ) and not isinstance(a0.value, bool)
            if op == "coercibility":
                return Literal(
                    type=_I64, value=4 if isinstance(a0, ast.Const) else 2
                )
            if op == "charset":
                return Literal(
                    type=_S, value="binary" if is_num else "utf8mb4"
                )
            return Literal(
                type=_S, value="binary" if is_num else "utf8mb4_bin"
            )
        args = tuple(self.lower(a) for a in e.args)
        return Func(op=op, args=args)

    @staticmethod
    def _interval_months(iv: ast.Interval) -> Optional[int]:
        """Months for month/year units (calendar-exact path); None for
        day-based units."""
        v = iv.value
        if isinstance(v, ast.Const):
            v = v.value
        v = int(v)
        if iv.unit == "month":
            return v
        if iv.unit == "year":
            return v * 12
        return None

    @staticmethod
    def _interval_days(iv: ast.Interval) -> int:
        v = iv.value
        if isinstance(v, ast.Const):
            v = v.value
        v = int(v)
        if iv.unit == "day":
            return v
        if iv.unit == "week":
            return v * 7
        raise PlanError(f"unsupported interval unit {iv.unit}")

    @staticmethod
    def _interval_micros(iv: ast.Interval):
        """Microseconds for sub-day units (hour/minute/second/microsecond);
        None for day-or-larger units."""
        from tidb_tpu.dtypes import US_PER_SECOND

        v = iv.value
        if isinstance(v, ast.Const):
            v = v.value
        v = int(v)
        scale = {
            "hour": 3600 * US_PER_SECOND,
            "minute": 60 * US_PER_SECOND,
            "second": US_PER_SECOND,
            "microsecond": 1,
        }.get(iv.unit)
        return None if scale is None else v * scale


# ---------------------------------------------------------------------------
# SELECT builder
# ---------------------------------------------------------------------------


def _add_months_host(days: int, months: int) -> int:
    """MySQL ADDDATE month semantics on a days-since-epoch int: exact
    calendar shift with day-of-month clamped to the target month's
    length (1998-03-31 - 1 month = 1998-02-28)."""
    import datetime

    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=days)
    total = d.year * 12 + (d.month - 1) + months
    y, m = divmod(total, 12)
    m += 1
    # clamp to month length via day-1-of-next-month minus one day
    if m == 12:
        nxt = datetime.date(y + 1, 1, 1)
    else:
        nxt = datetime.date(y, m + 1, 1)
    last = (nxt - datetime.timedelta(days=1)).day
    nd = datetime.date(y, m, min(d.day, last))
    return (nd - datetime.date(1970, 1, 1)).days


def _conjuncts(e):
    if isinstance(e, ast.Call) and e.op == "and":
        return _conjuncts(e.args[0]) + _conjuncts(e.args[1])
    f = _factor_dnf(e)
    if f is not None:
        return f
    return [e]


def _disjuncts(e):
    if isinstance(e, ast.Call) and e.op == "or":
        return _disjuncts(e.args[0]) + _disjuncts(e.args[1])
    return [e]


def _factor_dnf(e):
    """Common-conjunct extraction from a disjunction:
    (A and X) or (A and Y) -> [A, (X or Y)]. Surfaces equi-join
    conjuncts buried in every branch of a DNF predicate (TPC-H Q19's
    `p_partkey = l_partkey and ...` repeated per brand-group), so the
    planner sees a hash-joinable key instead of a cross join (reference:
    expression.ExtractFiltersFromDNF, pkg/expression/util.go). Returns
    None when nothing factors."""
    if not (isinstance(e, ast.Call) and e.op == "or"):
        return None
    branches = [_conjuncts_flat(b) for b in _disjuncts(e)]
    if len(branches) < 2:
        return None
    first = branches[0]
    common = [
        c for c in first if all(any(c == d for d in b) for b in branches[1:])
    ]
    if not common:
        return None
    rest_branches = []
    for b in branches:
        rest = [c for c in b if not any(c == k for k in common)]
        rest_branches.append(rest)
    out = list(common)
    if all(rest for rest in rest_branches):
        ors = [_and_all(rest) for rest in rest_branches]
        o = ors[0]
        for nxt in ors[1:]:
            o = ast.Call("or", [o, nxt])
        out.append(o)
    # else: some branch is exactly the common set -> the disjunction is
    # implied by `common` alone (A or (A and X) == A)
    return out


def _conjuncts_flat(e):
    """_conjuncts WITHOUT recursive DNF factoring (cycle guard)."""
    if isinstance(e, ast.Call) and e.op == "and":
        return _conjuncts_flat(e.args[0]) + _conjuncts_flat(e.args[1])
    return [e]


def _and_all(cs):
    out = cs[0]
    for c in cs[1:]:
        out = ast.Call("and", [out, c])
    return out


def _ast_columns(e, out: set):
    """Collect (table, column) names referenced by a parser expression."""
    if isinstance(e, ast.Name):
        out.add((e.table.lower() if e.table else None, e.column.lower()))
    elif isinstance(e, ast.Call):
        for a in e.args:
            _ast_columns(a, out)
    elif isinstance(e, ast.AggCall):
        if e.arg is not None:
            _ast_columns(e.arg, out)
    elif isinstance(e, ast.SubqueryExpr):
        if e.lhs is not None:
            _ast_columns(e.lhs, out)
        # correlated references inside subquery are handled separately
    elif isinstance(e, ast.Interval):
        pass
    return out


# per-thread stack of views currently being inlined (cycle/depth guard)
_VIEW_EXPANSION = threading.local()


def qualify_view_body(node, db: str, cte_names: frozenset = frozenset()):
    """Attach an explicit db qualifier to every bare TableRef in a view
    body, so the stored SELECT text resolves identically no matter which
    database the referencing session is in (scalar subqueries execute
    through the session executor against the session's CURRENT db —
    qualifiers anchor them to the view's db). CTE names are tracked
    scope-aware: a WITH's names shadow tables only inside that WITH's
    subtree, not across the whole body."""
    if isinstance(node, ast.With):
        inner = cte_names | {name.lower() for name, _q in node.ctes}
        for _name, q in node.ctes:
            qualify_view_body(q, db, inner)
        qualify_view_body(node.body, db, inner)
        return
    if isinstance(node, ast.TableRef):
        if node.db is None and node.name.lower() not in cte_names:
            node.db = db
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            qualify_view_body(getattr(node, f.name), db, cte_names)
    elif isinstance(node, (list, tuple)):
        for x in node:
            qualify_view_body(x, db, cte_names)


class SelectBuilder:
    """Builds a logical plan for one SELECT. ``ctes`` maps CTE names to
    their parser ASTs (resolved before catalog tables, like the
    reference's CTE name scope)."""

    def __init__(
        self, catalog, current_db: str, subquery_value_fn=None, ctes=None,
        hints=(),
    ):
        self.catalog = catalog
        self.db = current_db
        # subquery_value_fn(select_ast) -> Literal  (executes scalar subq)
        self.subquery_value_fn = subquery_value_fn
        self.ctes = ctes or {}
        # optimizer hints ((name, (args...)), ...) from /*+ ... */
        # (reference pkg/parser/hintparser.y + planner hint handling)
        self.hints = tuple(hints or ())
        # deterministic per-query naming for decorrelated scalar columns
        # (plan reprs key the jit cache, so names must be parse-stable)
        self._dsq_counter = 0

    # -- FROM --------------------------------------------------------------
    def build_from(self, node) -> LogicalPlan:
        if node is None:
            raise PlanError("SELECT without FROM not planned here")
        if isinstance(node, ast.TableRef):
            if node.db is None and node.name.lower() in self.ctes:
                inner = build_query(
                    self.ctes[node.name.lower()], self.catalog, self.db,
                    self.subquery_value_fn, self.ctes,
                )
                alias = (node.alias or node.name).lower()
                cols = [
                    OutCol(alias, c.name, f"{alias}.{c.name}", c.type)
                    for c in inner.schema
                ]
                return Projection(
                    Schema(cols),
                    inner,
                    [
                        (f"{alias}.{c.name}", ColumnRef(type=c.type, name=c.internal))
                        for c in inner.schema
                    ],
                )
            db = node.db or self.db
            vdef = self.catalog.view_def(db, node.name) if hasattr(
                self.catalog, "view_def"
            ) else None
            if vdef is not None:
                return self._expand_view(db, node, vdef)
            t = self.catalog.table(db, node.name)
            alias = (node.alias or node.name).lower()
            cols = [
                OutCol(alias, n, f"{alias}.{n}", typ)
                for n, typ in t.schema.columns
            ]
            names = [n for n, _ in t.schema.columns]
            if alias in _EXPOSE_ROWID.get():
                # virtual scan-order row handle for multi-table DML
                # (reference: _tidb_rowid, pkg/tablecodec). Only visible
                # inside session-built DML plans, never to star expansion.
                cols.append(OutCol(alias, ROWID_NAME, f"{alias}.{ROWID_NAME}", INT64))
                names.append(ROWID_NAME)
            return Scan(Schema(cols), db, node.name.lower(), alias, names)
        if isinstance(node, ast.SubqueryRef):
            inner = build_query(
                node.query, self.catalog, self.db, self.subquery_value_fn, self.ctes
            )
            alias = node.alias.lower()
            cols = [
                OutCol(alias, c.name, f"{alias}.{c.name}", c.type)
                for c in inner.schema
            ]
            ren = Projection(
                Schema(cols),
                inner,
                [(f"{alias}.{c.name}", ColumnRef(type=c.type, name=c.internal)) for c in inner.schema],
            )
            return ren
        if isinstance(node, ast.Join):
            left = self.build_from(node.left)
            right = self.build_from(node.right)
            schema = Schema(list(left.schema.cols) + list(right.schema.cols))
            if node.kind == "cross" or node.on is None:
                if node.kind in ("left", "full"):
                    raise PlanError(f"{node.kind.upper()} JOIN requires ON")
                return JoinPlan(schema, "cross", left, right, [], None)
            if node.kind == "full":
                return self._build_full_join(left, right, node.on, schema)
            return self._build_join(node.kind, left, right, node.on, schema)
        raise PlanError(f"unsupported FROM clause {node!r}")

    def _expand_view(self, db: str, node, vdef) -> LogicalPlan:
        """Inline a view reference: re-parse the stored SELECT text and
        plan it as a derived table under the view's (aliased) name.
        The body resolves against the VIEW's database and an empty CTE
        scope (a view cannot see the outer statement's CTEs), mirroring
        the reference's BuildDataSourceFromView
        (pkg/planner/core/logical_plan_builder.go). A thread-local
        expansion stack rejects definition cycles that OR REPLACE can
        introduce after creation."""
        from tidb_tpu.parser.sqlparse import parse as _parse

        sql_text, vcols = vdef
        key = f"{db.lower()}.{node.name.lower()}"
        stack = getattr(_VIEW_EXPANSION, "stack", None)
        if stack is None:
            stack = _VIEW_EXPANSION.stack = []
        if key in stack:
            raise PlanError(f"view {key} is recursively defined")
        if len(stack) >= 16:
            raise PlanError("view nesting too deep (limit 16)")
        stack.append(key)
        try:
            stmts = _parse(sql_text)
            qualify_view_body(stmts[0], db)
            inner = build_query(
                stmts[0], self.catalog, db, self.subquery_value_fn, None
            )
        finally:
            stack.pop()
        alias = (node.alias or node.name).lower()
        names = (
            list(vcols) if vcols else [c.name for c in inner.schema]
        )
        if len(names) != len(inner.schema.cols):
            raise PlanError(
                f"view {key} declares {len(names)} columns but its "
                f"SELECT yields {len(inner.schema.cols)}"
            )
        cols = [
            OutCol(alias, n, f"{alias}.{n}", c.type)
            for n, c in zip(names, inner.schema)
        ]
        return Projection(
            Schema(cols),
            inner,
            [
                (f"{alias}.{n}", ColumnRef(type=c.type, name=c.internal))
                for n, c in zip(names, inner.schema)
            ],
        )

    def _build_full_join(self, left, right, on, schema):
        """FULL OUTER JOIN as LEFT JOIN ∪ (right ANTI left with NULL
        left columns). The reference emits both-unmatched rows from one
        hash join via its joiner strategies (pkg/executor/join/joiner.go);
        on TPU the two branches are two fused static-shape programs and
        the union is a concat — no per-row emit state machine. ON must be
        pure equi-conjuncts (single-side ON predicates gate matching
        without filtering rows, which the rewrite can't express)."""
        lj = self._build_join("left", left, right, on, schema)
        if lj.residual is not None or lj.left is not left or lj.right is not right:
            raise PlanError(
                "FULL OUTER JOIN supports only equality ON conditions "
                "between the two sides"
            )
        anti_keys = [(r, l) for (l, r) in lj.equi_keys]
        aj = JoinPlan(right.schema, "anti", right, left, anti_keys)
        nl = len(left.schema.cols)
        ucols, exprs_l, exprs_a = [], [], []
        for i, c in enumerate(schema.cols):
            ucols.append(OutCol(c.qualifier, c.name, f"_u{i}", c.type))
            exprs_l.append((f"_u{i}", ColumnRef(type=c.type, name=c.internal)))
            exprs_a.append(
                (
                    f"_u{i}",
                    Literal(type=c.type, value=None)
                    if i < nl
                    else ColumnRef(type=c.type, name=c.internal),
                )
            )
        psch = Schema(
            [
                OutCol(None, f"_u{i}", f"_u{i}", c.type)
                for i, c in enumerate(schema.cols)
            ]
        )
        return UnionAll(
            Schema(ucols),
            [Projection(psch, lj, exprs_l), Projection(psch, aj, exprs_a)],
        )

    def _apply_join_hints(self, left, right, bcast):
        """BROADCAST_JOIN(alias): force-replicate the named side;
        NO_BROADCAST_JOIN(): force hash repartition. Unknown hints are
        ignored (MySQL warns-and-continues)."""
        if not self.hints:
            return bcast
        lq = {(c.qualifier or "").lower() for c in left.schema}
        rq = {(c.qualifier or "").lower() for c in right.schema}
        for name, args in self.hints:
            if name == "no_broadcast_join":
                return None
            if name == "broadcast_join":
                for a in args:
                    a = a.lower()
                    if a in rq:
                        return "right"
                    if a in lq:
                        return "left"
        return bcast

    def _build_join(self, kind, left, right, on, schema) -> JoinPlan:
        lq = {(c.qualifier or "").lower() for c in left.schema}
        rq = {(c.qualifier or "").lower() for c in right.schema}

        def side_of(e) -> Optional[str]:
            cols = _ast_columns(e, set())
            quals = set()
            for tbl, col in cols:
                if tbl is not None:
                    quals.add("l" if tbl in lq else ("r" if tbl in rq else "?"))
                else:
                    inl = inr = False
                    try:
                        left.schema.resolve(None, col)
                        inl = True
                    except PlanError:
                        pass
                    try:
                        right.schema.resolve(None, col)
                        inr = True
                    except PlanError:
                        pass
                    if inl and inr:
                        quals.add("?")
                    elif inl:
                        quals.add("l")
                    elif inr:
                        quals.add("r")
                    else:
                        quals.add("?")
            if quals <= {"l"}:
                return "l"
            if quals <= {"r"}:
                return "r"
            return None

        equi: List[Tuple[Expr, Expr]] = []
        residual: List = []
        pushd_l: List = []
        pushd_r: List = []
        lb = ExprBinder(left.schema)
        rb = ExprBinder(right.schema)
        for c in _conjuncts(on):
            if isinstance(c, ast.Call) and c.op == "eq":
                s0, s1 = side_of(c.args[0]), side_of(c.args[1])
                if s0 == "l" and s1 == "r":
                    equi.append((lb.bind(c.args[0]), rb.bind(c.args[1])))
                    continue
                if s0 == "r" and s1 == "l":
                    equi.append((lb.bind(c.args[1]), rb.bind(c.args[0])))
                    continue
            s = side_of(c)
            if kind == "inner" and s == "l":
                pushd_l.append(c)
                continue
            if s == "r" and kind in ("inner", "left"):
                # left join: right-only ON conjunct filters the build side
                pushd_r.append(c)
                continue
            residual.append(c)

        if pushd_l:
            pred = _and_all(pushd_l)
            left = Selection(left.schema, left, ExprBinder(left.schema).bind(pred))
        if pushd_r:
            pred = _and_all(pushd_r)
            right = Selection(right.schema, right, ExprBinder(right.schema).bind(pred))
        schema = Schema(list(left.schema.cols) + list(right.schema.cols))
        if not equi:
            if kind == "inner":
                res = ExprBinder(schema).bind(on) if residual else None
                return JoinPlan(schema, "cross", left, right, [], res)
            raise PlanError("non-equi LEFT JOIN not supported")
        res_bound = ExprBinder(schema).bind(_and_all(residual)) if residual else None
        # cost-based broadcast pick (outer joins may only replicate the
        # build side — the probe side must stay sharded)
        from tidb_tpu.planner import cardinality as C

        smap = C.StatsMap()
        smap.cols.update(C.gather_stats(left, self.catalog).cols)
        smap.cols.update(C.gather_stats(right, self.catalog).cols)
        el = C.est_rows(left, self.catalog, smap)
        er = C.est_rows(right, self.catalog, smap)
        bcast = _broadcast_choice(el, er)
        bcast = self._apply_join_hints(left, right, bcast)
        if kind != "inner" and bcast == "left":
            bcast = None
        return JoinPlan(schema, kind, left, right, equi, res_bound, broadcast=bcast)


def _and_all(conj: List):
    e = conj[0]
    for c in conj[1:]:
        e = ast.Call("and", [e, c])
    return e


def build_query(
    stmt, catalog, current_db: str, subquery_value_fn=None, ctes=None
) -> LogicalPlan:
    """Top-level query lowering: SELECT | UNION | WITH."""
    if isinstance(stmt, ast.With):
        merged = dict(ctes or {})
        for name, q in stmt.ctes:
            merged[name] = q
        if subquery_value_fn is not None:
            # Scalar subqueries under this WITH run through the session
            # executor in a fresh build; inject the CTE scope so they can
            # reference the views (e.g. TPC-H Q15's max over the CTE).
            inner_fn = subquery_value_fn

            def subquery_value_fn(q, _ctes=None, _inner=inner_fn, _m=merged):
                return _inner(q, _ctes if _ctes is not None else _m)

        return build_query(stmt.body, catalog, current_db, subquery_value_fn, merged)
    if isinstance(stmt, ast.Union):
        return _build_union(stmt, catalog, current_db, subquery_value_fn, ctes)
    if isinstance(stmt, ast.SetOp):
        return _build_setop(stmt, catalog, current_db, subquery_value_fn, ctes)
    return build_select(stmt, catalog, current_db, subquery_value_fn, ctes)


def _build_setop(so: ast.SetOp, catalog, db, subquery_value_fn, ctes) -> LogicalPlan:
    """INTERSECT / EXCEPT (DISTINCT set semantics) via the group-by
    kernel: tag each side, union, group by every column counting the
    side tags, filter. NULLs group together (SQL set semantics treats
    NULL rows as equal — the claim-loop group kernel already does),
    which a join-based rewrite would get wrong. Reference:
    pkg/parser grammar setOpr + the executor's hash-based set ops."""
    from tidb_tpu.dtypes import INT64 as _I64, common_type

    plans = [
        build_query(so.left, catalog, db, subquery_value_fn, ctes),
        build_query(so.right, catalog, db, subquery_value_fn, ctes),
    ]
    arity = len(plans[0].schema.cols)
    if len(plans[1].schema.cols) != arity:
        raise PlanError(f"{so.op.upper()} branches have different column counts")
    names = [c.name for c in plans[0].schema.cols]
    targets = []
    for i in range(arity):
        t = plans[0].schema.cols[i].type
        u_t = plans[1].schema.cols[i].type
        targets.append(t if u_t == t else common_type(t, u_t))
    children = []
    for side, p in enumerate(plans):
        exprs = []
        for i, tgt in enumerate(targets):
            c = p.schema.cols[i]
            ref = ColumnRef(type=c.type, name=c.internal)
            e: Expr = ref if c.type == tgt else Func(type=tgt, op="cast", args=(ref,))
            exprs.append((f"_u{i}", e))
        exprs.append(("_sl", Literal(type=_I64, value=1 if side == 0 else 0)))
        exprs.append(("_sr", Literal(type=_I64, value=0 if side == 0 else 1)))
        sch = Schema(
            [OutCol(None, names[i], f"_u{i}", targets[i]) for i in range(arity)]
            + [OutCol(None, "_sl", "_sl", _I64), OutCol(None, "_sr", "_sr", _I64)]
        )
        children.append(Projection(sch, p, exprs))
    u_schema = children[0].schema
    plan: LogicalPlan = UnionAll(u_schema, children)
    groups = [
        (f"_u{i}", ColumnRef(type=targets[i], name=f"_u{i}"))
        for i in range(arity)
    ]
    aggs = [
        ("_cl", "sum", ColumnRef(type=_I64, name="_sl"), False),
        ("_cr", "sum", ColumnRef(type=_I64, name="_sr"), False),
    ]
    agg_schema = Schema(
        [OutCol(None, names[i], f"_u{i}", targets[i]) for i in range(arity)]
        + [OutCol(None, "_cl", "_cl", _I64), OutCol(None, "_cr", "_cr", _I64)]
    )
    plan = Aggregate(agg_schema, plan, groups, aggs)
    zero = Literal(type=_I64, value=0)
    left_present = Func(
        type=None, op="gt", args=(ColumnRef(type=_I64, name="_cl"), zero)
    )
    right_cond = Func(
        type=None,
        op="gt" if so.op == "intersect" else "eq",
        args=(ColumnRef(type=_I64, name="_cr"), zero),
    )
    pred = Func(type=None, op="and", args=(left_present, right_cond))
    from tidb_tpu.expression.expr import bind_expr

    pred = bind_expr(pred, agg_schema.types())
    plan = Selection(agg_schema, plan, pred)
    out_schema = Schema(
        [OutCol(None, names[i], f"_u{i}", targets[i]) for i in range(arity)]
    )
    plan = Projection(
        out_schema, plan,
        [(f"_u{i}", ColumnRef(type=targets[i], name=f"_u{i}")) for i in range(arity)],
    )
    if so.order_by:
        ob = ExprBinder(out_schema)
        keys = []
        for oi in so.order_by:
            e = oi.expr
            if isinstance(e, ast.Const) and isinstance(e.value, int):
                e = ast.Name(None, names[e.value - 1])
            keys.append((ob.bind(e), oi.desc))
        plan = Sort(out_schema, plan, keys)
    if so.limit is not None:
        plan = Limit(out_schema, plan, so.limit, so.offset or 0)
    return plan


def _build_union(u: ast.Union, catalog, db, subquery_value_fn, ctes) -> LogicalPlan:
    from tidb_tpu.dtypes import common_type

    plans = [build_query(s, catalog, db, subquery_value_fn, ctes) for s in u.selects]
    arity = len(plans[0].schema.cols)
    for p in plans[1:]:
        if len(p.schema.cols) != arity:
            raise PlanError("UNION branches have different column counts")
    names = [c.name for c in plans[0].schema.cols]
    targets = []
    for i in range(arity):
        t = plans[0].schema.cols[i].type
        for p in plans[1:]:
            u_t = p.schema.cols[i].type
            if u_t != t:
                t = common_type(t, u_t)
        targets.append(t)
    children = []
    for p in plans:
        exprs = []
        for i, tgt in enumerate(targets):
            c = p.schema.cols[i]
            ref = ColumnRef(type=c.type, name=c.internal)
            e: Expr = ref if c.type == tgt else Func(type=tgt, op="cast", args=(ref,))
            exprs.append((f"_u{i}", e))
        sch = Schema([OutCol(None, names[i], f"_u{i}", targets[i]) for i in range(arity)])
        children.append(Projection(sch, p, exprs))
    out_schema = Schema(
        [OutCol(None, names[i], f"_u{i}", targets[i]) for i in range(arity)]
    )
    plan: LogicalPlan = UnionAll(out_schema, children)
    if not u.all:
        plan = Aggregate(
            out_schema,
            plan,
            [(f"_u{i}", ColumnRef(type=targets[i], name=f"_u{i}")) for i in range(arity)],
            [],
        )
        # rename group keys back to _u names: Aggregate outputs use the
        # given key names, which are already _u{i}
    if u.order_by:
        ob = ExprBinder(out_schema)
        keys = []
        for oi in u.order_by:
            e = oi.expr
            if isinstance(e, ast.Const) and isinstance(e.value, int):
                e = ast.Name(None, names[e.value - 1])
            keys.append((ob.bind(e), oi.desc))
        plan = Sort(out_schema, plan, keys)
    if u.limit is not None:
        plan = Limit(out_schema, plan, u.limit, u.offset or 0)
    return plan


def _expr_has_modifier_subq(e) -> bool:
    if isinstance(e, ast.SubqueryExpr):
        return e.modifier is not None
    if isinstance(e, ast.Call):
        return any(_expr_has_modifier_subq(a) for a in e.args)
    if isinstance(e, ast.AggCall) and e.arg is not None:
        return _expr_has_modifier_subq(e.arg)
    return False


def _rewrite_derived_aggs(sel) -> None:
    """AST-level expansion of derived aggregates (reference: the
    var/stddev aggfuncs, pkg/executor/aggfuncs/func_varpop.go et al —
    there incremental accumulators, here algebraic rewrites over
    SUM/COUNT so the whole family rides the existing kernels):

      VAR_POP(x)    -> sum(x*x)/n - (sum(x)/n)^2
      VAR_SAMP(x)   -> (sum(x*x) - sum(x)^2/n) / (n-1)
      STDDEV_POP(x) -> sqrt(var_pop)   STDDEV_SAMP -> sqrt(var_samp)
      ANY_VALUE(x)  -> x when ungrouped, first-per-group when grouped

    n=0 (and n-1=0 for the sample forms) divides by zero, which is SQL
    NULL — matching MySQL's NULL over empty/singleton groups."""
    var_funcs = {
        "variance": "pop", "var_pop": "pop", "var_samp": "samp",
        "std": "pop_sqrt", "stddev": "pop_sqrt",
        "stddev_pop": "pop_sqrt", "stddev_samp": "samp_sqrt",
    }
    # grouped = explicit GROUP BY or implicit one-group aggregation
    # (ANY_VALUE(a) alongside COUNT(*) must aggregate, like MySQL)
    has_other_agg = [False]

    def scan(node):
        if isinstance(node, (ast.Select, ast.Union, ast.SubqueryExpr)):
            return
        if isinstance(node, ast.AggCall) and node.func not in (
            "any_value",
        ):
            has_other_agg[0] = True
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            for f in dataclasses.fields(node):
                scan(getattr(node, f.name))
        elif isinstance(node, (list, tuple)):
            for x in node:
                scan(x)

    for it in sel.items:
        scan(it.expr)
    if sel.having is not None:
        scan(sel.having)
    grouped = bool(sel.group_by) or has_other_agg[0]

    def rw(node):
        if isinstance(node, (ast.Select, ast.Union, ast.SubqueryExpr)):
            # subqueries rewrite against their OWN group-by context
            # when they are planned
            return node
        if isinstance(node, ast.AggCall) and node.func in var_funcs:
            kind = var_funcs[node.func]
            x = rw(node.arg)
            d = node.distinct
            sx = ast.AggCall("sum", x, d)
            sxx = ast.AggCall("sum", ast.Call("mul", [x, x]), d)
            n = ast.AggCall("count", x, d)
            if kind.startswith("pop"):
                mean = ast.Call("div", [sx, n])
                v = ast.Call(
                    "sub",
                    [ast.Call("div", [sxx, n]),
                     ast.Call("mul", [mean, mean])],
                )
            else:
                v = ast.Call(
                    "div",
                    [ast.Call(
                        "sub",
                        [sxx, ast.Call("div", [ast.Call("mul", [sx, sx]), n])],
                    ),
                     ast.Call("sub", [n, ast.Const(1)])],
                )
            if kind.endswith("sqrt"):
                # clamp tiny negative rounding residue before sqrt
                v = ast.Call("sqrt", [ast.Call("greatest", [v, ast.Const(0)])])
            return v
        if isinstance(node, ast.AggCall) and node.func == "any_value":
            inner = rw(node.arg)
            return (
                ast.AggCall("first", inner, False) if grouped else inner
            )
        if isinstance(node, ast.Call) and node.op == "any_value" and node.args:
            inner = rw(node.args[0])
            return (
                ast.AggCall("first", inner, False) if grouped else inner
            )
        if (
            dataclasses.is_dataclass(node)
            and not isinstance(node, type)
            and not node.__dataclass_params__.frozen  # SQLType et al
        ):
            for f in dataclasses.fields(node):
                setattr(node, f.name, rw(getattr(node, f.name)))
            return node
        if isinstance(node, list):
            return [rw(x) for x in node]
        if isinstance(node, tuple):
            return tuple(rw(x) for x in node)
        return node

    for it in sel.items:
        it.expr = rw(it.expr)
    if sel.having is not None:
        sel.having = rw(sel.having)
    if sel.order_by:
        sel.order_by = rw(list(sel.order_by))


def build_select(
    sel: ast.Select, catalog, current_db: str, subquery_value_fn=None, ctes=None
) -> LogicalPlan:
    """Full SELECT lowering: FROM -> WHERE (with pushdown + IN/EXISTS to
    semi/anti joins) -> AGG -> HAVING -> additive projection -> SORT ->
    LIMIT -> final projection."""
    # HAVING with IN/EXISTS subqueries: wrap as a derived table so the
    # subquery conjuncts run through the ordinary WHERE machinery over
    # the aggregated output (reference: HAVING lowers to a Selection
    # above the aggregation either way; the wrap reuses semi/mark joins
    # instead of a post-agg special case). Conjuncts must reference
    # select-list aliases, as MySQL HAVING requires for outer scoping.
    _rewrite_derived_aggs(sel)
    if sel.having is not None and _expr_has_modifier_subq(sel.having):
        subq_conjs, plain_conjs = [], []
        for c in _conjuncts(sel.having):
            (subq_conjs if _expr_has_modifier_subq(c) else plain_conjs).append(c)
        inner = dataclasses.replace(
            sel,
            having=_and_all(plain_conjs) if plain_conjs else None,
            order_by=[], limit=None, offset=None,
        )
        outer = ast.Select(
            items=[ast.SelectItem(ast.Star())],
            from_=ast.SubqueryRef(inner, "_hv"),
            where=_and_all(subq_conjs),
            order_by=sel.order_by, limit=sel.limit, offset=sel.offset,
        )
        return build_select(outer, catalog, current_db, subquery_value_fn, ctes)
    b = SelectBuilder(
        catalog, current_db, subquery_value_fn, ctes,
        hints=getattr(sel, "hints", ()),
    )

    if sel.from_ is None:
        plan = OneRow(Schema([]))
    else:
        plan = b.build_from(sel.from_)

    # ---- WHERE ----
    if sel.where is not None and not isinstance(plan, OneRow):
        plan = _apply_where(b, plan, sel.where, subquery_value_fn, catalog, current_db)
    elif sel.where is not None:
        binder0 = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
        plan = Selection(plan.schema, plan, binder0.bind(sel.where))

    # ---- IN/EXISTS in value positions -> mark joins ----
    if not isinstance(plan, OneRow):
        _mk_counter = [0]
        new_items = []
        changed = False
        for it in sel.items:
            if isinstance(it.expr, ast.Star) or isinstance(it.expr, ast.Name):
                new_items.append(it)
                continue
            e2, plan = attach_value_subqueries(
                b, plan, it.expr, subquery_value_fn, catalog, current_db,
                _mk_counter,
            )
            if e2 is not it.expr:
                it = dataclasses.replace(it, expr=e2)
                changed = True
            new_items.append(it)
        if changed:
            sel = dataclasses.replace(sel, items=new_items)

    # ---- aggregate detection ----
    agg_calls: List[ast.AggCall] = []

    def find_aggs(e):
        if isinstance(e, ast.AggCall):
            agg_calls.append(e)
        elif isinstance(e, ast.Call):
            for a in e.args:
                find_aggs(a)
        elif isinstance(e, ast.WindowCall):
            # `sum(sum(x)) over (...)`: the inner AggCall forces grouping
            if e.arg is not None:
                find_aggs(e.arg)
            for p in e.partition_by:
                find_aggs(p)
            for oi in e.order_by:
                find_aggs(oi.expr)

    # expand stars first
    items: List[ast.SelectItem] = []
    for it in sel.items:
        if isinstance(it.expr, ast.Star):
            for c in plan.schema:
                if c.name == ROWID_NAME:
                    continue  # DML row handles are never star-visible
                if it.expr.table is None or (c.qualifier or "").lower() == it.expr.table.lower():
                    items.append(
                        ast.SelectItem(ast.Name(c.qualifier, c.name), None)
                    )
            continue
        items.append(it)

    for it in items:
        find_aggs(it.expr)
    if sel.having is not None:
        find_aggs(sel.having)
    for oi in sel.order_by:
        find_aggs(oi.expr)

    grouped = bool(sel.group_by) or bool(agg_calls)

    # resolve GROUP BY ordinals / aliases
    group_by = []
    for g in sel.group_by:
        if isinstance(g, ast.Const) and isinstance(g.value, int):
            idx = g.value - 1
            if not 0 <= idx < len(items):
                raise PlanError(f"GROUP BY position {g.value} out of range")
            group_by.append(items[idx].expr)
        elif isinstance(g, ast.Name) and g.table is None:
            alias_match = next(
                (it.expr for it in items if (it.alias or "").lower() == g.column.lower()),
                None,
            )
            group_by.append(alias_match if alias_match is not None else g)
        else:
            group_by.append(g)

    if grouped:
        plan, rewrite = _build_aggregate(
            b, plan, group_by, agg_calls,
            rollup=bool(getattr(sel, "rollup", False)),
        )
    else:
        rewrite = {}

    # ---- window functions (after aggregation, reference WindowExec) ----
    win_calls: List[ast.WindowCall] = []

    def find_wins(e):
        if isinstance(e, ast.WindowCall):
            win_calls.append(e)
        elif isinstance(e, ast.Call):
            for a in e.args:
                find_wins(a)

    for it in items:
        find_wins(it.expr)
    if win_calls:
        plan = _build_windows(plan, win_calls, rewrite)

    binder = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))

    def lower_item(e):
        e2 = _rewrite_aggs(e, rewrite) if rewrite else e
        return binder.bind(e2)

    # ---- additive projection: select outputs + hidden order keys ----
    out_names: List[str] = []
    proj_exprs: List[Tuple[str, Expr]] = []
    display: List[str] = []
    used = set()
    for i, it in enumerate(items):
        disp = it.alias or _display_name(it.expr)
        name = disp.lower()
        if name in used:
            name = f"{name}#{i}"
        used.add(name)
        bound = lower_item(it.expr)
        proj_exprs.append((name, bound))
        out_names.append(name)
        display.append(disp)

    # schema after additive projection: child cols + outputs
    add_cols = list(plan.schema.cols) + [
        OutCol(None, n, n, e.type) for n, e in proj_exprs
    ]
    # select aliases shadow child columns of the same bare name for ORDER BY
    proj = Projection(Schema(add_cols), plan, proj_exprs, additive=True)

    out_schema = Schema([OutCol(None, n, n, e.type) for n, e in proj_exprs])

    # ---- HAVING (after projection so select aliases are in scope) ----
    if sel.having is not None:
        hb = ExprBinder(
            LayeredSchema(out_schema, plan.schema), _scalar_subq(subquery_value_fn)
        )
        h = _rewrite_aggs(sel.having, rewrite) if rewrite else sel.having
        proj = Selection(proj.schema, proj, hb.bind(h))

    # ---- DISTINCT (group-by over outputs; applies before ORDER BY) ----
    if sel.distinct:
        dk = [(n, ColumnRef(type=e.type, name=n)) for n, e in proj_exprs]
        plan = Aggregate(out_schema, proj, dk, [])
        sort_schema = LayeredSchema(out_schema)
    else:
        plan = proj
        sort_schema = LayeredSchema(out_schema, plan.child.schema if isinstance(plan, Projection) else plan.schema)

    # ---- ORDER BY ----
    if sel.order_by:
        ob = ExprBinder(sort_schema, _scalar_subq(subquery_value_fn))
        keys = []
        for oi in sel.order_by:
            e = oi.expr
            if isinstance(e, ast.Const) and isinstance(e.value, int):
                e = ast.Name(None, out_names[e.value - 1])
            e2 = _rewrite_aggs(e, rewrite) if rewrite else e
            bound = ob.bind(e2)
            # per-column collation drives ORDER BY: a CI-collated string
            # key sorts by its dense collation rank (collate.go Key()
            # semantics), not by binary dictionary order
            if (
                bound.type is not None
                and bound.type.kind == Kind.STRING
                and bound.type.collation is not None
            ):
                from tidb_tpu.utils import collate as _coll

                if not _coll.is_binary(bound.type.collation):
                    from tidb_tpu.dtypes import INT64 as _I64

                    bound = Func(
                        op="_collation_rank", args=(bound,), type=_I64
                    )
            keys.append((bound, oi.desc))
        plan = Sort(plan.schema, plan, keys)

    # ---- LIMIT ----
    if sel.limit is not None:
        plan = Limit(plan.schema, plan, sel.limit, sel.offset or 0)

    # ---- final projection to the select list ----
    final_cols = [
        OutCol(None, disp, n, e.type)
        for disp, (n, e) in zip(display, proj_exprs)
    ]
    plan = Projection(
        Schema(final_cols),
        plan,
        [(n, ColumnRef(type=e.type, name=n)) for n, e in proj_exprs],
    )
    # aggregation pushdown through joins + post-agg selection sinking
    # (reference rule_aggregation_push_down.go; exactness conditions in
    # _try_push_agg) — before pruning so the narrowed sides prune harder
    plan = push_aggs_through_joins(plan, catalog)
    plan = sink_selections(plan)
    # column pruning over the finished tree (reference columnPruner)
    plan = prune_plan(plan, {c.internal for c in plan.schema.cols}, catalog)
    return plan


def _rebuild_children(plan: LogicalPlan, fn) -> LogicalPlan:
    """Apply fn to every direct child plan, rebuilding the node."""
    if isinstance(plan, (Scan, OneRow, Staged)):
        return plan
    if isinstance(plan, JoinPlan):
        return dataclasses.replace(plan, left=fn(plan.left), right=fn(plan.right))
    if isinstance(plan, UnionAll):
        return dataclasses.replace(plan, children=[fn(c) for c in plan.children])
    if hasattr(plan, "child"):
        return dataclasses.replace(plan, child=fn(plan.child))
    return plan


def _key_unique_on(plan: LogicalPlan, key_internals, catalog) -> bool:
    """True when `plan` provably yields at most one row per distinct
    value tuple of key_internals: a PK / public unique index on a scan
    (looked through Selections and renaming Projections), or an
    Aggregate whose full group-key set is covered. The join-side
    uniqueness proof behind aggregation pushdown (reference:
    rule_aggregation_push_down.go checkAnyCountAndSum preconditions)."""
    keys = list(key_internals)
    p = plan
    while True:
        if isinstance(p, Selection):
            p = p.child  # filtering can't break uniqueness
            continue
        if isinstance(p, Projection):
            m = {
                n: e.name for n, e in p.exprs if isinstance(e, ColumnRef)
            }
            nxt = []
            for k in keys:
                if k in m:
                    nxt.append(m[k])
                elif p.additive:
                    nxt.append(k)
                else:
                    return False
            keys = nxt
            p = p.child
            continue
        break
    if isinstance(p, Aggregate):
        gnames = {n for n, _ in p.group_exprs}
        return bool(gnames) and gnames.issubset(set(keys))
    if not isinstance(p, Scan):
        return False
    cols = []
    pre = f"{p.alias}."
    for k in keys:
        if not k.startswith(pre):
            return False
        cols.append(k[len(pre):])
    try:
        t = catalog.table(p.db, p.table)
    except Exception:
        return False
    pk = t.schema.primary_key
    if pk and set(pk).issubset(cols):
        return True
    for iname in getattr(t, "unique_indexes", ()):
        if hasattr(t, "index_state") and t.index_state(iname) != "public":
            continue
        icols = t.indexes.get(iname) or []
        if icols and set(icols).issubset(cols):
            return True
    return False


def _try_push_agg(agg: Aggregate, catalog) -> Optional[LogicalPlan]:
    """Aggregate over inner Join -> Join over Aggregate, EXACTLY, when:
      1. every agg argument references one join side only (the push
         side), and gc_meta is absent;
      2. every group expr references the push side, or is a ColumnRef
         equal (via an equi key) to a push-side key column;
      3. every push-side equi key appears among the (rewritten) group
         exprs — all rows of a group share one join key; and
      4. the other side is provably unique on its equi-key tuple — each
         group matches at most one row, so no contribution duplicates.
    Under 3+4 the join becomes a per-group existence filter + column
    extension, which commutes with the aggregation (including count(*):
    per-group joined-row count == push-side row count). Reference:
    rule_aggregation_push_down.go (TiDB pushes a PARTIAL agg and
    re-aggregates; with the uniqueness proof the single aggregate is
    exact, which suits whole-plan XLA compilation better)."""
    j = agg.child
    if (
        not isinstance(j, JoinPlan)
        or j.kind != "inner"
        or j.residual is not None
        or j.null_aware
        or j.mark_name is not None
        or not j.equi_keys
        or agg.gc_meta
    ):
        return None
    if not all(
        isinstance(l, ColumnRef) and isinstance(r, ColumnRef)
        for l, r in j.equi_keys
    ):
        return None
    from tidb_tpu.expression.expr import walk_columns

    left_names = {c.internal for c in j.left.schema.cols}
    right_names = {c.internal for c in j.right.schema.cols}
    arg_cols: set = set()
    for _n, _f, a, _d in agg.aggs:
        if a is not None:
            arg_cols |= walk_columns(a)
    if arg_cols and arg_cols.issubset(left_names):
        sides = ["left"]
    elif arg_cols and arg_cols.issubset(right_names):
        sides = ["right"]
    elif not arg_cols:
        sides = ["left", "right"]  # COUNT(*)-only: either side may work
    else:
        return None

    for side in sides:
        push, other = (j.left, j.right) if side == "left" else (j.right, j.left)
        push_names = left_names if side == "left" else right_names
        pairs = [
            ((l, r) if side == "left" else (r, l)) for l, r in j.equi_keys
        ]  # (push key, other key)
        other_to_push = {ok.name: pk for pk, ok in pairs}
        new_groups = []
        ok = True
        for n, g in agg.group_exprs:
            gcols = walk_columns(g)
            if gcols.issubset(push_names):
                new_groups.append((n, g))
            elif isinstance(g, ColumnRef) and g.name in other_to_push:
                new_groups.append((n, other_to_push[g.name]))
            else:
                ok = False
                break
        if not ok:
            continue
        gmap = {
            g.name: n for n, g in new_groups if isinstance(g, ColumnRef)
        }
        if not all(pk.name in gmap for pk, _ok2 in pairs):
            continue
        if not _key_unique_on(other, [okk.name for _pk, okk in pairs], catalog):
            continue

        agg_cols = []
        agg_types = {c.internal: c.type for c in agg.schema.cols}
        for n, g in new_groups:
            agg_cols.append(OutCol(None, n, n, g.type))
        for n, _f, _a, _d in agg.aggs:
            agg_cols.append(OutCol(None, n, n, agg_types[n]))
        new_agg = Aggregate(Schema(agg_cols), push, new_groups, agg.aggs)
        new_keys = []
        for pk, okk in pairs:
            kref = ColumnRef(type=pk.type, name=gmap[pk.name])
            new_keys.append(
                (kref, okk) if side == "left" else (okk, kref)
            )
        nl, nr = (new_agg, other) if side == "left" else (other, new_agg)
        # broadcast choice reset: side sizes changed fundamentally
        return JoinPlan(
            Schema(list(nl.schema.cols) + list(nr.schema.cols)),
            "inner", nl, nr, new_keys, None,
        )
    return None


def _push_agg_cascade(agg: Aggregate, catalog) -> Optional[LogicalPlan]:
    """Push once, then re-try the pushed Aggregate against ITS join
    child — multi-join chains (fact ⨝ dim1 ⨝ dim2) push all the way
    down when every hop satisfies the exactness conditions."""
    pushed = _try_push_agg(agg, catalog)
    if pushed is None:
        return None
    for side in ("left", "right"):
        child = getattr(pushed, side)
        if isinstance(child, Aggregate):
            deeper = _push_agg_cascade(child, catalog)
            if deeper is not None:
                return dataclasses.replace(pushed, **{side: deeper})
    return pushed


def push_aggs_through_joins(plan: LogicalPlan, catalog) -> LogicalPlan:
    plan = _rebuild_children(
        plan, lambda c: push_aggs_through_joins(c, catalog)
    )
    if isinstance(plan, Aggregate):
        pushed = _push_agg_cascade(plan, catalog)
        if pushed is not None:
            return pushed
    return plan


def sink_selections(plan: LogicalPlan) -> LogicalPlan:
    """Post-build selection sinking: a Selection lands as low as its
    column footprint allows — through additive Projections and to one
    side of an inner join (the HAVING-below-join shape that aggregation
    pushdown exposes). WHERE conjuncts already sank during FROM build;
    this pass covers predicates created above joins afterwards."""
    plan = _rebuild_children(plan, sink_selections)
    if not isinstance(plan, Selection):
        return plan
    from tidb_tpu.expression.expr import walk_columns

    pred_cols = walk_columns(plan.predicate)
    child = plan.child
    if isinstance(child, Projection) and child.additive:
        produced = {n for n, _ in child.exprs}
        if not (pred_cols & produced):
            inner = sink_selections(
                Selection(child.child.schema, child.child, plan.predicate)
            )
            return Projection(
                child.schema, inner, child.exprs, child.additive
            )
    if isinstance(child, JoinPlan) and child.kind == "inner":
        left_names = {c.internal for c in child.left.schema.cols}
        right_names = {c.internal for c in child.right.schema.cols}
        if pred_cols and pred_cols.issubset(left_names):
            nl = sink_selections(
                Selection(child.left.schema, child.left, plan.predicate)
            )
            return dataclasses.replace(child, left=nl)
        if pred_cols and pred_cols.issubset(right_names):
            nr = sink_selections(
                Selection(child.right.schema, child.right, plan.predicate)
            )
            return dataclasses.replace(child, right=nr)
    return plan


_SUBST_KINDS = {Kind.INT, Kind.BOOL, Kind.DATE, Kind.DATETIME, Kind.TIME}


def _try_join_narrow(plan, required, catalog):
    """Inner-join demotion / outer-join elimination at prune time
    (reference rule_join_elimination.go + the semi-join side of
    rule_semi_join_rewrite.go, applied in reverse): when one join side
    is provably unique on its equi-key tuple and the parent consumes
    NOTHING from it beyond those key columns, the join exists only to
    filter (inner) or for nothing at all (left outer):

      inner -> semi: the kept side's rows that match survive exactly
        once either way; parent references to the dropped side's key
        columns are satisfied by the kept side's key exprs (equal by
        the join predicate — restricted to exact-equality kinds so the
        substituted VALUE is identical, not merely comparing equal).
      left -> eliminated entirely when the parent consumes nothing from
        the inner side: every probe row survives exactly once.

    Returns a replacement plan (not yet pruned) or None. The payoff is
    architectural, not just planner cosmetics: a semi join compiles to
    one existence scatter + mask where inner-unique builds a row table
    and gathers the build key at every probe position (Q18's post-
    agg-pushdown join; Q5's region hop)."""
    if (
        plan.residual is not None
        or plan.null_aware
        or plan.mark_name is not None
        or not plan.equi_keys
        or catalog is None
        or not all(
            isinstance(l, ColumnRef) and isinstance(r, ColumnRef)
            for l, r in plan.equi_keys
        )
    ):
        return None
    lcols = {c.internal for c in plan.left.schema.cols}
    rcols = {c.internal for c in plan.right.schema.cols}
    sides = (
        ("right", "left") if plan.kind == "inner"
        else ("right",) if plan.kind == "left"
        else ()
    )
    for drop_side in sides:
        drop, keep = (
            (plan.right, plan.left) if drop_side == "right"
            else (plan.left, plan.right)
        )
        drop_names = rcols if drop_side == "right" else lcols
        pairs = [
            ((r, l) if drop_side == "right" else (l, r))
            for l, r in plan.equi_keys
        ]  # (dropped key, kept key)
        dkey_names = {d.name for d, _k in pairs}
        needed = {n for n in required if n in drop_names}
        if not needed <= dkey_names:
            continue
        if plan.kind == "left" and needed:
            continue  # NULL-extended rows would expose the substitution
        if needed and not all(
            d.type.kind == k.type.kind and d.type.kind in _SUBST_KINDS
            for d, k in pairs
        ):
            continue
        if not _key_unique_on(drop, [d.name for d, _k in pairs], catalog):
            continue
        if plan.kind == "left":
            return keep  # == plan.left
        if drop_side == "right":
            semi = JoinPlan(
                plan.left.schema, "semi", plan.left, plan.right,
                list(plan.equi_keys),
                broadcast="right" if plan.broadcast == "right" else None,
            )
        else:
            semi = JoinPlan(
                plan.right.schema, "semi", plan.right, plan.left,
                [(r, l) for l, r in plan.equi_keys],
                broadcast="right" if plan.broadcast == "left" else None,
            )
        if not needed:
            return semi
        alias = [
            (d.name, ColumnRef(type=k.type, name=k.name))
            for d, k in pairs
            if d.name in needed
        ]
        sch = Schema(
            list(semi.schema.cols)
            + [OutCol(None, n, n, e.type) for n, e in alias]
        )
        return Projection(sch, semi, alias, additive=True)
    return None


def prune_plan(plan: LogicalPlan, required: set, catalog=None) -> LogicalPlan:
    """Column pruning (reference rule columnPruner, optimizer.go:98):
    walk top-down with the set of internal names the parent needs; scans
    read only referenced columns. With a catalog, unique-side joins the
    parent doesn't otherwise consume narrow to semi joins or disappear
    (_try_join_narrow)."""
    from tidb_tpu.expression.expr import walk_columns

    if isinstance(plan, Scan):
        keep = [
            n for n in plan.columns if f"{plan.alias}.{n}" in required
        ] or plan.columns[:1]  # keep one column for row count
        cols = [c for c in plan.schema.cols if c.name in keep]
        return Scan(Schema(cols), plan.db, plan.table, plan.alias, keep)
    if isinstance(plan, Selection):
        need = set(required) | walk_columns(plan.predicate)
        child = prune_plan(plan.child, need, catalog)
        return Selection(child.schema, child, plan.predicate)
    if isinstance(plan, Projection):
        exprs = [(n, e) for n, e in plan.exprs if n in required] or plan.exprs[:1]
        need = set()
        for _n, e in exprs:
            need |= walk_columns(e)
        if plan.additive:
            produced = {n for n, _ in plan.exprs}
            need |= {r for r in required if r not in produced}
        child = prune_plan(plan.child, need, catalog)
        sch = Schema([c for c in plan.schema.cols if c.internal in required or c.internal in {n for n, _ in exprs}])
        return Projection(sch, child, exprs, plan.additive)
    if isinstance(plan, Aggregate):
        need = set()
        for _n, e in plan.group_exprs:
            need |= walk_columns(e)
        for _n, _f, a, _d in plan.aggs:
            if a is not None:
                need |= walk_columns(a)
        for _sep, obs in (plan.gc_meta or {}).values():
            for e, _desc in obs:
                need |= walk_columns(e)
        child = prune_plan(plan.child, need, catalog)
        return dataclasses.replace(plan, child=child)
    if isinstance(plan, JoinPlan):
        narrowed = _try_join_narrow(plan, required, catalog)
        if narrowed is not None:
            return prune_plan(narrowed, required, catalog)
        lcols = {c.internal for c in plan.left.schema.cols}
        rcols = {c.internal for c in plan.right.schema.cols}
        lneed = {r for r in required if r in lcols}
        rneed = {r for r in required if r in rcols}
        for le, re_ in plan.equi_keys:
            lneed |= walk_columns(le)
            rneed |= walk_columns(re_)
        if plan.residual is not None:
            res_cols = walk_columns(plan.residual)
            lneed |= res_cols & lcols
            rneed |= res_cols & rcols
        left = prune_plan(plan.left, lneed, catalog)
        right = prune_plan(plan.right, rneed, catalog)
        if plan.kind in ("semi", "anti"):
            sch = left.schema
        elif plan.kind == "mark":
            sch = Schema(
                list(left.schema.cols)
                + [c for c in plan.schema.cols if c.internal == plan.mark_name]
            )
        else:
            sch = Schema(list(left.schema.cols) + list(right.schema.cols))
        return JoinPlan(
            sch, plan.kind, left, right, plan.equi_keys, plan.residual,
            plan.null_aware, plan.broadcast, plan.mark_name,
        )
    if isinstance(plan, Sort):
        need = set(required)
        for e, _d in plan.keys:
            need |= walk_columns(e)
        child = prune_plan(plan.child, need, catalog)
        return Sort(child.schema, child, plan.keys)
    if isinstance(plan, Window):
        need = {r for r in required if not r.startswith("_w")}
        for e in plan.partition_exprs:
            need |= walk_columns(e)
        for e, _d in plan.order_exprs:
            need |= walk_columns(e)
        for _n, _f, a, _o, _r, _fr in plan.descs:
            if a is not None:
                need |= walk_columns(a)
        child = prune_plan(plan.child, need, catalog)
        return Window(
            plan.schema, child, plan.partition_exprs, plan.order_exprs, plan.descs
        )
    if isinstance(plan, Limit):
        child = prune_plan(plan.child, required, catalog)
        return Limit(child.schema, child, plan.count, plan.offset)
    if isinstance(plan, UnionAll):
        # children always produce the full _u column set (positional union)
        all_u = {c.internal for c in plan.schema.cols}
        children = [prune_plan(c, all_u, catalog) for c in plan.children]
        return UnionAll(plan.schema, children)
    return plan


def _display_name(e) -> str:
    if isinstance(e, ast.Name):
        return e.column
    if isinstance(e, ast.AggCall):
        inner = "*" if e.arg is None else _display_name(e.arg)
        d = "distinct " if e.distinct else ""
        return f"{e.func}({d}{inner})"
    if isinstance(e, ast.Const):
        return repr(e.value)
    if isinstance(e, ast.Call):
        return f"{e.op}(...)" if len(e.args) > 2 else e.op
    return "expr"


def _scalar_subq(subquery_value_fn):
    if subquery_value_fn is None:
        return None

    def run(e: ast.SubqueryExpr):
        if e.modifier is None:
            return subquery_value_fn(e.query)
        if e.modifier in ("exists", "not exists"):
            # uncorrelated EXISTS in a scalar position (e.g. tableless
            # SELECT): COUNT over a derived table keeps GROUP BY /
            # HAVING / LIMIT semantics
            from tidb_tpu.dtypes import BOOL as _BOOL

            cnt_q = ast.Select(
                items=[
                    ast.SelectItem(ast.AggCall("count", None), alias="_c")
                ],
                from_=ast.SubqueryRef(
                    dataclasses.replace(e.query, order_by=[]), "_ex"
                ),
            )
            n = subquery_value_fn(cnt_q).value
            hit = (n or 0) > 0
            return Literal(
                type=_BOOL, value=hit if e.modifier == "exists" else not hit
            )
        raise PlanError(
            "IN/EXISTS subquery not supported in this position"
        )

    return run


def _apply_where(b, plan, where, subquery_value_fn, catalog, db):
    """Split WHERE conjuncts: IN/EXISTS subqueries become semi/anti
    joins; conjuncts containing a correlated scalar subquery are
    decorrelated into a left join on the correlation keys (reference
    decorrelateSolver, optimizer.go:98-123); plain predicates run
    through cross-join elimination (ppdSolver + joinReOrderSolver):
    single-relation conjuncts sink onto their relation, eq-conjuncts
    linking two relations of a comma-join become inner-join keys, the
    rest filter on top."""
    plain: List = []
    subq: List = []
    corr_scalar: List = []
    for c in _conjuncts(where):
        if isinstance(c, ast.SubqueryExpr) and c.modifier in ("in", "not in", "exists", "not exists"):
            subq.append(c)
        elif isinstance(c, ast.Call) and c.op == "not" and isinstance(c.args[0], ast.SubqueryExpr):
            sq = c.args[0]
            mod = {"in": "not in", "exists": "not exists"}[sq.modifier]
            subq.append(ast.SubqueryExpr(sq.query, mod, sq.lhs))
        elif any(
            _is_correlated(s.query, plan.schema, b)
            for s in _scalar_subqs_in(c, [])
        ):
            corr_scalar.append(c)
        else:
            plain.append(c)
    if plain:
        plan = _reorder_joins(plan, plain, subquery_value_fn, catalog)
    for c in subq:
        plan = _subquery_semijoin(b, plan, c, subquery_value_fn, catalog, db)
    for c in corr_scalar:
        plan = _decorrelate_scalar(b, plan, c, subquery_value_fn, catalog, db)
    return plan


def _flatten_cross(p: LogicalPlan) -> List[LogicalPlan]:
    if isinstance(p, JoinPlan) and p.kind == "cross" and p.residual is None:
        return _flatten_cross(p.left) + _flatten_cross(p.right)
    return [p]


def _rels_of(conj, rels: List[LogicalPlan]) -> Optional[set]:
    """Which relations a conjunct's columns come from; None if a column
    is unresolvable (shouldn't happen for bound-checked input)."""
    cols = _ast_columns(conj, set())
    out = set()
    for tbl, col in cols:
        found = None
        for i, r in enumerate(rels):
            try:
                r.schema.resolve(tbl, col)
                found = i if found is None else found
                if found != i:
                    # ambiguous across relations: unqualified name in two
                    return None
            except PlanError:
                continue
        if found is None:
            return None
        out.add(found)
    return out


def _broadcast_choice(est_left: float, est_right: float) -> Optional[str]:
    """Mesh exchange pick: broadcast the side small enough that an
    all_gather of it beats an all_to_all of both sides (reference:
    broadcast-vs-shuffle MPP join cost in exhaust_physical_plans.go;
    our threshold plays the role of tidb_broadcast_join_threshold_count)."""
    from tidb_tpu.planner.cardinality import BROADCAST_ROW_LIMIT

    if est_right <= BROADCAST_ROW_LIMIT and est_right * 4 <= est_left:
        return "right"
    if est_left <= BROADCAST_ROW_LIMIT and est_left * 4 <= est_right:
        return "left"
    return None


def _reorder_joins(plan, conjuncts, subquery_value_fn, catalog=None) -> LogicalPlan:
    rels = _flatten_cross(plan)
    if len(rels) == 1:
        binder = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
        return Selection(plan.schema, plan, binder.bind(_and_all(conjuncts)))

    rel_filters: Dict[int, List] = {}
    edges: List[Tuple[int, int, object, object]] = []  # (ri, rj, ast_i, ast_j)
    post: List = []
    for c in conjuncts:
        rs = _rels_of(c, rels)
        if rs is not None and len(rs) == 1:
            rel_filters.setdefault(next(iter(rs)), []).append(c)
            continue
        if (
            isinstance(c, ast.Call)
            and c.op == "eq"
            and rs is not None
            and len(rs) == 2
        ):
            s0 = _rels_of(c.args[0], rels)
            s1 = _rels_of(c.args[1], rels)
            if s0 is not None and s1 is not None and len(s0) == 1 and len(s1) == 1 and s0 != s1:
                edges.append((next(iter(s0)), next(iter(s1)), c.args[0], c.args[1]))
                continue
        post.append(c)

    # sink single-relation filters (predicate pushdown)
    for i, fs in rel_filters.items():
        r = rels[i]
        binder = ExprBinder(r.schema, _scalar_subq(subquery_value_fn))
        rels[i] = Selection(r.schema, r, binder.bind(_and_all(fs)))

    # cost-driven greedy join tree (reference: join reorder consuming
    # cardinality estimates, pkg/planner/core/rule_join_reorder.go +
    # cardinality/selectivity.go): start from the smallest estimated
    # relation; at each step join the connected relation that minimizes
    # the estimated result size. Falls back to structural heuristics
    # when no stats exist (estimates then come from pseudo rates).
    from tidb_tpu.planner import cardinality as C

    smap = C.StatsMap()
    rel_est: Dict[int, float] = {}
    for i, r in enumerate(rels):
        if catalog is not None:
            sub = C.gather_stats(r, catalog)
            smap.cols.update(sub.cols)
    for i, r in enumerate(rels):
        rel_est[i] = (
            C.est_rows(r, catalog, smap) if catalog is not None else 1000.0
        )

    start = min(range(len(rels)), key=lambda i: (rel_est[i], i))
    joined = {start}
    cur = rels[start]
    cur_est = rel_est[start]
    remaining = set(range(len(rels))) - joined
    while remaining:
        # all edges between the joined set and one new relation
        candidates: Dict[int, List[Tuple[object, object]]] = {}
        for (ri, rj, ei, ej) in edges:
            if ri in joined and rj in remaining:
                candidates.setdefault(rj, []).append((ei, ej))
            elif rj in joined and ri in remaining:
                candidates.setdefault(ri, []).append((ej, ei))
        if not candidates:
            nxt = min(remaining, key=lambda i: (rel_est[i], i))
            r = rels[nxt]
            schema = Schema(list(cur.schema.cols) + list(r.schema.cols))
            cur = JoinPlan(schema, "cross", cur, r, [], None)
            cur_est = cur_est * rel_est[nxt]
            joined.add(nxt)
            remaining.discard(nxt)
            continue
        # bind each candidate's keys and estimate its join size; pick min
        bound: Dict[int, List[Tuple[Expr, Expr]]] = {}
        cand_est: Dict[int, float] = {}
        for k, pairs in candidates.items():
            lb = ExprBinder(cur.schema)
            rb = ExprBinder(rels[k].schema)
            keys = [(lb.bind(ei), rb.bind(ej)) for ei, ej in pairs]
            bound[k] = keys
            cand_est[k] = C.est_join(cur_est, rel_est[k], keys, "inner", smap)
        nxt = min(
            candidates,
            key=lambda k: (cand_est[k], -len(candidates[k]), k),
        )
        r = rels[nxt]
        keys = bound[nxt]
        schema = Schema(list(cur.schema.cols) + list(r.schema.cols))
        bcast = _broadcast_choice(cur_est, rel_est[nxt])
        cur = JoinPlan(schema, "inner", cur, r, keys, None, broadcast=bcast)
        cur_est = cand_est[nxt]
        joined.add(nxt)
        remaining.discard(nxt)

    if post:
        binder = ExprBinder(cur.schema, _scalar_subq(subquery_value_fn))
        cur = Selection(cur.schema, cur, binder.bind(_and_all(post)))
    return cur


# -- correlated subquery support (reference: decorrelateSolver +
# expression_rewriter.go semi-join / scalar-agg rewrites) -------------------


def _scalar_subqs_in(e, out: List) -> List:
    """Collect scalar (modifier=None) SubqueryExprs one level deep."""
    if isinstance(e, ast.SubqueryExpr):
        if e.modifier is None:
            out.append(e)
        if e.lhs is not None:
            _scalar_subqs_in(e.lhs, out)
    elif isinstance(e, ast.Call):
        for a in e.args:
            _scalar_subqs_in(a, out)
    return out


def _replace_node(e, target, repl):
    """Rebuild expression AST with the (identity-matched) target node
    replaced."""
    if e is target:
        return repl
    if isinstance(e, ast.Call):
        return ast.Call(e.op, [_replace_node(a, target, repl) for a in e.args], e.cast_type)
    return e


def _has_agg(e) -> bool:
    if isinstance(e, ast.AggCall):
        return True
    if isinstance(e, ast.Call):
        return any(_has_agg(a) for a in e.args)
    return False


def _inner_from_schema(q: ast.Select, b) -> Optional[Schema]:
    if q.from_ is None:
        return None
    cache = getattr(b, "_ifs_cache", None)
    if cache is None:
        cache = b._ifs_cache = {}
    key = id(q)
    if key not in cache:
        inner_b = SelectBuilder(b.catalog, b.db, b.subquery_value_fn, b.ctes)
        cache[key] = inner_b.build_from(q.from_).schema
    return cache[key]


def _is_correlated(q: ast.Select, outer_schema: Schema, b) -> bool:
    """True if q.where references columns resolvable only in the outer
    scope (one level; inner scope shadows outer, standard SQL)."""
    if q.from_ is None or q.where is None:
        return False
    try:
        inner_schema = _inner_from_schema(q, b)
    except PlanError:
        return False
    for tbl, col in _ast_columns(q.where, set()):
        try:
            inner_schema.resolve(tbl, col)
        except PlanError:
            try:
                outer_schema.resolve(tbl, col)
                return True
            except PlanError:
                pass
    return False


def _corr_split(q: ast.Select, outer_schema: Schema, b):
    """Split q.where by correlation.

    Returns (corr_pairs, kept_where, residuals, extra_items):
    corr_pairs is a list of (outer_ast, inner_ast) from conjuncts of the
    form ``inner_expr = outer_expr``; kept_where is the AND of the
    purely inner conjuncts (or None); residuals are the remaining
    correlated conjuncts with their inner column references rewritten to
    ``_cr{j}`` names, and extra_items the (alias, inner Name) pairs the
    subquery must additionally project so those residuals can evaluate
    on the joined row (reference: other-conditions on semi joins,
    joiner.go)."""
    inner_schema = _inner_from_schema(q, b)

    def scope(e) -> str:
        has_inner = has_outer = False
        for tbl, col in _ast_columns(e, set()):
            try:
                inner_schema.resolve(tbl, col)
                has_inner = True
                continue
            except PlanError:
                pass
            try:
                outer_schema.resolve(tbl, col)
                has_outer = True
            except PlanError:
                raise PlanError(f"unknown column {col} in subquery")
        if has_inner and has_outer:
            return "mixed"
        if has_outer:
            return "outer"
        return "inner"  # includes constant-only

    extra_items: List[Tuple[str, ast.Name]] = []
    cr_map: Dict[Tuple[Optional[str], str], str] = {}

    def rewrite_inner(e):
        if isinstance(e, ast.Name):
            try:
                inner_schema.resolve(e.table, e.column)
            except PlanError:
                return e  # outer reference, binds over the joined schema
            key = (e.table.lower() if e.table else None, e.column.lower())
            if key not in cr_map:
                alias = f"_cr{len(cr_map)}"
                cr_map[key] = alias
                extra_items.append((alias, e))
            return ast.Name(None, cr_map[key])
        if isinstance(e, ast.Call):
            return ast.Call(e.op, [rewrite_inner(a) for a in e.args], e.cast_type)
        return e

    corr_pairs: List[Tuple[object, object]] = []
    kept: List = []
    residuals: List = []
    for c in _conjuncts(q.where) if q.where is not None else []:
        if _scalar_subqs_in(c, []) or isinstance(c, ast.SubqueryExpr):
            kept.append(c)  # nested subqueries resolve in their own pass
            continue
        s = scope(c)
        if s == "inner":
            kept.append(c)
            continue
        if isinstance(c, ast.Call) and c.op == "eq":
            s0, s1 = scope(c.args[0]), scope(c.args[1])
            if s0 == "inner" and s1 == "outer":
                corr_pairs.append((c.args[1], c.args[0]))
                continue
            if s0 == "outer" and s1 == "inner":
                corr_pairs.append((c.args[0], c.args[1]))
                continue
        residuals.append(rewrite_inner(c))
    return corr_pairs, (_and_all(kept) if kept else None), residuals, extra_items


def _check_simple_subquery(q: ast.Select, what: str) -> None:
    if q.group_by or q.having or q.order_by or q.limit is not None:
        raise PlanError(
            f"correlated {what} subquery with GROUP BY/HAVING/ORDER/LIMIT "
            "not supported"
        )


def _items_aggregate(q: ast.Select) -> bool:
    return any(
        not isinstance(it.expr, ast.Star) and _has_agg(it.expr)
        for it in q.items
    )


def _empty_group_value(e):
    """Value of an aggregate output expression over an EMPTY group:
    count -> 0, other aggs -> NULL, NULL propagating through arithmetic
    (MySQL scalar-subquery-with-no-rows semantics). Returns None for
    NULL or when the expression can't be folded."""
    if isinstance(e, ast.AggCall):
        return 0 if e.func == "count" else None
    if isinstance(e, ast.Const):
        return e.value
    if isinstance(e, ast.Call):
        args = [_empty_group_value(a) for a in e.args]
        if e.op == "coalesce":
            return next((a for a in args if a is not None), None)
        if any(a is None for a in args):
            return None
        if e.op == "add":
            return args[0] + args[1]
        if e.op == "sub":
            return args[0] - args[1]
        if e.op == "mul":
            return args[0] * args[1]
        if e.op == "div":
            return None if args[1] == 0 else args[0] / args[1]
        if e.op == "neg":
            return -args[0]
    return None


def _bind_corr_keys(ob: "ExprBinder", corr_pairs, inner_cols) -> List[Tuple[Expr, Expr]]:
    return [
        (ob.bind(oe), ColumnRef(type=c.type, name=c.internal))
        for (oe, _ie), c in zip(corr_pairs, inner_cols)
    ]


def _bind_residuals(outer_schema, inner_schema, residuals, subquery_value_fn):
    if not residuals:
        return None
    joined = Schema(list(outer_schema.cols) + list(inner_schema.cols))
    return ExprBinder(joined, _scalar_subq(subquery_value_fn)).bind(
        _and_all(residuals)
    )


def attach_value_subqueries(b, plan, node, subquery_value_fn, catalog, db, counter):
    """Rewrite IN/EXISTS subqueries appearing in VALUE positions (select
    items, CASE conditions, DML WHERE item evaluation) into mark joins:
    the probe keeps every row and gains a boolean (three-valued for IN)
    result column (reference: expression_rewriter.go building
    LeftOuterSemiJoin with a mark). Returns (rewritten ast node, plan).

    Uncorrelated EXISTS folds to a constant. NOT wrappers become NOT of
    the mark — the mark's validity carries the NULL semantics, so the
    3-valued negation is free."""
    if isinstance(node, ast.SubqueryExpr) and node.modifier in (
        "in", "not in", "exists", "not exists",
    ):
        plan, ref = _make_mark(
            b, plan, node, subquery_value_fn, catalog, db, counter
        )
        return ref, plan
    if (
        isinstance(node, ast.SubqueryExpr)
        and node.modifier is None
        and _is_correlated(node.query, plan.schema, b)
    ):
        # correlated SCALAR subquery in a value position: the same
        # agg-pull-up left join as the WHERE path, but the joined value
        # column replaces the expression directly
        plan, ref = _attach_corr_scalar(
            b, plan, node, subquery_value_fn, catalog, db
        )
        return ref, plan
    if isinstance(node, ast.Call):
        new_args = []
        for a in node.args:
            a2, plan = attach_value_subqueries(
                b, plan, a, subquery_value_fn, catalog, db, counter
            )
            new_args.append(a2)
        if new_args != list(node.args):
            node = dataclasses.replace(node, args=new_args)
        return node, plan
    if isinstance(node, ast.AggCall) and node.arg is not None:
        a2, plan = attach_value_subqueries(
            b, plan, node.arg, subquery_value_fn, catalog, db, counter
        )
        if a2 is not node.arg:
            node = dataclasses.replace(node, arg=a2)
        return node, plan
    return node, plan


def _make_mark(b, plan, sq: ast.SubqueryExpr, subquery_value_fn, catalog, db, counter):
    """One IN/EXISTS value-position subquery -> (plan with mark join,
    replacement ast node)."""
    q = sq.query
    negate = sq.modifier in ("not in", "not exists")
    exists = sq.modifier in ("exists", "not exists")
    correlated = _is_correlated(q, plan.schema, b)

    def maybe_not(e):
        return ast.Call("not", [e]) if negate else e

    if exists and not correlated:
        if (
            not q.group_by and _items_aggregate(q)
            and q.having is None and q.limit is None
        ):
            # bare aggregate: always exactly one row
            return plan, ast.Const(not negate)
        if subquery_value_fn is None:
            raise PlanError("EXISTS subquery needs a session context")
        cnt_q = ast.Select(
            items=[ast.SelectItem(ast.AggCall("count", None), alias="_c")],
            from_=ast.SubqueryRef(dataclasses.replace(q, order_by=[]), "_ex"),
        )
        n = subquery_value_fn(cnt_q).value
        return plan, ast.Const(((n or 0) > 0) != negate)

    counter[0] += 1
    mark = f"_mk{counter[0]}"
    from tidb_tpu.dtypes import BOOL as _BOOL

    if exists:
        _check_simple_subquery(q, "EXISTS")
        corr_pairs, kept, residuals, extra = _corr_split(q, plan.schema, b)
        if not corr_pairs or residuals:
            raise PlanError(
                "correlated EXISTS in value position needs exactly "
                "equality correlations"
            )
        inner_q = dataclasses.replace(
            q,
            items=[
                ast.SelectItem(ie, alias=f"_ck{i}")
                for i, (_oe, ie) in enumerate(corr_pairs)
            ],
            where=kept,
            distinct=False,
        )
        inner = build_query(inner_q, catalog, db, subquery_value_fn, b.ctes)
        ob = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
        keys = _bind_corr_keys(ob, corr_pairs, inner.schema.cols)
        three = False
    else:
        if correlated:
            raise PlanError(
                "correlated IN in value position not supported "
                "(rewrite as EXISTS)"
            )
        _check_simple_subquery(q, "IN")
        inner = build_query(q, catalog, db, subquery_value_fn, b.ctes)
        if len(inner.schema.cols) != 1:
            raise PlanError("IN subquery must return one column")
        ob = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
        lhs = ob.bind(sq.lhs)
        c0 = inner.schema.cols[0]
        keys = [(lhs, ColumnRef(type=c0.type, name=c0.internal))]
        three = True
    if len(keys) != 1:
        raise PlanError(
            "value-position subqueries support one correlation key"
        )
    sch = Schema(
        list(plan.schema.cols) + [OutCol(None, mark, mark, _BOOL)]
    )
    plan = JoinPlan(
        sch, "mark", plan, inner, keys,
        null_aware=three, mark_name=mark,
    )
    return plan, maybe_not(ast.Name(None, mark))


def _subquery_semijoin(b, plan, sq: ast.SubqueryExpr, subquery_value_fn, catalog, db):
    """IN/EXISTS (correlated or not) -> semi/anti join (reference:
    decorrelation + semi-join rewrite in expression_rewriter.go)."""
    q = sq.query
    correlated = _is_correlated(q, plan.schema, b)

    if sq.modifier in ("exists", "not exists"):
        if (
            not q.group_by and _items_aggregate(q)
            and q.having is None and q.limit is None
        ):
            # A bare aggregate subquery (no GROUP BY/HAVING/LIMIT)
            # yields exactly one row regardless of its input (even an
            # empty, even a correlated one) -> EXISTS is always true.
            want = sq.modifier == "exists"
            return plan if want else Limit(plan.schema, plan, 0, 0)
        if not correlated:
            # Evaluate once: COUNT(*) over the subquery as a derived table
            # (keeps GROUP BY/HAVING/LIMIT semantics intact).
            if subquery_value_fn is None:
                raise PlanError("EXISTS subquery needs a session context")
            cnt_q = ast.Select(
                items=[ast.SelectItem(ast.AggCall("count", None), alias="_c")],
                from_=ast.SubqueryRef(dataclasses.replace(q, order_by=[]), "_ex"),
            )
            n = subquery_value_fn(cnt_q).value
            hit = (n or 0) > 0
            want = sq.modifier == "exists"
            return plan if hit == want else Limit(plan.schema, plan, 0, 0)
        _check_simple_subquery(q, "EXISTS")
        corr_pairs, kept, residuals, extra = _corr_split(q, plan.schema, b)
        if not corr_pairs:
            raise PlanError(
                "correlated EXISTS needs at least one equality correlation"
            )
        inner_q = dataclasses.replace(
            q,
            items=[
                ast.SelectItem(ie, alias=f"_ck{i}")
                for i, (_oe, ie) in enumerate(corr_pairs)
            ]
            + [ast.SelectItem(ie, alias=al) for al, ie in extra],
            where=kept,
            distinct=False,
        )
        inner = build_query(inner_q, catalog, db, subquery_value_fn, b.ctes)
        ob = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
        keys = _bind_corr_keys(ob, corr_pairs, inner.schema.cols)
        res = _bind_residuals(plan.schema, inner.schema, residuals, subquery_value_fn)
        kind = "semi" if sq.modifier == "exists" else "anti"
        return JoinPlan(plan.schema, kind, plan, inner, keys, res)

    # IN: probe side = plan, build side = inner's single output column
    corr_pairs: List[Tuple[object, object]] = []
    inner_q = q
    if correlated:
        if isinstance(sq.lhs, ast.RowExpr):
            raise PlanError(
                "correlated row-value IN not supported (use EXISTS)"
            )
        if sq.modifier == "not in":
            raise PlanError(
                "correlated NOT IN not supported (use NOT EXISTS)"
            )
        _check_simple_subquery(q, "IN")
        if _items_aggregate(q):
            raise PlanError(
                "aggregate in correlated IN subquery not supported "
                "(rewrite as a comparison with the scalar subquery)"
            )
        corr_pairs, kept, residuals, extra = _corr_split(q, plan.schema, b)
        if len(q.items) != 1:
            raise PlanError("IN subquery must select exactly one column")
        inner_q = dataclasses.replace(
            q,
            items=list(q.items)
            + [
                ast.SelectItem(ie, alias=f"_ck{i}")
                for i, (_oe, ie) in enumerate(corr_pairs)
            ]
            + [ast.SelectItem(ie, alias=al) for al, ie in extra],
            where=kept,
            distinct=False,
        )
    else:
        residuals, extra = [], []
    inner = build_query(inner_q, catalog, db, subquery_value_fn, b.ctes)
    ob = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
    kind = "semi" if sq.modifier == "in" else "anti"
    if isinstance(sq.lhs, ast.RowExpr):
        # (a, b) IN (SELECT x, y ...): one equality key per column
        if corr_pairs:
            raise PlanError("correlated row-value IN not supported")
        if sq.modifier == "not in":
            # row-value NOT IN needs per-column 3-valued NULL handling
            # the multi-key anti join can't express — refuse rather
            # than silently dropping NULL semantics
            raise PlanError(
                "row-value NOT IN is not supported (rewrite as NOT EXISTS)"
            )
        ncols = len(sq.lhs.items)
        if len(inner.schema.cols) != ncols + len(extra):
            raise PlanError("row-value IN subquery arity mismatch")
        keys = [
            (ob.bind(le), ColumnRef(type=c.type, name=c.internal))
            for le, c in zip(sq.lhs.items, inner.schema.cols[:ncols])
        ]
        res = _bind_residuals(
            plan.schema, inner.schema, residuals, subquery_value_fn
        )
        # NOT IN was rejected above: this is always a plain semi join
        return JoinPlan(plan.schema, "semi", plan, inner, keys, res)
    if len(inner.schema.cols) != 1 + len(corr_pairs) + len(extra):
        raise PlanError("IN subquery must select exactly one column")
    lhs_bound = ob.bind(sq.lhs)
    rhs_col = inner.schema.cols[0]
    keys = [(lhs_bound, ColumnRef(type=rhs_col.type, name=rhs_col.internal))]
    keys += _bind_corr_keys(ob, corr_pairs, inner.schema.cols[1 : 1 + len(corr_pairs)])
    res = _bind_residuals(plan.schema, inner.schema, residuals, subquery_value_fn)
    return JoinPlan(
        plan.schema,
        kind,
        plan,
        inner,
        keys,
        res,
        null_aware=(sq.modifier == "not in"),
    )


def _attach_corr_scalar(b, plan, sq, subquery_value_fn, catalog, db):
    """Correlated aggregate scalar subquery -> left join onto the
    grouped-by-correlation-keys derived table. Returns (joined plan,
    replacement ast) — the caller decides whether the value feeds a
    predicate (WHERE) or a projection (value position)."""
    q = sq.query
    _check_simple_subquery(q, "scalar")
    if len(q.items) != 1:
        raise PlanError("scalar subquery must select exactly one column")
    if not _has_agg(q.items[0].expr):
        raise PlanError(
            "correlated scalar subquery must aggregate (else it can "
            "return multiple rows per outer row)"
        )
    corr_pairs, kept, residuals, _extra = _corr_split(q, plan.schema, b)
    if not corr_pairs:
        raise PlanError("correlated scalar subquery has no correlation keys")
    if residuals:
        raise PlanError(
            "correlated scalar subquery supports only equality correlation"
        )
    n = b._dsq_counter
    b._dsq_counter += 1
    ck = [f"_dsq{n}_ck{i}" for i in range(len(corr_pairs))]
    sv = f"_dsq{n}_v"
    derived = ast.Select(
        items=[
            ast.SelectItem(ie, alias=ck[i])
            for i, (_oe, ie) in enumerate(corr_pairs)
        ]
        + [ast.SelectItem(q.items[0].expr, alias=sv)],
        from_=q.from_,
        where=kept,
        group_by=[ie for (_oe, ie) in corr_pairs],
    )
    inner = build_query(derived, catalog, db, subquery_value_fn, b.ctes)
    ob = ExprBinder(plan.schema, _scalar_subq(subquery_value_fn))
    keys = _bind_corr_keys(ob, corr_pairs, inner.schema.cols)
    joined = Schema(list(plan.schema.cols) + list(inner.schema.cols))
    jp = JoinPlan(joined, "left", plan, inner, keys, None)
    # An outer row with no matching group sees the aggregate's
    # empty-group value: NULL for most, but COUNT-driven expressions
    # fold to a non-NULL constant (count()=0) which the left join's NULL
    # must be coalesced to. Safe because such expressions are also
    # never NULL for matching groups.
    ref: object = ast.Name(None, sv)
    empty_v = _empty_group_value(q.items[0].expr)
    if empty_v is not None:
        ref = ast.Call("coalesce", [ref, ast.Const(empty_v)])
    return jp, ref


def _decorrelate_scalar(b, plan, conjunct, subquery_value_fn, catalog, db):
    """``expr CMP (SELECT agg(...) FROM t WHERE t.k = outer.k)`` ->
    left join onto ``SELECT k, agg(...) FROM t GROUP BY k`` and rewrite
    the comparison against the joined value column (reference:
    decorrelateSolver's agg-pull-up, logical Apply -> join conversion).

    An outer row with no matching group sees NULL (COUNT sees 0), which
    matches MySQL's empty-scalar-subquery semantics."""
    subqs = [
        s
        for s in _scalar_subqs_in(conjunct, [])
        if _is_correlated(s.query, plan.schema, b)
    ]
    if len(subqs) != 1:
        raise PlanError("only one correlated scalar subquery per predicate")
    sq = subqs[0]
    orig_schema = plan.schema
    jp, ref = _attach_corr_scalar(b, plan, sq, subquery_value_fn, catalog, db)
    new_pred = _replace_node(conjunct, sq, ref)
    jb = ExprBinder(jp.schema, _scalar_subq(subquery_value_fn))
    sel = Selection(jp.schema, jp, jb.bind(new_pred))
    return Projection(
        orig_schema,
        sel,
        [(c.internal, ColumnRef(type=c.type, name=c.internal)) for c in orig_schema],
    )


def _rewrite_aggs(e, rewrite: Dict):
    """Replace AggCall / WindowCall / group-expr subtrees with references
    to their computed output columns."""
    key = _ast_key(e)
    if key in rewrite:
        name, typ = rewrite[key]
        return ast.Name(None, name)
    if isinstance(e, ast.Call):
        if e.op == "grouping":
            raise PlanError(
                "GROUPING() requires GROUP BY ... WITH ROLLUP and its "
                "argument must be a single group-key expression"
            )
        return ast.Call(e.op, [_rewrite_aggs(a, rewrite) for a in e.args], e.cast_type)
    if isinstance(e, ast.AggCall):
        raise PlanError("aggregate expression not in rewrite map (nested aggs?)")
    if isinstance(e, ast.WindowCall):
        raise PlanError("window expression not in rewrite map")
    return e


def _build_windows(plan, win_calls: List[ast.WindowCall], rewrite: Dict) -> LogicalPlan:
    """Insert one Window node per distinct OVER spec; register outputs in
    the rewrite map (reference: logical window building in
    logical_plan_builder.go buildWindowFunctions)."""
    from tidb_tpu.dtypes import FLOAT64, INT64

    specs: Dict[str, Tuple[ast.WindowCall, List[ast.WindowCall]]] = {}
    order: List[str] = []
    for call in win_calls:
        key = _ast_key(call)
        if key in rewrite:
            continue
        spec_key = repr(call.partition_by) + "||" + repr(call.order_by)
        if spec_key not in specs:
            specs[spec_key] = (call, [])
            order.append(spec_key)
        specs[spec_key][1].append(call)

    widx = 0
    for spec_key in order:
        proto, calls = specs[spec_key]
        binder = ExprBinder(plan.schema)

        def lower(e):
            e2 = _rewrite_aggs(e, rewrite) if rewrite else e
            return binder.bind(e2)

        part_exprs = [lower(p) for p in proto.partition_by]
        order_exprs = [(lower(oi.expr), oi.desc) for oi in proto.order_by]
        running = bool(proto.order_by)
        descs: List[Tuple[str, str, Optional[Expr], int, bool]] = []
        new_cols = list(plan.schema.cols)
        for call in calls:
            key = _ast_key(call)
            if key in rewrite:
                continue
            name = f"_w{widx}"
            widx += 1
            arg = lower(call.arg) if call.arg is not None else None
            if call.func in ("row_number", "rank", "dense_rank", "count", "ntile"):
                t = INT64
            elif call.func in ("avg", "percent_rank", "cume_dist"):
                t = FLOAT64
            elif call.func in (
                "sum", "min", "max", "lag", "lead",
                "first_value", "last_value", "nth_value",
            ):
                if arg is None:
                    raise PlanError(f"{call.func} window needs an argument")
                t = arg.type
            else:
                raise PlanError(f"unsupported window function {call.func}")
            if call.func in (
                "row_number", "rank", "dense_rank", "ntile",
                "percent_rank", "cume_dist",
            ) and not proto.order_by:
                raise PlanError(f"{call.func}() requires ORDER BY in its OVER clause")
            frame = call.frame
            call_running = running
            if (
                frame is not None
                and len(frame) == 3
                and frame[0] == "range"
                and call.func in ("sum", "avg", "count")
            ):
                frame = _encode_range_frame(call, frame, order_exprs)
            if frame is not None:
                if call.func in (
                    "row_number", "rank", "dense_rank", "lag", "lead",
                    "ntile", "percent_rank", "cume_dist",
                ):
                    frame = None  # frame clause is ignored for ranking funcs
                elif call.func in ("first_value", "last_value", "nth_value"):
                    raise PlanError(
                        f"{call.func} with an explicit frame is not "
                        "supported (default framing only)"
                    )
                elif frame == (None, 0):
                    frame, call_running = None, True  # running aggregate
                elif frame == (None, None):
                    frame, call_running = None, False  # whole partition
                elif call.func in ("min", "max"):
                    raise PlanError(
                        "MIN/MAX window frames support only UNBOUNDED "
                        "PRECEDING starts"
                    )
            descs.append((name, call.func, arg, call.offset, call_running, frame))
            rewrite[key] = (name, t)
            new_cols.append(OutCol(None, name, name, t))
        plan = Window(Schema(new_cols), plan, part_exprs, order_exprs, descs)
    return plan


def _encode_range_frame(call, frame, order_exprs):
    """Resolve a parsed RANGE frame against the (single) ORDER BY key:
    numeric offsets scale to the key's physical encoding (DECIMAL scaled
    ints), INTERVAL offsets to days (DATE) or micros (DATETIME/TIME).
    Variable-length units (MONTH/YEAR) are rejected — their width
    depends on the anchor date. Reference: pkg/executor/window.go range
    frame bound evaluation."""
    if call.func not in ("sum", "avg", "count"):
        raise PlanError(
            "RANGE offset frames support SUM/AVG/COUNT aggregates"
        )
    if len(order_exprs) != 1:
        raise PlanError("RANGE offset frames need exactly one ORDER BY key")
    ktype = order_exprs[0][0].type
    if ktype is None:
        raise PlanError("RANGE frame ORDER BY key has no type")

    _US = {
        "microsecond": 1, "second": 1_000_000, "minute": 60_000_000,
        "hour": 3_600_000_000, "day": 86_400_000_000,
        "week": 7 * 86_400_000_000,
    }

    def enc(bound):
        if bound is None or bound == "cur":
            return bound
        tag = bound[0]
        if tag == "num":
            v = float(bound[1])
            if ktype.kind == Kind.DECIMAL:
                return v * 10**ktype.scale
            if ktype.kind in (Kind.INT, Kind.FLOAT):
                return v
            if ktype.kind == Kind.DATE:
                return v  # bare N over a DATE key counts days (MySQL)
            raise PlanError(
                "numeric RANGE offsets need a numeric ORDER BY key"
            )
        _i, n, unit = bound
        if unit not in _US:
            raise PlanError(
                f"RANGE INTERVAL unit {unit!r} is variable-length; "
                "use DAY or smaller"
            )
        if ktype.kind == Kind.DATE:
            if unit not in ("day", "week"):
                raise PlanError("DATE keys take DAY/WEEK RANGE offsets")
            return float(n * (1 if unit == "day" else 7))
        if ktype.kind in (Kind.DATETIME, Kind.TIME):
            return float(n * _US[unit])
        raise PlanError("INTERVAL offsets need a temporal ORDER BY key")

    return ("range", enc(frame[1]), enc(frame[2]))


def _ast_key(e) -> str:
    return repr(e)


def _build_aggregate(b, plan, group_by, agg_calls, rollup=False):
    """Insert Aggregate node; return (plan, rewrite map ast-key ->
    (output internal name, type)). rollup=True (GROUP BY ... WITH
    ROLLUP, reference: pkg/planner/core expand for rollup /
    pkg/executor with TiFlash Expand): the result is the UNION ALL of
    the full grouping plus every group-key prefix, dropped keys
    presented as NULL — each level aggregates the base input
    independently, which is exact for every supported aggregate and
    lets common-subtree sharing compile the shared scan once."""
    binder = ExprBinder(plan.schema)
    rewrite: Dict[str, Tuple[str, SQLType]] = {}
    group_exprs: List[Tuple[str, Expr]] = []
    for i, g in enumerate(group_by):
        bound = binder.bind(g)
        name = f"_g{i}"
        group_exprs.append((name, bound))
        rewrite[_ast_key(g)] = (name, bound.type)

    aggs: List[Tuple[str, str, Optional[Expr], bool]] = []
    seen: Dict[str, str] = {}
    gc_meta: Dict[str, Tuple[str, tuple]] = {}
    from tidb_tpu.dtypes import FLOAT64, DECIMAL, STRING

    for call in agg_calls:
        key = _ast_key(call)
        if key in rewrite:
            continue
        name = f"_a{len(aggs)}"
        arg = binder.bind(call.arg) if call.arg is not None else None
        if call.func == "count":
            t = INT64
        elif call.func == "avg":
            t = FLOAT64
        elif call.func in ("min", "max", "sum", "first"):
            t = arg.type
            if call.func == "sum" and t is not None and t.kind == Kind.BOOL:
                t = INT64  # MySQL: SUM over booleans counts (0/1 ints)
        elif call.func in (
            "group_concat", "json_arrayagg", "json_objectagg"
        ):
            # string-producing aggregates run host-assisted (hostagg.py);
            # json_objectagg carries its KEY expression in the order-by
            # slot (projected alongside, marker separator selects the
            # rendering)
            t = STRING
            gc_meta[name] = (
                call.separator,
                tuple((binder.bind(e), d) for e, d in call.order_by),
            )
        else:
            raise PlanError(f"unsupported aggregate {call.func}")
        aggs.append((name, call.func, arg, call.distinct))
        rewrite[key] = (name, t)

    out_cols = [OutCol(None, n, n, e.type) for n, e in group_exprs]
    for (n, f, a, d) in aggs:
        t = next(t for (nn, t) in rewrite.values() if nn == n)
        out_cols.append(OutCol(None, n, n, t))

    if gc_meta:
        # GROUP_CONCAT runs host-assisted (hostagg.py) which computes
        # every aggregate of the node in one pass — DISTINCT included, so
        # no stacked rewrite
        agg_plan = Aggregate(
            Schema(out_cols), plan, group_exprs, aggs, gc_meta=gc_meta
        )
    elif any(d for (_n, _f, _a, d) in aggs):
        d_args = {repr(a) for (_n, _f, a, d) in aggs if d}
        if len(d_args) > 1:
            # multiple different DISTINCT arguments: the stacked-rewrite
            # trick needs one shared dedup key, so fall through to the
            # kernel's per-agg representative-row dedup
            # (executor/aggregate._distinct_reps)
            agg_plan = Aggregate(Schema(out_cols), plan, group_exprs, aggs)
        else:
            agg_plan = _expand_distinct_aggs(plan, group_exprs, aggs, out_cols)
    else:
        agg_plan = Aggregate(Schema(out_cols), plan, group_exprs, aggs)
    if rollup and group_exprs:
        k = len(group_exprs)
        gnames = {n for n, _g in group_exprs}
        agg_refs = [
            (c.internal, ColumnRef(type=c.type, name=c.internal))
            for c in agg_plan.schema.cols
            if c.internal not in gnames
        ]
        # GROUPING(g): 1 on levels where g was rolled away, 0 where it
        # grouped — a per-child CONSTANT lane, referenced via the
        # rewrite map (reference: GROUPING under rollup expand)
        grp_cols = [
            OutCol(None, f"_grp{i}", f"_grp{i}", INT64) for i in range(k)
        ]
        u_schema = Schema(list(agg_plan.schema.cols) + grp_cols)
        for i, g_ast in enumerate(group_by):
            rewrite[_ast_key(ast.Call("grouping", [g_ast]))] = (
                f"_grp{i}", INT64,
            )

        def grp_lits(level):
            return [
                (
                    f"_grp{i}",
                    Literal(type=INT64, value=0 if i < level else 1),
                )
                for i in range(k)
            ]

        full_exprs = [
            (c.internal, ColumnRef(type=c.type, name=c.internal))
            for c in agg_plan.schema.cols
        ]
        children = [
            Projection(u_schema, agg_plan, full_exprs + grp_lits(k))
        ]
        for j in range(k - 1, -1, -1):
            # the grand-total level grouped by NOTHING would emit one
            # row even over empty input (scalar-aggregate semantics);
            # MySQL returns an empty set for rollup over no rows, so
            # group by a constant instead — zero groups when empty
            sub_groups = group_by[:j] if j else [ast.Const(1)]
            sub, _ = _build_aggregate(b, plan, sub_groups, agg_calls)
            exprs = []
            for i, (n, g) in enumerate(group_exprs):
                exprs.append((
                    n,
                    ColumnRef(type=g.type, name=n)
                    if i < j
                    else Literal(type=g.type, value=None),
                ))
            children.append(
                Projection(u_schema, sub, exprs + agg_refs + grp_lits(j))
            )
        agg_plan = UnionAll(u_schema, children)
    return agg_plan, rewrite


def _expand_distinct_aggs(plan, group_exprs, aggs, out_cols):
    """Rewrite Aggregate-with-DISTINCT into two stacked Aggregates:
    inner groups by (keys, distinct arg) — collapsing duplicates — and
    pre-aggregates the non-distinct functions; the outer re-aggregates.
    The reference evaluates DISTINCT inside each agg function's update
    path (pkg/executor/aggfuncs count_distinct); on TPU a second grouped
    pass is one more fused XLA reduction, so the rewrite is free of
    per-row set probes and reuses the scatter-free group-by kernels.
    """
    from tidb_tpu.dtypes import FLOAT64
    from tidb_tpu.expression.expr import ColumnRef

    d_args = {}
    for (_n, _f, a, d) in aggs:
        if d:
            d_args[repr(a)] = a
    assert len(d_args) == 1, "multi-distinct handled by the kernel path"
    dx = next(iter(d_args.values()))
    dname = "_dx"

    inner_groups = list(group_exprs) + [(dname, dx)]
    inner_aggs: List[Tuple[str, str, Optional[Expr], bool]] = []
    final_aggs: List[Tuple[str, str, Optional[Expr], bool]] = []
    # (out name, Σsum col, Σcount col, arg type) for non-distinct AVGs:
    # re-assembled as a division in a Projection above the outer agg
    avg_fixups: List[Tuple[str, str, str, SQLType]] = []
    for (name, func, arg, d) in aggs:
        if d:
            # duplicates are collapsed by the inner group-by; COUNT/SUM/AVG
            # over the (now unique, NULL-preserving) _dx column give the
            # DISTINCT semantics, NULLs skipped by the agg kernels.
            final_aggs.append((name, func, ColumnRef(dx.type, dname), False))
            continue
        pn = f"_p{len(inner_aggs)}"
        if func == "count":
            inner_aggs.append((pn, "count", arg, False))
            final_aggs.append((name, "sum", ColumnRef(INT64, pn), False))
        elif func in ("sum", "min", "max"):
            inner_aggs.append((pn, func, arg, False))
            final_aggs.append((name, func, ColumnRef(arg.type, pn), False))
        elif func == "avg":
            # AVG across the two stacked aggregates = Σ(partial sums) /
            # Σ(partial counts); the division happens in a Projection on
            # top (the reference's partial/final avg split,
            # pkg/executor/aggfuncs avg partial result)
            cn = f"_p{len(inner_aggs) + 1}"
            inner_aggs.append((pn, "sum", arg, False))
            inner_aggs.append((cn, "count", arg, False))
            fs, fc = f"_fs{name}", f"_fc{name}"
            final_aggs.append((fs, "sum", ColumnRef(arg.type, pn), False))
            final_aggs.append((fc, "sum", ColumnRef(INT64, cn), False))
            avg_fixups.append((name, fs, fc, arg.type))
        else:
            raise PlanError(
                f"{func.upper()} cannot be combined with DISTINCT aggregates"
            )

    inner_cols = [OutCol(None, n, n, e.type) for n, e in inner_groups]
    for (pn, f, a, _d) in inner_aggs:
        t = INT64 if f == "count" else a.type
        inner_cols.append(OutCol(None, pn, pn, t))
    inner = Aggregate(Schema(inner_cols), plan, inner_groups, inner_aggs)

    final_groups = [(n, ColumnRef(e.type, n)) for n, e in group_exprs]
    if not avg_fixups:
        return Aggregate(Schema(out_cols), inner, final_groups, final_aggs)

    outer_cols = [OutCol(None, n, n, e.type) for n, e in final_groups]
    for (n, f, a, _d) in final_aggs:
        t = INT64 if f == "count" else a.type
        outer_cols.append(OutCol(None, n, n, t))
    outer = Aggregate(Schema(outer_cols), inner, final_groups, final_aggs)

    fix = {name: (fs, fc, t) for name, fs, fc, t in avg_fixups}
    proj_exprs: List[Tuple[str, Expr]] = []
    for oc in out_cols:
        if oc.name in fix:
            fs, fc, at = fix[oc.name]
            proj_exprs.append(
                (
                    oc.name,
                    Func(
                        type=FLOAT64,
                        op="div",
                        args=(ColumnRef(at, fs), ColumnRef(INT64, fc)),
                    ),
                )
            )
        else:
            proj_exprs.append((oc.name, ColumnRef(oc.type, oc.name)))
    return Projection(Schema(out_cols), outer, proj_exprs)
