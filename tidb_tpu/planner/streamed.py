"""Streamed (paged) aggregation: the spill analog.

Reference: the engine never requires a table to fit one buffer — blocking
operators spill to disk (pkg/executor/aggregate/agg_spill.go, sortexec
spill, pkg/util/paging/paging.go progressive paging). On TPU the scarce
resource is HBM and the staging medium is host RAM: when an aggregation's
input table exceeds the device tile budget, the pre-aggregation pipeline
(scan -> filter -> project) runs CHUNK BY CHUNK on device, each chunk is
partially aggregated (the same partial/final split the mesh path uses
across devices — here applied across time), only the tiny partial group
rows accumulate on device, and one final aggregation merges them.

The streamed Aggregate's result is injected back into the plan as a
Staged node, and the remainder of the plan (HAVING / ORDER BY / joins
above the aggregate) executes normally — so any plan shape whose large
table feeds an aggregation benefits, not just bare GROUP BY queries.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk import Batch, DevCol, HostBlock, block_to_batch, pad_capacity
from tidb_tpu.executor.aggregate import (
    WIDTH_STALE,
    AggDesc,
    _next_pow2,
    group_aggregate,
)
from tidb_tpu.parallel.fragment import (
    _partial_descs,
    apply_post_avg,
    build_final_stage,
)
from tidb_tpu.planner import logical as L

_STAGED_NONCE = [0]


def _pipeline_below(plan) -> Optional[Tuple[L.Aggregate, list]]:
    """Find the lowest Aggregate whose input subtree is a pure
    scan pipeline (Scan with optional Selection/Projection on top).
    Returns (agg_node, [nodes from agg child down to scan]) or None."""
    found = None

    def walk(p):
        nonlocal found
        for c in _children(p):
            walk(c)
        if found is None and isinstance(p, L.Aggregate):
            chain = []
            cur = p.child
            while isinstance(cur, (L.Selection, L.Projection)):
                chain.append(cur)
                cur = cur.child
            if isinstance(cur, L.Scan):
                chain.append(cur)
                found = (p, chain)

    walk(plan)
    return found


def _children(p):
    out = []
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None:
            out.append(c)
    out.extend(getattr(p, "children", []) or [])
    return out


def _replace_node(plan, target, repl):
    if plan is target:
        return repl
    kw = {}
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is not None:
            kw[attr] = _replace_node(c, target, repl)
    ch = getattr(plan, "children", None)
    if ch:
        kw["children"] = [_replace_node(c, target, repl) for c in ch]
    if not kw:
        return plan
    return dataclasses.replace(plan, **kw)


def _chunk_blocks(table, version, columns, chunk_rows: int):
    """Yield HostBlocks of <= chunk_rows rows over the table's blocks
    (numpy views — no copies until device transfer)."""
    for b in table.blocks(version):
        n = b.nrows
        for a in range(0, n, chunk_rows):
            z = min(a + chunk_rows, n)
            cols = {
                name: dataclasses.replace(
                    c, data=c.data[a:z], valid=c.valid[a:z]
                )
                for name, c in b.columns.items()
                if name in columns
            }
            yield HostBlock(cols, z - a)


def _device_budget() -> int:
    """Device memory available for one query's working set. TPU: the
    runtime reports bytes_limit. CPU backend (tests / fallback): stage
    through host RAM past a fixed 4GB budget."""
    try:
        ms = jax.local_devices()[0].memory_stats()
        if ms and ms.get("bytes_limit"):
            return int(ms["bytes_limit"])
    except Exception:
        pass
    return 4 << 30


def _row_bytes(table, version, columns) -> int:
    """Estimated device bytes per scanned row (data + validity mask)."""
    total = 0
    for b in table.blocks(version):
        for name in columns:
            c = b.columns.get(name)
            total += (c.data.dtype.itemsize if c is not None else 8) + 1
        break
    return max(total, 9)


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class _StreamPlan:
    """Cached compiled artifacts for one streamed plan: the pre-agg
    pipeline + agg descriptors, and jitted chunk/final programs keyed by
    (capacity, tile) so repeated executes and same-shape chunks reuse one
    XLA compilation (the first cut re-built and ran everything eagerly —
    per-op dispatch at 2M rows was ~4x slower than the jitted program)."""

    def __init__(self, pipe_fn, dicts, site, key_fns, key_names, key_widths,
                 partial, final, nonnull=()):
        self.pipe_fn = pipe_fn
        self.dicts = dicts
        self.site = site
        self.nonnull = list(nonnull)
        self.key_fns = key_fns
        self.key_names = key_names
        self.key_widths = key_widths
        self.partial = partial
        self.final = final
        self.jits = {}

    def chunk_step(self, cap: int):
        j = self.jits.get(("partial", cap))
        if j is None:
            def step(chunk, _cap=cap):
                piped, _needs = self.pipe_fn({self.site.node_id: chunk}, {})
                return group_aggregate(
                    piped, self.key_fns, self.partial, _cap, self.key_names,
                    key_widths=self.key_widths,
                )

            j = self.jits[("partial", cap)] = jax.jit(step)
        return j

    def final_step(self, fcap: int):
        j = self.jits.get(("final", fcap))
        if j is None:
            fkeys, fdescs, post_avg = build_final_stage(
                self.key_names, self.final
            )

            def step(combined, _cap=fcap, _keys=fkeys, _descs=fdescs):
                return group_aggregate(
                    combined, _keys, _descs, _cap, self.key_names,
                    key_widths=self.key_widths,
                )

            j = self.jits[("final", fcap)] = (jax.jit(step), post_avg)
        return j


def _stream_plan(executor, plan, agg, conservative=False) -> Optional[_StreamPlan]:
    from tidb_tpu.planner.physical import PlanCompiler, build_agg_parts

    cache = getattr(executor, "_stream_plans", None)
    if cache is None:
        cache = executor._stream_plans = {}
    key = (executor._cache_key(plan), conservative)
    if key in cache:
        return cache[key]
    while len(cache) >= 32:
        cache.pop(next(iter(cache)))
    # compile the pre-aggregation pipeline once; its only input is the
    # scan site, fed one chunk at a time
    comp = PlanCompiler(
        executor.catalog, resolver=executor._resolve,
        conservative=conservative,
    )
    pipe_fn, dicts = comp._build(agg.child)
    entry = None
    if not comp.sized and len(comp.scans) == 1:
        site = comp.scans[0]
        key_fns, key_names, key_widths, descs = build_agg_parts(agg, dicts)
        if not any(a.distinct for a in descs):
            # DISTINCT can't be split into partial sums across chunks
            # (dedup must see all rows of a group at once): run unpaged
            partial, final = _partial_descs(descs)
            entry = _StreamPlan(
                pipe_fn, dicts, site, key_fns, key_names, key_widths,
                partial, final, nonnull=comp.nonnull,
            )
    cache[key] = entry
    return entry


def try_streamed(executor, plan, conservative=False) -> Optional[Tuple[Batch, dict]]:
    """Execute `plan` with a streamed aggregate when it qualifies:
    single-device, lowest Aggregate over a pure scan pipeline, and the
    scanned table too large for the device. stream_rows: -1 = auto
    (stream when the scan working set overruns the device memory
    budget), >0 = explicit row threshold, 0/None = never stream."""
    threshold = getattr(executor, "stream_rows", None)
    if not threshold or executor.mesh is not None:
        return None
    m = _pipeline_below(plan)
    if m is None:
        return None
    agg, chain = m
    scan = chain[-1]
    t, v = executor._resolve(scan.db, scan.table)
    if threshold == -1:
        rb = _row_bytes(t, v, scan.columns)
        budget = _device_budget()
        # ~4x the raw scan: filter/projection intermediates + the
        # double-buffered copy during compaction
        if t.nrows * rb * 4 <= budget:
            return None
        # budget-derived chunk size; the floor is small enough never to
        # override the budget for any plausible row width
        chunk_rows = max(1 << 16, min(1 << 24, _pow2_floor(budget // (4 * rb))))
    else:
        if t.nrows <= threshold:
            return None
        chunk_rows = max(int(threshold), 1)

    from tidb_tpu.planner.physical import StaleWidthsError, agg_out_dicts
    from tidb_tpu.utils.failpoint import inject

    inject("executor/stream-start")
    sp = _stream_plan(executor, plan, agg, conservative=conservative)
    if sp is None:
        return None
    site, key_fns, key_names, key_widths, dicts = (
        sp.site, sp.key_fns, sp.key_names, sp.key_widths, sp.dicts
    )

    for _ in range(8):
        if t.pin_verified(v):
            break
        t, v = executor._resolve(scan.db, scan.table)
    else:
        return None  # snapshot churned away repeatedly: run unpaged
    try:
        # NULL-free folding assumptions must hold at the pinned version
        for _nid, coln in sp.nonnull:
            if t.col_has_nulls(coln, v):
                raise StaleWidthsError()
        # one fixed tile for every chunk: all chunks share one compiled
        # program (the last, shorter chunk pads up to the same tile)
        chunk_tile = pad_capacity(chunk_rows)
        cap = 1024
        partial_batches: List[Batch] = []
        for hb in _chunk_blocks(t, v, site.columns, chunk_rows):
            inject("executor/stream-chunk")
            if executor.kill_check is not None:
                executor.kill_check()
            chunk = block_to_batch(hb, capacity=chunk_tile)
            while True:
                out, ng = sp.chunk_step(cap)(chunk)
                ngi = int(jax.device_get(ng))
                if ngi >= WIDTH_STALE:
                    raise StaleWidthsError()
                # overflow whenever the true group count exceeds the
                # batch the kernel emitted (tile size differs by path:
                # 2x cap for hash tables, 1x for dense compaction)
                if key_fns and ngi > out.capacity:
                    cap = cap * 2  # partial table overflowed: retry bigger
                    continue
                break
            partial_batches.append(out)
    finally:
        t.unpin(v)

    combined = _concat_batches(partial_batches)

    # final merge: shared with the mesh path's final stage (fragment.py)
    fcap = max(cap, 1024)
    while True:
        jfin, post_avg = sp.final_step(fcap)
        fin, ng = jfin(combined)
        ngi = int(jax.device_get(ng))
        if ngi >= WIDTH_STALE:
            raise StaleWidthsError()
        if sp.key_names and ngi > fin.capacity:
            fcap *= 2
            continue
        break

    cols = apply_post_avg(dict(fin.cols), post_avg)
    result = Batch(
        {n: cols[n] for n in [c.internal for c in agg.schema]}, fin.row_valid
    )

    if not key_fns:
        # scalar aggregate over possibly-empty input: ensure one row
        # (COUNT=0, others NULL) like the in-plan aggregation node
        any_group = jnp.any(result.row_valid)
        first = jnp.zeros(result.capacity, dtype=bool).at[0].set(True)
        rv = jnp.where(any_group, result.row_valid, first)
        cols2 = {}
        agg_funcs = {n: f for n, f, _a, _d in agg.aggs}
        for n, c in result.cols.items():
            if agg_funcs.get(n) == "count":
                cols2[n] = DevCol(
                    jnp.where(any_group, c.data, jnp.zeros_like(c.data)),
                    jnp.where(any_group, c.valid, first),
                )
            else:
                cols2[n] = DevCol(
                    c.data, jnp.where(any_group, c.valid, jnp.zeros_like(c.valid))
                )
        result = Batch(cols2, rv)

    _STAGED_NONCE[0] += 1
    staged = L.Staged(
        agg.schema,
        batch=result,
        dicts=agg_out_dicts(agg, dicts),
        nonce=_STAGED_NONCE[0],
    )
    if plan is agg:
        new_plan = staged
    else:
        new_plan = _replace_node(plan, agg, staged)
    return executor.run(new_plan)


def _concat_batches(batches: List[Batch]) -> Batch:
    if len(batches) == 1:
        return batches[0]
    names = list(batches[0].cols)
    cols = {}
    for n in names:
        cols[n] = DevCol(
            jnp.concatenate([b.cols[n].data for b in batches]),
            jnp.concatenate([b.cols[n].valid for b in batches]),
        )
    rv = jnp.concatenate([b.row_valid for b in batches])
    return Batch(cols, rv)
