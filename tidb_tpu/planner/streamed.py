"""Streamed (paged) aggregation: the spill analog.

Reference: the engine never requires a table to fit one buffer — blocking
operators spill to disk (pkg/executor/aggregate/agg_spill.go, sortexec
spill, pkg/util/paging/paging.go progressive paging). On TPU the scarce
resource is HBM and the staging medium is host RAM: when an aggregation's
input table exceeds the device tile budget, the pre-aggregation pipeline
(scan -> filter -> project) runs CHUNK BY CHUNK on device, each chunk is
partially aggregated (the same partial/final split the mesh path uses
across devices — here applied across time), only the tiny partial group
rows accumulate on device, and one final aggregation merges them.

The streamed Aggregate's result is injected back into the plan as a
Staged node, and the remainder of the plan (HAVING / ORDER BY / joins
above the aggregate) executes normally — so any plan shape whose large
table feeds an aggregation benefits, not just bare GROUP BY queries.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk import Batch, DevCol, HostBlock, block_to_batch, pad_capacity
from tidb_tpu.executor.aggregate import (
    WIDTH_STALE,
    AggDesc,
    _next_pow2,
    group_aggregate,
)
from tidb_tpu.parallel.fragment import (
    _partial_descs,
    apply_post_avg,
    build_final_stage,
)
from tidb_tpu.planner import logical as L

_STAGED_NONCE = [0]


def _collect_pipeline_scans(p, scans, flags, chunkable=True) -> bool:
    """Walk a streaming pipeline (Selection/Projection chains over Scans
    composed with equi-joins) collecting (scan, chunkable) pairs.

    A scan is CHUNKABLE when splitting it into row chunks and unioning
    the per-chunk pipeline outputs equals running the whole pipeline:
    either side of an inner join distributes over row-union, but only
    the probe (left) side of left/semi/anti/mark joins does — chunking
    the build side would change unmatched-row semantics per chunk.
    Returns False when the subtree contains anything else (the shape
    doesn't stream)."""
    while isinstance(p, (L.Selection, L.Projection)):
        p = p.child
    if isinstance(p, L.Staged):
        # an already-staged (streamed lower aggregate) result: resident
        # and closed over by the compiled pipeline — a valid leaf, never
        # the chunked side. Lets a SECOND aggregate above a staged one
        # stream too (Q18's outer GROUP BY over the HAVING subquery).
        return True
    if isinstance(p, L.Scan):
        if p.frag is not None:
            # cross-host fragment slices (fragmenter.py) pin their row
            # numbering to the whole-plan fetch path; the streamed
            # re-chunkers don't know the slice and would scan full tables
            return False
        scans.append(p)
        flags.append(chunkable)
        return True
    if isinstance(p, L.JoinPlan):
        if p.kind == "inner":
            return _collect_pipeline_scans(
                p.left, scans, flags, chunkable
            ) and _collect_pipeline_scans(p.right, scans, flags, chunkable)
        if p.kind in ("left", "semi", "anti", "mark"):
            return _collect_pipeline_scans(
                p.left, scans, flags, chunkable
            ) and _collect_pipeline_scans(p.right, scans, flags, False)
    return False


def _pipeline_below(plan) -> Optional[Tuple[L.Aggregate, list, list]]:
    """Find the lowest Aggregate whose input subtree is a streaming
    pipeline. Returns (agg_node, scans, chunkable_flags) or None."""
    found = None

    def walk(p):
        nonlocal found
        for c in _children(p):
            walk(c)
        if found is None and isinstance(p, L.Aggregate):
            scans, flags = [], []
            if _collect_pipeline_scans(p.child, scans, flags) and scans:
                found = (p, scans, flags)

    walk(plan)
    return found


def _pick_big_scan(executor, scans, flags):
    """(index, (table, version) list) of the largest chunkable scan."""
    resolved = [executor._resolve(s.db, s.table) for s in scans]
    big_i = None
    for i, ok in enumerate(flags):
        if ok and (
            big_i is None or resolved[i][0].nrows > resolved[big_i][0].nrows
        ):
            big_i = i
    return big_i, resolved


def _stream_sizing(executor, scans, resolved, big_i, threshold, force=False):
    """(chunk_rows, should_stream, ctx): budget math shared by the agg
    and sort streaming paths. Auto mode streams when the whole working
    set (big scan + resident sides, times an intermediates multiplier)
    overruns the device budget, and sizes chunks from the budget
    REMAINING after the resident sides. Explicit thresholds chunk at
    that row count. `ctx` carries the computed (budget, others_bytes,
    rb) so callers (the device-resident gate) never re-derive them.
    force: stream even when this aggregate's own working set fits —
    the quota-admission retry path, where the WHOLE plan (join tiles
    above this aggregate) blew the budget."""
    t, v = resolved[big_i]
    big = scans[big_i]
    budget = _device_budget()
    # the admission quota (tidb_mem_quota_query) caps the working set
    # below physical memory: streaming must engage at the quota, not at
    # HBM exhaustion, or small-quota queries die at admission instead
    # of spilling (reference: spill triggers on the memory tracker's
    # quota, pkg/executor/aggregate/agg_spill.go)
    q = getattr(executor, "quota_bytes", None)
    if q:
        budget = min(budget, int(q))
    rb = _row_bytes(t, v, big.columns)
    others_bytes = sum(
        ot.nrows * _row_bytes(ot, ov, s.columns)
        for i, (s, (ot, ov)) in enumerate(zip(scans, resolved))
        if i != big_i
    )
    ctx = {"budget": budget, "others_bytes": others_bytes, "rb": rb}
    if others_bytes * 4 > budget:
        # resident join sides don't fit: run unpaged
        return None, False, ctx
    # intermediates multiplier: a single-scan plan (scan->filter->proj->
    # agg, no join sides) keeps only a couple of row-width temporaries
    # live in the fused program. Join plans materialize gathered
    # columns per probe stage — a deep chain (TPC-H Q5's 6-way) peaks
    # far above 4x: the round-5 hardware run at est. 10.6GB against a
    # 13.6GB budget crashed the TPU worker, so join plans hold 6x and
    # stream (device-resident when the raw columns fit) instead of
    # gambling the whole worker on resident execution
    mult = 2 if others_bytes == 0 and len(scans) == 1 else 6
    if threshold == -1 or force:
        if not force and (t.nrows * rb + others_bytes) * mult <= budget:
            return None, False, ctx
        avail = max(budget - 4 * others_bytes, budget // 8)
        chunk_rows = max(1 << 14, min(1 << 24, _pow2_floor(avail // (4 * rb))))
        if force and chunk_rows * rb * 4 > budget:
            # even one minimal chunk overruns the quota: streaming
            # cannot save this query — let admission's rejection stand
            return None, False, ctx
    else:
        if t.nrows <= threshold:
            return None, False, ctx
        chunk_rows = max(int(threshold), 1)
    return chunk_rows, True, ctx


def _fetch_resident(executor, site, st, sv):
    """One resident (non-chunked) site's device batch, honoring PK-range
    pushdown like PhysicalExecutor._fetch_inputs."""
    from tidb_tpu.storage import scan_table

    from tidb_tpu.planner.physical import fetch_site_rows

    narrowed = fetch_site_rows(st, site, sv)
    if narrowed is not None:
        return narrowed
    batch, _d = scan_table(
        st, site.columns, version=sv, partitions=site.partitions
    )
    return batch


def _expr_column_refs(e, out) -> None:
    """Collect ColumnRef names from an expression tree."""
    from tidb_tpu.expression.expr import ColumnRef

    if isinstance(e, ColumnRef):
        out.add(e.name)
        return
    if dataclasses.is_dataclass(e):
        for f in dataclasses.fields(e):
            val = getattr(e, f.name)
            for item in val if isinstance(val, (list, tuple)) else [val]:
                if dataclasses.is_dataclass(item):
                    _expr_column_refs(item, out)


def _children(p):
    out = []
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None:
            out.append(c)
    out.extend(getattr(p, "children", []) or [])
    return out


def _replace_node(plan, target, repl):
    if plan is target:
        return repl
    kw = {}
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is not None:
            kw[attr] = _replace_node(c, target, repl)
    ch = getattr(plan, "children", None)
    if ch:
        kw["children"] = [_replace_node(c, target, repl) for c in ch]
    if not kw:
        return plan
    return dataclasses.replace(plan, **kw)


def _chunk_blocks(table, version, columns, chunk_rows: int, partitions=None):
    """Yield HostBlocks of <= chunk_rows rows over the table's blocks
    (numpy views — no copies until device transfer)."""
    for b in table.blocks(version, partitions=partitions):
        n = b.nrows
        for a in range(0, n, chunk_rows):
            z = min(a + chunk_rows, n)
            cols = {
                name: dataclasses.replace(
                    c, data=c.data[a:z], valid=c.valid[a:z]
                )
                for name, c in b.columns.items()
                if name in columns
            }
            yield HostBlock(cols, z - a)


# HBM per chip by device_kind, for runtimes that don't report
# memory_stats (the axon tunnel returns None). Sized at 85% of physical
# to leave runtime headroom.
_HBM_BY_KIND = {
    "TPU v5 lite": 16 << 30,   # v5e (one core per chip)
    "TPU v4": 32 << 30,        # megacore: one device per chip
    "TPU v4 lite": 8 << 30,    # v4i
    # v2/v3 expose each CORE as a device with half the chip's HBM
    "TPU v3": 16 << 30,
    "TPU v2": 8 << 30,
}


def _device_budget() -> int:
    """Device memory available for one query's working set. TPU: the
    runtime reports bytes_limit; when it doesn't (the tunnel transport
    strips memory_stats), fall back to the chip's known HBM size —
    treating a 16GB v5e as a 4GB device forced SF10 onto the streamed
    path and re-paid the full tunnel h2d on every execute (round-5
    hardware capture: 73.7s/run, ~0.13x). CPU backend (tests /
    fallback): stage through host RAM past a fixed 4GB budget."""
    try:
        from tidb_tpu.utils.backend import is_tpu

        d = jax.local_devices()[0]
        ms = d.memory_stats()
        if ms and ms.get("bytes_limit"):
            return int(ms["bytes_limit"])
        if is_tpu():
            hbm = _HBM_BY_KIND.get(getattr(d, "device_kind", ""), 16 << 30)
            return int(hbm * 0.85)
    except Exception:
        pass
    return 4 << 30


def _row_bytes(table, version, columns) -> int:
    """Estimated device bytes per scanned row (data + validity mask)."""
    total = 0
    for b in table.blocks(version):
        for name in columns:
            c = b.columns.get(name)
            total += (c.data.dtype.itemsize if c is not None else 8) + 1
        break
    return max(total, 9)


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class _StreamPlan:
    """Cached compiled artifacts for one streamed plan: the pre-agg
    pipeline (which may contain joins: the big scan streams through in
    chunks while the other scans' batches stay device-resident) + agg
    descriptors, and jitted chunk/final programs keyed by the capacity
    vector so repeated executes and same-shape chunks reuse one XLA
    compilation."""

    def __init__(self, pipe_fn, dicts, big_site, other_sites, sized,
                 key_fns, key_names, key_widths, partial, final, nonnull=()):
        self.pipe_fn = pipe_fn
        self.dicts = dicts
        self.big_site = big_site
        self.other_sites = other_sites
        self.sized = list(sized)  # pipeline capacity-knob node ids (joins)
        self.nonnull = list(nonnull)
        self.key_fns = key_fns
        self.key_names = key_names
        self.key_widths = key_widths
        self.partial = partial
        self.final = final
        self.jits = {}
        self.caps = None  # sticky discovered pipeline capacities
        self.sig = None  # plan signature for the engine watch

    def chunk_step(self, cap: int, caps: dict):
        key = ("partial", cap, tuple(sorted(caps.items())))
        j = self.jits.get(key)
        if j is None:
            from tidb_tpu.expression.kernels import param_scope
            from tidb_tpu.obs.engine_watch import watched_jit

            frozen = dict(caps)

            def step(inputs, params, _cap=cap, _caps=frozen):
                with param_scope(params):
                    piped, needs = self.pipe_fn(inputs, _caps)
                    out, ng = group_aggregate(
                        piped, self.key_fns, self.partial, _cap,
                        self.key_names, key_widths=self.key_widths,
                    )
                return out, ng, needs

            j = self.jits[key] = watched_jit(
                step, sig=("stream-partial", self.sig)
            )
        return j

    def final_step(self, fcap: int):
        j = self.jits.get(("final", fcap))
        if j is None:
            from tidb_tpu.obs.engine_watch import watched_jit

            fkeys, fdescs, post_avg = build_final_stage(
                self.key_names, self.final
            )

            def step(combined, _cap=fcap, _keys=fkeys, _descs=fdescs):
                return group_aggregate(
                    combined, _keys, _descs, _cap, self.key_names,
                    key_widths=self.key_widths,
                )

            j = self.jits[("final", fcap)] = (
                watched_jit(step, sig=("stream-final", self.sig)), post_avg
            )
        return j


def _stream_plan(executor, plan, agg, big_scan, conservative=False):
    from tidb_tpu.planner.physical import PlanCompiler, build_agg_parts

    cache = getattr(executor, "_stream_plans", None)
    if cache is None:
        cache = executor._stream_plans = {}
    # the big-scan identity is part of the key: table growth can flip
    # which scan streams, and a stale entry would pin/load the wrong one
    key = (
        executor._cache_key(plan),
        (big_scan.db, big_scan.table, big_scan.alias),
        conservative,
    )
    if key in cache:
        return cache[key]
    while len(cache) >= 32:
        cache.pop(next(iter(cache)))
    # compile the pre-aggregation pipeline once; the big scan's site is
    # fed one chunk at a time, every other site its full batch
    comp = PlanCompiler(
        executor.catalog, resolver=executor._resolve,
        conservative=conservative,
    )
    pipe_fn, dicts = comp._build(agg.child)
    entry = None
    big_site = next(
        (
            s
            for s in comp.scans
            if (s.db, s.table, s.alias)
            == (big_scan.db, big_scan.table, big_scan.alias)
        ),
        None,
    )
    if big_site is not None and big_site.pk_range is None:
        others = [s for s in comp.scans if s is not big_site]
        key_fns, key_names, key_widths, descs = build_agg_parts(agg, dicts)
        if not any(a.distinct for a in descs):
            # DISTINCT can't be split into partial sums across chunks
            # (dedup must see all rows of a group at once): run unpaged
            partial, final = _partial_descs(descs)
            entry = _StreamPlan(
                pipe_fn, dicts, big_site, others, comp.sized,
                key_fns, key_names, key_widths,
                partial, final, nonnull=comp.nonnull,
            )
            entry.sig = (executor.watch_sig(key[0]), key[1])
    cache[key] = entry
    return entry


def try_streamed(
    executor, plan, conservative=False, force=False
) -> Optional[Tuple[Batch, dict]]:
    """Execute `plan` with a streamed aggregate when it qualifies:
    single-device, lowest Aggregate over a streaming pipeline
    (Selection/Projection chains + equi-joins over scans), with the
    largest chunkable table too big for the device. The big scan streams
    through the whole pipeline (including joins against the resident
    small sides) chunk by chunk — the TPU analog of the reference's
    spill-to-disk join/agg executors. stream_rows: -1 = auto (stream
    when the working set overruns the device memory budget), >0 =
    explicit row threshold, 0/None = never stream."""
    threshold = getattr(executor, "stream_rows", None)
    if not threshold or executor.mesh is not None:
        return None
    m = _pipeline_below(plan)
    if m is None:
        return None
    agg, scans, flags = m

    # the streamed scan: largest chunkable table
    big_i, resolved = _pick_big_scan(executor, scans, flags)
    if big_i is None:
        return None
    big_scan = scans[big_i]
    t, v = resolved[big_i]
    chunk_rows, should, sizing = _stream_sizing(
        executor, scans, resolved, big_i, threshold, force=force
    )
    if not should:
        return None

    from tidb_tpu.planner.physical import StaleWidthsError, agg_out_dicts
    from tidb_tpu.utils.failpoint import inject

    inject("executor/stream-start")
    sp = _stream_plan(executor, plan, agg, big_scan, conservative=conservative)
    if sp is None:
        return None
    key_fns, key_names, key_widths, dicts = (
        sp.key_fns, sp.key_names, sp.key_widths, sp.dicts
    )

    # pin one snapshot of every scanned table for the whole statement
    pins = []
    try:
        site_tables = {}
        for s in [sp.big_site] + sp.other_sites:
            st, sv = executor._resolve(s.db, s.table)
            for _ in range(8):
                if st.pin_verified(sv):
                    break
                st, sv = executor._resolve(s.db, s.table)
            else:
                return None  # snapshot churned away repeatedly: unpaged
            pins.append((st, sv))
            site_tables[s.node_id] = (st, sv)
        t, v = site_tables[sp.big_site.node_id]
        # NULL-free folding assumptions must hold at the pinned versions
        for nid, coln in sp.nonnull:
            st, sv = site_tables.get(nid, (None, None))
            if st is not None and st.col_has_nulls(coln, sv):
                raise StaleWidthsError()
        # resident small-side batches, fetched once (device-cached)
        inputs_base = {}
        for s in sp.other_sites:
            st, sv = site_tables[s.node_id]
            inputs_base[s.node_id] = _fetch_resident(executor, s, st, sv)

        # one fixed tile for every chunk: all chunks share one compiled
        # program (the last, shorter chunk pads up to the same tile)
        chunk_tile = pad_capacity(chunk_rows)

        # device-resident streaming: when the big table's RAW columns
        # fit comfortably in the budget but the per-chunk pipeline's
        # intermediates are what forced streaming, transfer the table
        # ONCE (through the scan cache — repeats re-use it) and slice
        # chunk windows on device. Streaming then bounds COMPUTE
        # intermediates without re-paying host->device per execute —
        # on the TPU tunnel that transfer was 50-70s per run at SF10.
        # The reference's paging equally re-reads from the store, not
        # from the client (pkg/store/copr paging). A small admission
        # quota caps sizing's budget, so quota-forced streaming keeps
        # chunking from host — the quota's purpose.
        big_bytes = t.nrows * sizing["rb"]
        device_resident = (
            big_bytes * 2.5 + sizing["others_bytes"] * 4
            <= sizing["budget"]
        )

        def feeds():
            if device_resident:
                from tidb_tpu.storage import scan_table

                full, _fd = scan_table(
                    t, big_scan.columns, version=v,
                    partitions=sp.big_site.partitions,
                )
                cap = full.capacity
                for a in range(0, cap, chunk_tile):
                    inject("executor/stream-chunk")
                    inject("executor/stream-chunk-device")
                    z = min(a + chunk_tile, cap)
                    pad = chunk_tile - (z - a)
                    cols = {}
                    for name, c in full.cols.items():
                        d, vl = c.data[a:z], c.valid[a:z]
                        if pad:
                            d = jnp.pad(d, (0, pad))
                            vl = jnp.pad(vl, (0, pad))
                        cols[name] = DevCol(d, vl)
                    rv = full.row_valid[a:z]
                    if pad:
                        rv = jnp.pad(rv, (0, pad))
                    inputs = dict(inputs_base)
                    inputs[sp.big_site.node_id] = Batch(cols, rv)
                    yield inputs
                return
            for hb in _chunk_blocks(
                t, v, sp.big_site.columns, chunk_rows,
                partitions=sp.big_site.partitions,
            ):
                inject("executor/stream-chunk")
                chunk = block_to_batch(hb, capacity=chunk_tile)
                inputs = dict(inputs_base)
                inputs[sp.big_site.node_id] = chunk
                yield inputs

        partial_batches, cap = _drain_partials(
            executor, sp, feeds(), key_fns, default_tile=chunk_tile
        )
    finally:
        for pt, pv in pins:
            pt.unpin(pv)

    return _finalize_partials(
        executor, plan, agg, sp, partial_batches, cap, dicts, key_fns
    )


def _drain_partials(executor, sp, feeds, key_fns, default_tile):
    """Run the compiled pipeline + partial aggregation over each input
    feed (one chunk or one hash partition), growing capacity knobs on
    overflow exactly like the discovery loop. Returns (partial batches,
    final partial-table cap)."""
    from tidb_tpu.planner.physical import StaleWidthsError

    cap = 1024
    caps = dict(sp.caps) if sp.caps else {
        nid: default_tile for nid in sp.sized
    }
    partial_batches: List[Batch] = []
    for inputs in feeds:
        if executor.kill_check is not None:
            executor.kill_check()
        for _retry in range(24):
            out, ng, needs = sp.chunk_step(cap, caps)(
                inputs, executor._params()
            )
            got = jax.device_get((ng, needs))
            ngi = int(got[0])
            if ngi >= WIDTH_STALE:
                raise StaleWidthsError()
            bumped = False
            for nid, n in got[1].items():
                n = int(n)
                if n >= WIDTH_STALE:
                    raise StaleWidthsError()
                if nid in caps and n > caps[nid]:
                    caps[nid] = pad_capacity(n, floor=16, pow2=True)
                    bumped = True
            if bumped:
                continue
            # overflow whenever the true group count exceeds the
            # batch the kernel emitted (tile size differs by path:
            # 2x cap for hash tables, 1x for dense compaction)
            if key_fns and ngi > out.capacity:
                cap = cap * 2  # partial table overflowed: retry bigger
                continue
            break
        else:
            raise StaleWidthsError()  # capacities never converged
        partial_batches.append(out)
    sp.caps = dict(caps)  # discovered capacities stick for reuse
    return partial_batches, cap


def _finalize_partials(
    executor, plan, agg, sp, partial_batches, cap, dicts, key_fns
):
    """Merge partial aggregates into the final stage, inject the result
    as a Staged node, and run the remainder of the plan."""
    from tidb_tpu.planner.physical import StaleWidthsError, agg_out_dicts

    combined = _concat_batches(partial_batches)

    # final merge: shared with the mesh path's final stage (fragment.py)
    fcap = max(cap, 1024)
    while True:
        jfin, post_avg = sp.final_step(fcap)
        fin, ng = jfin(combined)
        ngi = int(jax.device_get(ng))
        if ngi >= WIDTH_STALE:
            raise StaleWidthsError()
        if sp.key_names and ngi > fin.capacity:
            fcap *= 2
            continue
        break

    cols = apply_post_avg(dict(fin.cols), post_avg)
    result = Batch(
        {n: cols[n] for n in [c.internal for c in agg.schema]}, fin.row_valid
    )

    if not key_fns:
        # scalar aggregate over possibly-empty input: ensure one row
        # (COUNT=0, others NULL) like the in-plan aggregation node
        any_group = jnp.any(result.row_valid)
        first = jnp.zeros(result.capacity, dtype=bool).at[0].set(True)
        rv = jnp.where(any_group, result.row_valid, first)
        cols2 = {}
        agg_funcs = {n: f for n, f, _a, _d in agg.aggs}
        for n, c in result.cols.items():
            if agg_funcs.get(n) == "count":
                cols2[n] = DevCol(
                    jnp.where(any_group, c.data, jnp.zeros_like(c.data)),
                    jnp.where(any_group, c.valid, first),
                )
            else:
                cols2[n] = DevCol(
                    c.data, jnp.where(any_group, c.valid, jnp.zeros_like(c.valid))
                )
        result = Batch(cols2, rv)

    _STAGED_NONCE[0] += 1
    staged = L.Staged(
        agg.schema,
        batch=result,
        dicts=agg_out_dicts(agg, dicts),
        nonce=_STAGED_NONCE[0],
    )
    if plan is agg:
        new_plan = staged
    else:
        new_plan = _replace_node(plan, agg, staged)
    return executor.run(new_plan)


def _trace_col(p, name: str):
    """Descend Selection/Projection/Join chains to the Scan producing
    internal column `name`; returns (scan, bare column) or None (the
    column is computed, not a bare scan column)."""
    from tidb_tpu.expression.expr import ColumnRef

    while True:
        if isinstance(p, L.Selection):
            p = p.child
            continue
        if isinstance(p, L.Projection):
            m = dict(p.exprs)
            e = m.get(name)
            if e is None:
                if p.additive:
                    p = p.child
                    continue
                return None
            if isinstance(e, ColumnRef):
                name = e.name
                p = p.child
                continue
            return None
        if isinstance(p, L.Scan):
            pref = p.alias + "."
            if name.startswith(pref) and name[len(pref):] in p.columns:
                return p, name[len(pref):]
            return None
        if isinstance(p, L.JoinPlan):
            hit = _trace_col(p.left, name)
            return hit if hit is not None else _trace_col(p.right, name)
        return None


def _derive_partition_cols(p, big_aliases: set, out: dict) -> bool:
    """Walk the join tree assigning one hash-partition column to every
    big scan via the equi keys of joins whose BOTH subtrees hold big
    scans (the grace-hash co-partitioning condition). Returns False when
    any such join cannot be co-partitioned (non-equi, null-aware NOT IN,
    or a key that does not trace to a bare big-scan column)."""
    from tidb_tpu.expression.expr import ColumnRef

    def walk(p) -> Optional[set]:
        if isinstance(p, (L.Selection, L.Projection)):
            return walk(p.child)
        if isinstance(p, L.Scan):
            return {p.alias} if p.alias in big_aliases else set()
        if isinstance(p, L.Staged):
            return set()
        if isinstance(p, L.JoinPlan):
            lb = walk(p.left)
            rb = walk(p.right)
            if lb is None or rb is None:
                return None
            if lb and rb:
                if (
                    p.null_aware
                    or not p.equi_keys
                    or p.kind not in ("inner", "left", "semi", "anti", "mark")
                ):
                    return None
                lk, rk = p.equi_keys[0]
                if not (
                    isinstance(lk, ColumnRef) and isinstance(rk, ColumnRef)
                ):
                    return None
                for key, side, bigs in ((lk, p.left, lb), (rk, p.right, rb)):
                    hit = _trace_col(side, key.name)
                    if hit is None:
                        return None
                    scan, col = hit
                    if scan.alias not in big_aliases:
                        # the join key lives on a small scan while this
                        # subtree holds a DIFFERENT big one: that big is
                        # not co-partitioned by this join
                        return None
                    if out.get(scan.alias, col) != col:
                        return None  # conflicting partition columns
                    out[scan.alias] = col
            elif rb and not lb and p.kind in ("left", "anti", "mark"):
                # partitioned bigs ONLY on the build side while the
                # PRESERVED/probe side is resident (replicated to every
                # partition feed): an unmatched probe row would be
                # left-NULL/anti-emitted once PER FEED — duplicated
                # results. (inner/semi stay correct: a probe row's
                # matches all live in one partition; cross unions
                # cleanly; bigs-on-probe-side is fine for every kind.)
                return None
            return lb | rb
        return None

    return walk(p) is not None


def _partition_assignment(t, v, col: str, K: int, partitions=None):
    """Per-block (stable partition-sorted row order, K+1 slice starts,
    per-partition counts): ONE argsort pass per block yields every
    partition's row indices as a slice — gathering K partitions costs
    O(N log N) total, not K full scans. NULLs land in partition 0 (they
    never equi-match, and probe-side NULL rows must still appear exactly
    once)."""
    out = []
    for b in t.blocks(v, partitions=partitions):
        hc = b.columns.get(col)
        if hc is None:
            part = np.zeros(b.nrows, dtype=np.int64)
        else:
            vals = hc.data
            if np.issubdtype(vals.dtype, np.floating):
                v64 = vals.astype(np.float64, copy=True)
                v64[v64 == 0.0] = 0.0  # -0.0 equi-matches 0.0
                vals = v64.view(np.int64)
            h = vals.astype(np.uint64, copy=False) * np.uint64(
                0x9E3779B97F4A7C15
            )
            part = ((h >> np.uint64(33)) % np.uint64(K)).astype(np.int64)
            part[~hc.valid] = 0
        order = np.argsort(part, kind="stable")
        counts = np.bincount(part, minlength=K)
        starts = np.concatenate([[0], np.cumsum(counts)])
        out.append((order, starts, counts))
    return out


def _gather_partition(t, v, columns, assign, k, partitions=None) -> HostBlock:
    """One hash partition of a table as a single HostBlock (slicing the
    precomputed partition-sorted order)."""
    cols: dict = {c: ([], []) for c in columns}
    dicts: dict = {}
    n = 0
    for b, (order, starts, _counts) in zip(
        t.blocks(v, partitions=partitions), assign
    ):
        idx = order[starts[k]:starts[k + 1]]
        n += len(idx)
        for c in columns:
            hc = b.columns.get(c)
            if hc is None:
                cols[c][0].append(np.zeros(len(idx), dtype=np.int64))
                cols[c][1].append(np.zeros(len(idx), dtype=bool))
            else:
                cols[c][0].append(hc.data[idx])
                cols[c][1].append(hc.valid[idx])
                if hc.dictionary is not None:
                    dicts[c] = hc.dictionary
    from tidb_tpu.chunk import HostColumn

    types = t.schema.types
    built = {
        c: HostColumn(
            types[c],
            np.concatenate(d) if d else np.zeros(0, dtype=np.int64),
            np.concatenate(vm) if vm else np.zeros(0, dtype=bool),
            dicts.get(c),
        )
        for c, (d, vm) in cols.items()
    }
    return HostBlock(built, n)


def try_partitioned(
    executor, plan, conservative=False, force=False
) -> Optional[Tuple[Batch, dict]]:
    """Grace-hash spill: when TWO OR MORE pipeline tables exceed the
    memory budget (lineitem self-joins in EXISTS chains, partsupp
    vs partsupp minima), hash-partition every big table on its equi-join
    key into K co-partitions, run the whole compiled pipeline + partial
    aggregation once per partition, and final-merge — the TPU analog of
    the reference's spill-to-disk partitioned hash join
    (pkg/executor/join hash_table spill). Single-big shapes use
    try_streamed (row chunking, no key requirement); this path needs
    key co-location, which row chunks cannot give the build side."""
    threshold = getattr(executor, "stream_rows", None)
    if not threshold or executor.mesh is not None:
        return None
    m = _pipeline_below(plan)
    if m is None:
        return None
    agg, scans, flags = m
    if any(s.alias is None for s in scans):
        return None
    budget = _device_budget()
    q = getattr(executor, "quota_bytes", None)
    if q:
        budget = min(budget, int(q))
    resolved = [executor._resolve(s.db, s.table) for s in scans]
    sizes = [
        t.nrows * _row_bytes(t, v, s.columns)
        for s, (t, v) in zip(scans, resolved)
    ]
    # auto mode: a table is "big" when its working set overruns the
    # budget. force mode (the unpaged plan ALREADY failed admission):
    # partition anything that meaningfully contributes, since join tiles
    # — not raw scan bytes — blew the budget
    bar = budget // 8 if force else budget // 4
    bigs = [i for i, sz in enumerate(sizes) if sz > bar]
    if len(bigs) < 2:
        return None  # zero/one big side: try_streamed's territory
    big_aliases = {scans[i].alias for i in bigs}
    partcols: dict = {}
    if not _derive_partition_cols(agg.child, big_aliases, partcols):
        return None
    if set(partcols) != big_aliases:
        return None  # some big scan never meets another big via a key
    # partition hashing happens on the RAW stored representation, so all
    # co-partitioned keys must share one representation:
    # - dictionary codes are per-table (self-joins share one dict; a
    #   cross-table string key would split equal values), and
    # - numeric keys must agree on (kind, scale): the compare kernels
    #   rescale decimal(10,2) vs decimal(10,4) to match, but raw scaled
    #   ints 500 vs 50000 hash apart.
    # Decline rather than silently drop matches.
    key_types = set()
    for i in bigs:
        t_i, _v_i = resolved[i]
        col = partcols[scans[i].alias]
        ty = t_i.schema.types[col]
        key_types.add((ty.kind, ty.scale))
        if (
            t_i.dictionaries.get(col) is not None
            and len({scans[j].table.lower() for j in bigs}) > 1
        ):
            return None
    if len(key_types) > 1:
        return None
    big_bytes = sum(sizes[i] for i in bigs)
    K = 2
    while K < 64 and (big_bytes * 4) // K > budget:
        K *= 2

    from tidb_tpu.planner.physical import StaleWidthsError
    from tidb_tpu.utils.failpoint import inject

    sp = _stream_plan(
        executor, plan, agg, scans[bigs[0]], conservative=conservative
    )
    if sp is None:
        return None
    all_sites = [sp.big_site] + sp.other_sites
    if any(
        s.pk_range is not None
        for s in all_sites
        if s.alias in partcols
    ):
        return None  # index-range pushdown on a partitioned site
    dicts, key_fns = sp.dicts, sp.key_fns

    pins = []
    try:
        site_tables = {}
        for s in all_sites:
            st, sv = executor._resolve(s.db, s.table)
            for _ in range(8):
                if st.pin_verified(sv):
                    break
                st, sv = executor._resolve(s.db, s.table)
            else:
                return None
            pins.append((st, sv))
            site_tables[s.node_id] = (st, sv)
        for nid, coln in sp.nonnull:
            st, sv = site_tables.get(nid, (None, None))
            if st is not None and st.col_has_nulls(coln, sv):
                raise StaleWidthsError()

        # per-site partition assignment + tile (max partition size)
        assigns = {}
        tiles = {}
        resident = {}
        part_bytes = 0
        for s in all_sites:
            st, sv = site_tables[s.node_id]
            if s.alias in partcols:
                a = _partition_assignment(
                    st, sv, partcols[s.alias], K, partitions=s.partitions
                )
                counts = np.zeros(K, dtype=np.int64)
                for _order, _starts, c in a:
                    counts += c
                assigns[s.node_id] = a
                tiles[s.node_id] = pad_capacity(int(counts.max()) or 1)
                part_bytes += tiles[s.node_id] * _row_bytes(
                    st, sv, s.columns
                )
        # key skew check: a hot key can put ~everything in one partition
        # — running that would silently defeat the quota; decline and
        # let admission's rejection (with its tracker report) stand
        if part_bytes * 4 > budget * 2:
            return None
        for s in all_sites:
            st, sv = site_tables[s.node_id]
            if s.alias not in partcols:
                resident[s.node_id] = _fetch_resident(executor, s, st, sv)

        inject("executor/partition-start")  # the path is committed

        def feeds():
            for k in range(K):
                inject("executor/partition-feed")
                inputs = dict(resident)
                for s in all_sites:
                    if s.node_id in assigns:
                        st, sv = site_tables[s.node_id]
                        hb = _gather_partition(
                            st, sv, s.columns, assigns[s.node_id], k,
                            partitions=s.partitions,
                        )
                        inputs[s.node_id] = block_to_batch(
                            hb, capacity=tiles[s.node_id]
                        )
                yield inputs

        partial_batches, cap = _drain_partials(
            executor, sp, feeds(), key_fns,
            default_tile=max(tiles.values()),
        )
    finally:
        for pt, pv in pins:
            pt.unpin(pv)

    return _finalize_partials(
        executor, plan, agg, sp, partial_batches, cap, dicts, key_fns
    )


class _SortStreamPlan:
    """Cached compiled artifacts for one streamed full ORDER BY: the
    chunked pipeline, its sort-key expressions, and jitted chunk
    programs — repeated executes reuse one XLA compilation, and the
    discovered capacity vector sticks across executes."""

    def __init__(self, pipe_fn, dicts, big_site, other_sites, sized,
                 key_fns, nonnull):
        self.pipe_fn = pipe_fn
        self.dicts = dicts
        self.big_site = big_site
        self.other_sites = other_sites
        self.sized = list(sized)
        self.key_fns = key_fns
        self.nonnull = list(nonnull)
        self.jits = {}
        self.caps = None
        self.sig = None  # plan signature for the engine watch


def _sort_stream_plan(executor, plan, sort, big_scan, conservative=False):
    from tidb_tpu.expression import compile_expr
    from tidb_tpu.planner.physical import PlanCompiler

    cache = getattr(executor, "_stream_plans", None)
    if cache is None:
        cache = executor._stream_plans = {}
    key = (
        executor._cache_key(plan),
        ("sort", big_scan.db, big_scan.table, big_scan.alias),
        conservative,
    )
    if key in cache:
        return cache[key]
    while len(cache) >= 32:
        cache.pop(next(iter(cache)))
    entry = None
    # compile the whole plan MINUS the Sort: projections above it apply
    # per chunk; the host merge only reorders rows. Sort keys must still
    # be computable on that pipeline's output — a pruning projection
    # above the Sort may have dropped a hidden ORDER BY column, in which
    # case this path declines (the in-device path still handles it).
    inner_plan = _replace_node(plan, sort, sort.child)
    schema_names = {c.internal for c in inner_plan.schema}
    refs = set()
    for e, _d in sort.keys:
        _expr_column_refs(e, refs)
    if refs <= schema_names:
        comp = PlanCompiler(
            executor.catalog, resolver=executor._resolve,
            conservative=conservative,
        )
        pipe_fn, dicts = comp._build(inner_plan)
        big_site = next(
            (
                s
                for s in comp.scans
                if (s.db, s.table, s.alias)
                == (big_scan.db, big_scan.table, big_scan.alias)
            ),
            None,
        )
        if big_site is not None and big_site.pk_range is None:
            key_fns = [compile_expr(e, dicts) for e, _ in sort.keys]
            entry = _SortStreamPlan(
                pipe_fn, dicts, big_site,
                [s for s in comp.scans if s is not big_site],
                comp.sized, key_fns, comp.nonnull,
            )
            entry.sig = (executor.watch_sig(key[0]), key[1])
    cache[key] = entry
    return entry


def try_streamed_sort(executor, plan, conservative=False):
    """Out-of-HBM full ORDER BY: when the ROOT of a plan is a Sort (with
    optional Projections above) over a streaming pipeline whose big scan
    exceeds the device budget, the pipeline runs chunk-by-chunk on
    device, each chunk's (pre-sorted) key+payload columns stage to host
    RAM, and the host merges the sorted runs into the final row order.
    Returns (column internal names, ordered numpy column dict, row
    count) or None. Reference: sortexec's disk-spill partitions + merge
    (pkg/executor/sortexec/sort_partition.go) — here HBM is the scarce
    buffer and host RAM the staging medium.

    LIMIT shapes never reach this path (the packed top-k keeps them
    in-device); this is for full-result sorts whose OUTPUT itself
    exceeds device memory, so rows are delivered host-side."""
    threshold = getattr(executor, "stream_rows", None)
    if not threshold or executor.mesh is not None:
        return None
    # peel Projections above the root Sort; the peeled projections apply
    # per chunk (inner_plan below), so sort keys referencing columns THEY
    # prune are checked against the pipeline schema before engaging
    node = plan
    while isinstance(node, L.Projection):
        node = node.child
    if not isinstance(node, L.Sort):
        return None
    sort = node
    scans, flags = [], []
    if not _collect_pipeline_scans(sort.child, scans, flags) or not scans:
        return None
    big_i, resolved = _pick_big_scan(executor, scans, flags)
    if big_i is None:
        return None
    big_scan = scans[big_i]
    chunk_rows, should, _sz = _stream_sizing(
        executor, scans, resolved, big_i, threshold
    )
    if not should:
        return None

    from tidb_tpu.planner.physical import StaleWidthsError
    from tidb_tpu.utils.failpoint import inject

    inject("executor/stream-sort")
    sp = _sort_stream_plan(
        executor, plan, sort, big_scan, conservative=conservative
    )
    if sp is None:
        return None
    big_site = sp.big_site
    key_descs = [d for _, d in sort.keys]
    out_names = [c.internal for c in plan.schema]

    pins = []
    try:
        site_tables = {}
        for s in [sp.big_site] + sp.other_sites:
            st, sv = executor._resolve(s.db, s.table)
            for _ in range(8):
                if st.pin_verified(sv):
                    break
                st, sv = executor._resolve(s.db, s.table)
            else:
                return None
            pins.append((st, sv))
            site_tables[s.node_id] = (st, sv)
        t, v = site_tables[big_site.node_id]
        for nid, coln in sp.nonnull:
            st, sv = site_tables.get(nid, (None, None))
            if st is not None and st.col_has_nulls(coln, sv):
                raise StaleWidthsError()
        inputs_base = {}
        for s in sp.other_sites:
            st, sv = site_tables[s.node_id]
            inputs_base[s.node_id] = _fetch_resident(executor, s, st, sv)

        chunk_tile = pad_capacity(chunk_rows)
        caps = dict(sp.caps) if sp.caps else {
            nid: chunk_tile for nid in sp.sized
        }
        host_runs = []  # per chunk: (row mask, key arrays, col arrays)

        def step_for(caps_t):
            j = sp.jits.get(caps_t)
            if j is None:
                from tidb_tpu.expression.kernels import param_scope
                from tidb_tpu.obs.engine_watch import watched_jit

                frozen = dict(caps)

                def step(inputs, params, _caps=frozen):
                    with param_scope(params):
                        b, needs = sp.pipe_fn(inputs, _caps)
                        keys = [f(b) for f in sp.key_fns]
                    return b, keys, needs

                j = sp.jits[caps_t] = watched_jit(
                    step, sig=("stream-sort-chunk", sp.sig)
                )
            return j

        for hb in _chunk_blocks(
            t, v, big_site.columns, chunk_rows,
            partitions=big_site.partitions,
        ):
            inject("executor/stream-chunk")
            if executor.kill_check is not None:
                executor.kill_check()
            chunk = block_to_batch(hb, capacity=chunk_tile)
            inputs = dict(inputs_base)
            inputs[big_site.node_id] = chunk
            for _retry in range(24):
                b, keys, needs = step_for(tuple(sorted(caps.items())))(
                    inputs, executor._params()
                )
                needs_host = jax.device_get(needs)
                bumped = False
                for nid, n in needs_host.items():
                    n = int(n)
                    if n >= WIDTH_STALE:
                        raise StaleWidthsError()
                    if nid in caps and n > caps[nid]:
                        caps[nid] = pad_capacity(n, floor=16, pow2=True)
                        bumped = True
                if not bumped:
                    break
            else:
                raise StaleWidthsError()
            # stage this chunk's valid rows to host RAM
            rv, kd, cd = jax.device_get(
                (
                    b.row_valid,
                    [(k.data, k.valid) for k in keys],
                    {
                        n: (b.cols[n].data, b.cols[n].valid)
                        for n in out_names
                    },
                )
            )
            host_runs.append((rv, kd, cd))
        sp.caps = dict(caps)  # discovered capacities stick for reuse
    finally:
        for pt, pv in pins:
            pt.unpin(pv)

    # host merge: stable lexsort over the staged runs (numpy's C sort —
    # the "disk merge" analog with host RAM as the spill medium)
    mask = np.concatenate([r[0] for r in host_runs])
    sort_cols = []
    for ki in range(len(sp.key_fns)):
        kdat = np.concatenate([r[1][ki][0] for r in host_runs])[mask]
        kval = np.concatenate([r[1][ki][1] for r in host_runs])[mask]
        sort_cols.append((kdat, kval))
    order = np.arange(int(mask.sum()))
    # np.lexsort sorts by its LAST array first: build
    # [val_kN, rank_kN, ..., val_k0, rank_k0] so key 0's NULL-rank is
    # most significant, then key 0's value, then key 1... Each key gets
    # an explicit NULL-rank array (MySQL: NULLs first asc, last desc) —
    # no in-band sentinel values that could collide with real data.
    lex = []
    for (kdat, kval), desc in zip(sort_cols, key_descs):
        if desc:
            rank = np.where(kval, 0, 1)  # NULLs last
            val = -kdat.astype(np.float64) if np.issubdtype(
                kdat.dtype, np.floating
            ) else -kdat.astype(np.int64)
        else:
            rank = np.where(kval, 1, 0)  # NULLs first
            val = kdat
        val = np.where(kval, val, 0)
        lex = [val, rank] + lex
    if lex:
        order = np.lexsort(lex)
    cols = {}
    for n in out_names:
        dat = np.concatenate([r[2][n][0] for r in host_runs])[mask][order]
        val = np.concatenate([r[2][n][1] for r in host_runs])[mask][order]
        cols[n] = (dat, val)
    return out_names, cols, int(mask.sum()), sp.dicts


def _concat_batches(batches: List[Batch]) -> Batch:
    if len(batches) == 1:
        return batches[0]
    names = list(batches[0].cols)
    cols = {}
    for n in names:
        cols[n] = DevCol(
            jnp.concatenate([b.cols[n].data for b in batches]),
            jnp.concatenate([b.cols[n].valid for b in batches]),
        )
    rv = jnp.concatenate([b.row_valid for b in batches])
    return Batch(cols, rv)
