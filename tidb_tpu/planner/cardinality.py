"""Cardinality estimation: the stats -> planner loop.

Reference: pkg/planner/cardinality/selectivity.go (predicate selectivity
from histograms/TopN/NDV), pkg/statistics/histogram.go. ANALYZE stores
exact per-column stats on the table (tidb_tpu/stats/collect.py); this
module consumes them to estimate row counts of logical subtrees. The
estimates drive join ordering, broadcast-vs-repartition exchange choice
(pkg/planner/core/exhaust_physical_plans.go MPP join picks), and the
est-rows column of EXPLAIN (pkg/planner/core/explain.go).

Without ANALYZE the estimator falls back to the reference's pseudo
selectivities (pseudoEqualRate 1/1000, pseudoLessRate 1/3 in
pkg/statistics/table.go) softened for tiny tables.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import numpy as np

from tidb_tpu.dtypes import Kind, SQLType
from tidb_tpu.expression.expr import ColumnRef, Func, Literal

# pseudo selectivities (reference pkg/statistics/table.go pseudo rates)
SEL_EQ_DEFAULT = 0.05
SEL_RANGE_DEFAULT = 1.0 / 3.0
SEL_LIKE_PREFIX = 0.05
SEL_LIKE_CONTAINS = 0.10
SEL_DEFAULT = 0.25

# mesh exchange choice: a build side at most this many rows is cheaper to
# broadcast (all_gather of the small side) than to all_to_all both sides
BROADCAST_ROW_LIMIT = 65536


class StatsMap:
    """internal column name -> (ColumnStats|None, SQLType, table_rows)."""

    def __init__(self):
        self.cols: Dict[str, Tuple[object, SQLType, int]] = {}

    def add(self, name, stats, typ, table_rows):
        self.cols[name] = (stats, typ, table_rows)

    def stats_of(self, e) -> Optional[Tuple[object, SQLType, int]]:
        if isinstance(e, ColumnRef) and e.name in self.cols:
            return self.cols[e.name]
        return None

    def ndv_of(self, e) -> Optional[int]:
        got = self.stats_of(e)
        if got is None or got[0] is None:
            return None
        return max(int(got[0].ndv), 1)


def gather_stats(plan, catalog) -> StatsMap:
    """Collect column stats reachable from the plan's scans, following
    pass-through projection renames (derived tables / CTE wrappers)."""
    from tidb_tpu.planner import logical as L

    smap = StatsMap()

    def walk(p):
        for c in _children(p):
            walk(c)
        if isinstance(p, L.Scan):
            try:
                t = catalog.table(p.db, p.table)
            except Exception:
                return
            tstats = getattr(t, "stats", None) or {}
            types = dict(t.schema.columns)
            for c in p.columns:
                smap.add(
                    f"{p.alias}.{c}", tstats.get(c), types.get(c), t.nrows
                )
        elif isinstance(p, L.Projection):
            for name, e in p.exprs:
                if isinstance(e, ColumnRef) and e.name in smap.cols:
                    smap.cols[name] = smap.cols[e.name]

    walk(plan)
    return smap


def _children(p):
    out = []
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None:
            out.append(c)
    out.extend(getattr(p, "children", []) or [])
    return out


# ---------------------------------------------------------------------------
# predicate selectivity
# ---------------------------------------------------------------------------


def _encode_literal(value, typ: Optional[SQLType]):
    """Literal -> the column's raw on-device encoding (scaled decimal)."""
    if value is None or typ is None:
        return None
    if typ.kind == Kind.DECIMAL and isinstance(value, (int, float)):
        return round(float(value) * 10**typ.scale)
    if typ.kind == Kind.DATE and isinstance(value, str):
        try:
            from tidb_tpu.dtypes import date_to_days

            return int(date_to_days(value))
        except Exception:
            return None
    if typ.kind == Kind.DATETIME and isinstance(value, str):
        try:
            from tidb_tpu.dtypes import datetime_to_micros

            return int(datetime_to_micros(value))
        except Exception:
            return None
    if isinstance(value, (int, float)):
        return value
    return None  # strings handled via TopN only


def _col_lit(e: Func):
    """Match col-vs-literal in either order; returns (col, lit, flipped)."""
    a, b = e.args[0], e.args[1]
    if isinstance(a, ColumnRef) and isinstance(b, Literal):
        return a, b, False
    if isinstance(b, ColumnRef) and isinstance(a, Literal):
        return b, a, True
    return None, None, False


_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


def selectivity(e, smap: StatsMap) -> float:
    """P(row passes predicate); independence assumed across conjuncts
    (the reference does the same absent multi-column stats)."""
    if e is None:
        return 1.0
    if isinstance(e, Literal):
        if e.value is None:
            return 0.0
        return 1.0 if e.value else 0.0
    if not isinstance(e, Func):
        return SEL_DEFAULT
    op = e.op
    if op == "and":
        # intersect range predicates on the same column before falling
        # back to the independence product — `d >= a AND d < b` is one
        # interval, not two independent 1/3s (reference: range building
        # in pkg/util/ranger feeding histogram row counts)
        conj = _flatten_and(e)
        ranges: Dict[str, list] = {}
        rest = []
        for c in conj:
            m = _range_bound(c, smap)
            if m is None:
                rest.append(c)
                continue
            col, kind, frac = m
            lo, hi = ranges.get(col, (0.0, 1.0))
            if kind == "lo":
                lo = max(lo, frac)
            else:
                hi = min(hi, frac)
            ranges[col] = [lo, hi]
        sel = 1.0
        for lo, hi in ranges.values():
            sel *= max(0.0, hi - lo)
        for c in rest:
            sel *= selectivity(c, smap)
        return sel
    if op == "or":
        s1 = selectivity(e.args[0], smap)
        s2 = selectivity(e.args[1], smap)
        return min(1.0, s1 + s2 - s1 * s2)
    if op == "not":
        return max(0.0, 1.0 - selectivity(e.args[0], smap))
    if op in ("eq", "ne", "lt", "le", "gt", "ge") and len(e.args) == 2:
        col, lit, flipped = _col_lit(e)
        if col is None:
            if op == "eq":
                # col = col (join-ish residual): 1/max ndv if known
                n1, n2 = smap.ndv_of(e.args[0]), smap.ndv_of(e.args[1])
                n = max(n1 or 0, n2 or 0)
                return 1.0 / n if n else SEL_EQ_DEFAULT
            return SEL_RANGE_DEFAULT
        got = smap.stats_of(col)
        if got is None or got[0] is None:
            return SEL_EQ_DEFAULT if op in ("eq", "ne") else SEL_RANGE_DEFAULT
        st, typ, _rows = got
        total = max(st.row_count - st.null_count, 1)
        if op in ("eq", "ne"):
            sel = None
            for v, f in st.topn or []:
                if v == lit.value:
                    sel = f / total
                    break
            if sel is None:
                sel = 1.0 / max(st.ndv, 1)
            return min(1.0, sel) if op == "eq" else max(0.0, 1.0 - sel)
        x = _encode_literal(lit.value, typ)
        if x is None:
            return SEL_RANGE_DEFAULT
        real_op = _FLIP[op] if flipped else op
        frac = _hist_le_frac(st, x)
        if real_op in ("lt", "le"):
            return frac
        return max(0.0, 1.0 - frac)
    if op == "between" and len(e.args) == 3:
        col = e.args[0]
        got = smap.stats_of(col)
        if (
            got is not None
            and got[0] is not None
            and isinstance(e.args[1], Literal)
            and isinstance(e.args[2], Literal)
        ):
            st, typ, _rows = got
            lo = _encode_literal(e.args[1].value, typ)
            hi = _encode_literal(e.args[2].value, typ)
            if lo is not None and hi is not None:
                return max(0.0, _hist_le_frac(st, hi) - _hist_le_frac(st, lo - 1))
        return SEL_RANGE_DEFAULT / 2
    if op == "in":
        col = e.args[0]
        k = len(e.args) - 1
        ndv = smap.ndv_of(col)
        if ndv:
            return min(1.0, k / ndv)
        return min(1.0, k * SEL_EQ_DEFAULT)
    if op == "like":
        if isinstance(e.args[1], Literal) and isinstance(e.args[1].value, str):
            pat = e.args[1].value
            return SEL_LIKE_CONTAINS if pat.startswith("%") else SEL_LIKE_PREFIX
        return SEL_LIKE_CONTAINS
    if op in ("isnull",):
        got = smap.stats_of(e.args[0])
        if got is not None and got[0] is not None:
            st = got[0]
            return st.null_count / max(st.row_count, 1)
        return 0.02
    if op in ("isnotnull",):
        return 1.0 - selectivity(Func(e.type, "isnull", e.args), smap)
    return SEL_DEFAULT


def _flatten_and(e):
    if isinstance(e, Func) and e.op == "and":
        return _flatten_and(e.args[0]) + _flatten_and(e.args[1])
    return [e]


def _range_bound(e, smap: StatsMap):
    """Match a histogram-estimable one-sided range predicate; returns
    (column name, 'lo'|'hi', P(col <= bound)) or None."""
    if not (isinstance(e, Func) and e.op in ("lt", "le", "gt", "ge")):
        return None
    col, lit, flipped = _col_lit(e)
    if col is None:
        return None
    got = smap.stats_of(col)
    if got is None or got[0] is None:
        return None
    st, typ, _rows = got
    x = _encode_literal(lit.value, typ)
    if x is None:
        return None
    op = _FLIP[e.op] if flipped else e.op
    frac = _hist_le_frac(st, x)
    if op in ("lt", "le"):
        return col.name, "hi", frac
    return col.name, "lo", frac


def _hist_le_frac(st, x) -> float:
    """P(col <= x) from the equal-depth histogram bounds."""
    bounds = np.asarray(st.bounds)
    if bounds.size == 0:
        return SEL_RANGE_DEFAULT
    pos = int(np.searchsorted(bounds, x, side="right"))
    frac = pos / bounds.size
    lo = st.min_val
    if lo is not None and isinstance(lo, (int, float)) and x < lo:
        return 0.0
    return min(1.0, max(0.0, frac))


# ---------------------------------------------------------------------------
# row-count estimation over the logical tree
# ---------------------------------------------------------------------------


def est_rows(plan, catalog, smap: Optional[StatsMap] = None) -> float:
    """Estimate output rows; annotates every node with ``.est`` for
    EXPLAIN (the reference's estRows column). Annotations double as a
    memo: repeated estimation over shared subtrees during join building
    returns the cached value instead of re-walking (keeps planning O(k)
    in the number of joins, not O(k^2))."""
    from tidb_tpu.planner import logical as L

    if smap is None:
        smap = gather_stats(plan, catalog)

    def walk(p) -> float:
        cached = p.__dict__.get("est")
        if cached is not None:
            return cached
        if isinstance(p, L.Scan):
            try:
                n = float(catalog.table(p.db, p.table).nrows)
            except Exception:
                n = 1000.0
        elif isinstance(p, L.Selection):
            n = walk(p.child) * selectivity(p.predicate, smap)
        elif isinstance(p, L.JoinPlan):
            nl, nr = walk(p.left), walk(p.right)
            n = est_join(nl, nr, p.equi_keys, p.kind, smap)
            if p.residual is not None:
                n *= selectivity(p.residual, smap)
        elif isinstance(p, L.Aggregate):
            c = walk(p.child)
            if not p.group_exprs:
                n = 1.0
            else:
                ndv = 1.0
                known = True
                for _nm, ge in p.group_exprs:
                    gn = smap.ndv_of(ge)
                    if gn is None:
                        known = False
                        break
                    ndv *= gn
                # unknown group NDV: sqrt heuristic keeps it sublinear
                n = min(c, ndv) if known else min(c, max(1.0, math.sqrt(c) * 8))
        elif isinstance(p, L.Limit):
            n = min(walk(p.child), float(p.count))
        elif isinstance(p, L.Projection):
            n = walk(p.child)
        else:
            cs = _children(p)
            n = max((walk(c) for c in cs), default=1.0)
        p.est = max(n, 0.0)
        return p.est

    return walk(plan)


# ---------------------------------------------------------------------------
# history-seeded cardinality feedback (AQE, PR 15)
# ---------------------------------------------------------------------------


class CardinalityFeedback:
    """Per-digest OBSERVED cardinalities fed back into planning — the
    learned half of the cost model (the PR 8 admission-mem-estimate
    pattern applied to row counts). The DCN scheduler records each
    routed statement's per-side produced rows (exact, from the fenced
    worker stage stats) plus the root est/act pair; the next run of
    the same digest, with ``tidb_tpu_aqe_feedback=on``, seeds
    ``ShuffleSide.est_rows`` from the recorded actuals so
    ``shuffle_mode=auto`` gates and ``choose_edge_modes`` start from
    measured rather than static stats (parallel/dcn.py _choose_cut).

    ``warm_from_history`` re-seeds the store from
    information_schema.statements_summary_history rows after a
    restart of the live summary — the trajectories the StmtHistory
    fold-in keeps for exactly the digests the live map churned out.
    Bounded: oldest digest evicted past ``capacity``."""

    def __init__(self, capacity: int = 512):
        from tidb_tpu.utils import racecheck

        self._lock = racecheck.make_lock("planner.card_feedback")
        self._capacity = int(capacity)
        # digest -> {"sides": {tag: rows}, "est": float, "act": float,
        #            "n": int}
        self._map: Dict[str, dict] = {}

    def record(
        self, digest: str, est: float = 0.0, act: float = 0.0,
        sides: Optional[Dict[str, int]] = None,
    ) -> None:
        """``sides`` keys are ``"<kind>:<stage>:<tag>"`` — per-side
        produced rows from the fenced stage stats, scoped by the cut
        kind that executed (dcn._record_feedback)."""
        if not digest:
            return
        with self._lock:
            ent = self._map.pop(digest, None)
            if ent is None:
                ent = {"sides": {}, "est": 0.0, "act": 0.0, "n": 0}
            if sides:
                for tag, rows in sides.items():
                    ent["sides"][str(tag)] = int(rows)
            if est or act:
                ent["est"] = float(est)
                ent["act"] = float(act)
            ent["n"] += 1
            self._map[digest] = ent  # re-insert: LRU-ish recency
            while len(self._map) > self._capacity:
                self._map.pop(next(iter(self._map)))

    def sides_for(self, digest: str) -> Optional[Dict[int, int]]:
        """Observed per-side produced rows of this digest's last run,
        or None when nothing was recorded."""
        with self._lock:
            ent = self._map.get(digest)
            return dict(ent["sides"]) if ent and ent["sides"] else None

    def est_act(self, digest: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            ent = self._map.get(digest)
            if ent is None or not (ent["est"] or ent["act"]):
                return None
            return ent["est"], ent["act"]

    def warm_from_history(self, history=None) -> int:
        """Seed root est/act pairs from statements_summary_history
        rows (per-side detail does not survive the summary fold, so
        warmed digests seed the divergence only). Returns the number
        of digests seeded."""
        if history is None:
            from tidb_tpu.utils.metrics import STMT_HISTORY

            history = STMT_HISTORY
        n = 0
        for _b, _e, row in history.rows():
            est = float(row.get("est_rows", 0.0) or 0.0)
            act = float(row.get("act_rows", 0.0) or 0.0)
            digest = row.get("digest_text", "")
            if digest and (est or act):
                self.record(digest, est=est, act=act)
                n += 1
        return n

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


#: process-wide feedback store (one cost model per coordinator, like
#: the shared plan cache); tests construct private instances
CARD_FEEDBACK = CardinalityFeedback()


def est_join(nl: float, nr: float, equi_keys, kind: str, smap: StatsMap) -> float:
    if kind == "cross" or not equi_keys:
        return nl * nr
    denom = 1.0
    for le, re_ in equi_keys:
        n1 = smap.ndv_of(le)
        n2 = smap.ndv_of(re_)
        if n1 or n2:
            denom *= max(n1 or 1, n2 or 1)
        else:
            denom *= max(min(nl, nr), 1.0)
    n = nl * nr / max(denom, 1.0)
    if kind in ("semi", "anti"):
        n = min(n, nl)
    if kind == "left":
        n = max(n, nl)
    return max(n, 1.0)
