"""Physical execution of logical plans.

Reference: pkg/executor/builder.go (executorBuilder.build dispatching plan
types to executors) + the volcano Open/Next/Close loop. The TPU engine has
no iterator protocol: each operator is a whole-batch device function and
the interpreter walks the plan bottom-up, the way unistore's closure
executor fuses a whole DAG into one callable (cophandler/closure_exec.go).

Dynamic result sizes (group counts, join fan-out) are handled by the
static-capacity + retry pattern: run at a capacity tile, read the true
count (one scalar transfer), recompile at the next tile on overflow
(SURVEY.md §7 "hard parts" #3).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from tidb_tpu.chunk import Batch, DevCol, pad_capacity
from tidb_tpu.dtypes import Kind, SQLType
from tidb_tpu.executor import (
    AggDesc,
    equi_join,
    filter_batch,
    group_aggregate,
    limit_op,
    order_by,
)
from tidb_tpu.expression import compile_expr
from tidb_tpu.expression.expr import ColumnRef, Expr
from tidb_tpu.planner import logical as L
from tidb_tpu.storage import scan_table

Dicts = Dict[str, np.ndarray]


class ExecError(RuntimeError):
    pass


class PhysicalExecutor:
    def __init__(self, catalog):
        self.catalog = catalog

    def run(self, plan: L.LogicalPlan) -> Tuple[Batch, Dicts]:
        return self._exec(plan)

    # ------------------------------------------------------------------
    def _exec(self, plan: L.LogicalPlan) -> Tuple[Batch, Dicts]:
        if isinstance(plan, L.Scan):
            t = self.catalog.table(plan.db, plan.table)
            batch, dicts = scan_table(t, plan.columns)
            renamed = Batch(
                {f"{plan.alias}.{n}": c for n, c in batch.cols.items()},
                batch.row_valid,
            )
            return renamed, {f"{plan.alias}.{n}": d for n, d in dicts.items()}

        if isinstance(plan, L.Selection):
            batch, dicts = self._exec(plan.child)
            fn = compile_expr(plan.predicate, dicts)
            return filter_batch(batch, fn), dicts

        if isinstance(plan, L.Projection):
            batch, dicts = self._exec(plan.child)
            out_cols = {}
            out_dicts: Dicts = {}
            if plan.additive:
                out_cols.update(batch.cols)
                out_dicts.update(dicts)
            for name, e in plan.exprs:
                out_cols[name] = compile_expr(e, dicts)(batch)
                d = _expr_dict(e, dicts)
                if d is not None:
                    out_dicts[name] = d
            return Batch(out_cols, batch.row_valid), out_dicts

        if isinstance(plan, L.Aggregate):
            return self._exec_aggregate(plan)

        if isinstance(plan, L.JoinPlan):
            return self._exec_join(plan)

        if isinstance(plan, L.Sort):
            batch, dicts = self._exec(plan.child)
            key_fns = [compile_expr(e, dicts) for e, _ in plan.keys]
            descs = [d for _, d in plan.keys]
            return order_by(batch, key_fns, descs), dicts

        if isinstance(plan, L.Limit):
            batch, dicts = self._exec(plan.child)
            return limit_op(batch, plan.count, plan.offset), dicts

        raise ExecError(f"no physical impl for {type(plan).__name__}")

    # ------------------------------------------------------------------
    def _exec_aggregate(self, plan: L.Aggregate) -> Tuple[Batch, Dicts]:
        batch, dicts = self._exec(plan.child)
        key_fns = [compile_expr(e, dicts) for _, e in plan.group_exprs]
        key_names = [n for n, _ in plan.group_exprs]
        descs = []
        for name, func, arg, distinct in plan.aggs:
            if distinct:
                raise ExecError("DISTINCT aggregates not yet supported")
            fn = compile_expr(arg, dicts) if arg is not None else None
            scale = arg.type.scale if arg is not None and arg.type.kind == Kind.DECIMAL else 0
            descs.append(AggDesc(func, fn, name, arg_scale=scale))

        cap = 1024
        max_cap = max(pad_capacity(batch.capacity), 1024)
        while True:
            out, ngroups = group_aggregate(batch, key_fns, descs, cap, key_names)
            n = int(ngroups)
            if n <= cap:
                break
            cap = max(cap * 8, pad_capacity(n))
            if cap > max_cap:
                cap = max_cap
        # MySQL: scalar aggregation over empty input yields exactly one
        # row — COUNT is 0 (valid), SUM/MIN/MAX/AVG are NULL.
        if not plan.group_exprs and n == 0:
            rv = jnp.zeros(out.capacity, dtype=bool).at[0].set(True)
            cols = {}
            for (name, func, _arg, _d) in plan.aggs:
                c = out.cols[name]
                if func == "count":
                    first_true = jnp.zeros_like(c.valid).at[0].set(True)
                    cols[name] = DevCol(jnp.zeros_like(c.data), first_true)
                else:
                    cols[name] = DevCol(c.data, jnp.zeros_like(c.valid))
            out = Batch(cols, rv)

        out_dicts: Dicts = {}
        for (kname, e) in plan.group_exprs:
            d = _expr_dict(e, dicts)
            if d is not None:
                out_dicts[kname] = d
        for (name, func, arg, _d) in plan.aggs:
            if func in ("min", "max", "first") and arg is not None:
                d = _expr_dict(arg, dicts)
                if d is not None:
                    out_dicts[name] = d
        return out, out_dicts

    # ------------------------------------------------------------------
    def _exec_join(self, plan: L.JoinPlan) -> Tuple[Batch, Dicts]:
        left_batch, ldicts = self._exec(plan.left)
        right_batch, rdicts = self._exec(plan.right)
        dicts = {**ldicts, **rdicts}

        if plan.kind == "cross":
            out, _total = _cross_join(left_batch, right_batch)
            if plan.residual is not None:
                out = filter_batch(out, compile_expr(plan.residual, dicts))
            return out, dicts

        # ---- key compilation (with string-dictionary alignment) ----
        lkeys, rkeys = [], []
        for le, re_ in plan.equi_keys:
            lf, rf = _align_key_fns(le, re_, ldicts, rdicts)
            lkeys.append(lf)
            rkeys.append(rf)
        if len(lkeys) == 1:
            lkey, rkey = lkeys[0], rkeys[0]
            verify = None
        else:
            if plan.kind != "inner":
                raise ExecError("multi-key non-inner join not yet supported")
            # hash-combine keys; collisions removed by a verify filter
            lkey = _hash_combine(lkeys)
            rkey = _hash_combine(rkeys)
            verify = (lkeys, rkeys)

        # join sides: reference picks build side by cost; we build on the
        # smaller batch for inner joins (probe = larger).
        kind = plan.kind
        build_b, probe_b = right_batch, left_batch
        build_k, probe_k = rkey, lkey
        if kind == "inner" and left_batch.capacity < right_batch.capacity:
            build_b, probe_b = left_batch, right_batch
            build_k, probe_k = lkey, rkey

        if kind in ("semi", "anti"):
            out, _total = equi_join(
                build_b, probe_b, build_k, probe_k, 0, kind,
            )
            if plan.null_aware and kind == "anti":
                # NOT IN: empty result if build side contains a NULL key;
                # probe NULL keys never pass.
                bk = build_k(build_b)
                has_null = jnp.any(~bk.valid & build_b.row_valid)
                pk = probe_k(out)
                keep = out.row_valid & ~has_null & pk.valid
                out = Batch(out.cols, keep)
            return out, dicts

        cap = pad_capacity(max(probe_b.capacity, 1024))
        max_cap = 1 << 26
        while True:
            out, total = equi_join(
                build_b, probe_b, build_k, probe_k, cap, kind,
            )
            t = int(total)
            if t <= cap:
                break
            cap = pad_capacity(t)
            if cap > max_cap:
                raise ExecError(f"join result too large ({t} rows)")
        if verify is not None:
            lk, rk = verify
            def vf(b):
                ok = jnp.ones(b.capacity, dtype=bool)
                vv = jnp.ones(b.capacity, dtype=bool)
                for lf, rf in zip(lk, rk):
                    a, c = lf(b), rf(b)
                    ok = ok & (a.data == c.data)
                    vv = vv & a.valid & c.valid
                return DevCol(ok, vv)
            out = filter_batch(out, vf)
        if plan.residual is not None:
            out = filter_batch(out, compile_expr(plan.residual, dicts))
        return out, dicts


def _expr_dict(e: Expr, dicts: Dicts) -> Optional[np.ndarray]:
    """Dictionary of a string-valued output expr (shared with the
    compiler's string_expr so codes and dictionary always agree)."""
    if e.type is None or e.type.kind != Kind.STRING:
        return None
    from tidb_tpu.expression.kernels import expr_dictionary

    return expr_dictionary(e, dicts)


def _align_key_fns(le: Expr, re_: Expr, ldicts: Dicts, rdicts: Dicts):
    """Compile join key exprs; for STRING keys, remap both sides' codes
    into a merged dictionary so integer equality == string equality."""
    if le.type is not None and le.type.kind == Kind.STRING:
        if not isinstance(le, ColumnRef) or not isinstance(re_, ColumnRef):
            raise ExecError("string join keys must be plain columns")
        ld = ldicts.get(le.name)
        rd = rdicts.get(re_.name)
        if ld is None or rd is None:
            raise ExecError("string join keys need dictionaries")
        merged = np.array(sorted(set(ld.tolist()) | set(rd.tolist())), dtype=object)
        lut_l = jnp.asarray(np.searchsorted(merged, ld).astype(np.int64) if len(ld) else np.zeros(1, np.int64))
        lut_r = jnp.asarray(np.searchsorted(merged, rd).astype(np.int64) if len(rd) else np.zeros(1, np.int64))
        lname, rname = le.name, re_.name

        def lf(b: Batch) -> DevCol:
            c = b.cols[lname]
            return DevCol(lut_l[jnp.clip(c.data, 0, lut_l.shape[0] - 1)], c.valid)

        def rf(b: Batch) -> DevCol:
            c = b.cols[rname]
            return DevCol(lut_r[jnp.clip(c.data, 0, lut_r.shape[0] - 1)], c.valid)

        return lf, rf
    lfn = compile_expr(le, ldicts)
    rfn = compile_expr(re_, rdicts)
    return lfn, rfn


def _hash_combine(key_fns):
    def f(b: Batch) -> DevCol:
        h = jnp.zeros(b.capacity, dtype=jnp.int64)
        valid = jnp.ones(b.capacity, dtype=bool)
        for fn in key_fns:
            c = fn(b)
            k = c.data.astype(jnp.int64)
            h = (h * jnp.int64(-7046029254386353131)) ^ (
                k + jnp.int64(-9061461749304837403) + (h << 6) + (h >> 2)
            )
            valid = valid & c.valid
        return DevCol(h, valid)

    return f


def _cross_join(left: Batch, right: Batch):
    """Nested-loop cross join via broadcast (small sides only)."""
    lcap, rcap = left.capacity, right.capacity
    if lcap * rcap > (1 << 24):
        raise ExecError("cross join too large")
    li = jnp.repeat(jnp.arange(lcap), rcap)
    ri = jnp.tile(jnp.arange(rcap), lcap)
    cols = {}
    for n, c in left.cols.items():
        cols[n] = DevCol(c.data[li], c.valid[li])
    for n, c in right.cols.items():
        cols[n] = DevCol(c.data[ri], c.valid[ri])
    rv = left.row_valid[li] & right.row_valid[ri]
    total = jnp.sum(rv.astype(jnp.int64))
    return Batch(cols, rv), total
