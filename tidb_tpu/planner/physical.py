"""Physical compilation + execution of logical plans.

Reference: pkg/executor/builder.go (executorBuilder.build) + unistore's
closure executor (cophandler/closure_exec.go:165,470) which fuses a whole
DAG into one callable — here the whole plan compiles into ONE jitted XLA
program per (plan fingerprint, capacity vector), the TPU-native answer to
the reference's volcano iterator tree, and the engine side of its plan
cache (pkg/planner/core/plan_cache.go:231).

Execution is two-phase:

1. **Discovery (eager)**: the plan function runs op-by-op with a default
   capacity vector; every Aggregate/Join node reports its true output
   cardinality. Overflows bump that node's capacity tile and re-run.
2. **Steady state (jitted)**: the discovered capacities are frozen and
   the whole plan becomes one jit-compiled program over the scan batches.
   Each run still returns the cardinality scalars; if data growth makes a
   node overflow its tile, execution transparently falls back to
   discovery and re-jits at the larger tile.

Dynamic result sizes are thereby handled with static shapes only —
SURVEY.md §7 "hard parts" #3/#7.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk import Batch, DevCol, pad_capacity
from tidb_tpu.dtypes import Kind
from tidb_tpu.executor import (
    AggDesc,
    equi_join,
    filter_batch,
    group_aggregate,
    limit_op,
    order_by,
)
from tidb_tpu.executor.aggregate import WIDTH_STALE as _WIDTH_STALE
from tidb_tpu.expression import compile_expr
from tidb_tpu.expression.expr import ColumnRef, Expr
from tidb_tpu.planner import logical as L
from tidb_tpu.storage import scan_table
from tidb_tpu.utils import racecheck

Dicts = Dict[str, np.ndarray]
# node function: (inputs by scan id, caps by node id) -> (batch, needs dict)
PlanFn = Callable[[Dict[int, Batch], Dict[int, int]], Tuple[Batch, Dict[int, jax.Array]]]


class ExecError(RuntimeError):
    pass


class StaleWidthsError(RuntimeError):
    """A compiled program's baked key-width bounds no longer cover the
    data (rows grew past the bounds observed at compile time). The
    executor recompiles the plan against fresh Table.col_bounds."""


# reserved dicts-map key prefix for integer-column value bounds (column
# names never contain NUL); see Table.col_bounds and _key_width
_BOUNDS_PREFIX = "\x00b\x00"
# reserved prefix marking a column as unique-valued (single-column PK /
# unique index at scan, GROUP BY key of a single-key aggregate). Joins
# use it to prove a 1:1 build side (dense unique join); it survives
# row-filtering operators and is stripped where rows can duplicate.
_UNIQ_PREFIX = "\x00u\x00"


def _strip_uniq(dicts: Dicts) -> Dicts:
    return {k: v for k, v in dicts.items() if not k.startswith(_UNIQ_PREFIX)}


def _merge_join_dicts(ldicts: Dicts, rdicts: Dicts, lu: bool, ru: bool) -> Dicts:
    """Join output dictionaries with SELECTIVE uniqueness survival: an
    inner join duplicates one side's rows only when the OTHER side's
    equi key repeats, so a provably-unique build key (ru for the left
    side's entries, lu for the right's) preserves that side's
    uniqueness proofs. Keeps chained star joins (Q5's
    region->nation->supplier->lineitem) on the dense 1:1 join path
    instead of degrading to probe-chain hashing after the first hop."""
    out: Dicts = {}
    for k, v in ldicts.items():
        if k.startswith(_UNIQ_PREFIX) and not ru:
            continue
        out[k] = v
    for k, v in rdicts.items():
        if k.startswith(_UNIQ_PREFIX) and not lu:
            continue
        out[k] = v
    return out


class _LazyBounds:
    """Deferred Table.col_bounds lookup pinned to a (table, col, version):
    scans emit one per integer column, but the min/max host pass only
    runs if a packed-aggregation or dense-join site consumes it (the
    Table caches the result per version for repeat consumers)."""

    __slots__ = ("table", "col", "version", "nid")

    def __init__(self, table, col, version, nid=None):
        self.table = table
        self.col = col
        self.version = version
        # scan node id: lets consumers that bake these bounds register a
        # fetch-time re-check against the scan's resolved version
        self.nid = nid

    def get(self):
        return self.table.col_bounds(self.col, self.version)


def _resolve_bounds(entry):
    if entry is None:
        return None
    if isinstance(entry, _LazyBounds):
        return entry.get()
    return entry


def _stale_only(total):
    """Pass the WIDTH_STALE sentinel through a needs slot, 0 otherwise."""
    return jnp.where(total >= _WIDTH_STALE, total, jnp.int64(0))


@dataclasses.dataclass
class ScanSite:
    node_id: int
    db: str
    table: str
    alias: str
    columns: List[str]
    # PK range pushdown (reference: point_get.go:132 + pkg/util/ranger):
    # (pk column, lo, hi) in raw encoded units — the fetch gathers only
    # matching rows via the table's sorted index instead of a full scan
    pk_range: Optional[Tuple[str, int, int]] = None
    # partition pruning (reference partitionProcessor,
    # pkg/planner/core/rule_partition_processor.go): partition ids the
    # predicate can reach; None = all partitions scan
    partitions: Optional[Tuple[int, ...]] = None
    # index-merge UNION reader (pkg/executor/index_merge_reader.go:88):
    # OR-of-indexable-ranges — the fetch unions each range's sorted-
    # index row ids (dedup via np.unique) and gathers once; the
    # original predicate still filters, so over-approximation is safe
    merge_ranges: Optional[Tuple[Tuple[str, int, int], ...]] = None
    # cross-host fragment slice (idx, n): this engine scans only every
    # n-th row starting at idx (planner/fragmenter.py dispatch)
    frag: Optional[Tuple[int, int]] = None


@dataclasses.dataclass
class CompiledQuery:
    fn: PlanFn
    scans: List[ScanSite]
    sized_nodes: List[int]  # node ids with a capacity knob
    default_caps: Dict[int, int]
    out_dicts: Dicts
    # (node id, Staged.key): keyed staged batches fed as runtime inputs
    # per run — the shuffle consumer's stage partitions, so one compile
    # serves every stage of the plan shape
    staged_sites: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list
    )
    # steady state:
    jitted: Optional[Callable] = None
    caps: Optional[Dict[int, int]] = None
    input_shape_key: Optional[tuple] = None
    # the CONSISTENT steady snapshot: (jitted, caps, input_shape_key)
    # published as ONE atomic tuple after the post-discovery
    # verification run passes. Concurrent executors sharing this cq
    # (the cross-session plan cache) read the tuple, never the three
    # loose fields above — a reader pairing thread A's program with
    # thread B's caps could accept a silently-truncated output (the
    # program's true cardinalities are checked against the caps IT was
    # compiled for). The loose fields stay as a warm-start hint for
    # discovery and for the profiling scripts.
    steady: Optional[tuple] = None
    # set when a post-shrink steady run overflowed (e.g. a probe chain no
    # longer fit the smaller hash table): discovery stops shrinking caps
    # for this plan so grow/shrink cannot oscillate
    no_shrink: bool = False
    # mesh mode: distribution of the root output ('shard' = row-partitioned
    # over the mesh axis, 'repl' = identical on every device)
    out_tag: str = "shard"
    # per sized-node estimated row width in bytes (for quota admission)
    widths: Dict[int, int] = dataclasses.field(default_factory=dict)
    # (scan node id, column) pairs whose validity was folded into the row
    # mask because the column held no NULLs at compile time; re-checked
    # at fetch, violation -> StaleWidthsError recompile
    nonnull: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    # (scan node id, column, lo, hi): compile-time column bounds that
    # proved a decimal SUM safe for single-lane int64 accumulation
    # (AggDesc.wide narrowing); re-checked at fetch like nonnull —
    # growth past the baked interval recompiles, never silently wraps
    bound_checks: List[Tuple[int, str, int, int]] = dataclasses.field(
        default_factory=list
    )
    # plan signature for the engine watch: a second jit trace for the
    # same sig is a retrace (obs/engine_watch.py)
    sig: Optional[object] = None



def _schema_width(schema) -> int:
    """Bytes per row of a plan schema (data + validity per column)."""
    total = 1  # row_valid bit (byte on device)
    for c in schema:
        try:
            total += c.type.np_dtype.itemsize + 1
        except Exception:
            total += 9
    return total

def plan_fingerprint(plan: L.LogicalPlan) -> str:
    """Deterministic structural key for the plan cache."""
    parts: List[str] = []

    def walk(p):
        parts.append(type(p).__name__)
        if isinstance(p, L.Scan):
            parts.append(f"{p.db}.{p.table} as {p.alias} {sorted(p.columns)}")
            if p.frag is not None:
                # two hosts' fragment plans differ ONLY in the slice —
                # without this the plan cache would serve host 0's scan
                # to host 1
                parts.append(f"frag{p.frag[0]}/{p.frag[1]}")
        elif isinstance(p, L.Selection):
            parts.append(repr(p.predicate))
        elif isinstance(p, L.Projection):
            parts.append(repr(p.exprs) + str(p.additive))
        elif isinstance(p, L.Aggregate):
            parts.append(repr(p.group_exprs) + repr(p.aggs))
        elif isinstance(p, L.JoinPlan):
            parts.append(
                p.kind
                + repr(p.equi_keys)
                + repr(p.residual)
                + str(p.null_aware)
                + str(p.broadcast)
                # mark joins: the mark column name is part of the output
                # schema — without it two same-shaped IN-subqueries would
                # collide in the subtree memo (_build)
                + str(getattr(p, "mark_name", None))
            )
        elif isinstance(p, L.Sort):
            parts.append(repr(p.keys))
        elif isinstance(p, L.Window):
            parts.append(
                repr(p.partition_exprs) + repr(p.order_exprs) + repr(p.descs)
            )
        elif isinstance(p, L.Limit):
            parts.append(f"{p.count},{p.offset}")
        elif isinstance(p, L.Staged):
            if p.key is not None:
                # keyed staged input: the batch is a runtime input, so
                # the fingerprint carries everything the compiled
                # program bakes in — shape (capacity + column dtypes)
                # and string dictionary CONTENT (key-alignment LUTs are
                # compile-time) — and two stages with matching shapes
                # share one program
                import hashlib as _hashlib

                b = p.batch
                # LOGICAL types (kind + scale) must key too: two
                # DECIMAL scales share one int64 physical dtype but
                # compile scale-dependent programs, and scan-free
                # staged plans carry no schema-version entries to
                # catch an ALTER
                ltypes = {
                    c.internal: c.type
                    for c in getattr(p.schema, "cols", [])
                }

                def lsig(n):
                    t = ltypes.get(n)
                    return (
                        f"{t.kind.name}s{t.scale}"
                        if t is not None else "?"
                    )

                colsig = ",".join(
                    f"{n}:{dc.data.dtype.str}:{lsig(n)}"
                    for n, dc in sorted(b.cols.items())
                )
                dsig = ";".join(
                    n + "="
                    + _hashlib.blake2b(
                        "\x00".join(map(str, d.tolist())).encode(),
                        digest_size=8,
                    ).hexdigest()
                    for n, d in sorted((p.dicts or {}).items())
                    if d is not None
                )
                parts.append(
                    f"staged@{p.key}#cap{b.capacity}#{colsig}#{dsig}"
                )
            else:
                parts.append(f"staged#{p.nonce}")
        kids = _plan_children(p)
        # child count disambiguates flat vs nested n-ary nodes
        # (UnionAll([U([A,B]),C]) vs UnionAll([U([A,B,C])]))
        parts.append(f"#{len(kids)}")
        for c in kids:
            walk(c)

    walk(plan)
    return "|".join(parts)


def _plan_shareable(plan: L.LogicalPlan) -> bool:
    """Whether a compiled plan may cross the executor boundary via the
    process-wide SharedPlanCache. Only DATA-INDEPENDENT compiles may: a
    non-keyed Staged leaf bakes its batch into the compiled closure
    under a nonce-only fingerprint, and nonces are unique per
    ALLOCATOR, not per process — two in-process shuffle workers mint
    the same nonce and would serve each other's baked partitions as
    results. Keyed Staged leaves are fine: their batches are runtime
    inputs (staged_sites) and their fingerprints carry shape + dict
    content. Scans are fine: data is resolved from the RUNNING
    executor's catalog per run, and baked string LUTs are keyed by the
    table's process-unique uid + version."""
    if isinstance(plan, L.Staged) and plan.key is None:
        return False
    return all(_plan_shareable(c) for c in _plan_children(plan))


def _worth_sharing(plan) -> bool:
    """Subtrees worth memoizing for common-subtree sharing: a join or
    aggregate anywhere beneath (cheap nodes cost less than the
    fingerprint), and never a bare Scan root (pending pushdown state)."""
    if isinstance(plan, L.Scan):
        return False

    def heavy(p):
        if isinstance(p, (L.JoinPlan, L.Aggregate, L.Window, L.Sort)):
            return True
        return any(heavy(c) for c in _plan_children(p))

    return heavy(plan)


def _share_result(fn, registry=None):
    """Per-trace result memo: when the same compiled subtree fn is
    invoked twice with the same (inputs, caps) — two call sites sharing
    one memo entry — the second call returns the FIRST call's traced
    arrays, so the jaxpr (and the compiled program) contains one copy
    of the subtree's work. Keyed by inputs-dict identity (fresh per
    trace/execution) + the static caps; holds only the latest entry."""
    memo: list = []

    def shared(inputs, caps):
        # (registered in the compiler's _share_memos; the root fn wipes
        # every memo after each invocation — see compile())
        capskey = tuple(sorted(caps.items()))
        for (kin, kcaps), v in memo:
            if kin is inputs and kcaps == capskey:
                return v
        v = fn(inputs, caps)
        del memo[:]
        memo.append(((inputs, capskey), v))
        return v

    if registry is not None:
        registry.append(memo)
    return shared


def _plan_children(p) -> List[L.LogicalPlan]:
    out = []
    for attr in ("child", "left", "right"):
        c = getattr(p, attr, None)
        if c is not None:
            out.append(c)
    out.extend(getattr(p, "children", []) or [])
    return out


def _staged_inputs(plan) -> Optional[Dict[str, "Batch"]]:
    """Staged.key -> batch for every keyed staged node in the plan —
    the runtime inputs a cached compile of this plan shape consumes
    (None when the plan has none, the overwhelmingly common case)."""
    out: Dict[str, Batch] = {}

    def walk(p):
        if isinstance(p, L.Staged) and p.key is not None:
            out[p.key] = p.batch
        for c in _plan_children(p):
            walk(c)

    walk(plan)
    return out or None



def _prune_partitions(pred, scan: "L.Scan", resolver):
    """Partition ids of `scan`'s table the predicate can reach, or None
    (all). Range partitioning prunes by bound comparison against the
    VALUES LESS THAN ladder; hash partitioning prunes on equality.
    Reference: partitionProcessor (rule_partition_processor.go)."""
    if "_tidb_rowid" in scan.columns:
        # multi-table DML handle scans: row ids address the FULL block
        # concatenation, so the scan must never see a partition subset
        return None
    try:
        t, _v = resolver(scan.db, scan.table)
    except Exception:
        return None
    # defs at the SNAPSHOT version: a pinned reader must prune with the
    # ladder its blocks were tagged under, not post-ALTER defs
    try:
        part = t.partition_defs_at(_v)
    except AttributeError:
        part = getattr(t, "partition", None)
    if part is None or pred is None:
        return None
    pcol = part[1]
    r = _extract_col_range(pred, scan, t, pcol, open_ok=True)
    if r is None:
        return None
    _col, lo, hi = r
    if lo is not None and hi is not None and lo > hi:
        return ()
    nparts = (
        int(part[2]) if part[0] == "hash" else len(part[2])
    )
    if part[0] == "list":
        keep = []
        for i, (_n, vals) in enumerate(part[2]):
            hit = any(
                v is not None
                and (lo is None or v >= lo)
                and (hi is None or v <= hi)
                for v in vals
            )
            if hit:
                keep.append(i)
        return None if len(keep) == nparts else tuple(keep)
    if part[0] == "hash":
        # hash pruning needs a small CLOSED range (point lookups mostly)
        n = int(part[2])
        if lo is None or hi is None or hi - lo + 1 >= n:
            return None
        return tuple(sorted({(v % n + n) % n for v in range(lo, hi + 1)}))
    uppers = [u for _n, u in part[2]]
    keep = []
    lower = None
    for i, u in enumerate(uppers):
        # partition i holds [lower, u)
        p_lo = lower
        p_hi = None if u is None else u - 1
        lo_ok = lo is None or p_hi is None or lo <= p_hi
        hi_ok = hi is None or p_lo is None or hi >= p_lo
        if lo_ok and hi_ok:
            keep.append(i)
        lower = u
    if len(keep) == nparts:
        return None
    return tuple(keep)


def _extract_pk_range(pred, scan: "L.Scan", resolver):
    """Predicate -> (col, lo, hi) raw-encoded range over the best access
    path: the single-column PK or any single-leading-column secondary
    index whose column is bounded on both sides by the predicate (the
    point-get / IndexRangeScan case, pkg/executor/point_get.go:132 +
    pkg/util/ranger). When several candidates qualify the narrowest
    range wins. Remaining conjuncts still filter the fetched batch, so
    over-extraction is impossible."""
    if "_tidb_rowid" in scan.columns:
        # DML handle scans address full-scan row positions; an index
        # range fetch would renumber them
        return None
    try:
        t, _v = resolver(scan.db, scan.table)
    except Exception:
        return None
    candidates = _index_candidates(t)
    best = None
    for col in candidates:
        r = _extract_col_range(pred, scan, t, col)
        if r is None:
            continue
        width = r[2] - r[1]
        if best is None or width < best[0]:
            best = (width, r)
    return best[1] if best else None


def _index_candidates(t) -> list:
    """Single-column access paths: the one-column PK plus leading
    columns of PUBLIC indexes (shared by range and merge extraction)."""
    candidates = []
    pk = t.schema.primary_key
    if pk and len(pk) == 1:
        candidates.append(pk[0])
    idx_map = (
        t.public_indexes()
        if hasattr(t, "public_indexes")
        else getattr(t, "indexes", {})
    )
    for icols in idx_map.values():
        if icols and icols[0] not in candidates:
            candidates.append(icols[0])
    return candidates


def _extract_index_merge(pred, scan: "L.Scan", resolver):
    """OR-of-indexable-ranges -> tuple of (col, lo, hi) whose UNION
    covers every accepting row (the IndexMerge union reader,
    pkg/executor/index_merge_reader.go:88). Sound because each
    disjunct's range over-approximates that disjunct and the original
    predicate re-filters the fetched batch; extraction fails — full
    scan — if ANY disjunct is not range-expressible on an indexed
    column (a non-indexable disjunct could accept rows outside every
    range). AND-of-ranges (intersection) needs no special reader here:
    the single-range path takes the narrowest conjunct and the filter
    applies the rest."""
    from tidb_tpu.expression.expr import Func

    if "_tidb_rowid" in scan.columns:
        return None
    try:
        t, _v = resolver(scan.db, scan.table)
    except Exception:
        return None
    candidates = _index_candidates(t)
    if not candidates:
        return None

    def conjs(e):
        if isinstance(e, Func) and e.op == "and":
            return conjs(e.args[0]) + conjs(e.args[1])
        return [e]

    def disjuncts(e):
        if isinstance(e, Func) and e.op == "or":
            return disjuncts(e.args[0]) + disjuncts(e.args[1])
        return [e]

    # one OR-shaped conjunct suffices: the other conjuncts only filter
    # further, so the union over this OR stays a superset of the result
    for c in conjs(pred):
        ds = disjuncts(c)
        if len(ds) < 2:
            continue
        ranges = []
        for d in ds:
            best = None
            for col in candidates:
                r = _extract_col_range(d, scan, t, col, open_ok=True)
                if r is not None:
                    # open sides take FULL int64 extremes — the
                    # union reader must never under-approximate, and
                    # values beyond any smaller sentinel would be
                    # silently excluded by the inclusive range fetch
                    col_, lo, hi = r
                    lo = -(1 << 63) if lo is None else lo
                    hi = (1 << 63) - 1 if hi is None else hi
                    width = hi - lo
                    if best is None or width < best[0]:
                        best = (width, (col_, lo, hi))
            if best is None:
                ranges = None
                break
            ranges.append(best[1])
        if ranges:
            return tuple(ranges)
    return None


def _extract_col_range(pred, scan: "L.Scan", t, pkcol: str, open_ok=False):
    typ = t.schema.types.get(pkcol)
    if typ is None or typ.kind not in (
        Kind.INT, Kind.DATE, Kind.DECIMAL, Kind.DATETIME,
    ):
        return None
    internal = f"{scan.alias}.{pkcol}"
    from tidb_tpu.expression.expr import ColumnRef, Func, Literal

    def conjuncts(e):
        if isinstance(e, Func) and e.op == "and":
            return conjuncts(e.args[0]) + conjuncts(e.args[1])
        return [e]

    import math

    def scaled(v):
        """Literal -> exact value in raw encoded units (float; fractional
        when the literal falls between representable values). DATE/
        DATETIME literals may still carry their source string (typed
        temporal literals skip the string-vs-temporal coercion)."""
        if isinstance(v, str) and typ.kind in (Kind.DATE, Kind.DATETIME):
            from tidb_tpu.dtypes import date_to_days, datetime_to_micros

            try:
                if typ.kind == Kind.DATE:
                    return float(date_to_days(v))
                return float(datetime_to_micros(v))
            except Exception:
                return None
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return None
        if typ.kind == Kind.DECIMAL:
            return float(v) * 10**typ.scale
        return float(v)

    lo, hi = None, None
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}

    def bound_hi(x, strict):
        # col < x  ->  col <= ceil(x)-1 ; col <= x -> col <= floor(x)
        return int(math.ceil(x)) - 1 if strict else int(math.floor(x))

    def bound_lo(x, strict):
        # col > x  ->  col >= floor(x)+1 ; col >= x -> col >= ceil(x)
        return int(math.floor(x)) + 1 if strict else int(math.ceil(x))

    for c in conjuncts(pred):
        if not (isinstance(c, Func) and len(c.args) >= 2):
            continue
        op = c.op
        a, b = c.args[0], c.args[1]
        if op == "between" and len(c.args) == 3:
            if (
                isinstance(a, ColumnRef)
                and a.name == internal
                and isinstance(c.args[1], Literal)
                and isinstance(c.args[2], Literal)
            ):
                from tidb_tpu.expression.kernels import baked_value

                x, y = scaled(baked_value(c.args[1])), scaled(
                    baked_value(c.args[2])
                )
                if x is not None and y is not None:
                    xl, yh = bound_lo(x, False), bound_hi(y, False)
                    lo = xl if lo is None else max(lo, xl)
                    hi = yh if hi is None else min(hi, yh)
            continue
        if op not in ("eq", "lt", "le", "gt", "ge"):
            continue
        if isinstance(a, ColumnRef) and a.name == internal and isinstance(b, Literal):
            pass
        elif isinstance(b, ColumnRef) and b.name == internal and isinstance(a, Literal):
            a, b, op = b, a, flip[op]
        else:
            continue
        from tidb_tpu.expression.kernels import baked_value

        x = scaled(baked_value(b))
        if x is None:
            continue
        if op == "eq":
            if x != int(x):
                return (pkcol, 1, 0)  # empty range: no integer equals x
            xi = int(x)
            lo = xi if lo is None else max(lo, xi)
            hi = xi if hi is None else min(hi, xi)
        elif op in ("lt", "le"):
            y = bound_hi(x, op == "lt")
            hi = y if hi is None else min(hi, y)
        else:
            y = bound_lo(x, op == "gt")
            lo = y if lo is None else max(lo, y)
    if not open_ok and (lo is None or hi is None):
        return None
    if lo is None and hi is None:
        return None
    return (pkcol, lo, hi)



def _expr_abs_bound(e: Expr, dicts: Dicts):
    """Max-abs of an expression's SCALED integer representation via
    interval arithmetic over storage column bounds, or None (unbounded /
    unsupported shape). Returns (bound, [contributing _LazyBounds]).
    Sound only while every referenced column stays inside its
    compile-time bounds — callers must register a fetch-time re-check
    for each returned entry (CompiledQuery.bound_checks)."""
    import math

    from tidb_tpu.expression.expr import Func, Literal

    kind = e.type.kind if e.type is not None else None
    if kind not in (Kind.INT, Kind.DECIMAL, Kind.BOOL):
        return None
    scale = e.type.scale if kind == Kind.DECIMAL else 0
    if isinstance(e, ColumnRef):
        entry = dicts.get(_BOUNDS_PREFIX + e.name)
        cb = _resolve_bounds(entry)
        if cb is None or not isinstance(entry, _LazyBounds):
            return None
        return (max(abs(int(cb[0])), abs(int(cb[1]))), [entry])
    if isinstance(e, Literal):
        if e.param_slot is not None:
            return None  # value changes per EXECUTE; no static bound
        v = e.value
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            return None
        return (int(math.ceil(abs(v) * 10 ** scale)) + 1, [])
    if isinstance(e, Func) and e.op in ("add", "sub", "mul", "neg"):
        subs = [_expr_abs_bound(a, dicts) for a in e.args]
        if any(s is None for s in subs):
            return None
        if e.op == "neg":
            return subs[0]
        (b1, c1), (b2, c2) = subs

        def sc(a):
            return (
                a.type.scale
                if a.type is not None and a.type.kind == Kind.DECIMAL
                else 0
            )

        s1, s2 = sc(e.args[0]), sc(e.args[1])
        if e.op == "mul":
            # scaled product == product of scaled operands at result
            # scale s1+s2; a result rescaled DOWN is only smaller
            return (b1 * b2, c1 + c2)
        if scale < max(s1, s2):
            return None  # add/sub never narrows scale; bail if odd
        return (b1 * 10 ** (scale - s1) + b2 * 10 ** (scale - s2), c1 + c2)
    return None


def build_agg_parts(plan: "L.Aggregate", dicts, compiler=None):
    """Compile an Aggregate node's pieces: (key fns, key names, packed key
    widths, AggDescs). Shared by the in-plan aggregation node and the
    streamed (chunked) execution path. With a compiler, wide decimal
    sums whose arguments are provably small (interval arithmetic over
    storage bounds) drop to single-lane int64 accumulation, halving the
    reduction passes; the proof is re-checked at every fetch."""
    key_fns = [compile_expr(e, dicts) for _, e in plan.group_exprs]
    key_names = [n for n, _ in plan.group_exprs]
    key_widths = [_key_width(e, dicts) for _, e in plan.group_exprs]
    # collation-correct grouping: dict-coded string keys under a CI
    # collation group by their dense collation RANK (equal-under-
    # collation entries share a rank) instead of the binary dict code,
    # so GROUP BY name merges 'Ann'/'ANN' under *_ci like MySQL
    # (reference pkg/util/collate/collate.go:66 — Key() drives hash).
    # agg_out_dicts applies the matching rank->representative dict.
    for i, (_n, e) in enumerate(plan.group_exprs):
        lr = _collation_rank(e, dicts)
        if lr is None:
            continue
        key_fns[i] = _rank_wrap(key_fns[i], jnp.asarray(lr[0]))
        key_widths[i] = (max(1, int(len(lr[1])).bit_length()), 0)
    descs = []
    for name, func, arg, distinct in plan.aggs:
        fn = compile_expr(arg, dicts) if arg is not None else None
        scale = (
            arg.type.scale
            if arg is not None and arg.type.kind == Kind.DECIMAL
            else 0
        )
        # scale-4+ decimal products (price*(1-disc)*(1+tax)) overflow
        # int64 accumulation at SF100 row counts: use the dual-lane
        # wide accumulator (AggDesc.wide)
        wide = func in ("sum", "avg") and scale >= 4
        pack_bound = None
        if func in ("sum", "avg") and compiler is not None and arg is not None:
            r = _expr_abs_bound(arg, dicts)
            # 2^31 rows is past any single-program tile (int32 row
            # indexing); bound * 2^31 < 2^62 proves no int64 wraparound.
            # The same proof funds the packed (sum,count) single-pass
            # reduction (AggDesc.pack_bound) for ALL integer sums —
            # re-verified against live storage bounds at every fetch.
            if r is not None and r[0] < (1 << 31) and all(
                lb.nid is not None for lb in r[1]
            ):
                for lb in r[1]:
                    cb = lb.get()
                    compiler.bound_checks.append(
                        (lb.nid, lb.col, int(cb[0]), int(cb[1]))
                    )
                wide = False
                pack_bound = int(r[0])
        # DISTINCT is a no-op for min/max (duplicate-insensitive); for
        # sum/avg/count the kernel dedupes via representative-row masks
        # (executor/aggregate._distinct_reps)
        d = bool(distinct) and func in ("sum", "avg", "count") and arg is not None
        # MIN/MAX over CI-collated strings must order by collation, not
        # binary code: compose cmp_rank*D + code so the int reduction
        # picks the collation extreme; AggDesc.post decodes the winning
        # member's original dict code (output dict unchanged). COUNT
        # (DISTINCT s) dedupes by equality class; plain COUNT reads
        # only validity and `first` is a row passthrough — both keep
        # raw codes.
        post = None
        if func in ("min", "max") and arg is not None:
            cw = _collation_compose(arg, dicts)
            if cw is not None:
                fn, post = cw[0](fn), cw[1]
        elif func == "count" and distinct and arg is not None:
            lr = _collation_rank(arg, dicts)
            if lr is not None:
                fn = _rank_wrap(fn, jnp.asarray(lr[0]))
        descs.append(
            AggDesc(
                func, fn, name, distinct=d, arg_scale=scale, wide=wide,
                post=post, pack_bound=pack_bound,
            )
        )
    return key_fns, key_names, key_widths, descs


def _collation_compose(e: Expr, dicts):
    """For a CI-collated dict-coded string expr: (wrapper making the
    compiled fn yield cmp_rank*D + code, post decoding code) so MIN/MAX
    order by collation while returning a real dictionary code. None
    when binary / no dictionary."""
    if e.type is None or e.type.kind != Kind.STRING or not e.type.collation:
        return None
    from tidb_tpu.utils import collate as _coll

    if _coll.is_binary(e.type.collation):
        return None
    d = _expr_dict(e, dicts)
    if d is None or not len(d):
        return None
    from tidb_tpu.expression.kernels import _collation_rank_lut

    cr, _keys, _kf = _collation_rank_lut(d, e.type.collation)
    D = int(len(d))

    def wrap(fn):
        def composed(b: Batch) -> DevCol:
            c = fn(b)
            code = jnp.clip(c.data.astype(jnp.int64), 0, D - 1)
            return DevCol(cr[code] * D + code, c.valid)

        return composed

    return wrap, (lambda v: v % D)


def _collation_rank(e: Expr, dicts):
    """(jnp rank LUT, representative dict) for a dict-coded string expr
    under a non-binary collation; None when binary/no dictionary."""
    if e.type is None or e.type.kind != Kind.STRING or not e.type.collation:
        return None
    from tidb_tpu.utils import collate as _coll

    if _coll.is_binary(e.type.collation):
        return None
    d = _expr_dict(e, dicts)
    if d is None:
        return None
    lr = _coll.rank_lut(d, e.type.collation)
    if lr is None or len(lr[0]) == 0:
        return None
    return lr  # (np lut, rep) — callers upload the LUT only when used


def _rank_wrap(fn, jlut):
    def wrapped(b: Batch) -> DevCol:
        c = fn(b)
        return DevCol(
            jlut[jnp.clip(c.data, 0, jlut.shape[0] - 1)], c.valid
        )

    return wrapped



def agg_out_dicts(plan: "L.Aggregate", dicts) -> Dicts:
    """Dictionaries surviving an aggregation: group keys and
    min/max/first outputs over dictionary-coded columns."""
    out_dicts: Dicts = {}
    for (kname, e) in plan.group_exprs:
        d = _expr_dict(e, dicts)
        if d is not None:
            # CI-collated keys group (and emit codes) in rank space:
            # publish the matching rank->representative dictionary
            # (build_agg_parts applies the mirror-image rank LUT)
            lr = _collation_rank(e, dicts)
            out_dicts[kname] = d if lr is None else lr[1]
            if lr is not None:
                continue  # code bounds describe the pre-rank codes
        if isinstance(e, ColumnRef):
            cb = dicts.get(_BOUNDS_PREFIX + e.name)
            if cb is not None:
                out_dicts[_BOUNDS_PREFIX + kname] = cb
    if len(plan.group_exprs) == 1:
        # a single GROUP BY key is unique in the aggregate's output
        out_dicts[_UNIQ_PREFIX + plan.group_exprs[0][0]] = True
    for (name, func, arg, _d) in plan.aggs:
        if func in ("min", "max", "first") and arg is not None:
            # min/max decode back to original dict codes (AggDesc.post),
            # so the original dictionary stays correct under CI too
            d = _expr_dict(arg, dicts)
            if d is not None:
                out_dicts[name] = d
    return out_dicts


class PlanCompiler:
    """Builds the pure plan function; dictionaries and LUTs are resolved
    at build time (they change only with table versions).

    With instrument=True every node is wrapped with wall-time + row-count
    probes (forces per-op sync — diagnostic mode only): the engine side
    of EXPLAIN ANALYZE (reference RuntimeStatsColl,
    pkg/util/execdetails/execdetails.go:1273)."""

    def __init__(
        self, catalog, instrument: bool = False, resolver=None,
        mesh_n: Optional[int] = None, conservative: bool = False,
    ):
        # conservative=True drops every runtime-verified compile-time
        # assumption (int-column bounds, unique marks, NULL-free folding,
        # assumed top-k widths): the executor's stale-retry loop falls
        # back to it when assumptions keep failing (e.g. a duplicate in a
        # column the planner believed unique), guaranteeing termination.
        self.conservative = conservative
        self.catalog = catalog
        self.resolver = resolver or (
            lambda db, tbl: (catalog.table(db, tbl), catalog.table(db, tbl).version)
        )
        self._next_id = 0
        self.scans: List[ScanSite] = []
        #: (node id, Staged.key) for keyed staged inputs: the executor
        #: feeds these batches at run time like scan inputs
        self.staged_sites: List[Tuple[int, str]] = []
        self.sized: List[int] = []
        self.defaults: Dict[int, int] = {}
        # estimated bytes per row of each sized node's output schema:
        # quota admission pre-accounts cap x width before any launch
        # (pkg/util/memory/tracker.go:74 as admission control)
        self.widths: Dict[int, int] = {}
        self.instrument = instrument
        self.nonnull: List[Tuple[int, str]] = []
        self.bound_checks: List[Tuple[int, str, int, int]] = []
        # fingerprint -> (shared fn, dicts, tag): see _build
        self._subtree_memo: dict = {}
        self._share_memos: list = []  # per-trace result memos to wipe
        self.node_labels: List[Tuple[int, int, str]] = []  # (nid, depth, label)
        self.stats: Dict[int, Dict[str, float]] = {}
        self._depth = 0
        # mesh mode: plan functions run per-shard inside shard_map over a
        # mesh_n-device axis. Every node output carries a distribution tag
        # ('shard' = row-partitioned over the mesh, 'repl' = identical on
        # every device); _tag holds the tag of the most recently built
        # node (stack discipline: a parent reads it right after building
        # each child). The mapping mirrors the reference's MPP task types
        # (pkg/planner/core/fragment.go:47): sharded scan fragments,
        # exchange at aggregation/join boundaries, singleton (gathered)
        # fragments for order-sensitive operators.
        self.mesh_n = mesh_n
        self._tag = "shard"

    def fresh_id(self) -> int:
        self._next_id += 1
        return self._next_id

    def _stale_sentinel_node(self, props) -> Optional[int]:
        """Semi/anti/mark joins have no output-capacity knob, so a dense
        build side gets a dedicated sized node whose `needs` carries only
        the WIDTH_STALE sentinel (0 otherwise) back to the discovery
        loop."""
        if props[0] is None:
            return None
        nid = self.fresh_id()
        self.sized.append(nid)
        self.defaults[nid] = 16
        self.widths[nid] = 8
        return nid

    def _gathered(self, fn, tag):
        """Wrap fn so its output is replicated on every device (the
        reference's PassThrough/singleton exchange)."""
        if self.mesh_n is None or tag == "repl":
            return fn
        from tidb_tpu.parallel import broadcast_gather

        def g(inputs, caps):
            b, needs = fn(inputs, caps)
            return broadcast_gather(b), needs

        return g

    def _gather_child(self, child):
        """Singleton-fragment transition for order-sensitive operators
        (Sort/Window/Limit): gather the child, mark output replicated."""
        child = self._gathered(child, self._tag)
        if self.mesh_n:
            self._tag = "repl"
        return child

    def _build(self, plan: L.LogicalPlan):
        # Common-subtree sharing: structurally identical subtrees that
        # contain a join or aggregate (inlined WITH/CTE references used
        # from several IN-subqueries — Q95's ws_wh shape) compile ONCE;
        # the second call site reuses the first's traced result, so the
        # XLA program contains one copy of the work. (Reference: CTE
        # materialization, pkg/planner/core/logical_plan_builder.go
        # buildWith — there a disk spool, here graph sharing inside one
        # program.) Bare scans never memoize: their build consumes the
        # caller's pending range/partition pushdown state.
        fp = None
        if _worth_sharing(plan):
            fp = plan_fingerprint(plan)
            hit = self._subtree_memo.get(fp)
            if hit is not None:
                fn, dicts, tag = hit
                self._tag = tag
                return fn, dicts
        nid = self.fresh_id()
        self.node_labels.append((nid, self._depth, _node_label(plan)))
        self._depth += 1
        fn, dicts = self._build_node(plan)
        self._depth -= 1
        if self.instrument:
            fn = self._wrap(nid, fn)
        if fp is not None:
            fn = _share_result(fn, registry=self._share_memos)
            self._subtree_memo[fp] = (fn, dicts, self._tag)
        if self._depth == 0 and self._share_memos:
            # root of the build (compile() and the streamed pipeline
            # builder both enter here at depth 0): wipe every per-trace
            # result memo after each invocation — a retained entry would
            # pin the previous run's input batches or leak tracers
            inner, memos = fn, list(self._share_memos)

            def fn(inputs, caps, _f=inner, _m=memos):
                try:
                    return _f(inputs, caps)
                finally:
                    for mm in _m:
                        del mm[:]
        return fn, dicts

    def _wrap(self, nid: int, fn):
        stats = self.stats

        def timed(inputs, caps):
            import time as _time

            t0 = _time.perf_counter()
            batch, needs = fn(inputs, caps)
            jax.block_until_ready(batch.row_valid)
            el = _time.perf_counter() - t0
            rows = int(jnp.sum(batch.row_valid.astype(jnp.int32)))
            st = stats.setdefault(nid, {"time_s": 0.0, "rows": 0, "calls": 0})
            st["time_s"] += el
            st["rows"] = rows
            st["calls"] += 1
            return batch, needs

        return timed

    def compile(self, plan: L.LogicalPlan) -> CompiledQuery:
        self._tag = "shard"
        fn, dicts = self._build(plan)
        # bounds/uniqueness entries are compile-time plumbing; result
        # consumers (materialization, the RPC seam) expect name ->
        # dictionary only (all reserved prefixes start with NUL)
        out = {k: v for k, v in dicts.items() if not k.startswith("\x00")}
        return CompiledQuery(
            fn=fn,
            out_tag=self._tag,
            scans=self.scans,
            staged_sites=list(self.staged_sites),
            sized_nodes=self.sized,
            default_caps=dict(self.defaults),
            out_dicts=out,
            widths=dict(self.widths),
            nonnull=list(self.nonnull),
            bound_checks=list(self.bound_checks),
        )

    # ------------------------------------------------------------------
    def _build_node(self, plan: L.LogicalPlan):
        if isinstance(plan, L.OneRow):

            def fn_one(inputs, caps):
                rv = jnp.zeros(256, dtype=bool).at[0].set(True)
                return Batch({}, rv), {}

            self._tag = "repl"
            return fn_one, {}

        if isinstance(plan, L.Staged):
            batch = plan.batch
            sdicts = dict(plan.dicts or {})
            self._tag = "repl"
            if plan.key is not None:
                # runtime staged input: the executor feeds the batch
                # per run (PhysicalExecutor collects keyed Staged
                # nodes), so the cached program never pins stage data
                # and fresh data reuses the compile
                nid = self.fresh_id()
                self.staged_sites.append((nid, plan.key))

                def fn_staged_input(inputs, caps, _nid=nid):
                    return inputs[_nid], {}

                return fn_staged_input, sdicts

            def fn_staged(inputs, caps, _b=batch):
                return _b, {}

            return fn_staged, sdicts

        if isinstance(plan, L.Scan):
            nid = self.fresh_id()
            parts = getattr(self, "_pending_parts", None)
            self._pending_parts = None
            self.scans.append(
                ScanSite(
                    nid, plan.db, plan.table, plan.alias, plan.columns,
                    pk_range=getattr(self, "_pending_range", None),
                    partitions=parts,
                    merge_ranges=getattr(self, "_pending_merge", None),
                    frag=plan.frag,
                )
            )
            self._pending_merge = None
            if parts is not None and self.node_labels:
                # surface pruning in EXPLAIN: the Scan is a leaf, so its
                # label is the most recently appended
                lnid, ldepth, ltext = self.node_labels[-1]
                self.node_labels[-1] = (
                    lnid, ldepth, ltext + f" partitions={list(parts)}"
                )
            t, _v = self.resolver(plan.db, plan.table)
            dicts = {
                f"{plan.alias}.{n}": d
                for n, d in t.dictionaries.items()
                if n in plan.columns
            }
            # integer-column value bounds ride the dicts map under a
            # reserved key (columns can't contain NUL): they give the
            # packed-aggregation paths sound static widths for int keys.
            # Programs verify them at run time (aggregate._pack_keys), so
            # jit reuse across versions stays sound after data growth.
            # Entries are lazy (resolved by _resolve_bounds at the group/
            # join key that consumes them): a wide scan never pays the
            # full-column min/max host pass for unused columns.
            if not self.conservative:
                for n in plan.columns:
                    dicts[_BOUNDS_PREFIX + f"{plan.alias}.{n}"] = _LazyBounds(
                        t, n, _v, nid
                    )
            pk = t.schema.primary_key
            uniq_cols = set([pk[0]] if pk and len(pk) == 1 else [])
            for iname in t.unique_indexes:
                # a unique index not yet PUBLIC may still cover
                # unvalidated duplicate rows: no uniqueness proofs
                if hasattr(t, "index_state") and t.index_state(iname) != "public":
                    continue
                icols = t.indexes.get(iname) or []
                if len(icols) == 1:
                    uniq_cols.add(icols[0])
            if self.conservative:
                uniq_cols = set()
            for n in plan.columns:
                if n in uniq_cols:
                    dicts[_UNIQ_PREFIX + f"{plan.alias}.{n}"] = True
            alias = plan.alias
            # NULL-free columns: fold the per-column validity mask into
            # the row mask so XLA constant-folds every downstream
            # `valid & ...` (measured ~25% of Q1's memory traffic was
            # validity loads/ANDs over columns that never hold NULLs).
            # The assumption is re-checked host-side at every fetch
            # (_run_pinned) and a violation recompiles via the stale path.
            nonnull = [] if self.conservative else [
                n for n in plan.columns if not t.col_has_nulls(n, _v)
            ]
            self.nonnull.extend((nid, n) for n in nonnull)
            nonnull_set = frozenset(nonnull)

            def fn_scan(inputs, caps, _nid=nid, _alias=alias, _nn=nonnull_set):
                raw = inputs[_nid]
                return (
                    Batch(
                        {
                            f"{_alias}.{n}": (
                                DevCol(c.data, raw.row_valid)
                                if n in _nn
                                else c
                            )
                            for n, c in raw.cols.items()
                        },
                        raw.row_valid,
                    ),
                    {},
                )

            self._tag = "shard"
            return fn_scan, dicts

        if isinstance(plan, L.Selection):
            if (
                isinstance(plan.child, L.Aggregate)
                and plan.child.group_exprs
                and not plan.child.gc_meta
            ):
                names = {n for n, _ in plan.child.group_exprs} | {
                    n for n, _f, _a, _d in plan.child.aggs
                }
                pc = _bound_pred_cols(plan.predicate)
                if pc is not None and pc <= names:
                    # HAVING fusion: evaluate the predicate inside the
                    # aggregation kernel — the dense path then compacts
                    # only surviving groups, so the discovered output
                    # tile (and every downstream operator's capacity)
                    # shrinks to the survivor count
                    return self._build_aggregate(
                        plan.child, post_pred=plan.predicate
                    )
            if isinstance(plan.child, L.Scan) and not self.mesh_n:
                self._pending_range = _extract_pk_range(
                    plan.predicate, plan.child, self.resolver
                )
                if self._pending_range is None:
                    self._pending_merge = _extract_index_merge(
                        plan.predicate, plan.child, self.resolver
                    )
            if isinstance(plan.child, L.Scan):
                self._pending_parts = _prune_partitions(
                    plan.predicate, plan.child, self.resolver
                )
            child, dicts = self._build(plan.child)
            self._pending_range = None
            self._pending_merge = None
            self._pending_parts = None
            pred = compile_expr(plan.predicate, dicts)

            def fn_sel(inputs, caps):
                b, needs = child(inputs, caps)
                return filter_batch(b, pred), needs

            return fn_sel, dicts

        if isinstance(plan, L.Projection):
            child, dicts = self._build(plan.child)
            exprs = [(n, compile_expr(e, dicts)) for n, e in plan.exprs]
            out_dicts: Dicts = dict(dicts) if plan.additive else {}
            for n, e in plan.exprs:
                d = _expr_dict(e, dicts)
                if d is not None:
                    out_dicts[n] = d
                if isinstance(e, ColumnRef):
                    cb = dicts.get(_BOUNDS_PREFIX + e.name)
                    if cb is not None:
                        out_dicts[_BOUNDS_PREFIX + n] = cb
                    if dicts.get(_UNIQ_PREFIX + e.name):
                        out_dicts[_UNIQ_PREFIX + n] = True
            additive = plan.additive

            def fn_proj(inputs, caps):
                b, needs = child(inputs, caps)
                cols = dict(b.cols) if additive else {}
                for n, f in exprs:
                    cols[n] = f(b)
                return Batch(cols, b.row_valid), needs

            return fn_proj, out_dicts

        if isinstance(plan, L.Aggregate):
            return self._build_aggregate(plan)

        if isinstance(plan, L.JoinPlan):
            return self._build_join(plan)

        if isinstance(plan, L.Sort):
            child, dicts = self._build(plan.child)
            key_fns = [compile_expr(e, dicts) for e, _ in plan.keys]
            descs = [d for _, d in plan.keys]
            if self.mesh_n and self._tag == "shard":
                # distributed sample sort (no whole-dataset gather): rows
                # range-partition by sampled splitters of the first key,
                # each shard sorts locally, and shard-major array order
                # IS the total order (the output compaction is stable).
                # Replaces the round-1 broadcast_gather Sort path
                # (reference: sortexec multi-way merge over partitions;
                # VERDICT round-1 weak #2).
                mesh_n = self.mesh_n
                nid = self.fresh_id()
                self.sized.append(nid)
                self.defaults[nid] = 0  # filled from the dominant tile
                # the exchange allocates an (n, B) send buffer + an n*B
                # receive batch per device: account ~n tiles of width,
                # not one (memory-quota admission honesty)
                self.widths[nid] = _schema_width(plan.schema) * mesh_n
                first_fn, first_desc = key_fns[0], descs[0]

                def fn_dsort(inputs, caps):
                    from tidb_tpu.parallel import range_repartition

                    b, needs = child(inputs, caps)
                    k0 = first_fn(b)
                    data = k0.data
                    if data.dtype == jnp.bool_:
                        data = data.astype(jnp.int32)
                    dird = (-data if first_desc else data).astype(jnp.float64)
                    # MySQL null order: first ASC, last DESC — rank NULLs
                    # at the matching infinity so they colocate in the
                    # end bucket (float64 ranking: equal keys always map
                    # to equal ranks, so ties never split across shards)
                    null_rank = -jnp.inf if not first_desc else jnp.inf
                    isnull = b.row_valid & ~k0.valid
                    rank = jnp.where(isnull, null_rank, dird)
                    B = caps[nid]
                    ex, dropped, xneed = range_repartition(
                        b, rank, mesh_n, B, "d"
                    )
                    needs = dict(needs)
                    # xneed is the exact per-bucket requirement in BOTH
                    # directions: discovery shrinks an over-provisioned
                    # tile toward rows/n and grows an overflowed one to
                    # the true hot-bucket size in one step
                    needs[nid] = xneed
                    return order_by(ex, key_fns, descs), needs

                # output stays sharded (range-partitioned + locally
                # sorted = totally ordered in shard-major array order)
                return fn_dsort, dicts
            child = self._gather_child(child)

            def fn_sort(inputs, caps):
                b, needs = child(inputs, caps)
                return order_by(b, key_fns, descs), needs

            return fn_sort, dicts

        if isinstance(plan, L.Window):
            from tidb_tpu.executor.window import WindowDesc, window_op

            child, dicts = self._build(plan.child)
            child = self._gather_child(child)
            part_fns = [compile_expr(e, dicts) for e in plan.partition_exprs]
            order_fns = [compile_expr(e, dicts) for e, _ in plan.order_exprs]
            order_descs = [d for _, d in plan.order_exprs]
            wdescs = []
            out_dicts = dict(dicts)
            for name, func, arg, offset, running, frame in plan.descs:
                fn = compile_expr(arg, dicts) if arg is not None else None
                scale = (
                    arg.type.scale
                    if arg is not None and arg.type.kind == Kind.DECIMAL
                    else 0
                )
                wdescs.append(
                    WindowDesc(func, fn, name, offset, scale, running, frame)
                )
                if func in ("lag", "lead", "min", "max") and arg is not None:
                    d = _expr_dict(arg, dicts)
                    if d is not None:
                        out_dicts[name] = d

            def fn_win(inputs, caps):
                b, needs = child(inputs, caps)
                return (
                    window_op(b, part_fns, order_fns, order_descs, wdescs),
                    needs,
                )

            return fn_win, out_dicts

        if isinstance(plan, L.Limit):
            if isinstance(plan.child, L.Sort) and plan.count is not None:
                return self._build_topn(plan)
            child, dicts = self._build(plan.child)
            child = self._gather_child(child)
            k, off = plan.count, plan.offset

            def fn_lim(inputs, caps):
                b, needs = child(inputs, caps)
                return limit_op(b, k, off), needs

            return fn_lim, dicts

        if isinstance(plan, L.UnionAll):
            built, ctags = [], []
            for c in plan.children:
                built.append(self._build(c))
                ctags.append(self._tag)
            if self.mesh_n and not all(t == "shard" for t in ctags):
                # mixed distribution: gather everything, emit replicated
                built = [
                    (self._gathered(f, t), d)
                    for (f, d), t in zip(built, ctags)
                ]
                self._tag = "repl"
            else:
                self._tag = "shard" if self.mesh_n else self._tag
            fns = [f for f, _ in built]
            child_dicts = [d for _, d in built]
            internals = [c.internal for c in plan.schema.cols]
            types = {c.internal: c.type for c in plan.schema.cols}
            # merge dictionaries per string output column; per-child LUTs
            out_dicts: Dicts = {}
            luts: Dict[str, List[Optional[jax.Array]]] = {}
            for name in internals:
                if types[name].kind != Kind.STRING:
                    continue
                ds = [cd.get(name) for cd in child_dicts]
                merged = np.array(
                    sorted({s for d in ds if d is not None for s in d.tolist()}),
                    dtype=object,
                )
                out_dicts[name] = merged
                luts[name] = [
                    jnp.asarray(
                        np.searchsorted(merged, d).astype(np.int32)
                        if d is not None and len(d)
                        else np.zeros(1, np.int32)
                    )
                    for d in ds
                ]

            def fn_union(inputs, caps):
                needs: Dict[int, jax.Array] = {}
                batches = []
                for f in fns:
                    b, n = f(inputs, caps)
                    needs.update(n)
                    batches.append(b)
                cols = {}
                for name in internals:
                    datas, valids = [], []
                    for ci, b in enumerate(batches):
                        c = b.cols[name]
                        d = c.data
                        if name in luts:
                            lut = luts[name][ci]
                            d = lut[jnp.clip(d, 0, lut.shape[0] - 1)]
                        datas.append(d)
                        valids.append(c.valid)
                    cols[name] = DevCol(
                        jnp.concatenate(datas), jnp.concatenate(valids)
                    )
                rv = jnp.concatenate([b.row_valid for b in batches])
                return Batch(cols, rv), needs

            return fn_union, out_dicts

        raise ExecError(f"no physical impl for {type(plan).__name__}")

    # ------------------------------------------------------------------
    def _build_aggregate(self, plan: L.Aggregate, post_pred=None):
        child, dicts = self._build(plan.child)
        child_tag = self._tag
        nid = self.fresh_id()
        self.sized.append(nid)
        self.defaults[nid] = 1024
        self.widths[nid] = _schema_width(plan.schema)
        key_fns, key_names, key_widths, descs = build_agg_parts(
            plan, dicts, compiler=self
        )
        scalar = not plan.group_exprs
        agg_names = [(n, f) for n, f, _a, _d in plan.aggs]
        mesh_n = self.mesh_n if child_tag == "shard" else None
        post_fn = (
            compile_expr(post_pred, agg_out_dicts(plan, dicts))
            if post_pred is not None
            else None
        )
        if mesh_n:
            # partial agg per shard -> all_to_all of group rows -> final
            # agg; groups end hash-sharded (keyed) / replicated (scalar)
            self._tag = "repl" if scalar else "shard"

        def fn_agg(inputs, caps):
            b, needs = child(inputs, caps)
            cap = caps[nid]
            if mesh_n:
                from tidb_tpu.parallel import distributed_group_aggregate

                out, total, dropped, xneed = distributed_group_aggregate(
                    b, key_fns, descs, cap, mesh_n,
                    key_names=key_names, key_widths=key_widths,
                )
                ngroups = jnp.maximum(
                    total, (dropped > 0).astype(total.dtype) * xneed
                )
                if post_fn is not None:
                    # distributed path: the fused HAVING applies as a
                    # row mask on the final (hash-sharded) groups —
                    # semantically the Selection node it replaced
                    c = post_fn(out)
                    out = Batch(
                        out.cols,
                        out.row_valid & c.valid & (c.data != 0),
                    )
            else:
                out, ngroups = group_aggregate(
                    b, key_fns, descs, cap, key_names,
                    key_widths=key_widths, post_filter=post_fn,
                )
            if scalar:
                # MySQL: scalar aggregation over empty input yields one
                # row: COUNT=0 valid, others NULL (branchless form).
                empty = ngroups == 0
                first = jnp.zeros(out.capacity, dtype=bool).at[0].set(True)
                rv = jnp.where(empty, first, out.row_valid)
                cols = {}
                for name, func in agg_names:
                    c = out.cols[name]
                    if func == "count":
                        cols[name] = DevCol(
                            jnp.where(empty, jnp.zeros_like(c.data), c.data),
                            jnp.where(empty, first, c.valid),
                        )
                    else:
                        cols[name] = DevCol(
                            c.data, jnp.where(empty, jnp.zeros_like(c.valid), c.valid)
                        )
                out = Batch(cols, rv)
            needs = dict(needs)
            needs[nid] = ngroups
            return out, needs

        return fn_agg, agg_out_dicts(plan, dicts)

    # ------------------------------------------------------------------
    def _topn_widths(self, keys, dicts):
        """Per-key (bit width, bias) for the packed top-k encoding, or
        None when the keys don't pack into <= 62 bits. Unlike the
        aggregation widths, integer-typed keys WITHOUT bounds (e.g. SUM
        outputs) get an assumed 40-bit width — runtime-verified, and
        dropped by the conservative recompile if values exceed it."""
        out = []
        total = 0
        for e, _d in keys:
            w = _key_width(e, dicts)
            if w is None and not self.conservative:
                kind = e.type.kind if e.type is not None else None
                if kind in (Kind.INT, Kind.DECIMAL, Kind.DATETIME, Kind.TIME):
                    w = (40, 1 << 39)  # covers |v| < 2^39
            if w is None:
                return None
            total += w[0]
            out.append(w)
        return out if total <= 62 else None

    def _build_topn(self, plan: L.Limit):
        """ORDER BY ... LIMIT n without sorting the dataset.

        Fast path: when every sort key packs into one int64 (dictionary
        codes, dates, bounded/assumed-width ints — desc keys keep their
        limb, asc keys flip it, so bigger packed == earlier row and
        MySQL NULL ordering falls out of the 0-limb), the top (n+offset)
        rows come from ONE jax.lax.top_k over the packed key: O(rows log
        n) and no gather of the full dataset. On a mesh each shard
        top-k's locally, only the n-row tiles all_gather, and a final
        top-k runs on mesh x n rows (reference: TopNExec pushed to each
        region + root merge, pkg/executor/sortexec/topn.go:31).

        Fallback (unpackable keys): full local sort + head tile, same
        shard/merge structure."""
        sort = plan.child
        inner, dicts = self._build(sort.child)
        if self._tag != "shard":
            inner = self._gathered(inner, self._tag)
            self._tag = "repl"
        key_fns = [compile_expr(e, dicts) for e, _ in sort.keys]
        descs = [d for _, d in sort.keys]
        n = plan.count + (plan.offset or 0)
        k, off = plan.count, plan.offset
        mesh_on = bool(self.mesh_n) and self._tag == "shard"
        if self.mesh_n:
            self._tag = "repl"

        widths = self._topn_widths(sort.keys, dicts) if n <= 4096 else None
        if widths is not None:
            total_bits = sum(w for w, _b in widths)
            snid = self.fresh_id()
            self.sized.append(snid)
            self.defaults[snid] = 16
            self.widths[snid] = 8

            def pack(b):
                packed = jnp.zeros(b.capacity, dtype=jnp.int64)
                stale = jnp.zeros((), dtype=bool)
                offb = total_bits
                for (w, bias), f, d in zip(widths, key_fns, descs):
                    offb -= w
                    kcol = f(b)
                    limb = jnp.where(
                        kcol.valid,
                        kcol.data.astype(jnp.int64) + (bias + 1),
                        0,
                    )
                    bad = kcol.valid & ((limb < 1) | (limb > ((1 << w) - 1)))
                    stale = stale | jnp.any(b.row_valid & bad)
                    enc = limb if d else ((1 << w) - 1) - limb
                    packed = packed | (enc << offb)
                # invalid rows sink below every real row (packed >= 0)
                return jnp.where(b.row_valid, packed, -1), stale

            def take(b, packed, kk):
                _vals, idx = jax.lax.top_k(packed, kk)
                cols = {
                    nm: DevCol(c.data[idx], c.valid[idx])
                    for nm, c in b.cols.items()
                }
                return Batch(cols, b.row_valid[idx])

            def fn_topk(inputs, caps):
                b, needs = inner(inputs, caps)
                packed, stale = pack(b)
                head = take(b, packed, min(n, b.capacity))
                if mesh_on:
                    from tidb_tpu.parallel import broadcast_gather

                    head = broadcast_gather(head)
                    p2, st2 = pack(head)
                    stale = stale | st2
                    head = take(head, p2, min(n, head.capacity))
                needs = dict(needs)
                needs[snid] = jnp.where(
                    stale, jnp.int64(_WIDTH_STALE), jnp.int64(0)
                )
                return limit_op(head, k, off), needs

            return fn_topk, dicts

        tile = pad_capacity(max(n, 1), floor=32)

        def fn_topn(inputs, caps):
            b, needs = inner(inputs, caps)
            b = order_by(b, key_fns, descs)
            # top-n per shard: sorted order puts valid rows first, so a
            # static head slice after masking rows past n is exact
            keep = jnp.cumsum(b.row_valid.astype(jnp.int32)) <= n
            t = min(tile, b.capacity)
            head = Batch(
                {
                    nm: DevCol(c.data[:t], c.valid[:t] & keep[:t])
                    for nm, c in b.cols.items()
                },
                b.row_valid[:t] & keep[:t],
            )
            if mesh_on:
                from tidb_tpu.parallel import broadcast_gather

                head = broadcast_gather(head)
                head = order_by(head, key_fns, descs)
            return limit_op(head, k, off), needs

        return fn_topn, dicts

    # ------------------------------------------------------------------
    def _build_join(self, plan: L.JoinPlan):
        left, ldicts = self._build(plan.left)
        ltag = self._tag
        right, rdicts = self._build(plan.right)
        rtag = self._tag
        dicts = {**ldicts, **rdicts}
        mesh = self.mesh_n

        def _gather_both():
            nonlocal left, right, ltag, rtag
            left = self._gathered(left, ltag)
            right = self._gathered(right, rtag)
            ltag = rtag = "repl"
            self._tag = "repl"

        if plan.kind == "cross":
            if mesh:
                _gather_both()
            res = compile_expr(plan.residual, dicts) if plan.residual is not None else None

            def fn_cross(inputs, caps):
                lb, n1 = left(inputs, caps)
                rb, n2 = right(inputs, caps)
                out, _total = _cross_join(lb, rb)
                if res is not None:
                    out = filter_batch(out, res)
                return out, {**n1, **n2}

            return fn_cross, _strip_uniq(dicts)

        lkeys, rkeys = [], []
        for le, re_ in plan.equi_keys:
            lf, rf = _align_key_fns(le, re_, ldicts, rdicts)
            lkeys.append(lf)
            rkeys.append(rf)
        lprops = rprops = ((None, False))
        chosen = None
        if len(lkeys) == 1:
            lkey, rkey = lkeys[0], rkeys[0]
            verify = None
            le0, re0 = plan.equi_keys[0]
            lprops = _join_key_props(le0, ldicts)
            rprops = _join_key_props(re0, rdicts)
        else:
            if plan.kind not in ("inner", "semi", "anti", "left"):
                raise ExecError("multi-key outer join not yet supported")
            # multi-key inner join: when one pair's key is provably
            # unique on its side, join on THAT pair alone (dense 1:1
            # path) and let the verify filter apply the remaining
            # equalities post-join — the unique key already guarantees
            # <= 1 match per probe row, so no hash-combine collisions
            # and no probe-chain expansion. (Q5's customer join:
            # c_custkey unique, c_nationkey = s_nationkey demoted.)
            # Semi/anti joins use the same trick but can't swap sides,
            # so only BUILD-side (right) uniqueness qualifies.
            if plan.kind in ("inner", "semi", "anti"):
                for i, (le0, re0) in enumerate(plan.equi_keys):
                    lp = _join_key_props(le0, ldicts)
                    rp = _join_key_props(re0, rdicts)
                    if rp[1] or (plan.kind == "inner" and lp[1]):
                        chosen, lprops, rprops = i, lp, rp
                        break
            if chosen is not None:
                lkey, rkey = lkeys[chosen], rkeys[chosen]
                # the join itself enforces the chosen pair's equality
                # exactly (dense 1:1 / searchsorted, runtime-verified):
                # verify only the demoted pairs
                verify = (
                    [f for j, f in enumerate(lkeys) if j != chosen],
                    [f for j, f in enumerate(rkeys) if j != chosen],
                )
            else:
                lkey = _hash_combine(lkeys)
                rkey = _hash_combine(rkeys)
                verify = (lkeys, rkeys)

        kind = plan.kind
        null_aware = plan.null_aware
        res = compile_expr(plan.residual, dicts) if plan.residual is not None else None

        if kind == "mark":
            # mark join: probe rows survive, gaining a boolean IN/EXISTS
            # result column (three-valued under null_aware — the IN
            # semantics; two-valued for EXISTS)
            if verify is not None or res is not None:
                raise ExecError(
                    "mark join supports a single equality key and no "
                    "residual conditions"
                )
            mark = getattr(plan, "mark_name", None) or plan.schema.cols[-1].internal
            three = null_aware
            if mesh:
                # replicate the build side: every shard marks its own
                # probe rows against the full build set
                right = self._gathered(right, rtag)
                rtag = "repl"
                self._tag = ltag
            snid = self._stale_sentinel_node(rprops)

            def fn_mark(inputs, caps):
                lb, n1 = left(inputs, caps)
                rb, n2 = right(inputs, caps)
                out, t = equi_join(
                    rb, lb, rkey, lkey, 0, "mark",
                    mark_name=mark, mark_three_valued=three,
                    build_bounds=rprops[0],
                )
                needs = {**n1, **n2}
                if snid is not None:
                    needs[snid] = _stale_only(t)
                return out, needs

            return fn_mark, {**ldicts}

        if kind in ("semi", "anti"):
            if verify is None and res is None:
                part_nid = None
                build_sharded = rtag == "shard"
                if mesh:
                    if ltag == "shard" and rtag == "shard" and plan.broadcast == "right":
                        right = self._gathered(right, rtag)
                        rtag, build_sharded = "repl", False
                    if ltag == "repl" and rtag == "shard":
                        # replicated probe vs sharded build: gather build
                        right = self._gathered(right, rtag)
                        rtag, build_sharded = "repl", False
                    if ltag == "shard" and rtag == "shard":
                        # repartition both sides on the join key so equal
                        # keys colocate (MPP HashPartition exchange)
                        part_nid = self.fresh_id()
                        self.sized.append(part_nid)
                        self.widths[part_nid] = _schema_width(plan.schema)
                        self.defaults[part_nid] = 0
                    self._tag = ltag

                snid = self._stale_sentinel_node(rprops)

                def fn_semi(inputs, caps):
                    lb, n1 = left(inputs, caps)
                    rb, n2 = right(inputs, caps)
                    needs = {**n1, **n2}
                    if part_nid is not None:
                        from tidb_tpu.parallel import repartition_pair

                        B = caps[part_nid]
                        lb, rb, drp, xneed = repartition_pair(
                            lb, rb, lkey, rkey, mesh, B
                        )
                        # overflow reports the TRUE per-bucket need: a
                        # hot key costs ONE recompile at the exact
                        # size, not a doubling ladder
                        needs[part_nid] = jnp.where(drp > 0, xneed, B)
                    out, _t = equi_join(
                        rb, lb, rkey, lkey, 0, kind, build_bounds=rprops[0]
                    )
                    if snid is not None:
                        needs[snid] = _stale_only(_t)
                    if null_aware and kind == "anti":
                        bk = rkey(rb)
                        has_null = jnp.any(~bk.valid & rb.row_valid)
                        if mesh and build_sharded:
                            has_null = jax.lax.pmax(has_null, "d")
                        pk = lkey(out)
                        out = Batch(out.cols, out.row_valid & ~has_null & pk.valid)
                    return out, needs

                return fn_semi, {**ldicts}

            # Multi-key semi/anti with a provably-unique build pair:
            # probe-aligned 1:1 lookup on that pair, demoted equalities
            # and any residual verified on the gathered build row — one
            # build pass + one probe pass, no expansion, no row-id
            # re-join (the expand path below cost Q5's customer-semi
            # rewrite 0.14s/run at SF1 before this).
            if (
                chosen is not None
                or (verify is None and res is not None and rprops[1])
            ) and not (null_aware and kind == "anti"):
                # (second disjunct: single-key correlated EXISTS whose
                # build side is unique — same lookup, no demoted pairs)
                from tidb_tpu.executor.join import lookup_build_rows

                part_nid = None
                if mesh:
                    if rtag == "shard" and (
                        ltag == "repl"
                        or (ltag == "shard" and plan.broadcast == "right")
                    ):
                        right = self._gathered(right, rtag)
                        rtag = "repl"
                    if ltag == "shard" and rtag == "shard":
                        part_nid = self.fresh_id()
                        self.sized.append(part_nid)
                        self.widths[part_nid] = _schema_width(plan.schema)
                        self.defaults[part_nid] = 0
                    self._tag = ltag
                # the sorted lookup's stale source is a runtime
                # uniqueness violation, not just outgrown bounds — the
                # sentinel is needed whenever either assumption is baked
                snid = self._stale_sentinel_node(
                    rprops if rprops[0] is not None else ((0, 0), True)
                )
                lks_rks = verify

                def fn_semi_lookup(inputs, caps):
                    lb, n1 = left(inputs, caps)
                    rb, n2 = right(inputs, caps)
                    needs = {**n1, **n2}
                    if part_nid is not None:
                        from tidb_tpu.parallel import repartition_pair

                        B = caps[part_nid]
                        lb, rb, drp, xneed = repartition_pair(
                            lb, rb, lkey, rkey, mesh, B
                        )
                        needs[part_nid] = jnp.where(drp > 0, xneed, B)
                    brow, matched, stale = lookup_build_rows(
                        rb, lb, rkey, lkey, build_bounds=rprops[0]
                    )
                    # joined namespace, probe-aligned: verify fns and the
                    # residual see probe cols + the matched build row's
                    # cols (junk where unmatched — masked right after)
                    bb = Batch(
                        {
                            **lb.cols,
                            **{
                                n: DevCol(
                                    c.data[brow], c.valid[brow] & matched
                                )
                                for n, c in rb.cols.items()
                            },
                        },
                        lb.row_valid,
                    )
                    ok = matched
                    if lks_rks is not None:
                        for lf2, rf2 in zip(*lks_rks):
                            a, c = lf2(bb), rf2(bb)
                            ok = ok & (a.data == c.data) & a.valid & c.valid
                    if res is not None:
                        r = res(bb)
                        ok = ok & r.data & r.valid
                    keep = ok if kind == "semi" else ~ok
                    out = Batch(lb.cols, lb.row_valid & keep)
                    if snid is not None:
                        needs[snid] = jnp.where(
                            stale, jnp.int64(_WIDTH_STALE), jnp.int64(0)
                        )
                    return out, needs

                return fn_semi_lookup, {**ldicts}

            # Semi/anti with multiple keys and/or a residual predicate
            # (correlated EXISTS): hash-combined keys can collide and
            # residuals need both sides' columns, so expand via an inner
            # join carrying a probe row id, verify every key pair exactly,
            # apply the residual, then mask the probe batch by surviving
            # row ids (an exact single-key semi join).
            if null_aware:
                raise ExecError("null-aware multi-key anti join not supported")
            if mesh:
                # row-id re-join must see both sides whole: run replicated
                _gather_both()
            nid = self.fresh_id()
            self.sized.append(nid)
            self.widths[nid] = _schema_width(plan.schema)
            self.defaults[nid] = 0
            lks_rks = verify

            def fn_semi_multi(inputs, caps):
                lb, n1 = left(inputs, caps)
                rb, n2 = right(inputs, caps)
                rid = jnp.arange(lb.capacity, dtype=jnp.int64)
                lb2 = Batch(
                    {**lb.cols, "_srowid": DevCol(rid, lb.row_valid)},
                    lb.row_valid,
                )
                cap = caps[nid] or pad_capacity(max(lb.capacity, 1024))
                j, total = equi_join(rb, lb2, rkey, lkey, cap, "inner")
                if lks_rks is not None:
                    lks, rks = lks_rks

                    def vf(bb):
                        ok = jnp.ones(bb.capacity, dtype=bool)
                        for lf2, rf2 in zip(lks, rks):
                            a, c = lf2(bb), rf2(bb)
                            ok = ok & (a.data == c.data) & a.valid & c.valid
                        return DevCol(ok, jnp.ones(bb.capacity, dtype=bool))

                    j = filter_batch(j, vf)
                if res is not None:
                    j = filter_batch(j, res)
                ridc = lambda b: b.cols["_srowid"]
                out, _t = equi_join(j, lb2, ridc, ridc, 0, kind)
                out = Batch(
                    {k: v for k, v in out.cols.items() if k != "_srowid"},
                    out.row_valid,
                )
                needs = {**n1, **n2}
                needs[nid] = total
                return out, needs

            return fn_semi_multi, {**ldicts}

        if kind == "left" and (verify is not None or res is not None):
            # LEFT join with multiple equi keys and/or an ON-residual.
            # Hash-combined keys collide and a post-join residual filter
            # would wrongly drop NULL-extended rows, so: (1) inner-join
            # with a probe row id, verifying every key pair exactly and
            # applying the residual to matched pairs only, then (2) LEFT
            # join the original probe against the survivors on row id —
            # exact single-key — so unmatched probe rows NULL-extend.
            # Reference: ON-clause vs WHERE-clause semantics in
            # pkg/planner/core/logical_plan_builder.go (outer join ON
            # conditions never filter the outer side).
            if mesh:
                _gather_both()
            nid = self.fresh_id()
            self.sized.append(nid)
            self.widths[nid] = _schema_width(plan.schema)
            self.defaults[nid] = 0
            nid2 = self.fresh_id()
            self.sized.append(nid2)
            self.widths[nid2] = _schema_width(plan.schema)
            self.defaults[nid2] = 0
            lks_rks = verify

            def fn_left_multi(inputs, caps):
                lb, n1 = left(inputs, caps)
                rb, n2 = right(inputs, caps)
                rid = jnp.arange(lb.capacity, dtype=jnp.int64)
                lb2 = Batch(
                    {**lb.cols, "_lrowid": DevCol(rid, lb.row_valid)},
                    lb.row_valid,
                )
                cap = caps[nid] or pad_capacity(max(lb.capacity, 1024))
                j, total = equi_join(rb, lb2, rkey, lkey, cap, "inner")
                if lks_rks is not None:
                    lks, rks = lks_rks

                    def vf(bb):
                        ok = jnp.ones(bb.capacity, dtype=bool)
                        for lf2, rf2 in zip(lks, rks):
                            a, c = lf2(bb), rf2(bb)
                            ok = ok & (a.data == c.data) & a.valid & c.valid
                        return DevCol(ok, jnp.ones(bb.capacity, dtype=bool))

                    j = filter_batch(j, vf)
                if res is not None:
                    j = filter_batch(j, res)
                rnames = set(rb.cols)
                j2 = Batch(
                    {
                        k: v
                        for k, v in j.cols.items()
                        if k in rnames or k == "_lrowid"
                    },
                    j.row_valid,
                )
                ridc = lambda b: b.cols["_lrowid"]
                cap2 = caps[nid2] or pad_capacity(max(lb.capacity, 1024))
                out, total2 = equi_join(j2, lb2, ridc, ridc, cap2, "left")
                out = Batch(
                    {k: v for k, v in out.cols.items() if k != "_lrowid"},
                    out.row_valid,
                )
                needs = {**n1, **n2}
                needs[nid] = total
                needs[nid2] = total2
                return out, needs

            return fn_left_multi, _strip_uniq(dicts)

        part_nid = None
        forced_swap = False
        if mesh and ltag == "shard" and rtag == "shard":
            # cost-based broadcast: replicate the estimated-small side
            # (all_gather of it) instead of all_to_all on both sides
            bc = plan.broadcast
            if bc == "right":
                right = self._gathered(right, rtag)
                rtag = "repl"
            elif bc == "left" and kind == "inner":
                left = self._gathered(left, ltag)
                ltag = "repl"
        if mesh:
            if ltag == "repl" and rtag == "shard":
                if kind == "inner":
                    # broadcast-style: replicated left is the build side
                    forced_swap = True
                    self._tag = "shard"
                else:
                    # outer probe must see every build row: gather build
                    right = self._gathered(right, rtag)
                    rtag = "repl"
                    self._tag = "repl"
            elif ltag == "shard" and rtag == "shard":
                part_nid = self.fresh_id()
                self.sized.append(part_nid)
                self.widths[part_nid] = _schema_width(plan.schema)
                self.defaults[part_nid] = 0
                self._tag = "shard"
            else:
                # rtag repl: build side already everywhere (broadcast join)
                self._tag = ltag
        nid = self.fresh_id()
        self.sized.append(nid)
        self.widths[nid] = _schema_width(plan.schema)
        self.defaults[nid] = 0  # resolved at first execution from probe cap

        def fn_join(inputs, caps):
            lb, n1 = left(inputs, caps)
            rb, n2 = right(inputs, caps)
            extra_needs = {}
            if part_nid is not None:
                from tidb_tpu.parallel import repartition_pair

                B = caps[part_nid]
                lb, rb, drp, xneed = repartition_pair(
                    lb, rb, lkey, rkey, mesh, B
                )
                extra_needs[part_nid] = jnp.where(drp > 0, xneed, B)
            build_b, probe_b, build_k, probe_k = rb, lb, rkey, lkey
            build_props = rprops
            if forced_swap or (
                kind == "inner" and not mesh and lb.capacity < rb.capacity
            ):
                build_b, probe_b, build_k, probe_k = lb, rb, lkey, rkey
                build_props = lprops
            cap = caps[nid] or pad_capacity(max(probe_b.capacity, 1024))
            out, total = equi_join(
                build_b, probe_b, build_k, probe_k, cap, kind,
                build_bounds=build_props[0], build_unique=build_props[1],
            )
            if verify is not None:
                lk, rk = verify

                def vf(b):
                    ok = jnp.ones(b.capacity, dtype=bool)
                    vv = jnp.ones(b.capacity, dtype=bool)
                    for lf2, rf2 in zip(lk, rk):
                        a, c = lf2(b), rf2(b)
                        ok = ok & (a.data == c.data)
                        vv = vv & a.valid & c.valid
                    return DevCol(ok, vv)

                out = filter_batch(out, vf)
            if res is not None:
                out = filter_batch(out, res)
            needs = {**n1, **n2}
            needs[nid] = total
            return out, needs

        if kind == "inner" and (len(plan.equi_keys) == 1 or chosen is not None):
            # inner join keyed (or chosen-keyed) on a single pair: a
            # unique build key can't duplicate the other side's rows, so
            # that side's uniqueness survives; the verify filter for
            # demoted pairs only drops rows and can't duplicate either
            return fn_join, _merge_join_dicts(
                ldicts, rdicts, lprops[1], rprops[1]
            )
        return fn_join, _strip_uniq(dicts)


# ---------------------------------------------------------------------------
# Executor: discovery loop + jit cache
# ---------------------------------------------------------------------------

_MAX_JOIN_CAP = 1 << 26


def _cap_tile(n: int) -> int:
    """Power-of-two tile >= n for capacity knobs (floor 16 — unlike batch
    tiles, small group/join tables benefit from staying small; group
    slot counts derived from these are used as bitmask moduli)."""
    return pad_capacity(n, floor=16, pow2=True)


class SharedPlanCache:
    """Process-wide compiled-plan cache shared across sessions.

    Every PhysicalExecutor keeps its private LRU (below), but executors
    are per-session / per-connection, so under the serving tier N
    concurrent sessions would otherwise each pay the XLA compile for
    the SAME plan shape — the dominant cost "Accelerating Presto with
    GPUs" (PAPERS.md) identifies at high concurrency. This cache is the
    cross-session tier: keyed exactly like the private LRU (plan
    fingerprint + per-scan table-uid/versions — which already folds in
    the PR 5 keyed-staged fingerprints: staged capacity, logical
    dtypes, dictionary content) plus the executor's mesh width (a mesh
    program is not a single-device program). An executor consults it on
    a private miss and publishes after a compile; entries remember
    their creating executor so CROSS-session reuse is observable
    (tidbtpu_executor_shared_plan_cache_cross_session_hits_total — the
    bench --serve-load acceptance signal).

    Sharing one CompiledQuery across concurrent executors is safe
    because the steady state is published as one atomic tuple
    (CompiledQuery.steady) and everything else on the dataclass is
    written once at compile time.

    Entries are WEAK references: a shared entry lives exactly as long
    as at least one executor still holds the CompiledQuery in its
    private LRU. That is the serving scenario (concurrent sessions
    reuse each other's live compiles) without the pathology of a
    strong process-global cache — compiled closures capture table
    readers, so a strong cache would pin whole dead catalogs (every
    test's, every closed connection's) for the life of the process.

    Misses are SINGLEFLIGHT: the first executor to miss a key CLAIMS
    it and compiles; concurrent requesters of the same key wait for
    that one publish instead of stampeding N identical compiles — the
    flash-crowd case (64 sessions, one dashboard query) pays one
    compile, and every waiter lands a (cross-session) hit. A claimant
    that fails releases the claim (abandon, via the caller's finally),
    and a bounded wait means a wedged claimant degrades a waiter to
    compiling itself, never to hanging."""

    def __init__(self):
        import weakref as _wr

        self._cv = racecheck.make_condition("executor.plan_cache")
        self._map: "_wr.WeakValueDictionary" = _wr.WeakValueDictionary()
        #: in-flight compiles: (mesh_n, key) -> claiming owner
        self._pending: Dict[tuple, int] = {}

    def get(self, mesh_n, key: tuple, owner: int, wait_s: float = 120.0):
        """A hit returns the CompiledQuery. A miss returns None and
        CLAIMS the key — the caller MUST publish via put() or release
        via abandon() (exception paths). If another executor holds the
        claim, block for its publish (same-key waits cannot deadlock:
        a claimant never re-enters get() for the key it holds)."""
        from tidb_tpu.utils.metrics import REGISTRY

        k = (mesh_n, key)
        deadline = None
        cq = None
        with self._cv:
            while True:
                cq = self._map.get(k)
                if cq is not None:
                    break
                claimant = self._pending.get(k)
                if claimant is None:
                    self._pending[k] = owner
                    break
                now = time.monotonic()
                if deadline is None:
                    deadline = now + wait_s
                if now >= deadline:
                    # claimant wedged: compile ourselves (duplicate
                    # work, never wrong). No claim taken — the original
                    # one stands until its publish/abandon.
                    break
                self._cv.wait(min(deadline - now, 0.1))
        if cq is None:
            REGISTRY.counter(
                "tidbtpu_executor_shared_plan_cache_misses_total",
                "shared plan-cache lookups that missed",
            ).inc()
            return None
        REGISTRY.counter(
            "tidbtpu_executor_shared_plan_cache_hits_total",
            "compiles avoided via the cross-session plan cache",
        ).inc()
        if getattr(cq, "shared_owner", None) != owner:
            REGISTRY.counter(
                "tidbtpu_executor_shared_plan_cache_cross_session_hits_total",
                "shared plan-cache hits on a plan another session compiled",
            ).inc()
        return cq

    def put(self, mesh_n, key: tuple, cq, owner: int) -> None:
        cq.shared_owner = owner  # creator id: cross-session accounting
        with self._cv:
            self._map[(mesh_n, key)] = cq
            self._pending.pop((mesh_n, key), None)
            self._cv.notify_all()

    def abandon(self, mesh_n, key: tuple, owner: int) -> None:
        """A claimant's compile failed: release the claim (only the
        claiming owner's — a waiter that timed out and then failed must
        not free someone else's live claim) so waiters stop waiting and
        the next requester claims."""
        with self._cv:
            if self._pending.get((mesh_n, key)) == owner:
                del self._pending[(mesh_n, key)]
                self._cv.notify_all()

    def invalidate(self, mesh_n, key: tuple) -> None:
        """Drop one entry (StaleWidthsError: the compiled program's
        baked bounds no longer cover the data — every session must
        recompile, not just the one that noticed)."""
        with self._cv:
            self._map.pop((mesh_n, key), None)

    def clear(self) -> None:
        with self._cv:
            self._map.clear()
            self._pending.clear()
            self._cv.notify_all()


SHARED_PLAN_CACHE = SharedPlanCache()


class PhysicalExecutor:
    """Runs compiled plans. With mesh_devices=N, every plan compiles to a
    single shard_map program over an N-device mesh: scans row-sharded
    (the Region data-parallel analog), aggregation/joins exchanged via
    all_to_all/all_gather collectives (the MPP HashPartition/Broadcast
    exchanges, pkg/store/mockstore/unistore/cophandler/mpp_exec.go:597),
    order-sensitive operators on gathered singleton fragments."""

    def __init__(self, catalog, mesh_devices: Optional[int] = None):
        self.catalog = catalog
        # fingerprint + versions -> CompiledQuery; ordered dict used as an
        # LRU (move-to-end on hit, evict oldest past capacity) like the
        # reference's plan-cache LRU (pkg/planner/core/plan_cache_lru.go)
        from collections import OrderedDict

        self._cache: "OrderedDict[tuple, CompiledQuery]" = OrderedDict()
        # session hook: (db, table) -> (Table, version) — lets snapshot
        # transactions pin versions / substitute shadow tables.
        self.table_hook = None
        # per-query device-memory budget in bytes (tidb_mem_quota_query);
        # session refreshes it per statement. None/0 = unlimited.
        self.quota_bytes = None
        # aggregate inputs execute chunked through host RAM when the scan
        # working set overruns device memory (tidb_tpu_stream_rows):
        # -1 = auto (bytes-based vs the device budget), >0 = explicit row
        # threshold, None/0 = never stream
        self.stream_rows = -1
        # kill safepoint hook (utils/sqlkiller): raises to abort
        self.kill_check = None
        # prepared-statement parameter bindings for the CURRENT statement
        # (slot -> numpy scalar in physical encoding); the session sets
        # them before run(). Empty for plain statements.
        self.param_values: Dict[int, object] = {}
        self.mesh = None
        self.mesh_n = mesh_devices
        if mesh_devices:
            from tidb_tpu.parallel.mesh import make_mesh

            self.mesh = make_mesh(mesh_devices)

    def _resolve(self, db: str, table: str):
        if self.table_hook is not None:
            return self.table_hook(db, table)
        t = self.catalog.table(db, table)
        return t, t.version

    def _params(self) -> Dict[int, "jax.Array"]:
        """Current prepared-statement bindings as device scalars (the
        second argument of every compiled program). Mesh programs never
        see runtime parameters (values are baked there)."""
        if not self.param_values or self.mesh is not None:
            return {}
        return {k: jnp.asarray(v) for k, v in self.param_values.items()}

    @staticmethod
    def watch_sig(key: tuple) -> tuple:
        """Version-independent plan signature for the engine watch's
        retrace accounting: _cache_key is (deliberately) version-keyed
        for plans over string columns, but a recompile of the same
        logical plan driven by data growth IS the retrace the watch
        exists to count — so the signature drops the version column."""
        fp, versions = key
        return (fp, tuple(v[:3] for v in versions))

    def _cache_key(self, plan: L.LogicalPlan) -> tuple:
        fp = plan_fingerprint(plan)
        versions = []

        def walk(p):
            if isinstance(p, L.Scan):
                t, v = self._resolve(p.db, p.table)
                # compiled plans bake in dictionary LUTs, so plans over
                # string columns are version-keyed; string-free scans
                # compile version-independent programs (data is re-fetched
                # every run) — iterative workloads (recursive CTEs, DML
                # loops) then reuse the jit instead of recompiling
                types = t.schema.types
                has_str = any(
                    types.get(c) is not None and types[c].kind == Kind.STRING
                    for c in p.columns
                )
                versions.append(
                    (p.db, p.table, getattr(t, "uid", None) or id(t), v if has_str else -1)
                )
            for c in _plan_children(p):
                walk(c)

        walk(plan)
        return (fp, tuple(versions))

    def _fetch_inputs(
        self, cq: CompiledQuery, mesh=None, pins=None, resolved=None,
        staged=None,
    ) -> Dict[int, Batch]:
        inputs = {}
        for nid, skey in cq.staged_sites:
            if staged is None or skey not in staged:
                raise ExecError(
                    f"keyed staged input {skey!r} missing at run time"
                )
            inputs[nid] = staged[skey]
        for s in cq.scans:
            t, v = self._resolve(s.db, s.table)
            if pins is not None:
                # hold the snapshot for this statement: concurrent
                # committers bump versions and GC old ones; an unpinned
                # in-flight read racing 2+ commits would KeyError.
                # pin-then-verify closes the resolve/pin window: once a
                # pin lands on a still-present version, GC keeps it.
                for _ in range(8):
                    if t.pin_verified(v):
                        break
                    t, v = self._resolve(s.db, s.table)
                else:
                    raise ExecError(f"snapshot of {s.db}.{s.table} vanished")
                pins.append((t, v))
            if resolved is not None:
                resolved[s.node_id] = (t, v)
            narrowed = (
                fetch_site_rows(t, s, v)
                # a fragment slice addresses the FULL block concatenation:
                # index-narrowed gathers would re-number rows and break
                # the disjoint per-host cover
                if mesh is None and s.frag is None
                else None
            )
            if narrowed is not None:
                inputs[s.node_id] = narrowed
            else:
                batch, _d = scan_table(
                    t, s.columns, version=v, mesh=mesh,
                    partitions=s.partitions, frag=s.frag,
                )
                inputs[s.node_id] = batch
        return inputs

    def _make_program(self, cq: CompiledQuery, frozen_caps: Dict[int, int]):
        """The whole-query callable over (inputs, params): plain plan fn
        on one device, or the shard_map-wrapped SPMD program on a mesh
        (the entire fragment tree is ONE collective XLA program —
        exchanges are all_to_all/all_gather inside, not RPCs). `params`
        is the prepared-statement parameter dict (slot -> scalar array),
        made visible to compiled literal readers during tracing; empty
        for plain statements, and always empty on a mesh (the session
        bakes parameter values into mesh plans)."""
        fn = cq.fn
        if self.mesh is None:
            from tidb_tpu.expression.kernels import param_scope

            def prog(i, p, _f=fn, _c=frozen_caps):
                with param_scope(p):
                    return _f(i, _c)

            return prog
        from jax.sharding import PartitionSpec as P

        n = self.mesh_n

        def local(i, _f=fn, _c=frozen_caps):
            b, needs = _f(i, _c)
            # pmax proves replication of the cardinality scalars to
            # shard_map AND takes the per-shard max for sizing knobs
            needs = {k: jax.lax.pmax(v, "d") for k, v in needs.items()}
            return b, needs

        from tidb_tpu.parallel.mesh import reshard, shard_map

        sm = shard_map(
            local, mesh=self.mesh, in_specs=(P("d"),), out_specs=(P("d"), P())
        )
        if cq.out_tag == "repl":
            from jax.sharding import NamedSharding

            repl = NamedSharding(self.mesh, P())

            def run_repl(i, _p=None):
                b, needs = sm(i)
                # replicated output: every shard emitted an identical full
                # copy; reshard (so the slice is legal for any mesh size)
                # and keep the first copy
                b = jax.tree.map(
                    lambda a: reshard(a, repl)[: a.shape[0] // n], b
                )
                return b, needs

            return run_repl
        return lambda i, _p=None, _sm=sm: _sm(i)

    def _admit(self, cq: CompiledQuery, inputs, caps) -> None:
        """Quota admission: pre-account every static buffer (scan batches
        + sized-node tiles) against tidb_mem_quota_query BEFORE launching.
        The reference escalates via ActionOnExceed (spill/cancel,
        pkg/util/memory/action.go:30); with static shapes the whole
        footprint is known up front, so over-quota queries are rejected
        with a tracker report instead of being killed mid-flight."""
        quota = self.quota_bytes
        # working-set estimate (inputs + operator tiles) — always
        # computed: the instance watchdog ranks sessions by it when the
        # server memory limit is breached (servermemorylimit.go:51)
        ws = 0
        for _nid, b in inputs.items():
            nb = b.capacity
            for dc in b.cols.values():
                nb += b.capacity * (dc.data.dtype.itemsize + 1)
            ws += nb
        for nid, cap in caps.items():
            ws += 2 * cap * cq.widths.get(nid, 64)
        self.last_working_set = ws
        from tidb_tpu.obs.engine_watch import ENGINE_WATCH

        ENGINE_WATCH.note_device_mem(ws)
        if not quota:
            return
        from tidb_tpu.utils.failpoint import inject
        from tidb_tpu.utils.memtrack import MemoryTracker, QuotaExceeded

        inject("executor/admission")
        root = MemoryTracker("query", quota_bytes=int(quota))
        scans = root.child("scans")
        nodes = root.child("operators")
        try:
            for nid, b in inputs.items():
                nb = b.capacity
                for dc in b.cols.values():
                    nb += b.capacity * (dc.data.dtype.itemsize + 1)
                scans.child(f"scan#{nid}").consume(nb)
            for nid, cap in caps.items():
                w = cq.widths.get(nid, 64)
                # keyed group tables allocate 2x slots; exchanges double-
                # buffer: a conservative 2x multiplier covers both
                nodes.child(f"node#{nid}").consume(2 * cap * w)
        except QuotaExceeded as e:
            report = "\n".join(root.report())
            raise ExecError(
                f"memory quota exceeded ({e}); tracker report:\n{report}"
            ) from None

    def _discover(
        self, cq: CompiledQuery, inputs, jit: bool = True
    ) -> Tuple[Batch, Dict[int, int]]:
        """Find the capacity vector. Each iteration compiles the whole plan
        at the candidate caps and fetches only the cardinality scalars in a
        single device->host round trip (transfers on a TPU tunnel are
        latency-bound, ~the same cost for 8 bytes as for 32MB). jit=False
        runs op-by-op for the instrumented EXPLAIN ANALYZE path."""
        from tidb_tpu.utils import failpoint

        failpoint.inject("executor/before-discover")
        caps = dict(cq.caps or cq.default_caps)
        defaulted = []
        for nid, c in caps.items():
            if c == 0:  # join knobs start at the dominant input tile
                d = _join_default(inputs, cq)
                if jit and self.mesh_n:
                    d = _cap_tile(max(d // self.mesh_n, 1024))
                caps[nid] = d
                defaulted.append(nid)
        if self.quota_bytes and defaulted:
            # under a memory quota, DEFAULT tiles must not fail
            # admission on their own: start small enough to fit and let
            # the overflow loop grow each knob only as the data proves
            # necessary — every growth re-admits, so a genuinely
            # over-quota cardinality still errors with the tracker
            # report (reference: quota actions escalate before failing,
            # pkg/util/memory/action.go). Only _join_default guesses are
            # clamped — capacities a previous execution DISCOVERED are
            # known-needed; re-clamping them would force a re-discovery
            # launch on every run
            share = max(int(self.quota_bytes) // (4 * len(caps)), 1)
            for nid in defaulted:
                w = cq.widths.get(nid, 64)
                lim = _cap_tile(max(share // (2 * max(w, 1)), 1024))
                if caps[nid] > lim:
                    caps[nid] = lim
        from tidb_tpu.utils.sqlkiller import current_check

        while True:
            if self.kill_check is not None:
                self.kill_check()
            else:
                # no explicitly-wired killer (worker-side producer/
                # consumer executors shared across shuffle tasks): the
                # thread-local current killer — set per dispatched
                # fragment/shuffle task around execution — makes
                # fleet-wide cancellation land at the same safepoint
                current_check()
            self._admit(cq, inputs, caps)
            frozen = dict(caps)
            if jit:
                from tidb_tpu.obs.engine_watch import watched_jit

                jitted = watched_jit(
                    self._make_program(cq, frozen), sig=("discover", cq.sig)
                )
            else:
                # eager single-device path (EXPLAIN ANALYZE instrumentation)
                fn = cq.fn
                jitted = lambda i, _p, _f=fn, _c=frozen: _f(i, _c)
            out, needs = jitted(inputs, self._params())
            needs_host = jax.device_get(needs)
            bumped = False
            for nid, true_n in needs_host.items():
                n = int(true_n)
                if n >= _WIDTH_STALE:
                    # baked packed-key bounds no longer cover the data:
                    # capacity bumps can't fix this — recompile the plan
                    # against fresh Table.col_bounds (run()'s retry loop)
                    raise StaleWidthsError()
                if n > caps[nid]:
                    failpoint.inject("executor/cap-overflow")
                    caps[nid] = _cap_tile(n)
                    if caps[nid] > _MAX_JOIN_CAP:
                        raise ExecError(f"result too large at node {nid}: {n} rows")
                    bumped = True
            if not bumped:
                # shrink every knob to the tight tile of its true
                # cardinality: small group tables unlock the scatter-free
                # masked aggregation path, and join/exchange tiles stop
                # inheriting the (huge) default of their input capacity
                if not cq.no_shrink:
                    for nid, true_n in needs_host.items():
                        if nid in caps:
                            caps[nid] = min(caps[nid], _cap_tile(int(true_n)))
                return out, caps

    def run(self, plan: L.LogicalPlan) -> Tuple[Batch, Dicts]:
        from tidb_tpu.planner.hostagg import try_host_agg
        from tidb_tpu.planner.streamed import try_partitioned, try_streamed
        from tidb_tpu.utils.metrics import REGISTRY

        # keyed staged inputs (shuffle consumers, the DCN final stage):
        # their batches are fed at run time through _run_pinned — the
        # streamed/partitioned re-chunkers compile their own pipelines
        # and never feed staged sites, so keyed plans must take the
        # compiled path only (their sources are already resident device
        # batches; there is nothing to page in anyway)
        staged = _staged_inputs(plan)
        # stale-width retry: programs bake integer key bounds as static
        # widths and verify them at run time; growth past them recompiles
        # against fresh bounds. The last attempts compile conservatively
        # (no runtime-verified assumptions) so even an assumption the
        # data permanently violates terminates.
        for _stale_attempt in range(4):
            conservative = _stale_attempt >= 2
            try:
                hosted = try_host_agg(self, plan)
                if hosted is not None:
                    return hosted
                if staged is None:
                    streamed = try_streamed(
                        self, plan, conservative=conservative
                    )
                    if streamed is not None:
                        return streamed
                    parted = try_partitioned(
                        self, plan, conservative=conservative
                    )
                    if parted is not None:
                        return parted

                key = self._cache_key(plan)
                cq = None if conservative else self._cache.get(key)
                shareable = not conservative and _plan_shareable(plan)
                claimed = False
                if cq is None and shareable:
                    # cross-session tier: another session/connection may
                    # already have compiled this exact plan shape (the
                    # serving-tier reuse — one compile serves the
                    # fleet). A miss CLAIMS the key (singleflight):
                    # publish or abandon below, or waiters stall
                    cq = SHARED_PLAN_CACHE.get(
                        self.mesh_n, key, owner=id(self)
                    )
                    claimed = cq is None
                    if cq is not None:
                        # imported entries honor the same LRU bound as
                        # compiles, or cross-session hits would grow
                        # the private cache without limit
                        while len(self._cache) >= 256:
                            self._cache.popitem(last=False)
                        self._cache[key] = cq
                # flight recorder: plan-cache outcome + plan digest for
                # the statements_summary attribution (obs/flight.py)
                from tidb_tpu.obs.flight import FLIGHT

                FLIGHT.note_plan_cache(cq is not None, key=key)
                if cq is not None:
                    self._cache.move_to_end(key)
                    REGISTRY.counter("tidbtpu_executor_plan_cache_hits_total").inc()
                else:
                    REGISTRY.counter("tidbtpu_executor_plan_cache_misses_total").inc()
                    try:
                        compiler = PlanCompiler(
                            self.catalog, resolver=self._resolve,
                            mesh_n=self.mesh_n, conservative=conservative,
                        )
                        cq = compiler.compile(plan)
                        cq.sig = self.watch_sig(key)
                    except BaseException:
                        if claimed:
                            SHARED_PLAN_CACHE.abandon(
                                self.mesh_n, key, id(self)
                            )
                        raise
                    while len(self._cache) >= 256:
                        self._cache.popitem(last=False)
                    self._cache[key] = cq
                    if shareable:
                        SHARED_PLAN_CACHE.put(
                            self.mesh_n, key, cq, owner=id(self)
                        )

                pins = []
                try:
                    return self._run_pinned(cq, pins, staged=staged)
                except ExecError as e:
                    # quota admission rejected the unpaged plan: retry
                    # with streaming FORCED — the aggregate's own
                    # working set fit the budget, but join tiles above
                    # it did not (the reference escalates the same way:
                    # memory-tracker pressure triggers spill actions,
                    # pkg/util/memory/action.go). Keyed staged plans
                    # never stream (see above): for them the quota
                    # rejection surfaces as-is.
                    if staged is None and "memory quota exceeded" in str(e):
                        forced = try_streamed(
                            self, plan, conservative=conservative,
                            force=True,
                        )
                        if forced is None:
                            forced = try_partitioned(
                                self, plan, conservative=conservative,
                                force=True,
                            )
                        if forced is not None:
                            return forced
                    raise
                finally:
                    for t, v in pins:
                        t.unpin(v)
            except StaleWidthsError:
                key = self._cache_key(plan)
                self._cache.pop(key, None)
                # stale widths are a property of the PLAN, not of this
                # executor: evict the shared entry too, or every other
                # session keeps re-importing the stale program
                SHARED_PLAN_CACHE.invalidate(self.mesh_n, key)
                sp = getattr(self, "_stream_plans", {})
                for k in [k for k in sp if k[0] == key]:
                    sp.pop(k, None)
        raise ExecError("packed key widths did not stabilize after recompiles")

    def _run_pinned(
        self, cq: CompiledQuery, pins, staged=None
    ) -> Tuple[Batch, Dicts]:
        resolved = {}
        inputs = self._fetch_inputs(
            cq, mesh=self.mesh, pins=pins, resolved=resolved,
            staged=staged,
        )
        # compile-time NULL-free assumptions: columns whose validity mask
        # was folded away must still be NULL-free at the fetched version
        # (host-side O(1) after the table's per-version cache warms)
        for nid, col in cq.nonnull:
            t, v = resolved[nid]
            if t.col_has_nulls(col, v):
                raise StaleWidthsError()
        # compile-time bounds that narrowed a wide sum: the fetched
        # version must still fit the baked interval or single-lane
        # accumulation could silently wrap — recompile instead
        for nid, col, lo, hi in cq.bound_checks:
            t, v = resolved[nid]
            cb = t.col_bounds(col, v)
            if cb is not None and (cb[0] < lo or cb[1] > hi):
                raise StaleWidthsError()
        shape_key = tuple(sorted((nid, b.capacity) for nid, b in inputs.items()))

        from tidb_tpu.obs.engine_watch import ENGINE_WATCH, watched_jit

        # the steady snapshot is read as ONE tuple: under the shared
        # cross-session plan cache, another executor may republish it
        # concurrently, and a (program, caps) pair from two different
        # publishes could accept a truncated output
        st = cq.steady
        if st is not None and st[2] == shape_key:
            st_jitted, st_caps, _sk = st
            out, needs = st_jitted(inputs, self._params())
            # ONE device->host round trip: output batch + cardinality
            # scalars together. Also warms each array's host-value cache so
            # the session's materialization re-reads are free.
            needs_host = jax.device_get((needs, out))[0]
            ENGINE_WATCH.d2h_batch(out)
            if not _overflowed(needs_host, st_caps):
                return out, cq.out_dicts
            # data grew past a tile: rediscover (drop the snapshot only
            # if it is still the one that overflowed)
            if cq.steady is st:
                cq.steady = None
                cq.jitted = None

        for _attempt in range(8):
            out, caps = self._discover(cq, inputs)
            nvalid = int(jax.device_get(_count_valid(out.row_valid)))
            out_cap = min(_cap_tile(max(nvalid, 1)), out.capacity)
            full_caps = dict(caps)
            full_caps[_OUT_NODE] = out_cap
            cq.caps = dict(full_caps)  # warm-start hint for _discover
            program = self._make_program(cq, dict(caps))
            jitted = watched_jit(
                lambda i, pv, _p=program, _oc=out_cap: _steady_step(
                    _p, _oc, i, pv, mesh=self.mesh
                ),
                sig=("steady", cq.sig),
            )
            # compile + run the steady program now so every later run is a
            # single launch + single fetch
            out, needs = jitted(inputs, self._params())
            needs_host = jax.device_get((needs, out))[0]
            ENGINE_WATCH.d2h_batch(out)
            if not _overflowed(needs_host, full_caps):
                # verified: publish the consistent snapshot atomically
                # (plus the loose fields for the profiling scripts)
                cq.jitted = jitted
                cq.input_shape_key = shape_key
                cq.steady = (jitted, full_caps, shape_key)
                return out, cq.out_dicts
            # the post-shrink steady run overflowed: stop shrinking this
            # plan's caps and rediscover from the grown values
            cq.no_shrink = True
            for nid, n in needs_host.items():
                if nid in caps and int(n) > caps[nid]:
                    caps[nid] = _cap_tile(int(n))
            cq.caps = dict(caps)
        raise ExecError("capacity discovery did not converge")

    def run_analyze(
        self, plan: L.LogicalPlan, frag_stats=None, shuffle_stats=None
    ) -> Tuple[Batch, Dicts, List[str]]:
        """EXPLAIN ANALYZE: instrumented single run with per-node stats.

        `frag_stats` is the distributed case (parallel/dcn.py): per-host
        fragment runtime stats gathered from the worker replies, merged
        into the plan-tree rows beneath the Staged exchange node the way
        the reference merges cop-task RuntimeStatsColl into the
        coordinator's plan tree. `shuffle_stats` is the worker-to-worker
        shuffle case: a (stage summary, per-partition infos) pair whose
        Shuffle exchange rows render the same way."""
        from tidb_tpu.planner.hostagg import _find_gc_agg, try_host_agg

        if _find_gc_agg(plan) is not None:
            # GROUP_CONCAT aggregates execute host-assisted — per-node
            # device instrumentation doesn't apply; report the plan shape
            # with timing of the whole statement instead of crashing in
            # the device compiler (which has no string-concat kernel)
            import time as _time

            t0 = _time.perf_counter()
            out, dicts = try_host_agg(self, plan)
            dt = (_time.perf_counter() - t0) * 1000
            lines = [
                f"HostAssistedAggregate(GROUP_CONCAT)  time={dt:.2f}ms "
                "(per-node stats unavailable on the host-assisted path)"
            ]
            return out, dicts, lines
        compiler = PlanCompiler(self.catalog, instrument=True, resolver=self._resolve)
        cq = compiler.compile(plan)
        # unsharded: eager single-device (keyed staged batches fed like
        # the run() path)
        inputs = self._fetch_inputs(cq, staged=_staged_inputs(plan))
        out, _caps = self._discover(cq, inputs, jit=False)
        lines = []
        for nid, depth, label in compiler.node_labels:
            st = compiler.stats.get(nid)
            suffix = (
                f"  rows={st['rows']} time={st['time_s']*1000:.2f}ms calls={st['calls']}"
                if st
                else ""
            )
            lines.append("  " * depth + label + suffix)
        if frag_stats:
            lines = _merge_frag_stats(lines, frag_stats)
        if shuffle_stats:
            if isinstance(shuffle_stats, list):
                # shuffle DAG: one (stage summary, infos) pair per
                # exchange stage, rendered topo-order under the
                # Staged node with the same grammar (each insert
                # lands directly below the anchor, so reversed
                # iteration leaves stage 0 on top)
                for stage, infos in reversed(shuffle_stats):
                    lines = _merge_shuffle_stats(lines, stage, infos)
            else:
                lines = _merge_shuffle_stats(lines, *shuffle_stats)
        return out, cq.out_dicts, lines


def _merge_frag_stats(lines: List[str], frag_stats) -> List[str]:
    """Insert per-host fragment rows into an EXPLAIN ANALYZE plan tree
    beneath the Staged node (the DCN exchange's coordinator side): one
    summary row (time min/avg/max across hosts, total rows and bytes
    shipped) plus one row per fragment (rows/host, execution time,
    bytes). The distributed analog of the reference's cop-task rows."""
    frags = sorted(frag_stats, key=lambda f: f.get("fid", 0))
    times = [float(f.get("exec_s", 0.0)) for f in frags] or [0.0]
    hosts = sorted({f.get("host", "?") for f in frags})
    total_bytes = sum(int(f.get("bytes", 0)) for f in frags)
    total_rows = sum(int(f.get("rows", 0)) for f in frags)
    summary = (
        f"DCNFragments fragments={len(frags)} hosts={len(hosts)} "
        f"rows={total_rows} bytes_shipped={total_bytes} "
        f"time min={min(times)*1000:.2f}ms "
        f"avg={(sum(times)/len(times))*1000:.2f}ms "
        f"max={max(times)*1000:.2f}ms"
    )
    summary += _compile_cost_suffix(frags)
    per_frag = [
        (
            f"Fragment#{f.get('fid')} host={f.get('host', '?')} "
            f"attempt={f.get('attempt', 1)} rows={f.get('rows', 0)} "
            f"time={float(f.get('exec_s', 0.0))*1000:.2f}ms "
            f"bytes={f.get('bytes', 0)}"
        )
        for f in frags
    ]
    return _insert_below_staged(lines, summary, per_frag)


def _compile_cost_suffix(frags) -> str:
    """Worker-reported XLA compile cost summed across the fenced
    fragment replies (obs/engine_watch.py harvest, shipped in reply
    stats) — rendered on the exchange summary row when any worker
    actually compiled during this statement. Empty on warm runs."""
    flops = sum(
        float((f.get("compile") or {}).get("flops", 0.0)) for f in frags
    )
    nbytes = sum(
        float((f.get("compile") or {}).get("bytes_accessed", 0.0))
        for f in frags
    )
    if not flops and not nbytes:
        return ""
    return (
        f" compile_flops={flops:.0f} compile_bytes_accessed={nbytes:.0f}"
    )


def _insert_below_staged(
    lines: List[str], summary: str, rows: List[str]
) -> List[str]:
    """Splice an exchange block (one summary line + indented per-unit
    rows) beneath the plan tree's Staged node — the coordinator side
    of any DCN exchange. Shared by the fragment and shuffle renderers
    so the anchor/indent rules never diverge."""
    idx = next(
        (i for i, ln in enumerate(lines) if ln.lstrip().startswith("Staged")),
        None,
    )
    if idx is None:
        pad = ""
        insert_at = len(lines)
    else:
        pad = " " * (len(lines[idx]) - len(lines[idx].lstrip()) + 2)
        insert_at = idx + 1
    block = [pad + summary] + [pad + "  " + r for r in rows]
    return lines[:insert_at] + block + lines[insert_at:]


def _merge_shuffle_stats(lines: List[str], stage, infos) -> List[str]:
    """Insert the worker-to-worker shuffle exchange rows into an
    EXPLAIN ANALYZE plan tree beneath the Staged node: one DCNShuffle
    summary (partition count, attempts, tunnel bytes/rows, stalls,
    retransmits) plus one ShuffleExchange row per partition — the MPP
    ExchangeSender/ExchangeReceiver rows of the reference's plan tree,
    rendered coordinator-side from the fenced task replies."""
    frags = sorted(infos, key=lambda f: f.get("fid", 0))
    hosts = sorted({f.get("host", "?") for f in frags})
    total_rows = sum(int(f.get("rows", 0)) for f in frags)
    # overlap: the share of total worker stage time NOT spent blocked
    # idle in the store waits — the pipelining win made visible (a
    # barrier stage idles through the whole exchange; a pipelined one
    # decodes/stages on arrival while producers still run)
    total_exec = float(stage.get("exec_s", 0.0)) or sum(
        float(f.get("exec_s", 0.0)) for f in frags
    )
    idle = float(stage.get("wait_idle_s", 0.0))
    overlap = max(0.0, 1.0 - idle / total_exec) if total_exec > 0 else 0.0
    # shuffle-DAG stages additionally carry their chain position, the
    # exchange kind chosen per edge (hash | range | broadcast), and
    # the per-stage produce/wait/stage phase seconds
    dag_bits = ""
    if "exchange" in stage:
        modes = stage.get("modes") or ()
        exch = (
            "broadcast"
            if "broadcast" in modes
            else stage.get("exchange", "hash")
        )
        pos = (
            f"stage={int(stage.get('stage', 0)) + 1}/"
            f"{int(stage.get('n_stages', 1))} "
            if "stage" in stage else ""
        )
        dag_bits = (
            pos
            + f"exchange={exch} "
            f"produce={float(stage.get('produce_s', 0.0))*1000:.2f}ms "
            f"wait={float(stage.get('wait_s', 0.0))*1000:.2f}ms "
            f"stage_s={float(stage.get('stage_s', 0.0))*1000:.2f}ms "
        )
    # AQE (parallel/aqe.py): every taken adaptive decision renders on
    # the exchange row (adaptive=salted:3|broadcast-switch|feedback),
    # and the per-partition received-row skew ratio renders whenever
    # partition counts exist — detection stays auditable even when
    # nothing triggered
    aqe_bits = ""
    if stage.get("skew"):
        aqe_bits += f" skew={float(stage['skew']):.2f}"
    if stage.get("adaptive"):
        aqe_bits += f" adaptive={'|'.join(stage['adaptive'])}"
    # runtime filter (PR 19): kind + bloom geometry + predicted vs
    # OBSERVED selectivity (kept/tested probe rows — the auto cost
    # gate's feedback signal), and filter-lost degrade counts
    rf = stage.get("rf")
    if rf:
        aqe_bits += f" rf={rf.get('kind', '?')}"
        if rf.get("bits"):
            aqe_bits += f":{int(rf['bits'])}b"
        if rf.get("sel_pred") is not None:
            aqe_bits += f" sel_pred={float(rf['sel_pred']):.3f}"
        if rf.get("sel_obs") is not None:
            aqe_bits += f" sel_obs={float(rf['sel_obs']):.3f}"
        if rf.get("lost"):
            aqe_bits += f" rf_lost={int(rf['lost'])}"
    summary = (
        f"DCNShuffle kind={stage.get('kind')} "
        + dag_bits
        + f"partitions={stage.get('m')} hosts={len(hosts)} "
        f"attempts={stage.get('attempts')} rows={total_rows} "
        f"bytes_tunneled={stage.get('bytes_tunneled')} "
        f"rows_tunneled={stage.get('rows_tunneled')} "
        f"local_rows={stage.get('local_rows')} "
        f"stalls={stage.get('stalls')} "
        f"retransmits={stage.get('retransmits')} "
        f"codec={stage.get('codec', 'json')} "
        f"encode={float(stage.get('encode_s', 0.0))*1000:.2f}ms "
        f"pipeline={'on' if stage.get('pipeline') else 'off'} "
        f"overlap={overlap*100:.0f}% "
        f"wait_idle={idle*1000:.2f}ms "
        f"ttff={float(stage.get('ttff_s', 0.0))*1000:.2f}ms"
        + aqe_bits
    )
    summary += _compile_cost_suffix(frags)
    per_part = [
        (
            f"ShuffleExchange part={f.get('fid')} "
            f"host={f.get('host', '?')} attempt={f.get('attempt', 1)} "
            f"rows={f.get('rows', 0)} "
            f"time={float(f.get('exec_s', 0.0))*1000:.2f}ms "
            f"pushed={f.get('pushed_bytes', 0)}B "
            f"stalls={f.get('stalls', 0)} "
            f"wait_idle={float(f.get('wait_idle_s', 0.0))*1000:.2f}ms "
            f"ttff={float(f.get('ttff_s', 0.0))*1000:.2f}ms"
        )
        for f in frags
    ]
    return _insert_below_staged(lines, summary, per_part)


# pseudo node id for the final output's compaction capacity
_OUT_NODE = -1


def _steady_step(program, out_cap, inputs, params=None, mesh=None):
    """Steady-state whole-query program: plan (possibly a shard_map SPMD
    program) + output compaction + output cardinality, in one XLA launch.
    Compaction runs on the global (post-shard_map) arrays; on a mesh the
    result is resharded to replicated first (the compaction gather is not
    expressible over a row-sharded operand)."""
    out, needs = program(inputs, params)
    needs = dict(needs)
    needs[_OUT_NODE] = _count_valid(out.row_valid)
    if out_cap < out.capacity:
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from tidb_tpu.parallel.mesh import reshard

            repl = NamedSharding(mesh, P())
            out = jax.tree.map(lambda a: reshard(a, repl), out)
        out = _compact_impl(out, out_cap)
    return out, needs


def _overflowed(needs_host: Dict[int, np.ndarray], caps: Dict[int, int]) -> bool:
    for nid, true_n in needs_host.items():
        cap = caps.get(nid, 0)
        if cap and int(true_n) > cap:
            return True
    return False


def fetch_site_rows(t, site, version):
    """Narrowed host fetch for one scan site: PK range or index-merge
    union (shared by PhysicalExecutor._fetch_inputs and the streamed
    path's _fetch_resident — one implementation, no drift). Returns a
    device Batch or None when the site has no narrowing."""
    from tidb_tpu.chunk import block_to_batch

    if site.pk_range is not None:
        col, lo, hi = site.pk_range
        idx = t.range_rows(col, lo, hi, version=version)
        return block_to_batch(t.gather_rows(idx, site.columns, version=version))
    if getattr(site, "merge_ranges", None) is not None:
        ids = [
            t.range_rows(col, lo, hi, version=version)
            for col, lo, hi in site.merge_ranges
        ]
        idx = np.unique(np.concatenate(ids))
        return block_to_batch(t.gather_rows(idx, site.columns, version=version))
    return None


def _join_default(inputs, cq) -> int:
    return pad_capacity(max([b.capacity for b in inputs.values()] + [1024]))


# ---------------------------------------------------------------------------
# shared helpers (also used by PlanCompiler)
# ---------------------------------------------------------------------------


def _node_label(plan: L.LogicalPlan) -> str:
    name = type(plan).__name__
    if isinstance(plan, L.Scan):
        return f"Scan table={plan.db}.{plan.table} cols={len(plan.columns)}"
    if isinstance(plan, L.Selection):
        return f"Selection pred={plan.predicate!r}"
    if isinstance(plan, L.Aggregate):
        return (
            f"Aggregate groups={[n for n, _ in plan.group_exprs]} "
            f"aggs={[f'{f}({n})' for n, f, _, _ in plan.aggs]}"
        )
    if isinstance(plan, L.JoinPlan):
        return f"Join kind={plan.kind} keys={len(plan.equi_keys)}"
    if isinstance(plan, L.Sort):
        return f"Sort keys={len(plan.keys)}"
    if isinstance(plan, L.Window):
        return f"Window funcs={[d[1] for d in plan.descs]} parts={len(plan.partition_exprs)}"
    if isinstance(plan, L.Limit):
        return f"Limit limit={plan.count} offset={plan.offset}"
    if isinstance(plan, L.Projection):
        return (
            f"Projection exprs={[n for n, _ in plan.exprs]}"
            + (" +base" if plan.additive else "")
        )
    if isinstance(plan, L.UnionAll):
        return f"UnionAll branches={len(plan.children)}"
    return name


@jax.jit
def _count_valid(row_valid: jax.Array) -> jax.Array:
    from tidb_tpu.executor.fastreduce import count

    return count(row_valid)


def _compact_impl(batch: Batch, out_cap: int) -> Batch:
    """Stable-partition valid rows to the front and slice to out_cap —
    runs on device so only pad_capacity(true rows) transfers to host."""
    cap = batch.capacity
    sorted_ops = jax.lax.sort(
        [(~batch.row_valid).astype(jnp.int32), jnp.arange(cap, dtype=jnp.int32)],
        num_keys=2,
    )
    perm = sorted_ops[1][:out_cap]
    cols = {
        n: DevCol(c.data[perm], c.valid[perm]) for n, c in batch.cols.items()
    }
    return Batch(cols, (~sorted_ops[0][:out_cap].astype(bool)))


def _bound_pred_cols(e):
    """Column names referenced by a bound predicate, or None when the
    tree contains nodes other than ColumnRef/Func/Literal (bail from
    HAVING fusion rather than guess)."""
    from tidb_tpu.expression.expr import Func, Literal

    out: set = set()

    def walk(x):
        if isinstance(x, ColumnRef):
            out.add(x.name)
        elif isinstance(x, Func):
            for a in x.args:
                if isinstance(a, (ColumnRef, Func, Literal)):
                    walk(a)
                elif isinstance(a, Expr):
                    raise _PredBail
        elif not isinstance(x, Literal):
            raise _PredBail

    try:
        walk(e)
    except _PredBail:
        return None
    return out


class _PredBail(Exception):
    pass


def _key_width(e: Expr, dicts: Dicts):
    """(bit width, bias) of a group key's packed encoding when a sound
    static bound exists (enables the scatter-free packed aggregation
    path); None otherwise. Integer-typed plain columns take their width
    from the storage layer's value bounds (Table.col_bounds, riding the
    dicts map) — these are exact at compile time and runtime-verified in
    the kernel, so growth past them re-plans instead of mis-grouping."""
    kind = e.type.kind if e.type is not None else None
    if kind == Kind.STRING:
        d = _expr_dict(e, dicts)
        if d is None:
            return None
        return (max(1, int(len(d)).bit_length()), 0)
    if isinstance(e, ColumnRef):
        cb = _resolve_bounds(dicts.get(_BOUNDS_PREFIX + e.name))
        if cb is not None:
            lo, hi = cb
            w = int(hi - lo + 1).bit_length()
            if w <= 40:
                return (w, -lo)
    if kind == Kind.DATE:
        return (33, 1 << 31)
    if kind == Kind.BOOL:
        return (2, 0)
    return None


def _expr_dict(e: Expr, dicts: Dicts) -> Optional[np.ndarray]:
    """Dictionary of a string-valued output expr (shared with the
    compiler's string_expr so codes and dictionary always agree)."""
    if e.type is None or e.type.kind != Kind.STRING:
        return None
    from tidb_tpu.expression.kernels import expr_dictionary

    return expr_dictionary(e, dicts)


def _join_key_props(e: Expr, dicts: Dicts):
    """(bounds, unique) of a join key column for the dense join paths.
    STRING keys are excluded: their codes are remapped into a merged
    dictionary by _align_key_fns, so the storage-level code bounds no
    longer describe the values the kernel sees."""
    if not isinstance(e, ColumnRef):
        return (None, False)
    if e.type is not None and e.type.kind == Kind.STRING:
        return (None, False)
    return (
        _resolve_bounds(dicts.get(_BOUNDS_PREFIX + e.name)),
        bool(dicts.get(_UNIQ_PREFIX + e.name)),
    )


def _align_key_fns(le: Expr, re_: Expr, ldicts: Dicts, rdicts: Dicts):
    """Compile join key exprs; for STRING keys, remap both sides' codes
    into a merged dictionary so integer equality == string equality."""
    if le.type is not None and le.type.kind == Kind.STRING:
        if not isinstance(le, ColumnRef) or not isinstance(re_, ColumnRef):
            raise ExecError("string join keys must be plain columns")
        ld = ldicts.get(le.name)
        rd = rdicts.get(re_.name)
        if ld is None or rd is None:
            raise ExecError("string join keys need dictionaries")
        # collation coercion: a CI collation on EITHER side makes the
        # join key CI — merge in sort-KEY space so equal-under-collation
        # values land on equal merged codes (collate.go Key() semantics)
        from tidb_tpu.utils import collate as _coll

        coll = le.type.collation or (
            re_.type.collation if re_.type is not None else None
        )
        _m, ll, lr = _coll.merge_rank_luts(ld, rd, coll)
        lut_l = jnp.asarray(np.asarray(ll, dtype=np.int32))
        lut_r = jnp.asarray(np.asarray(lr, dtype=np.int32))
        lname, rname = le.name, re_.name

        def _mapped(c: DevCol, lut) -> DevCol:
            if lut.shape[0] == 0:
                # an EMPTY dictionary (a 0-row shuffle partition's
                # staged side): no valid rows exist, so any constant
                # key works — never index into a size-0 LUT
                return DevCol(
                    jnp.zeros(c.data.shape, dtype=jnp.int32), c.valid
                )
            return DevCol(lut[jnp.clip(c.data, 0, lut.shape[0] - 1)], c.valid)

        def lf(b: Batch) -> DevCol:
            return _mapped(b.cols[lname], lut_l)

        def rf(b: Batch) -> DevCol:
            return _mapped(b.cols[rname], lut_r)

        return lf, rf
    lfn = compile_expr(le, ldicts)
    rfn = compile_expr(re_, rdicts)
    return lfn, rfn


def _hash_combine(key_fns):
    def f(b: Batch) -> DevCol:
        h = jnp.zeros(b.capacity, dtype=jnp.int64)
        valid = jnp.ones(b.capacity, dtype=bool)
        for fn in key_fns:
            c = fn(b)
            k = c.data.astype(jnp.int64)
            h = (h * jnp.int64(-7046029254386353131)) ^ (
                k + jnp.int64(-9061461749304837403) + (h << 6) + (h >> 2)
            )
            valid = valid & c.valid
        return DevCol(h, valid)

    return f


def _cross_join(left: Batch, right: Batch):
    """Nested-loop cross join via broadcast (small sides only)."""
    lcap, rcap = left.capacity, right.capacity
    if lcap * rcap > (1 << 24):
        raise ExecError("cross join too large")
    li = jnp.repeat(jnp.arange(lcap), rcap)
    ri = jnp.tile(jnp.arange(rcap), lcap)
    cols = {}
    for n, c in left.cols.items():
        cols[n] = DevCol(c.data[li], c.valid[li])
    for n, c in right.cols.items():
        cols[n] = DevCol(c.data[ri], c.valid[ri])
    rv = left.row_valid[li] & right.row_valid[ri]
    total = jnp.sum(rv.astype(jnp.int64))
    return Batch(cols, rv), total
