"""Serializable plan IR — the tipb.DAGRequest analog.

Reference: the pushdown IR crossing the compute boundary —
`tipb.DAGRequest` with its `Executor` tree (TableScan/Selection/
Aggregation/TopN/Join/ExchangeSender/...) and `Expr` protobufs, built by
`pkg/planner/core/plan_to_pb.go:88,245` and shipped via
`kv.Request.Data` (pkg/kv/kv.go:523) to the coprocessor / MPP engine.

TPU-native shape: the bound LOGICAL plan serializes to a JSON-stable
tree (expressions included); the device engine deserializes and
compiles it to XLA exactly as if it had been built in-process — the
seam a multi-host frontend/engine split plugs into (see
tidb_tpu/server/engine_rpc.py for the loopback transport, the
unistore `RPCClient.SendRequest` short-circuit analog, rpc.go:64).

Staged nodes (device-resident batches) are deliberately NOT
serializable — they never cross the boundary, matching the reference
where intermediate MPP data moves as chunks, not plans.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tidb_tpu.dtypes import Kind, SQLType
from tidb_tpu.expression.expr import ColumnRef, Expr, Func, Literal
from tidb_tpu.planner import logical as L

# v2: Scan gained the semantically-mandatory `frag` fragment slice —
# an engine that ignored it would scan the full table and the merged
# final aggregate would count every row n times, so the version check
# must fence pre-frag engines instead of letting them answer wrongly.
# v3: ShuffleRead — the worker-to-worker shuffle exchange leaf
# (parallel/shuffle.py); a pre-shuffle engine cannot resolve it, so the
# version fence keeps mixed fleets from half-executing a shuffle plan
# v4: StageInput — the shuffle-DAG re-staging leaf (a worker's held
# output of an earlier exchange stage feeds the next stage's producer);
# a pre-DAG engine cannot resolve held stage outputs, so the fence
# keeps it from silently re-scanning base tables instead
IR_VERSION = 4


# -- types ------------------------------------------------------------------


def _type_to_ir(t: Optional[SQLType]):
    if t is None:
        return None
    return {"k": t.kind.value, "s": t.scale}


def _type_from_ir(d) -> Optional[SQLType]:
    if d is None:
        return None
    return SQLType(Kind(d["k"]), scale=d.get("s", 0))


# -- expressions ------------------------------------------------------------


def expr_to_ir(e: Optional[Expr]):
    if e is None:
        return None
    if isinstance(e, ColumnRef):
        return {"x": "col", "t": _type_to_ir(e.type), "name": e.name}
    if isinstance(e, Literal):
        return {"x": "lit", "t": _type_to_ir(e.type), "v": e.value}
    if isinstance(e, Func):
        return {
            "x": "fn", "t": _type_to_ir(e.type), "op": e.op,
            "args": [expr_to_ir(a) for a in e.args],
        }
    raise ValueError(f"unserializable expression {type(e).__name__}")


def expr_from_ir(d) -> Optional[Expr]:
    if d is None:
        return None
    x = d["x"]
    if x == "col":
        return ColumnRef(type=_type_from_ir(d["t"]), name=d["name"])
    if x == "lit":
        return Literal(type=_type_from_ir(d["t"]), value=d["v"])
    if x == "fn":
        return Func(
            type=_type_from_ir(d["t"]), op=d["op"],
            args=tuple(expr_from_ir(a) for a in d["args"]),
        )
    raise ValueError(f"bad expression tag {x!r}")


def _schema_to_ir(sch: L.Schema):
    return [
        [c.qualifier, c.name, c.internal, _type_to_ir(c.type)]
        for c in sch.cols
    ]


def _schema_from_ir(cols) -> L.Schema:
    return L.Schema(
        [L.OutCol(q, n, i, _type_from_ir(t)) for q, n, i, t in cols]
    )


# -- plan nodes -------------------------------------------------------------


def plan_to_ir(p: L.LogicalPlan) -> Dict:
    """Bound logical plan -> JSON-stable dict (the DAGRequest)."""
    sch = _schema_to_ir(p.schema)
    if isinstance(p, L.OneRow):
        return {"n": "one_row", "schema": sch}
    if isinstance(p, L.Scan):
        d = {
            "n": "scan", "schema": sch, "db": p.db, "table": p.table,
            "alias": p.alias, "columns": list(p.columns),
        }
        if p.frag is not None:
            # fragment slice rides the IR so a worker engine scans only
            # its host's disjoint share (the DCN fragment dispatch seam)
            d["frag"] = [int(p.frag[0]), int(p.frag[1])]
        return d
    if isinstance(p, L.Selection):
        return {
            "n": "selection", "schema": sch,
            "child": plan_to_ir(p.child), "pred": expr_to_ir(p.predicate),
        }
    if isinstance(p, L.Projection):
        return {
            "n": "projection", "schema": sch,
            "child": plan_to_ir(p.child), "additive": p.additive,
            "exprs": [[n, expr_to_ir(e)] for n, e in p.exprs],
        }
    if isinstance(p, L.Aggregate):
        if p.gc_meta:
            raise ValueError(
                "GROUP_CONCAT plans execute host-assisted; they do not "
                "cross the engine boundary"
            )
        return {
            "n": "aggregate", "schema": sch, "child": plan_to_ir(p.child),
            "groups": [[n, expr_to_ir(e)] for n, e in p.group_exprs],
            "aggs": [
                [n, f, expr_to_ir(a), bool(d)] for n, f, a, d in p.aggs
            ],
        }
    if isinstance(p, L.JoinPlan):
        return {
            "n": "join", "schema": sch, "kind": p.kind,
            "left": plan_to_ir(p.left), "right": plan_to_ir(p.right),
            "equi": [
                [expr_to_ir(l), expr_to_ir(r)] for l, r in p.equi_keys
            ],
            "residual": expr_to_ir(p.residual),
            "null_aware": p.null_aware, "broadcast": p.broadcast,
        }
    if isinstance(p, L.Sort):
        return {
            "n": "sort", "schema": sch, "child": plan_to_ir(p.child),
            "keys": [[expr_to_ir(e), bool(d)] for e, d in p.keys],
        }
    if isinstance(p, L.Limit):
        return {
            "n": "limit", "schema": sch, "child": plan_to_ir(p.child),
            "count": p.count, "offset": p.offset,
        }
    if isinstance(p, L.Window):
        return {
            "n": "window", "schema": sch, "child": plan_to_ir(p.child),
            "partition": [expr_to_ir(e) for e in p.partition_exprs],
            "order": [[expr_to_ir(e), bool(d)] for e, d in p.order_exprs],
            "descs": [
                [n, f, expr_to_ir(a), off, bool(run),
                 list(frame) if frame is not None else None]
                for n, f, a, off, run, frame in p.descs
            ],
        }
    if isinstance(p, L.UnionAll):
        return {
            "n": "union_all", "schema": sch,
            "children": [plan_to_ir(c) for c in p.children],
        }
    if isinstance(p, L.ShuffleRead):
        return {"n": "shuffle_read", "schema": sch, "tag": int(p.tag)}
    if isinstance(p, L.StageInput):
        return {"n": "stage_input", "schema": sch, "stage": int(p.stage)}
    raise ValueError(f"unserializable plan node {type(p).__name__}")


def plan_from_ir(d: Dict) -> L.LogicalPlan:
    n = d["n"]
    sch = _schema_from_ir(d["schema"])
    if n == "one_row":
        return L.OneRow(sch)
    if n == "scan":
        frag = d.get("frag")
        return L.Scan(
            sch, d["db"], d["table"], d["alias"], list(d["columns"]),
            frag=tuple(frag) if frag is not None else None,
        )
    if n == "selection":
        return L.Selection(sch, plan_from_ir(d["child"]), expr_from_ir(d["pred"]))
    if n == "projection":
        return L.Projection(
            sch, plan_from_ir(d["child"]),
            [(nm, expr_from_ir(e)) for nm, e in d["exprs"]],
            additive=d.get("additive", False),
        )
    if n == "aggregate":
        return L.Aggregate(
            sch, plan_from_ir(d["child"]),
            [(nm, expr_from_ir(e)) for nm, e in d["groups"]],
            [
                (nm, f, expr_from_ir(a), bool(dd))
                for nm, f, a, dd in d["aggs"]
            ],
        )
    if n == "join":
        return L.JoinPlan(
            sch, d["kind"], plan_from_ir(d["left"]), plan_from_ir(d["right"]),
            [(expr_from_ir(l), expr_from_ir(r)) for l, r in d["equi"]],
            expr_from_ir(d.get("residual")),
            bool(d.get("null_aware")), d.get("broadcast"),
        )
    if n == "sort":
        return L.Sort(
            sch, plan_from_ir(d["child"]),
            [(expr_from_ir(e), bool(dd)) for e, dd in d["keys"]],
        )
    if n == "limit":
        return L.Limit(
            sch, plan_from_ir(d["child"]), d["count"], d.get("offset", 0)
        )
    if n == "window":
        return L.Window(
            sch, plan_from_ir(d["child"]),
            [expr_from_ir(e) for e in d["partition"]],
            [(expr_from_ir(e), bool(dd)) for e, dd in d["order"]],
            [
                (nm, f, expr_from_ir(a), off, bool(run),
                 tuple(frame) if frame is not None else None)
                for nm, f, a, off, run, frame in d["descs"]
            ],
        )
    if n == "union_all":
        return L.UnionAll(sch, [plan_from_ir(c) for c in d["children"]])
    if n == "shuffle_read":
        return L.ShuffleRead(sch, tag=int(d.get("tag", 0)))
    if n == "stage_input":
        return L.StageInput(sch, stage=int(d.get("stage", 0)))
    raise ValueError(f"bad plan tag {n!r}")


def serialize_plan(p: L.LogicalPlan) -> bytes:
    import json

    return json.dumps({"v": IR_VERSION, "plan": plan_to_ir(p)}).encode()


def deserialize_plan(data: bytes) -> L.LogicalPlan:
    import json

    d = json.loads(data.decode())
    if d.get("v") != IR_VERSION:
        raise ValueError(f"unsupported IR version {d.get('v')}")
    return plan_from_ir(d["plan"])
