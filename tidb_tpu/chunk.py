"""Columnar batches: host side (numpy) and device side (jax pytrees).

Reference: pkg/util/chunk — Apache Arrow-format Chunk (chunk.go:34) with
Column{nullBitmap, offsets, data} (column.go:63) and a sel vector. The TPU
design keeps the same information with static shapes:

- ``HostColumn``: numpy data + bool validity (+ sorted string dictionary).
- ``HostBlock``: a set of named HostColumns with a row count — the unit of
  storage (a table partition holds a list of blocks).
- ``DevCol`` / ``Batch``: jax pytrees. ``Batch.row_valid`` plays the role of
  the reference's sel vector: filters do not compact, they mask. Row
  capacity is padded to a fixed tile ladder so XLA compiles one program per
  (plan, shape bucket) — the analog of the reference's plan cache
  (pkg/planner/core/plan_cache.go:231) interacting with paging sizes
  (pkg/util/paging/paging.go:25).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.dtypes import Kind, SQLType

# Fixed tile ladder (rows). Mirrors the reference's paging growth
# 128 -> 50k (pkg/util/paging/paging.go:25-28) but with powers of two so a
# handful of compiled programs cover all sizes.
_MIN_CAPACITY = 256


def pad_capacity(n: int, floor: int = _MIN_CAPACITY, pow2: bool = False) -> int:
    """Smallest tile >= n on the engine's tiling ladder (>= floor).

    Batch tiles use half-steps (.., 2^k, 3*2^(k-1), 2^(k+1), ..): pure
    power-of-two padding wastes up to 50% of every full-array pass (TPC-H
    SF1 lineitem is 6.0M rows — 8.39M padded vs 6.29M with half-steps).
    pow2=True restricts to powers of two for sizes used as bitmask moduli
    (hash-table slot counts, exchange buckets)."""
    cap = floor
    while cap < n:
        half = cap + cap // 2
        if not pow2 and cap % 2 == 0 and half >= n:
            return half
        cap *= 2
    return cap


@dataclasses.dataclass
class HostColumn:
    """Numpy-backed column. ``dictionary`` is present iff type is STRING;
    it is sorted, so code order == binary collation order."""

    type: SQLType
    data: np.ndarray
    valid: np.ndarray
    dictionary: Optional[np.ndarray] = None  # np.array of str objects

    def __post_init__(self) -> None:
        assert self.data.shape == self.valid.shape

    def __len__(self) -> int:
        return len(self.data)

    def decode(self) -> np.ndarray:
        """Materialize logical values (object array with None for NULL).
        Vectorized — the reference streams chunks to the wire without a
        per-row interpreter (pkg/server/conn.go writeChunks:2286); a
        Python per-row loop here dominated large result sets."""
        n = len(self.data)
        out = np.empty(n, dtype=object)
        if self.type.kind == Kind.STRING:
            if self.dictionary is not None and len(self.dictionary):
                codes = np.clip(self.data, 0, len(self.dictionary) - 1)
                out[:] = self.dictionary[codes]
            else:
                out[:] = ""
        elif self.type.kind == Kind.DECIMAL:
            out[:] = (self.data / (10 ** self.type.scale)).tolist()
        elif self.type.kind == Kind.BOOL:
            out[:] = self.data.astype(bool).tolist()
        elif self.type.kind == Kind.FLOAT:
            out[:] = self.data.astype(np.float64).tolist()
        else:
            out[:] = self.data.astype(np.int64).tolist()
        out[~self.valid] = None
        return out


def encode_strings(values: List[Optional[str]]) -> HostColumn:
    """Dictionary-encode a string column. The dictionary is sorted so that
    integer code comparisons implement binary-collation string comparisons
    on device (reference collation engine: pkg/util/collate)."""
    valid = np.array([v is not None for v in values], dtype=bool)
    present = sorted({v for v in values if v is not None})
    dictionary = np.array(present, dtype=object)
    lookup = {v: i for i, v in enumerate(present)}
    codes = np.array([lookup[v] if v is not None else 0 for v in values], dtype=np.int32)
    from tidb_tpu.dtypes import STRING

    return HostColumn(STRING, codes, valid, dictionary)


def column_from_values(values: List, typ: SQLType) -> HostColumn:
    if typ.kind == Kind.STRING:
        return encode_strings(values)
    valid = np.array([v is not None for v in values], dtype=bool)
    if typ.kind == Kind.DECIMAL:
        data = np.array(
            [round(float(v) * 10**typ.scale) if v is not None else 0 for v in values],
            dtype=np.int64,
        )
    elif typ.kind == Kind.DATE:
        from tidb_tpu.dtypes import date_to_days

        data = np.array(
            [date_to_days(v) if isinstance(v, str) else (v or 0) for v in values],
            dtype=np.int32,
        )
    elif typ.kind == Kind.DATETIME:
        from tidb_tpu.dtypes import datetime_to_micros

        data = np.array(
            [
                datetime_to_micros(v) if isinstance(v, str) else (v or 0)
                for v in values
            ],
            dtype=np.int64,
        )
    elif typ.kind == Kind.TIME:
        from tidb_tpu.dtypes import time_to_micros

        data = np.array(
            [
                time_to_micros(v) if isinstance(v, str) else (v or 0)
                for v in values
            ],
            dtype=np.int64,
        )
    else:
        data = np.array([v if v is not None else 0 for v in values], dtype=typ.np_dtype)
    return HostColumn(typ, data, valid)


_block_uid = itertools.count(1)


@dataclasses.dataclass
class HostBlock:
    """A batch of rows on the host: the storage unit of a table partition."""

    columns: Dict[str, HostColumn]
    nrows: int
    # partition id for blocks of a partitioned table (Table.split_by_
    # partition tags appends); None = unpartitioned
    part_id: Optional[int] = None
    # process-unique immutable-block identity: version deltas (log
    # backup) diff block lists by uid instead of object identity, which
    # GC could recycle
    uid: int = dataclasses.field(default_factory=lambda: next(_block_uid))

    @staticmethod
    def from_columns(columns: Dict[str, HostColumn]) -> "HostBlock":
        n = len(next(iter(columns.values()))) if columns else 0
        for c in columns.values():
            assert len(c) == n
        return HostBlock(columns, n)


def take_block(block: HostBlock, idx: np.ndarray) -> HostBlock:
    """Rows of a block selected by index array, column-wise (one
    ``np.take`` per column — the vectorized partition split of the
    shuffle producer; no Python row loop)."""
    cols = {
        n: HostColumn(c.type, c.data[idx], c.valid[idx], c.dictionary)
        for n, c in block.columns.items()
    }
    return HostBlock(cols, len(idx))


def slice_block(block: HostBlock, a: int, b: int) -> HostBlock:
    """Contiguous row range [a, b) of a block as numpy views (packet
    chunking on the shuffle send path — zero-copy)."""
    b = min(b, block.nrows)
    cols = {
        n: HostColumn(c.type, c.data[a:b], c.valid[a:b], c.dictionary)
        for n, c in block.columns.items()
    }
    return HostBlock(cols, max(b - a, 0))


def concat_host_columns(typ: SQLType, chunks: List[HostColumn]) -> HostColumn:
    """Concatenate column chunks into one HostColumn. For strings the
    chunks' per-batch dictionaries are unified into ONE sorted
    stage-local dictionary and every chunk's codes are re-keyed against
    it — dictionary codes become comparable across senders and across
    exchange sides (code order still == binary collation order), which
    is what makes string join keys shuffle-safe (ROADMAP item c)."""
    if typ.kind != Kind.STRING:
        if not chunks:
            return HostColumn(
                typ,
                np.zeros(0, dtype=typ.np_dtype),
                np.zeros(0, dtype=bool),
            )
        data = np.concatenate(
            [np.asarray(c.data, dtype=typ.np_dtype) for c in chunks]
        )
        valid = np.concatenate(
            [np.asarray(c.valid, dtype=bool) for c in chunks]
        )
        return HostColumn(typ, data, valid)
    vocab = set()
    for c in chunks:
        if c.dictionary is not None:
            vocab.update(str(s) for s in c.dictionary.tolist())
    unified = np.array(sorted(vocab), dtype=object)
    lut = {v: i for i, v in enumerate(unified.tolist())}
    datas, valids = [], []
    for c in chunks:
        valid = np.asarray(c.valid, dtype=bool)
        if c.dictionary is not None and len(c.dictionary):
            mapping = np.array(
                [lut[str(v)] for v in c.dictionary.tolist()],
                dtype=np.int32,
            )
            codes = mapping[
                np.clip(np.asarray(c.data), 0, len(c.dictionary) - 1)
            ]
        else:
            codes = np.zeros(len(c.data), dtype=np.int32)
        datas.append(np.where(valid, codes, 0).astype(np.int32))
        valids.append(valid)
    data = (
        np.concatenate(datas) if datas else np.zeros(0, dtype=np.int32)
    )
    valid = (
        np.concatenate(valids) if valids else np.zeros(0, dtype=bool)
    )
    return HostColumn(typ, data, valid, unified)


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DevCol:
    data: jax.Array
    valid: jax.Array  # bool, True = not NULL


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Batch:
    """Device-side batch: dict of columns + row validity (the sel vector)."""

    cols: Dict[str, DevCol]
    row_valid: jax.Array  # bool [capacity]

    @property
    def capacity(self) -> int:
        return self.row_valid.shape[0]

    def with_cols(self, cols: Dict[str, DevCol]) -> "Batch":
        return Batch(cols, self.row_valid)

    def nrows(self) -> jax.Array:
        return jnp.sum(self.row_valid.astype(jnp.int32))


def block_to_batch(block: HostBlock, capacity: Optional[int] = None) -> Batch:
    """Pad a host block to a static tile and move it to device layout."""
    from tidb_tpu.obs.engine_watch import ENGINE_WATCH

    cap = capacity or pad_capacity(block.nrows)
    pad = cap - block.nrows
    cols = {}
    h2d = cap  # the row-validity mask ships too
    for name, col in block.columns.items():
        data = np.pad(col.data, (0, pad))
        valid = np.pad(col.valid, (0, pad))
        h2d += data.nbytes + valid.nbytes
        cols[name] = DevCol(jnp.asarray(data), jnp.asarray(valid))
    row_valid = np.zeros(cap, dtype=bool)
    row_valid[: block.nrows] = True
    ENGINE_WATCH.note_h2d(h2d)
    return Batch(cols, jnp.asarray(row_valid))


def batch_from_padded(
    columns: Dict[str, HostColumn], nrows: int
) -> Batch:
    """Device batch from host columns ALREADY sized to the target tile
    capacity — the zero-extra-copy staging seam (ROADMAP PR 4 item a):
    the incremental shuffle stager writes each received chunk straight
    into capacity-sized buffers, so there is no concat-then-pad double
    copy here, just the h2d move. Every column must share one length
    (the capacity); rows past ``nrows`` are pad."""
    caps = {len(c.data) for c in columns.values()}
    assert len(caps) == 1, f"ragged staged columns: {sorted(caps)}"
    cap = caps.pop()
    assert nrows <= cap
    from tidb_tpu.obs.engine_watch import ENGINE_WATCH

    cols = {}
    h2d = cap  # the row-validity mask ships too
    for name, col in columns.items():
        h2d += col.data.nbytes + col.valid.nbytes
        cols[name] = DevCol(jnp.asarray(col.data), jnp.asarray(col.valid))
    row_valid = np.zeros(cap, dtype=bool)
    row_valid[:nrows] = True
    ENGINE_WATCH.note_h2d(h2d)
    return Batch(cols, jnp.asarray(row_valid))


def present_temporals(col: "HostColumn"):
    """decode() + MySQL string presentation for temporal kinds — the
    user-facing result seam (decode() itself stays raw ints for
    internal consumers). Vectorized via numpy datetime64 for
    DATE/DATETIME; TIME (rare in results) loops only over its rows."""
    k = col.type.kind
    if k not in (Kind.DATE, Kind.DATETIME, Kind.TIME):
        return col.decode()
    n = len(col.data)
    out = np.empty(n, dtype=object)
    if n == 0:
        # np.datetime_as_string rejects zero-size arrays; a 0-row
        # shuffle partition legitimately presents an empty column
        return out
    if k == Kind.DATE:
        out[:] = np.datetime_as_string(
            col.data.astype("datetime64[D]"), unit="D"
        )
    elif k == Kind.DATETIME:
        micros = col.data.astype(np.int64)
        secs = np.datetime_as_string(
            (micros // 1_000_000).astype("datetime64[s]"), unit="s"
        )
        secs = np.char.replace(secs, "T", " ")
        frac = micros % 1_000_000
        out[:] = secs
        nz = frac != 0
        if nz.any():
            from tidb_tpu.dtypes import micros_to_datetime

            idx = np.nonzero(nz)[0]
            for i in idx:
                out[i] = micros_to_datetime(int(micros[i]))
    else:
        from tidb_tpu.dtypes import micros_to_time

        out[:] = [micros_to_time(int(v)) for v in col.data]
    out[~col.valid] = None
    return out


def materialize_rows(batch, schema_cols, dicts):
    """Device batch -> python row tuples for a plan schema (one fetch,
    vectorized decode). The single implementation behind the session's
    result materialization and the engine-RPC response encoder.
    Temporal columns present as MySQL-formatted strings HERE — the
    user-facing seam — while decode() stays raw (day/micros ints) for
    internal consumers (oracles, dump, CDC diffing)."""
    types = {c.internal: c.type for c in schema_cols}
    return block_to_rows(batch_to_block(batch, types, dicts), schema_cols)


def block_to_rows(block: HostBlock, schema_cols) -> List[tuple]:
    """Host block -> presented python row tuples (the row half of
    materialize_rows, reusable for blocks that never touched a device —
    the shuffle producer's JSON fallback for mixed-version peers)."""
    internals = [c.internal for c in schema_cols]
    decoded = {
        i: present_temporals(block.columns[i]) for i in internals
    }
    return [
        tuple(decoded[i][r] for i in internals) for r in range(block.nrows)
    ]


def batch_to_block(
    batch: Batch, types: Dict[str, SQLType], dicts: Dict[str, Optional[np.ndarray]]
) -> HostBlock:
    """Pull a device batch back to host and compact out invalid rows.

    Fetches everything in ONE device->host transfer (device->host round
    trips are latency-bound on a TPU tunnel, so N column-wise pulls would
    cost N round trips)."""
    fetched = jax.device_get(
        (batch.row_valid, {n: (dc.data, dc.valid) for n, dc in batch.cols.items()})
    )
    row_valid, host_cols = fetched
    idx = np.nonzero(np.asarray(row_valid))[0]
    cols = {}
    for name, (data, valid) in host_cols.items():
        if name not in types:
            # additive projections keep base columns in the runtime
            # batch; only the plan schema's columns materialize (matters
            # for additive-rooted fragment plans over the RPC seam)
            continue
        cols[name] = HostColumn(
            types[name], np.asarray(data)[idx], np.asarray(valid)[idx], dicts.get(name)
        )
    return HostBlock(cols, len(idx))
