"""Span tracing through the query path.

Reference: pkg/util/tracing/util.go:21 (opentracing spans opened at
session.ExecuteStmt, Compiler.Compile, distsql.Select, rendered by
TRACE SELECT, pkg/executor/trace.go). Here: a per-session Tracer records
(name, start, duration, depth); the session opens spans around parse /
plan / execute / materialize, and `TRACE <select>` returns them as rows.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import List, Optional


@dataclasses.dataclass
class Span:
    name: str
    start_s: float
    dur_s: float
    depth: int


class Tracer:
    def __init__(self) -> None:
        self.spans: List[Span] = []
        self._depth = 0
        self._t0: Optional[float] = None
        #: wall-clock time of the last reset(): the cross-process span
        #: anchor — a remote worker ships its own wall_t0 and the
        #: coordinator rebases via the handshake-sampled clock offset
        #: (parallel/dcn.py _merge_remote_spans)
        self.wall_t0: Optional[float] = None
        self.enabled = False

    def reset(self) -> None:
        self.spans = []
        self._depth = 0
        self._t0 = time.perf_counter()
        self.wall_t0 = time.time()

    @contextlib.contextmanager
    def span(self, name: str):
        if not self.enabled:
            yield
            return
        if self._t0 is None:
            self.reset()
        start = time.perf_counter()
        self._depth += 1
        depth = self._depth
        try:
            yield
        finally:
            self._depth -= 1
            self.spans.append(
                Span(name, start - self._t0, time.perf_counter() - start, depth)
            )

    def add_remote(
        self, spans, label: str, base_s: float = 0.0,
        base_depth: int = 1,
    ) -> None:
        """Merge spans shipped back from a remote worker (the DCN
        fragment reply's span list), host-labeled so the coordinator's
        trace shows where each fragment ran. Accepts Span objects or
        (name, start_s, dur_s, depth) sequences. Remote start offsets
        are relative to the worker's own clock; `base_s` rebases them
        onto this tracer's timeline (the caller knows when the reply
        landed) so rows()'s start-sorted output doesn't put every
        remote span at time zero.

        Depths rebase the same way clocks do: a worker's spans carry
        depths relative to the WORKER's own nesting (a handler that
        opened spans inside other spans ships depths 2, 3, ...), and
        blindly clamping each to >= 1 kept absolute worker depths —
        the coordinator's TRACE output then indented remote spans
        under unrelated neighbouring rows (phantom parents) while a
        worker whose spans all clamped together FLATTENED real
        nesting. Instead the span list's minimum depth maps to
        ``base_depth`` and every other span keeps its RELATIVE depth
        under the host label, so a 2-level worker span renders as two
        nested rows wherever it lands in the merged trace."""
        rel = []
        for s in spans:
            if isinstance(s, Span):
                name, start_s, dur_s, depth = (
                    s.name, s.start_s, s.dur_s, s.depth
                )
            else:
                name, start_s, dur_s, depth = s
            rel.append((name, float(start_s), float(dur_s), int(depth)))
        if not rel:
            return
        dmin = min(d for _n, _s, _d, d in rel)
        base_depth = max(int(base_depth), 1)
        for name, start_s, dur_s, depth in rel:
            self.spans.append(
                Span(f"{label}:{name}", start_s + float(base_s),
                     dur_s, base_depth + (depth - dmin))
            )

    def rows(self):
        out = []
        for s in sorted(self.spans, key=lambda s: s.start_s):
            out.append(
                ("  " * (s.depth - 1) + s.name, f"{s.start_s*1e3:.3f}ms", f"{s.dur_s*1e3:.3f}ms")
            )
        return out

    def totals_by_name(self) -> dict:
        """Total duration per span name. The cross-check surface
        between the two timing systems: a TRACE'd statement's
        session.plan/executor.run span totals and the flight
        recorder's plan/execute phase charges (obs/flight.py) cover
        the same walls, so they must agree — tests/test_observability
        asserts it."""
        out: dict = {}
        for s in self.spans:
            out[s.name] = out.get(s.name, 0.0) + s.dur_s
        return out


# module-level convenience tracer used when no session is involved
_global = Tracer()


def span(name: str):
    return _global.span(name)
