"""PLAN REPLAYER DUMP: package everything needed to reproduce a plan.

Reference: pkg/server/handler/optimizor/plan_replayer.go — TiDB dumps a
zip of schema DDL, statistics JSON, bindings, session variables, the SQL
and its EXPLAIN so an engineer can replay an optimizer decision on
another machine. The columnar analog captures the same artifacts from
the live catalog/stats/sysvars.

Output directory: $TIDB_TPU_PLAN_REPLAYER_DIR, else
<tempdir>/tidb_tpu_plan_replayer.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import time
import zipfile
from typing import List, Tuple


def _stats_json(t) -> str:
    stats = getattr(t, "stats", None) or {}
    out = {}
    for col, cs in stats.items():
        out[col] = {
            "row_count": int(cs.row_count),
            "null_count": int(cs.null_count),
            "ndv": int(cs.ndv),
            "min": cs.min_val,
            "max": cs.max_val,
            "topn": [[v, int(c)] for v, c in cs.topn],
            "bucket_counts": [int(x) for x in cs.bucket_counts],
        }
    return json.dumps(out, indent=1, default=str)


def dump_plan_replayer(
    session,
    sql_text: str,
    tables: List[Tuple[str, str]],
    explain_rows: List[tuple],
) -> str:
    """Write the replayer zip; returns its path (also the statement's
    result token, like the reference's downloadable file name)."""
    from tidb_tpu.tools.dump import create_table_sql

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(
            "meta.txt",
            f"tidb_tpu plan replayer\nts: {time.time():.3f}\n"
            f"db: {session.db}\n",
        )
        z.writestr("sql/sql0.sql", sql_text)
        z.writestr(
            "explain.txt",
            "\n".join(str(r[0]) for r in explain_rows),
        )
        for db, name in tables:
            t = session.catalog.table(db, name)
            z.writestr(
                f"schema/{db}.{name}.schema.txt", create_table_sql(t)
            )
            z.writestr(f"stats/{db}.{name}.json", _stats_json(t))
        z.writestr(
            "variables.toml",
            "\n".join(
                f"{k} = {v!r}" for k, v in sorted(session.vars.all().items())
            ),
        )
        try:
            bindings = session.catalog.bindings  # may not exist
        except AttributeError:
            bindings = None
        if bindings:
            z.writestr(
                "bindings.sql",
                "\n".join(str(b) for b in bindings),
            )
    outdir = os.environ.get("TIDB_TPU_PLAN_REPLAYER_DIR") or os.path.join(
        tempfile.gettempdir(), "tidb_tpu_plan_replayer"
    )
    os.makedirs(outdir, exist_ok=True)
    fn = os.path.join(outdir, f"replayer_{int(time.time() * 1000)}.zip")
    with open(fn, "wb") as f:
        f.write(buf.getvalue())
    return fn
