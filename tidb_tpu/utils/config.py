"""Layered configuration: defaults <- TOML file <- CLI flags.

Reference: pkg/config/config.go (TOML config, 1,568 LoC) overridden by
cmd/tidb-server flags (main.go:200-262, overrideConfig). The TPU engine
keeps the same three layers over the subset of knobs that exist here;
global sysvar defaults can also be seeded from the file's [variables]
table (the reference persists globals in mysql.global_variables).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 4000
    # HTTP status/metrics side port (None disables; reference :10080)
    status_port: Optional[int] = None
    # persistence directory: catalog loads from it on boot and snapshots
    # back on graceful shutdown (reference --path / storage bootstrap)
    path: Optional[str] = None
    store: str = "tpu"
    # mesh size for SPMD sessions (None = single device)
    mesh_devices: Optional[int] = None
    # background stats loop interval (seconds)
    auto_analyze_interval_s: float = 30.0
    # seed values for GLOBAL sysvars ([variables] table in the TOML)
    variables: Dict[str, object] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        import tomllib

        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: Dict) -> "Config":
        cfg = cls()
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown config keys {sorted(unknown)}")
        for k, v in raw.items():
            setattr(cfg, k, v)
        return cfg

    def override(self, **kw) -> "Config":
        """CLI-flag layer: non-None values win over the file."""
        out = dataclasses.replace(self)
        for k, v in kw.items():
            if v is not None:
                setattr(out, k, v)
        return out

    def apply_variables(self, catalog) -> None:
        """Seed GLOBAL sysvars from the [variables] config table."""
        if not self.variables:
            return
        from tidb_tpu.utils.sysvar import SysVars

        sv = SysVars(catalog.global_sysvars)
        for name, val in self.variables.items():
            sv.set(name, val, scope="global")
