"""Layered configuration: defaults <- TOML file <- CLI flags.

Reference: pkg/config/config.go (TOML config, 1,568 LoC) overridden by
cmd/tidb-server flags (main.go:200-262, overrideConfig). The TPU engine
keeps the same three layers over the subset of knobs that exist here;
global sysvar defaults can also be seeded from the file's [variables]
table (the reference persists globals in mysql.global_variables).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


def _toml_scalar(s: str):
    """One TOML scalar of the subset the config surface uses: quoted
    strings, booleans, ints, floats."""
    s = s.strip()
    if len(s) >= 2 and s[0] == s[-1] and s[0] in ("'", '"'):
        return s[1:-1]
    if s in ("true", "false"):
        return s == "true"
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"unsupported TOML value {s!r}")


def _parse_toml_subset(text: str) -> Dict:
    """Minimal TOML parser for the config file shape (``key = value``
    scalars plus one-level ``[table]`` sections, ``#`` comments) —
    the fallback when the interpreter has no tomllib (< 3.11) and the
    container has no tomli. Anything outside the subset raises, so a
    fancy config fails loudly instead of half-loading."""
    out: Dict[str, object] = {}
    target = out
    for lineno, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        if s.startswith("[") and s.endswith("]"):
            name = s[1:-1].strip()
            if not name or "." in name:
                raise ValueError(
                    f"line {lineno}: unsupported TOML table {s!r}"
                )
            target = out.setdefault(name, {})
            continue
        if "=" not in s:
            raise ValueError(f"line {lineno}: expected key = value")
        key, _, val = s.partition("=")
        val = val.strip()
        # strip a trailing comment: after the closing quote for quoted
        # values, anywhere for bare scalars (subset: quoted values
        # contain no quotes or '#')
        if val.startswith(('"', "'")):
            end = val.find(val[0], 1)
            if end > 0:
                val = val[: end + 1]
        elif "#" in val:
            val = val.split("#", 1)[0]
        target[key.strip()] = _toml_scalar(val)
    return out


@dataclasses.dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 4000
    # HTTP status/metrics side port (None disables; reference :10080)
    status_port: Optional[int] = None
    # persistence directory: catalog loads from it on boot and snapshots
    # back on graceful shutdown (reference --path / storage bootstrap)
    path: Optional[str] = None
    store: str = "tpu"
    # mesh size for SPMD sessions (None = single device)
    mesh_devices: Optional[int] = None
    # background stats loop interval (seconds)
    auto_analyze_interval_s: float = 30.0
    # seed values for GLOBAL sysvars ([variables] table in the TOML)
    variables: Dict[str, object] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        try:
            import tomllib
        except ModuleNotFoundError:
            # Python < 3.11 without tomli: the config surface here is
            # a flat TOML subset (scalars + one-level [tables]) — the
            # gated fallback parser keeps the server binary bootable
            # instead of failing --config at import time
            with open(path, encoding="utf-8") as f:
                return cls.from_dict(_parse_toml_subset(f.read()))
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: Dict) -> "Config":
        cfg = cls()
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown config keys {sorted(unknown)}")
        for k, v in raw.items():
            setattr(cfg, k, v)
        return cfg

    def override(self, **kw) -> "Config":
        """CLI-flag layer: non-None values win over the file."""
        out = dataclasses.replace(self)
        for k, v in kw.items():
            if v is not None:
                setattr(out, k, v)
        return out

    def apply_variables(self, catalog) -> None:
        """Seed GLOBAL sysvars from the [variables] config table."""
        if not self.variables:
            return
        from tidb_tpu.utils.sysvar import SysVars

        sv = SysVars(catalog.global_sysvars)
        for name, val in self.variables.items():
            sv.set(name, val, scope="global")
