"""Lock-order race/deadlock detection (the race-detector analog).

Reference: the Go build runs `make race` (ut --race, Makefile:192) and
guards race-only code with pkg/util/israce; TiKV-side lock deadlocks
are caught at runtime by unistore's wait-for detector
(pkg/store/mockstore/unistore/tikv/detector.go). Python under the GIL
has no torn reads for the Go detector to catch — the race class that
DOES exist here is *lock-order inversion* between the engine's mutexes
(table lock vs catalog lock vs advancer mutexes), which deadlocks two
threads exactly like the reference's txn wait cycles.

`make_lock(name)` returns a plain threading.Lock unless
TIDB_TPU_RACECHECK=1 (or `enable()` was called), in which case it
returns an order-tracked wrapper: every acquisition records the
(held-class -> acquiring-class) edges; an edge that REVERSES an edge
seen anywhere earlier in the process is a potential deadlock and
raises LockOrderError with both stacks' lock names. The check is by
lock *class* (the `name` passed at construction), matching how
deadlock cycles are reasoned about, and the edge graph is global —
single test runs catch inversions exercised on any thread, the same
way one `--race` CI run guards the whole repo.

Self-deadlock (re-acquiring the same non-reentrant class in one
thread) is also reported — under a plain Lock it would hang forever.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Set, Tuple


class LockOrderError(RuntimeError):
    pass


_enabled = os.environ.get("TIDB_TPU_RACECHECK", "0") == "1"
_graph_mu = threading.Lock()
#: lock-class -> set of lock-classes acquired while it was held
_edges: Dict[str, Set[str]] = {}
#: where each recorded edge was first seen (for the report)
_edge_origin: Dict[Tuple[str, str], str] = {}
_held = threading.local()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the recorded edge graph (test isolation)."""
    with _graph_mu:
        _edges.clear()
        _edge_origin.clear()


def enabled() -> bool:
    return _enabled


def _held_stack() -> List[str]:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


class TrackedLock:
    """Order-tracking wrapper with the Lock/context-manager protocol."""

    def __init__(self, name: str):
        self.name = name
        self._lk = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stack = _held_stack()
        if self.name in stack:
            raise LockOrderError(
                f"self-deadlock: lock class '{self.name}' re-acquired "
                f"while held (stack: {stack})"
            )
        for held in stack:
            self._record_edge(held, self.name, stack)
        got = self._lk.acquire(blocking, timeout)
        if got:
            stack.append(self.name)
        return got

    def release(self) -> None:
        stack = _held_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:
            # out-of-LIFO release is legal for Lock; drop the entry
            stack.remove(self.name)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._lk.locked()

    @staticmethod
    def _record_edge(held: str, acquiring: str, stack) -> None:
        if held == acquiring:
            return
        with _graph_mu:
            fwd = _edges.setdefault(held, set())
            if acquiring in fwd:
                return  # known-consistent order
            # the reversal check BEFORE recording: if `held` is
            # REACHABLE from `acquiring` through recorded edges, adding
            # held->acquiring closes a cycle — N threads interleaving
            # the N paths deadlock (direct reversal is the 2-cycle;
            # BFS catches table->A->B->table style 3+-cycles too)
            seen, frontier = {acquiring}, [acquiring]
            while frontier:
                node = frontier.pop()
                for nxt in _edges.get(node, ()):
                    if nxt == held:
                        origin = _edge_origin.get((node, held), "?")
                        raise LockOrderError(
                            f"lock-order inversion: acquiring "
                            f"'{acquiring}' while holding {stack}, but "
                            f"'{node}' -> '{held}' was recorded at "
                            f"{origin}, making '{held}' reachable from "
                            f"'{acquiring}' — interleaving threads "
                            "deadlock on this cycle"
                        )
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            fwd.add(acquiring)
            import traceback

            frame = traceback.extract_stack(limit=6)[0]
            _edge_origin[(held, acquiring)] = (
                f"{frame.filename}:{frame.lineno}"
            )


def make_lock(name: str):
    """A mutex for lock class `name`: plain threading.Lock normally,
    TrackedLock under race checking."""
    if _enabled:
        return TrackedLock(name)
    return threading.Lock()


def edge_graph() -> Dict[str, Set[str]]:
    with _graph_mu:
        return {k: set(v) for k, v in _edges.items()}
