"""Lock-order race/deadlock detection (the race-detector analog).

Reference: the Go build runs `make race` (ut --race, Makefile:192) and
guards race-only code with pkg/util/israce; TiKV-side lock deadlocks
are caught at runtime by unistore's wait-for detector
(pkg/store/mockstore/unistore/tikv/detector.go). Python under the GIL
has no torn reads for the Go detector to catch — the race class that
DOES exist here is *lock-order inversion* between the engine's mutexes
(table lock vs catalog lock vs advancer mutexes), which deadlocks two
threads exactly like the reference's txn wait cycles.

`make_lock(name)` returns a plain threading.Lock unless
TIDB_TPU_RACECHECK=1 (or `enable()` was called), in which case it
returns an order-tracked wrapper: every acquisition records the
(held-class -> acquiring-class) edges; an edge that REVERSES an edge
seen anywhere earlier in the process is a potential deadlock and
raises LockOrderError with both stacks' lock names. The check is by
lock *class* (the `name` passed at construction), matching how
deadlock cycles are reasoned about, and the edge graph is global —
single test runs catch inversions exercised on any thread, the same
way one `--race` CI run guards the whole repo. `make_rlock` and
`make_condition` are the RLock/Condition analogs (same class
tracking; an RLock may re-enter the same *instance*, a Condition may
wait on itself while held).

Self-deadlock (re-acquiring the same non-reentrant class in one
thread) is also reported — under a plain Lock it would hang forever.

``LOCK_CLASSES`` is the DECLARED registry of every lock class in the
engine (the failpoint-SITES pattern): make_lock/make_rlock/
make_condition reject undeclared names, and scripts/
check_concurrency.py statically cross-checks every construction site
against this registry, bans raw threading.Lock/RLock/Condition
constructions outside this module, forbids declared-blocking calls
under a held lock, and proves the static lock-order graph acyclic.
``THREAD_NAME_PREFIXES`` is the sibling registry for thread names
(every threading.Thread must carry a declared, attributable name).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Set, Tuple

#: Every lock class the engine constructs, name -> what it guards.
#: Declared here FIRST (like failpoint SITES and metric SUBSYSTEMS),
#: then constructed via make_lock/make_rlock/make_condition — the
#: concurrency lint (scripts/check_concurrency.py) cross-checks both
#: directions and renders the observed partial order into README.md.
LOCK_CLASSES: Dict[str, str] = {
    # storage tier
    "table": "one table's rows/indexes during DML + shadow-commit swap",
    "catalog": "the shared schema map (create/drop/alter)",
    "catalog.commit": "whole-catalog commit serialization",
    "sequence": "sequence allocator state",
    "cdc.queue": "changefeed event queue + baseline maps",
    "cdc.advance": "whole-drain serialization per changefeed",
    "logbackup.queue": "log-backup event queue",
    "logbackup.advance": "whole-advance serialization per backup task",
    "storage.external": "process-global in-memory object-store buckets",
    "storage.native": "lazy build + load of the native .so",
    "storage.delta": "HTAP coordinator delta log (capture runs with "
                     "the table lock RELEASED — no table edge)",
    "storage.delta_replica": "worker replica delta buffers + fold/"
                             "resolve serialization (reentrant; folds "
                             "acquire 'table' beneath it)",
    "storage.compactor": "delta replicator acked-seq map + compaction "
                         "barrier state",
    "storage.txn_wait": "pessimistic lock-manager wait state (condition)",
    "storage.txn_id": "global txn id allocator",
    # planner tier
    "planner.card_feedback": "per-digest observed-cardinality feedback "
                             "store (AQE history-seeded cost model)",
    # dxf / sessions
    "dxf.manager": "DXF task/subtask tables",
    "session.user_locks": "GET_LOCK advisory-lock registry (condition)",
    # server tier
    "server.conns": "MySQL server connection counter/ids",
    "engine_rpc.registry": "per-server shipped-registry delta snapshot",
    "engine_rpc.shuffle_init": "lazy ShuffleWorker construction",
    "engine_rpc.cancel": "per-server cancelled-qid registry (fleet "
                         "cancellation)",
    "engine_pool.pool": "engine pool rotation + per-endpoint conn map",
    "engine_pool.prober": "quarantined-endpoint list",
    "engine_pool.conn": "one engine connection's request/response stream",
    # MPP tier
    "dcn.ledger": "exactly-once fragment ledger records",
    "dcn.scheduler": "scheduler rotation/suspects/last_query telemetry",
    "dcn.pool": "one endpoint's control-connection pool (condition)",
    "dcn.heartbeat": "heartbeat retune serialization (one beat thread)",
    "serving.admission": "admission queue/budget state (condition)",
    "serving.qid": "strictly-unique qid/nonce allocation",
    "serving.load": "serve-load driver's client latency/error lists",
    "executor.plan_cache": "process-wide shared compiled-plan cache "
                           "(condition: singleflight compile claims)",
    "shuffle.held": "held shuffle-DAG stage outputs + cached range-"
                    "side produce blocks",
    "shuffle.store": "receiver stage/stream buffers (condition)",
    "shuffle.tunnel": "one peer tunnel's queue + in-flight window "
                      "(condition)",
    "shuffle.negotiate": "per-tunnel one-shot codec negotiation",
    "shuffle.exec": "worker executor plan caches (reentrant)",
    "shuffle.tunnels": "per-task tunnel map creation + stats merge",
    # observability tier
    "metrics.registry": "the metric name -> collector map",
    "metrics.family": "one labeled family's children map",
    "metrics.metric": "one counter/gauge/histogram's value cells",
    "metrics.slowlog": "slow-query ring buffer",
    "metrics.slowlog_file": "slow-query file sink appends",
    "metrics.stmt_summary": "per-digest statement aggregates",
    "metrics.stmt_history": "closed statements_summary windows + "
                            "pending evicted-digest snapshots",
    "engine_watch": "finished engine-watch records ring",
    "flight.ring": "finished query-flight ring",
    "flight.links": "per-peer DCN link health maps",
    "timeline.ring": "fleet timeline tracer's bounded event ring",
    "obs.tsdb": "metric time-series retention rings + series map",
    "obs.tsdb_sampler": "sampler cadence state (retune + last-sample "
                        "stamp)",
    "obs.inspection": "inspection engine's last-run findings cache",
    "obs.topsql": "Top SQL per-digest sample aggregates + collapsed "
                  "stacks + ship buffers",
    "obs.topsql_sampler": "Top SQL sampler cadence state (retune "
                          "serialization, one thread invariant)",
    # utils
    "failpoint.registry": "armed failpoint actions",
    "failpoint.site": "one after_n() site's invocation counter",
    "resgroup": "resource-group definitions",
    "privilege": "user + grant store",
}

#: Declared thread-name families: every threading.Thread in the engine
#: must be named "<prefix>-..." (or exactly "<prefix>") with prefix
#: from this set, so /links, the flight recorder and py-spy dumps can
#: attribute a thread to its subsystem. Enforced by
#: scripts/check_concurrency.py (thread-hygiene rule).
THREAD_NAME_PREFIXES = frozenset({
    "cdc",
    "dcn",
    "delta",
    "dxf",
    "engine",
    "http",
    "logbackup",
    "mysql",
    "obs",
    "serve",
    "shuffle",
    "stats",
    "ttl",
    "watchdog",
})


class LockOrderError(RuntimeError):
    pass


_enabled = os.environ.get("TIDB_TPU_RACECHECK", "0") == "1"
_graph_mu = threading.Lock()
#: lock-class -> set of lock-classes acquired while it was held
_edges: Dict[str, Set[str]] = {}
#: where each recorded edge was first seen (for the report)
_edge_origin: Dict[Tuple[str, str], str] = {}
#: every class acquired at least once while tracking was on — the
#: "did this subsystem's locks participate in the run" signal the
#: stress tests assert (set.add is GIL-atomic)
_seen: Set[str] = set()
_held = threading.local()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the recorded edge graph (test isolation)."""
    with _graph_mu:
        _edges.clear()
        _edge_origin.clear()
        _seen.clear()


def enabled() -> bool:
    return _enabled


def _held_stack() -> List[str]:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _rdepths() -> Dict[int, int]:
    """Per-thread reentrancy depth per TrackedRLock instance."""
    d = getattr(_held, "rdepths", None)
    if d is None:
        d = _held.rdepths = {}
    return d


def _acquire_site() -> str:
    """file:line of the acquisition call site — the innermost stack
    frame OUTSIDE this module. A fixed extract_stack(limit=N)[0] slice
    reported an arbitrary ancestor frame instead (the deeper the
    caller, the wronger the report)."""
    import traceback

    here = os.path.abspath(__file__)
    for frame in reversed(traceback.extract_stack()):
        if os.path.abspath(frame.filename) != here:
            return f"{frame.filename}:{frame.lineno}"
    return "?"


def _check_and_record(acquiring: str) -> None:
    """Shared acquisition bookkeeping: self-deadlock check against the
    thread's held stack, then one (held -> acquiring) edge per held
    class, each cycle-checked against the global graph."""
    _seen.add(acquiring)
    stack = _held_stack()
    if acquiring in stack:
        raise LockOrderError(
            f"self-deadlock: lock class '{acquiring}' re-acquired "
            f"while held (stack: {stack})"
        )
    for held in stack:
        _record_edge(held, acquiring, stack)


def _record_edge(held: str, acquiring: str, stack) -> None:
    if held == acquiring:
        return
    with _graph_mu:
        fwd = _edges.setdefault(held, set())
        if acquiring in fwd:
            return  # known-consistent order
        # the reversal check BEFORE recording: if `held` is
        # REACHABLE from `acquiring` through recorded edges, adding
        # held->acquiring closes a cycle — N threads interleaving
        # the N paths deadlock (direct reversal is the 2-cycle;
        # BFS catches table->A->B->table style 3+-cycles too)
        seen, frontier = {acquiring}, [acquiring]
        while frontier:
            node = frontier.pop()
            for nxt in _edges.get(node, ()):
                if nxt == held:
                    origin = _edge_origin.get((node, held), "?")
                    raise LockOrderError(
                        f"lock-order inversion: acquiring "
                        f"'{acquiring}' while holding {stack}, but "
                        f"'{node}' -> '{held}' was recorded at "
                        f"{origin}, making '{held}' reachable from "
                        f"'{acquiring}' — interleaving threads "
                        "deadlock on this cycle"
                    )
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        fwd.add(acquiring)
        _edge_origin[(held, acquiring)] = _acquire_site()


class TrackedLock:
    """Order-tracking wrapper with the Lock/context-manager protocol."""

    def __init__(self, name: str):
        self.name = name
        self._lk = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _check_and_record(self.name)
        got = self._lk.acquire(blocking, timeout)
        if got:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        stack = _held_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:
            # out-of-LIFO release is legal for Lock; drop the entry
            stack.remove(self.name)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._lk.locked()


class TrackedRLock:
    """Order-tracked reentrant lock: re-acquiring the SAME instance on
    one thread is legal (no edges, depth-counted); re-acquiring the
    same CLASS through a different instance is still a potential
    deadlock — two threads, two instances, opposite orders."""

    def __init__(self, name: str):
        self.name = name
        self._lk = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        d = _rdepths()
        k = id(self)
        if d.get(k, 0) > 0:  # reentry on this thread
            got = self._lk.acquire(blocking, timeout)
            if got:
                d[k] += 1
            return got
        _check_and_record(self.name)
        got = self._lk.acquire(blocking, timeout)
        if got:
            d[k] = 1
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        d = _rdepths()
        k = id(self)
        if d.get(k, 0) > 1:
            d[k] -= 1
            self._lk.release()
            return
        d.pop(k, None)
        stack = _held_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:
            stack.remove(self.name)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TrackedCondition:
    """Order-tracked condition variable. acquire/release track like
    TrackedLock; wait/wait_for/notify delegate to a real Condition
    (wait releases and re-acquires the underlying lock internally —
    the thread is parked meanwhile, so the held-stack entry simply
    stays put: no other acquisition can happen on this thread)."""

    def __init__(self, name: str):
        self.name = name
        self._cv = threading.Condition()

    def acquire(self, *args) -> bool:
        _check_and_record(self.name)
        got = self._cv.acquire(*args)
        if got:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        stack = _held_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:
            stack.remove(self.name)
        self._cv.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        return self._cv.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        return self._cv.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cv.notify(n)

    def notify_all(self) -> None:
        self._cv.notify_all()


def _check_declared(name: str) -> None:
    if name not in LOCK_CLASSES:
        raise ValueError(
            f"undeclared lock class {name!r}: declare it in "
            "tidb_tpu/utils/racecheck.py LOCK_CLASSES (the "
            "check_concurrency.py lint enforces the same registry "
            "statically)"
        )


def make_lock(name: str):
    """A mutex for declared lock class `name`: plain threading.Lock
    normally (zero overhead), TrackedLock under race checking."""
    _check_declared(name)
    if _enabled:
        return TrackedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """A reentrant mutex for declared lock class `name`: plain
    threading.RLock normally, TrackedRLock under race checking."""
    _check_declared(name)
    if _enabled:
        return TrackedRLock(name)
    return threading.RLock()


def make_condition(name: str):
    """A condition variable for declared lock class `name`: plain
    threading.Condition normally, TrackedCondition under race
    checking."""
    _check_declared(name)
    if _enabled:
        return TrackedCondition(name)
    return threading.Condition()


def edge_graph() -> Dict[str, Set[str]]:
    with _graph_mu:
        return {k: set(v) for k, v in _edges.items()}


def seen_classes() -> Set[str]:
    """Lock classes acquired at least once since the last reset()
    while tracking was on — participation, independent of whether an
    acquisition happened to NEST (edge_graph() records only pairs).
    .copy() is one C-level call that never releases the GIL for str
    elements, so it is atomic against concurrent _seen.add()."""
    return _seen.copy()


def edge_origins() -> Dict[Tuple[str, str], str]:
    """(held, acquiring) -> 'file:line' of the first observation of
    that edge — the acquisition call site, not a racecheck frame."""
    with _graph_mu:
        return dict(_edge_origin)
