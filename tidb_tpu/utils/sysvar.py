"""System variables with SESSION/GLOBAL scope.

Reference: pkg/sessionctx/variable (444 sysvars, sysvar.go definitions
with scopes, validation and setter hooks; globals persisted in
mysql.global_variables). This engine defines the subset that has meaning
on TPU — memory quota, capacity-tile policy, mesh knobs — plus MySQL
compatibility variables the wire protocol needs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class SysVarDef:
    name: str
    default: object
    scope: str = "both"  # session | global | both | readonly
    validate: Optional[Callable[[object], object]] = None
    description: str = ""


def _int_range(lo, hi):
    def v(x):
        x = int(x)
        if not lo <= x <= hi:
            raise ValueError(f"value {x} out of range [{lo},{hi}]")
        return x

    return v


def _float_range(lo, hi):
    def v(x):
        x = float(x)
        if not lo <= x <= hi:
            raise ValueError(f"value {x} out of range [{lo},{hi}]")
        return x

    return v


def _bool(x):
    if isinstance(x, str):
        return x.strip().lower() in ("1", "on", "true", "yes")
    return bool(x)


def _enum(*allowed):
    lut = {a.lower(): a for a in allowed}

    def v(x):
        s = str(x).strip().lower()
        if s not in lut:
            raise ValueError(f"value {x!r} not in {allowed}")
        return lut[s]  # canonical casing as declared

    return v


SYSVAR_DEFS: Dict[str, SysVarDef] = {
    v.name: v
    for v in [
        # engine knobs (analogs of tidb_vars.go entries)
        SysVarDef("tidb_mem_quota_query", 8 << 30, "both", _int_range(16 << 20, 1 << 40),
                  "per-query device-memory budget in bytes (reference tidb_mem_quota_query)"),
        SysVarDef("tidb_tpu_min_tile", 256, "both", _int_range(64, 1 << 22),
                  "smallest row-capacity tile (reference paging min size, paging.go:25)"),
        SysVarDef("tidb_tpu_group_capacity", 1024, "both", _int_range(16, 1 << 24),
                  "initial group-table capacity before overflow retry"),
        SysVarDef("tidb_slow_log_threshold", 300, "both", _int_range(0, 1 << 31),
                  "statements slower than this many ms land in the slow "
                  "log (information_schema.slow_query)"),
        SysVarDef("tidb_tpu_stream_rows", -1, "both", _int_range(-1, 1 << 40),
                  "aggregate inputs execute chunked through host RAM "
                  "(spill analog; reference paging + agg_spill.go): -1 = "
                  "auto (when the scan overruns device memory), >0 = row "
                  "threshold, 0 = never"),
        SysVarDef("tidb_allow_mpp", True, "both", _bool,
                  "allow multi-device fragment plans (reference tidb_allow_mpp)"),
        SysVarDef("tidb_txn_mode", "pessimistic", "both",
                  _enum("pessimistic", "optimistic"),
                  "transaction mode: pessimistic takes blocking table "
                  "locks per DML statement (reference default); "
                  "optimistic is first-committer-wins"),
        SysVarDef("innodb_lock_wait_timeout", 50, "both", _int_range(1, 3600),
                  "seconds a pessimistic lock wait blocks before error "
                  "1205 (reference innodb_lock_wait_timeout)"),
        SysVarDef("tidb_broadcast_join_threshold_size", 1 << 20, "both", _int_range(0, 1 << 34),
                  "max build-side bytes for broadcast (vs hash-partition) joins"),
        SysVarDef("tidb_executor_concurrency", 1, "both", _int_range(1, 256),
                  "accepted for compatibility; device kernels are already parallel"),
        SysVarDef("tidb_enable_plan_cache", True, "both", _bool,
                  "cache jitted plans keyed by fingerprint + shapes"),
        SysVarDef("tidb_enable_auto_analyze", True, "both", _bool,
                  "refresh table statistics automatically once enough "
                  "rows changed (reference autoanalyze.go)"),
        SysVarDef("tidb_auto_analyze_ratio", 0.5, "both", _float_range(0.0, 1.0),
                  "modified-rows / total-rows ratio that triggers "
                  "auto-analyze (reference tidb_auto_analyze_ratio)"),
        SysVarDef("max_execution_time", 0, "both", _int_range(0, 1 << 31),
                  "per-statement wall-clock limit in ms (0 = unlimited); "
                  "runaway statements abort at the next kill safepoint"),
        # concurrency knobs: accepted for compatibility — device kernels
        # are already parallel, so these validate + round-trip but the
        # executor does not fan out host threads per statement
        SysVarDef("tidb_hash_join_concurrency", -1, "both", _int_range(-1, 256)),
        SysVarDef("tidb_index_lookup_concurrency", -1, "both", _int_range(-1, 256)),
        SysVarDef("tidb_index_serial_scan_concurrency", 1, "both", _int_range(1, 256)),
        SysVarDef("tidb_distsql_scan_concurrency", 15, "both", _int_range(1, 256)),
        SysVarDef("tidb_build_stats_concurrency", 4, "both", _int_range(1, 256)),
        SysVarDef("tidb_projection_concurrency", -1, "both", _int_range(-1, 256)),
        SysVarDef("tidb_window_concurrency", -1, "both", _int_range(-1, 256)),
        # engine-behavior flags accepted for compatibility (always-on or
        # by-design-different behaviors documented per entry)
        SysVarDef("tidb_enable_vectorized_expression", True, "both", _bool,
                  "always on: every expression lowers to fused XLA kernels"),
        SysVarDef("tidb_enable_clustered_index", "ON", "both",
                  _enum("ON", "OFF", "INT_ONLY"),
                  "accepted; storage is columnar with sorted-permutation "
                  "indexes, clustering is implicit"),
        SysVarDef("tidb_enable_async_commit", True, "both", _bool,
                  "accepted; single-process commits are atomic swaps"),
        SysVarDef("tidb_enable_1pc", True, "both", _bool),
        SysVarDef("tidb_row_format_version", 2, "both", _int_range(1, 2)),
        SysVarDef("tidb_enable_chunk_rpc", True, "both", _bool),
        SysVarDef("tidb_opt_agg_push_down", False, "both", _bool),
        SysVarDef("tidb_opt_distinct_agg_push_down", False, "both", _bool),
        SysVarDef("tidb_enable_index_merge", True, "both", _bool),
        SysVarDef("tidb_enable_stmt_summary", True, "both", _bool),
        SysVarDef("tidb_enable_collect_execution_info", True, "both", _bool),
        SysVarDef("tidb_retry_limit", 10, "both", _int_range(0, 1000)),
        SysVarDef("tidb_constraint_check_in_place", True, "both", _bool,
                  "always in place: uniqueness checks run on the append "
                  "path, there is no deferred prewrite"),
        SysVarDef("tidb_ddl_error_count_limit", 512, "both", _int_range(1, 1 << 20)),
        SysVarDef("tidb_max_chunk_size", 1024, "both", _int_range(32, 1 << 20)),
        SysVarDef("tidb_init_chunk_size", 32, "both", _int_range(1, 32)),
        # MySQL compatibility
        SysVarDef("autocommit", True, "both", _bool),
        SysVarDef("sql_select_limit", 2 ** 64 - 1, "both", _int_range(0, 2 ** 64 - 1)),
        SysVarDef("wait_timeout", 28800, "both", _int_range(0, 31536000)),
        SysVarDef("interactive_timeout", 28800, "both", _int_range(1, 31536000)),
        SysVarDef("net_write_timeout", 60, "both", _int_range(1, 31536000)),
        SysVarDef("net_read_timeout", 30, "both", _int_range(1, 31536000)),
        SysVarDef("lower_case_table_names", 2, "readonly"),
        SysVarDef("default_storage_engine", "InnoDB", "readonly"),
        SysVarDef("character_set_server", "utf8mb4", "both"),
        SysVarDef("character_set_client", "utf8mb4", "both"),
        SysVarDef("character_set_results", "utf8mb4", "both"),
        SysVarDef("character_set_database", "utf8mb4", "both"),
        SysVarDef("collation_server", "utf8mb4_bin", "both"),
        SysVarDef("collation_database", "utf8mb4_bin", "both"),
        SysVarDef("system_time_zone", "UTC", "readonly"),
        SysVarDef("init_connect", "", "both"),
        SysVarDef("license", "Apache License 2.0", "readonly"),
        SysVarDef("port", 4000, "readonly"),
        SysVarDef("socket", "", "readonly"),
        SysVarDef("innodb_buffer_pool_size", 134217728, "readonly"),
        SysVarDef("max_connections", 0, "both", _int_range(0, 100000)),
        SysVarDef("sql_safe_updates", False, "both", _bool),
        SysVarDef("foreign_key_checks", True, "both", _bool,
                  "accepted; FK RESTRICT/CASCADE enforcement is active "
                  "whenever constraints exist"),
        SysVarDef("unique_checks", True, "both", _bool),
        SysVarDef("group_concat_max_len", 1024, "both", _int_range(4, 1 << 30)),
        SysVarDef("sql_mode", "STRICT_TRANS_TABLES", "both"),
        SysVarDef("time_zone", "UTC", "both"),
        SysVarDef("max_allowed_packet", 64 << 20, "both", _int_range(1024, 1 << 30)),
        SysVarDef("version", "8.0.11-tidb-tpu-0.1.0", "readonly"),
        SysVarDef("version_comment", "tidb_tpu TPU-native SQL engine", "readonly"),
        SysVarDef("character_set_connection", "utf8mb4", "both"),
        SysVarDef("collation_connection", "utf8mb4_bin", "both"),
        SysVarDef("tx_isolation", "REPEATABLE-READ", "both",
                  _enum("REPEATABLE-READ", "READ-COMMITTED")),
        SysVarDef("transaction_isolation", "REPEATABLE-READ", "both",
                  _enum("REPEATABLE-READ", "READ-COMMITTED")),
        SysVarDef("tidb_read_staleness", 0, "both", _int_range(-86400, 0),
                  "negative seconds: autocommit reads resolve against "
                  "the newest table version at now+staleness (reference "
                  "tidb_read_staleness stale reads)"),
        SysVarDef("tidb_gc_life_time", 0, "global", _int_range(0, 86400 * 7),
                  "seconds of MVCC version history every table retains "
                  "for stale reads / AS OF TIMESTAMP (reference "
                  "tidb_gc_life_time; 0 = keep only pinned snapshots). "
                  "GLOBAL-only: it drives the engine-wide GC horizon"),
        # ---- driver/BI connect-time compatibility tier (reference:
        # pkg/sessionctx/variable/sysvar.go; clients SET/SELECT these on
        # connect — JDBC, mysql-connector, .NET, BI tools) ----
        SysVarDef("auto_increment_increment", 1, "both", _int_range(1, 65535)),
        SysVarDef("auto_increment_offset", 1, "both", _int_range(1, 65535)),
        SysVarDef("big_tables", False, "both", _bool),
        SysVarDef("block_encryption_mode", "aes-128-ecb", "both"),
        SysVarDef("bulk_insert_buffer_size", 8388608, "both"),
        SysVarDef("character_set_filesystem", "binary", "both"),
        SysVarDef("default_collation_for_utf8mb4", "utf8mb4_bin", "both"),
        SysVarDef("concurrent_insert", "AUTO", "readonly"),
        SysVarDef("connect_timeout", 10, "both", _int_range(2, 31536000)),
        SysVarDef("datadir", "/tmp/tidb_tpu", "readonly"),
        SysVarDef("default_authentication_plugin", "mysql_native_password", "readonly"),
        SysVarDef("default_week_format", 0, "both", _int_range(0, 7)),
        SysVarDef("delay_key_write", "ON", "both"),
        SysVarDef("div_precision_increment", 4, "both", _int_range(0, 30)),
        SysVarDef("event_scheduler", "OFF", "both"),
        SysVarDef("explicit_defaults_for_timestamp", True, "both", _bool),
        SysVarDef("flush", False, "both", _bool),
        SysVarDef("have_openssl", "DISABLED", "readonly"),
        SysVarDef("have_ssl", "DISABLED", "readonly"),
        SysVarDef("hostname", "tidb-tpu", "readonly"),
        SysVarDef("innodb_file_per_table", True, "readonly"),
        SysVarDef("join_buffer_size", 262144, "both"),
        SysVarDef("key_buffer_size", 8388608, "both"),
        SysVarDef("last_insert_id", 0, "session", _int_range(0, 2 ** 63 - 1)),
        SysVarDef("long_query_time", 10.0, "both"),
        SysVarDef("max_heap_table_size", 16777216, "both"),
        SysVarDef("max_join_size", 2 ** 64 - 1, "both"),
        SysVarDef("max_length_for_sort_data", 1024, "both"),
        SysVarDef("max_prepared_stmt_count", -1, "global"),
        SysVarDef("max_sort_length", 1024, "both"),
        SysVarDef("max_sp_recursion_depth", 0, "both", _int_range(0, 255)),
        SysVarDef("max_user_connections", 0, "both", _int_range(0, 4294967295)),
        SysVarDef("myisam_sort_buffer_size", 8388608, "both"),
        SysVarDef("net_buffer_length", 16384, "both"),
        SysVarDef("net_retry_count", 10, "both", _int_range(1, 4294967295)),
        SysVarDef("old_passwords", 0, "both", _int_range(0, 2)),
        SysVarDef("optimizer_switch", "", "both"),
        SysVarDef("performance_schema", False, "readonly", _bool),
        SysVarDef("profiling", False, "both", _bool),
        SysVarDef("protocol_version", 10, "readonly"),
        SysVarDef("query_cache_size", 0, "readonly"),
        SysVarDef("query_cache_type", "OFF", "readonly"),
        SysVarDef("rand_seed1", 0, "session"),
        SysVarDef("rand_seed2", 0, "session"),
        SysVarDef("read_buffer_size", 131072, "both"),
        SysVarDef("read_rnd_buffer_size", 262144, "both"),
        SysVarDef("skip_networking", False, "readonly", _bool),
        SysVarDef("sort_buffer_size", 262144, "both"),
        SysVarDef("sql_auto_is_null", False, "both", _bool),
        SysVarDef("sql_big_selects", True, "both", _bool),
        SysVarDef("sql_buffer_result", False, "both", _bool),
        SysVarDef("sql_log_bin", True, "both", _bool),
        SysVarDef("sql_log_off", False, "both", _bool),
        SysVarDef("sql_notes", True, "both", _bool),
        SysVarDef("sql_quote_show_create", True, "both", _bool),
        SysVarDef("sql_warnings", False, "both", _bool),
        SysVarDef("ssl_ca", "", "readonly"),
        SysVarDef("ssl_cert", "", "readonly"),
        SysVarDef("ssl_key", "", "readonly"),
        SysVarDef("table_definition_cache", -1, "both"),
        SysVarDef("thread_cache_size", -1, "both"),
        SysVarDef("timestamp", 0.0, "session"),
        SysVarDef("tmp_table_size", 16777216, "both"),
        SysVarDef("tmpdir", "/tmp", "readonly"),
        SysVarDef("transaction_alloc_block_size", 8192, "both"),
        SysVarDef("transaction_prealloc_size", 4096, "both"),
        SysVarDef("tx_read_only", False, "both", _bool),
        SysVarDef("transaction_read_only", False, "both", _bool),
        SysVarDef("unique_subquery_cache", True, "both", _bool),
        SysVarDef("version_compile_machine", "tpu", "readonly"),
        SysVarDef("version_compile_os", "Linux", "readonly"),
        SysVarDef("warning_count", 0, "readonly"),
        SysVarDef("error_count", 0, "readonly"),
        # tidb-prefixed compatibility knobs drivers/tools probe
        SysVarDef("tidb_allow_batch_cop", 1, "both", _int_range(0, 2)),
        SysVarDef("tidb_batch_insert", False, "both", _bool),
        SysVarDef("tidb_current_ts", 0, "readonly"),
        SysVarDef("tidb_enable_cascades_planner", False, "both", _bool),
        SysVarDef("tidb_enable_fast_analyze", False, "both", _bool),
        SysVarDef("tidb_enable_noop_functions", False, "both", _bool),
        SysVarDef("tidb_enable_parallel_apply", False, "both", _bool),
        SysVarDef("tidb_enable_window_function", True, "both", _bool),
        SysVarDef("tidb_force_priority", "NO_PRIORITY", "both"),
        SysVarDef("tidb_index_join_batch_size", 25000, "both"),
        SysVarDef("tidb_skip_utf8_check", False, "both", _bool),
        SysVarDef("tidb_snapshot", "", "session"),
        SysVarDef("tidb_wait_split_region_finish", True, "both", _bool),
    ]
}


class SysVars:
    """Session view over globals; SET GLOBAL updates the shared store."""

    def __init__(self, globals_store: Optional[Dict[str, object]] = None):
        self._globals = globals_store if globals_store is not None else {}
        self._session: Dict[str, object] = {}

    def get(self, name: str):
        name = name.lower()
        if name in self._session:
            return self._session[name]
        if name in self._globals:
            return self._globals[name]
        d = SYSVAR_DEFS.get(name)
        if d is None:
            raise KeyError(f"unknown system variable {name!r}")
        return d.default

    def set(self, name: str, value, scope: str = "session"):
        name = name.lower()
        d = SYSVAR_DEFS.get(name)
        if d is None:
            raise KeyError(f"unknown system variable {name!r}")
        if d.scope == "readonly":
            raise ValueError(f"variable {name} is read-only")
        if d.validate is not None:
            value = d.validate(value)
        # MySQL keeps the legacy alias and the canonical name in sync
        _ALIASES = (
            ("tx_isolation", "transaction_isolation"),
            ("tx_read_only", "transaction_read_only"),
        )
        names = next(
            (pair for pair in _ALIASES if name in pair), (name,)
        )
        if scope == "global":
            if d.scope == "session":
                raise ValueError(f"variable {name} is session-scoped")
            for n in names:
                self._globals[n] = value
        else:
            if d.scope == "global":
                raise ValueError(f"variable {name} is global-scoped; use SET GLOBAL")
            for n in names:
                self._session[n] = value

    def all(self) -> Dict[str, object]:
        out = {}
        for name in sorted(SYSVAR_DEFS):
            out[name] = self.get(name)
        return out
