"""System variables with SESSION/GLOBAL scope.

Reference: pkg/sessionctx/variable (444 sysvars, sysvar.go definitions
with scopes, validation and setter hooks; globals persisted in
mysql.global_variables). This engine defines the subset that has meaning
on TPU — memory quota, capacity-tile policy, mesh knobs — plus MySQL
compatibility variables the wire protocol needs.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class SysVarDef:
    name: str
    default: object
    scope: str = "both"  # session | global | both | readonly
    validate: Optional[Callable[[object], object]] = None
    description: str = ""


def _int_range(lo, hi):
    def v(x):
        x = int(x)
        if not lo <= x <= hi:
            raise ValueError(f"value {x} out of range [{lo},{hi}]")
        return x

    return v


def _float_range(lo, hi):
    def v(x):
        x = float(x)
        if not lo <= x <= hi:
            raise ValueError(f"value {x} out of range [{lo},{hi}]")
        return x

    return v


def _bool(x):
    if isinstance(x, str):
        return x.strip().lower() in ("1", "on", "true", "yes")
    return bool(x)


def _enum(*allowed):
    lut = {a.lower(): a for a in allowed}

    def v(x):
        s = str(x).strip().lower()
        if s not in lut:
            raise ValueError(f"value {x!r} not in {allowed}")
        return lut[s]  # canonical casing as declared

    return v


SYSVAR_DEFS: Dict[str, SysVarDef] = {
    v.name: v
    for v in [
        # engine knobs (analogs of tidb_vars.go entries)
        SysVarDef("tidb_mem_quota_query", 8 << 30, "both", _int_range(16 << 20, 1 << 40),
                  "per-query device-memory budget in bytes (reference tidb_mem_quota_query)"),
        SysVarDef("tidb_tpu_min_tile", 256, "both", _int_range(64, 1 << 22),
                  "smallest row-capacity tile (reference paging min size, paging.go:25)"),
        SysVarDef("tidb_tpu_group_capacity", 1024, "both", _int_range(16, 1 << 24),
                  "initial group-table capacity before overflow retry"),
        SysVarDef("tidb_slow_log_threshold", 300, "both", _int_range(0, 1 << 31),
                  "statements slower than this many ms land in the slow "
                  "log (information_schema.slow_query)"),
        SysVarDef("tidb_tpu_stream_rows", -1, "both", _int_range(-1, 1 << 40),
                  "aggregate inputs execute chunked through host RAM "
                  "(spill analog; reference paging + agg_spill.go): -1 = "
                  "auto (when the scan overruns device memory), >0 = row "
                  "threshold, 0 = never"),
        SysVarDef("tidb_allow_mpp", True, "both", _bool,
                  "allow multi-device fragment plans (reference tidb_allow_mpp)"),
        SysVarDef("tidb_timeline_capture", False, "both", _bool,
                  "start/stop the fleet timeline tracer "
                  "(obs/timeline.py): captures statement/compile/"
                  "fragment/shuffle/stall/admission events into a "
                  "bounded ring, dumped as Chrome trace-event JSON at "
                  "the /timeline endpoint (open in Perfetto)"),
        # serving-tier admission knobs (parallel/serving.py
        # AdmissionController.from_sysvars; a live SET on a session
        # with an attached scheduler re-tunes the running controller)
        SysVarDef("tidb_tpu_admission_budget_bytes", 2 << 30, "both",
                  _int_range(1 << 20, 1 << 50),
                  "fleet device-memory budget admitted queries may "
                  "hold concurrently (admission gates query START "
                  "against it)"),
        SysVarDef("tidb_tpu_admission_queue_limit", 256, "both",
                  _int_range(1, 1 << 20),
                  "queued admissions beyond which new queries are "
                  "rejected with error 8252"),
        SysVarDef("tidb_tpu_admission_starvation_s", 5.0, "both",
                  _float_range(0.05, 3600.0),
                  "seconds of queue wait that age a query's effective "
                  "priority up one rank (and reserve the fleet for a "
                  "starving head-of-queue)"),
        # DCN liveness/timeout knobs (parallel/dcn.py resolves unset
        # constructor args from these; a live SET re-tunes an attached
        # scheduler — session.py SetVariable hook). The 120s default is
        # WAN-scale: loopback dryruns and the serve-load driver SET it
        # down so survivor waits don't stack into minutes (PERF_NOTES).
        # GLOBAL-only: the scheduler these tune is SHARED by every
        # attached session — a session scope would validate, succeed,
        # and silently tune nothing (the fleet reads the global store)
        SysVarDef("tidb_tpu_shuffle_wait_timeout_s", 120.0, "global",
                  _float_range(0.1, 3600.0),
                  "seconds a shuffle consumer waits for its peers' "
                  "partition streams before reporting them as death "
                  "suspects (stage retry on the survivor set)"),
        SysVarDef("tidb_tpu_heartbeat_interval_s", 0.0, "global",
                  _float_range(0.0, 3600.0),
                  "worker-host heartbeat cadence for the DCN "
                  "scheduler's liveness thread (0 = no background "
                  "thread; beats run manually or at dispatch sites)"),
        SysVarDef("tidb_tpu_heartbeat_miss_threshold", 2, "global",
                  _int_range(1, 100),
                  "consecutive missed heartbeats that quarantine a "
                  "worker host into the prober"),
        # Adaptive query execution (PR 15, parallel/aqe.py): runtime
        # stats re-shape the plan mid-query. GLOBAL-only like the
        # other scheduler knobs — one shared scheduler serves every
        # attached session.
        SysVarDef("tidb_tpu_shuffle_skew_ratio", 0.0, "global",
                  _float_range(0.0, 1e6),
                  "hash-exchange skew bar: when a probe's summed "
                  "per-partition row counts show max > ratio x mean, "
                  "the hot partition's keys are salted across "
                  "tidb_tpu_shuffle_skew_salt_k hosts (0 disables "
                  "detection + salting; > 1 arms it)"),
        SysVarDef("tidb_tpu_shuffle_skew_salt_k", 4, "global",
                  _int_range(2, 64),
                  "hosts a skewed hash partition's hot keys salt "
                  "across (capped at the alive host count)"),
        SysVarDef("tidb_tpu_aqe_feedback", False, "global", _bool,
                  "seed per-digest shuffle-side row estimates from "
                  "observed actuals (statements_summary_history "
                  "feedback) so shuffle_mode=auto and edge-mode "
                  "choices start from measured rather than static "
                  "stats"),
        SysVarDef("tidb_tpu_aqe_replan_ratio", 4.0, "global",
                  _float_range(1.0, 1e6),
                  "observed-vs-estimated row divergence factor that "
                  "triggers stage-boundary re-planning (re-running "
                  "choose_edge_modes with observed counts between "
                  "shuffle DAG stages)"),
        # Runtime filters (PR 19, parallel/wire.py rf kernels): the
        # AQE probe round harvests a build-side key summary and the
        # stage dispatch ships it so producers drop non-matching rows
        # before partition+encode. GLOBAL-only scheduler knobs; a live
        # SET re-tunes an attached scheduler (session.py hook).
        SysVarDef("tidb_tpu_runtime_filter", "auto", "global",
                  _enum("auto", "off", "always"),
                  "sideways-information-passing runtime filters on "
                  "repartition joins: auto costs filter build+ship "
                  "bytes against CARD_FEEDBACK-predicted probe bytes "
                  "saved; always forces emission on every legal "
                  "probed join; off disables"),
        SysVarDef("tidb_tpu_runtime_filter_bloom_bits", 10, "global",
                  _int_range(2, 64),
                  "bloom filter bits per distinct build-side key "
                  "(hash count derives as bits*ln2, clamped to "
                  "[1, 8]; total size capped at wire.py "
                  "RF_MAX_BLOOM_BYTES)"),
        SysVarDef("tidb_tpu_runtime_filter_inlist_ndv", 256, "global",
                  _int_range(1, 65536),
                  "build-side NDV at or below which the runtime "
                  "filter ships an EXACT in-list of key ints (zero "
                  "false positives) instead of a bloom"),
        # HTAP delta tier (storage/delta.py): coordinator DML deltas
        # replicate to the fleet; routed reads merge a (fold, seq)
        # snapshot; a background compactor folds the log into the
        # workers' columnar base blocks.
        SysVarDef("tidb_tpu_delta_store", True, "global", _bool,
                  "capture + replicate coordinator DML as delta "
                  "batches when a DCN scheduler is attached (OFF "
                  "restores the static-snapshot attach contract: "
                  "writes silently diverge the fleet)"),
        SysVarDef("tidb_tpu_read_freshness", "read_your_writes",
                  "both", _enum("read_your_writes", "bounded"),
                  "routed-read freshness: read_your_writes blocks "
                  "dispatch until every alive worker acked the "
                  "session's high-water delta seq; bounded reads at "
                  "the fleet's already-acked floor with zero wait"),
        SysVarDef("tidb_tpu_delta_sync_timeout_s", 30.0, "both",
                  _float_range(0.1, 3600.0),
                  "seconds a read-your-writes dispatch waits for "
                  "fleet delta acks before erroring (never a silent "
                  "stale read)"),
        SysVarDef("tidb_tpu_delta_compact_depth", 32, "global",
                  _int_range(1, 1 << 20),
                  "buffered delta entries on any one table that "
                  "trigger a background fold barrier"),
        SysVarDef("tidb_tpu_delta_compact_interval_s", 0.5, "global",
                  _float_range(0.0, 3600.0),
                  "delta-compactor daemon cadence (0 = no background "
                  "thread; folds run only via explicit compact_now)"),
        # metric time-series tier (obs/tsdb.py — the metrics_schema
        # retention store; a live SET re-tunes the running sampler and
        # rings, session.py SetVariable hook). GLOBAL-only like the
        # heartbeat knobs: one store serves every session.
        SysVarDef("tidb_tpu_tsdb_sample_interval_s", 0.0, "global",
                  _float_range(0.0, 3600.0),
                  "background sampler cadence for the metric "
                  "time-series store behind metrics_schema (0 = no "
                  "thread; sampling rides statement close instead). "
                  "While the fleet timeline is capturing, each tick "
                  "also samples the counter tracks, so gaps between "
                  "statements stop rendering flat"),
        SysVarDef("tidb_tpu_tsdb_retention_points", 512, "global",
                  _int_range(4, 1 << 20),
                  "newest raw samples retained per metric series "
                  "(per host x label set); older points downsample "
                  "into a coarse ring of the same size before being "
                  "dropped"),
        SysVarDef("tidb_tpu_tsdb_downsample_every", 8, "global",
                  _int_range(1, 4096),
                  "raw points folded into one downsampled point when "
                  "they age out of the raw retention ring (counters "
                  "keep the last cumulative value, gauges the mean)"),
        # Top SQL continuous profiler (obs/profiler.py): the reference
        # pkg/util/topsql knobs, LIVE here — SET GLOBAL
        # tidb_enable_top_sql starts/stops every process's sampler
        # (workers learn the config from dispatch/heartbeat frames),
        # the two caps re-tune the store live. GLOBAL-only like the
        # DCN knobs: one fleet profiler serves every session, so a
        # session-scoped SET errors loudly instead of silently tuning
        # nothing.
        SysVarDef("tidb_enable_top_sql", False, "global", _bool,
                  "start/stop the fleet-wide Top SQL sampling "
                  "profiler: per-digest cpu/device/stall attribution "
                  "into information_schema.top_sql, tidbtpu_topsql_* "
                  "series and the /profile flamegraph exporter"),
        SysVarDef("tidb_top_sql_max_time_series_count", 100, "global",
                  _int_range(1, 1 << 20),
                  "max DISTINCT statement digests each process's Top "
                  "SQL store tracks; admitting past the cap folds the "
                  "coldest digest into the (others) aggregate"),
        SysVarDef("tidb_top_sql_max_meta_count", 5000, "global",
                  _int_range(8, 1 << 24),
                  "max Top SQL meta entries per process (distinct "
                  "collapsed stacks + digest->text mappings); "
                  "overflowing stacks fold into (truncated)"),
        SysVarDef("tidb_tpu_topsql_sample_interval_s", 0.02, "global",
                  _float_range(0.001, 10.0),
                  "Top SQL sampler cadence (seconds between "
                  "sys._current_frames walks) while "
                  "tidb_enable_top_sql is ON"),
        SysVarDef("tidb_txn_mode", "pessimistic", "both",
                  _enum("pessimistic", "optimistic"),
                  "transaction mode: pessimistic takes blocking table "
                  "locks per DML statement (reference default); "
                  "optimistic is first-committer-wins"),
        SysVarDef("innodb_lock_wait_timeout", 50, "both", _int_range(1, 3600),
                  "seconds a pessimistic lock wait blocks before error "
                  "1205 (reference innodb_lock_wait_timeout)"),
        SysVarDef("tidb_broadcast_join_threshold_size", 1 << 20, "both", _int_range(0, 1 << 34),
                  "max build-side bytes for broadcast (vs hash-partition) joins"),
        SysVarDef("tidb_executor_concurrency", 1, "both", _int_range(1, 256),
                  "accepted for compatibility; device kernels are already parallel"),
        SysVarDef("tidb_enable_plan_cache", True, "both", _bool,
                  "cache jitted plans keyed by fingerprint + shapes"),
        SysVarDef("tidb_enable_auto_analyze", True, "both", _bool,
                  "refresh table statistics automatically once enough "
                  "rows changed (reference autoanalyze.go)"),
        SysVarDef("tidb_auto_analyze_ratio", 0.5, "both", _float_range(0.0, 1.0),
                  "modified-rows / total-rows ratio that triggers "
                  "auto-analyze (reference tidb_auto_analyze_ratio)"),
        SysVarDef("max_execution_time", 0, "both", _int_range(0, 1 << 31),
                  "per-statement wall-clock limit in ms (0 = unlimited); "
                  "runaway statements abort at the next kill safepoint"),
        # concurrency knobs: accepted for compatibility — device kernels
        # are already parallel, so these validate + round-trip but the
        # executor does not fan out host threads per statement
        SysVarDef("tidb_hash_join_concurrency", -1, "both", _int_range(-1, 256)),
        SysVarDef("tidb_index_lookup_concurrency", -1, "both", _int_range(-1, 256)),
        SysVarDef("tidb_index_serial_scan_concurrency", 1, "both", _int_range(1, 256)),
        SysVarDef("tidb_distsql_scan_concurrency", 15, "both", _int_range(1, 256)),
        SysVarDef("tidb_build_stats_concurrency", 4, "both", _int_range(1, 256)),
        SysVarDef("tidb_projection_concurrency", -1, "both", _int_range(-1, 256)),
        SysVarDef("tidb_window_concurrency", -1, "both", _int_range(-1, 256)),
        # engine-behavior flags accepted for compatibility (always-on or
        # by-design-different behaviors documented per entry)
        SysVarDef("tidb_enable_vectorized_expression", True, "both", _bool,
                  "always on: every expression lowers to fused XLA kernels"),
        SysVarDef("tidb_enable_clustered_index", "ON", "both",
                  _enum("ON", "OFF", "INT_ONLY"),
                  "accepted; storage is columnar with sorted-permutation "
                  "indexes, clustering is implicit"),
        SysVarDef("tidb_enable_async_commit", True, "both", _bool,
                  "accepted; single-process commits are atomic swaps"),
        SysVarDef("tidb_enable_1pc", True, "both", _bool),
        SysVarDef("tidb_row_format_version", 2, "both", _int_range(1, 2)),
        SysVarDef("tidb_enable_chunk_rpc", True, "both", _bool),
        SysVarDef("tidb_opt_agg_push_down", False, "both", _bool),
        SysVarDef("tidb_opt_distinct_agg_push_down", False, "both", _bool),
        SysVarDef("tidb_enable_index_merge", True, "both", _bool),
        SysVarDef("tidb_enable_stmt_summary", True, "both", _bool),
        SysVarDef("tidb_enable_collect_execution_info", True, "both", _bool),
        SysVarDef("tidb_retry_limit", 10, "both", _int_range(0, 1000)),
        SysVarDef("tidb_constraint_check_in_place", True, "both", _bool,
                  "always in place: uniqueness checks run on the append "
                  "path, there is no deferred prewrite"),
        SysVarDef("tidb_ddl_error_count_limit", 512, "both", _int_range(1, 1 << 20)),
        SysVarDef("tidb_max_chunk_size", 1024, "both", _int_range(32, 1 << 20)),
        SysVarDef("tidb_init_chunk_size", 32, "both", _int_range(1, 32)),
        # MySQL compatibility
        SysVarDef("autocommit", True, "both", _bool),
        SysVarDef("sql_select_limit", 2 ** 64 - 1, "both", _int_range(0, 2 ** 64 - 1)),
        SysVarDef("wait_timeout", 28800, "both", _int_range(0, 31536000)),
        SysVarDef("interactive_timeout", 28800, "both", _int_range(1, 31536000)),
        SysVarDef("net_write_timeout", 60, "both", _int_range(1, 31536000)),
        SysVarDef("net_read_timeout", 30, "both", _int_range(1, 31536000)),
        SysVarDef("lower_case_table_names", 2, "readonly"),
        SysVarDef("default_storage_engine", "InnoDB", "readonly"),
        SysVarDef("character_set_server", "utf8mb4", "both"),
        SysVarDef("character_set_client", "utf8mb4", "both"),
        SysVarDef("character_set_results", "utf8mb4", "both"),
        SysVarDef("character_set_database", "utf8mb4", "both"),
        SysVarDef("collation_server", "utf8mb4_bin", "both"),
        SysVarDef("collation_database", "utf8mb4_bin", "both"),
        SysVarDef("system_time_zone", "UTC", "readonly"),
        SysVarDef("init_connect", "", "both"),
        SysVarDef("license", "Apache License 2.0", "readonly"),
        SysVarDef("port", 4000, "readonly"),
        SysVarDef("socket", "", "readonly"),
        SysVarDef("innodb_buffer_pool_size", 134217728, "readonly"),
        SysVarDef("max_connections", 0, "both", _int_range(0, 100000)),
        SysVarDef("sql_safe_updates", False, "both", _bool),
        SysVarDef("foreign_key_checks", True, "both", _bool,
                  "accepted; FK RESTRICT/CASCADE enforcement is active "
                  "whenever constraints exist"),
        SysVarDef("unique_checks", True, "both", _bool),
        SysVarDef("group_concat_max_len", 1024, "both", _int_range(4, 1 << 30)),
        SysVarDef("sql_mode", "STRICT_TRANS_TABLES", "both"),
        SysVarDef("time_zone", "UTC", "both"),
        SysVarDef("max_allowed_packet", 64 << 20, "both", _int_range(1024, 1 << 30)),
        SysVarDef("version", "8.0.11-tidb-tpu-0.1.0", "readonly"),
        SysVarDef("version_comment", "tidb_tpu TPU-native SQL engine", "readonly"),
        SysVarDef("character_set_connection", "utf8mb4", "both"),
        SysVarDef("collation_connection", "utf8mb4_bin", "both"),
        SysVarDef("tx_isolation", "REPEATABLE-READ", "both",
                  _enum("REPEATABLE-READ", "READ-COMMITTED")),
        SysVarDef("transaction_isolation", "REPEATABLE-READ", "both",
                  _enum("REPEATABLE-READ", "READ-COMMITTED")),
        SysVarDef("tidb_read_staleness", 0, "both", _int_range(-86400, 0),
                  "negative seconds: autocommit reads resolve against "
                  "the newest table version at now+staleness (reference "
                  "tidb_read_staleness stale reads)"),
        SysVarDef("tidb_gc_life_time", 0, "global", _int_range(0, 86400 * 7),
                  "seconds of MVCC version history every table retains "
                  "for stale reads / AS OF TIMESTAMP (reference "
                  "tidb_gc_life_time; 0 = keep only pinned snapshots). "
                  "GLOBAL-only: it drives the engine-wide GC horizon"),
        # ---- driver/BI connect-time compatibility tier (reference:
        # pkg/sessionctx/variable/sysvar.go; clients SET/SELECT these on
        # connect — JDBC, mysql-connector, .NET, BI tools) ----
        SysVarDef("auto_increment_increment", 1, "both", _int_range(1, 65535)),
        SysVarDef("auto_increment_offset", 1, "both", _int_range(1, 65535)),
        SysVarDef("big_tables", False, "both", _bool),
        SysVarDef("block_encryption_mode", "aes-128-ecb", "both"),
        SysVarDef("bulk_insert_buffer_size", 8388608, "both"),
        SysVarDef("character_set_filesystem", "binary", "both"),
        SysVarDef("default_collation_for_utf8mb4", "utf8mb4_bin", "both"),
        SysVarDef("concurrent_insert", "AUTO", "readonly"),
        SysVarDef("connect_timeout", 10, "both", _int_range(2, 31536000)),
        SysVarDef("datadir", "/tmp/tidb_tpu", "readonly"),
        SysVarDef("default_authentication_plugin", "mysql_native_password", "readonly"),
        SysVarDef("default_week_format", 0, "both", _int_range(0, 7)),
        SysVarDef("delay_key_write", "ON", "both"),
        SysVarDef("div_precision_increment", 4, "both", _int_range(0, 30)),
        SysVarDef("event_scheduler", "OFF", "both"),
        SysVarDef("explicit_defaults_for_timestamp", True, "both", _bool),
        SysVarDef("flush", False, "both", _bool),
        SysVarDef("have_openssl", "DISABLED", "readonly"),
        SysVarDef("have_ssl", "DISABLED", "readonly"),
        SysVarDef("hostname", "tidb-tpu", "readonly"),
        SysVarDef("innodb_file_per_table", True, "readonly"),
        SysVarDef("join_buffer_size", 262144, "both"),
        SysVarDef("key_buffer_size", 8388608, "both"),
        SysVarDef("last_insert_id", 0, "session", _int_range(0, 2 ** 63 - 1)),
        SysVarDef("long_query_time", 10.0, "both"),
        SysVarDef("max_heap_table_size", 16777216, "both"),
        SysVarDef("max_join_size", 2 ** 64 - 1, "both"),
        SysVarDef("max_length_for_sort_data", 1024, "both"),
        SysVarDef("max_prepared_stmt_count", -1, "global"),
        SysVarDef("max_sort_length", 1024, "both"),
        SysVarDef("max_sp_recursion_depth", 0, "both", _int_range(0, 255)),
        SysVarDef("max_user_connections", 0, "both", _int_range(0, 4294967295)),
        SysVarDef("myisam_sort_buffer_size", 8388608, "both"),
        SysVarDef("net_buffer_length", 16384, "both"),
        SysVarDef("net_retry_count", 10, "both", _int_range(1, 4294967295)),
        SysVarDef("old_passwords", 0, "both", _int_range(0, 2)),
        SysVarDef("optimizer_switch", "", "both"),
        SysVarDef("performance_schema", False, "readonly", _bool),
        SysVarDef("profiling", False, "both", _bool),
        SysVarDef("protocol_version", 10, "readonly"),
        SysVarDef("query_cache_size", 0, "readonly"),
        SysVarDef("query_cache_type", "OFF", "readonly"),
        SysVarDef("rand_seed1", 0, "session"),
        SysVarDef("rand_seed2", 0, "session"),
        SysVarDef("read_buffer_size", 131072, "both"),
        SysVarDef("read_rnd_buffer_size", 262144, "both"),
        SysVarDef("skip_networking", False, "readonly", _bool),
        SysVarDef("sort_buffer_size", 262144, "both"),
        SysVarDef("sql_auto_is_null", False, "both", _bool),
        SysVarDef("sql_big_selects", True, "both", _bool),
        SysVarDef("sql_buffer_result", False, "both", _bool),
        SysVarDef("sql_log_bin", True, "both", _bool),
        SysVarDef("sql_log_off", False, "both", _bool),
        SysVarDef("sql_notes", True, "both", _bool),
        SysVarDef("sql_quote_show_create", True, "both", _bool),
        SysVarDef("sql_warnings", False, "both", _bool),
        SysVarDef("ssl_ca", "", "readonly"),
        SysVarDef("ssl_cert", "", "readonly"),
        SysVarDef("ssl_key", "", "readonly"),
        SysVarDef("table_definition_cache", -1, "both"),
        SysVarDef("thread_cache_size", -1, "both"),
        SysVarDef("timestamp", 0.0, "session"),
        SysVarDef("tmp_table_size", 16777216, "both"),
        SysVarDef("tmpdir", "/tmp", "readonly"),
        SysVarDef("transaction_alloc_block_size", 8192, "both"),
        SysVarDef("transaction_prealloc_size", 4096, "both"),
        SysVarDef("tx_read_only", False, "both", _bool),
        SysVarDef("transaction_read_only", False, "both", _bool),
        SysVarDef("unique_subquery_cache", True, "both", _bool),
        SysVarDef("version_compile_machine", "tpu", "readonly"),
        SysVarDef("version_compile_os", "Linux", "readonly"),
        SysVarDef("warning_count", 0, "readonly"),
        SysVarDef("error_count", 0, "readonly"),
        # tidb-prefixed compatibility knobs drivers/tools probe
        SysVarDef("tidb_allow_batch_cop", 1, "both", _int_range(0, 2)),
        SysVarDef("tidb_batch_insert", False, "both", _bool),
        SysVarDef("tidb_current_ts", 0, "readonly"),
        SysVarDef("tidb_enable_cascades_planner", False, "both", _bool),
        SysVarDef("tidb_enable_fast_analyze", False, "both", _bool),
        SysVarDef("tidb_enable_noop_functions", False, "both", _bool),
        SysVarDef("tidb_enable_parallel_apply", False, "both", _bool),
        SysVarDef("tidb_enable_window_function", True, "both", _bool),
        SysVarDef("tidb_force_priority", "NO_PRIORITY", "both"),
        SysVarDef("tidb_index_join_batch_size", 25000, "both"),
        SysVarDef("tidb_skip_utf8_check", False, "both", _bool),
        SysVarDef("tidb_snapshot", "", "session"),
        SysVarDef("tidb_wait_split_region_finish", True, "both", _bool),
    ]
}

# round-5 compatibility surface (reference sysvar.go defaults,
# prioritized by what mysql-connector / JDBC / mysqlclient / common
# ORMs SET or SELECT at connect time). ADDITIVE ONLY: an entry above
# (with its validator/scope/default) always wins over a compat entry
# of the same name. Entries without a validator round-trip any value;
# behavioral knobs with no analog here validate + persist only.
_COMPAT_VARS = [
            # -- MySQL connector handshake set ----------------------
            ("character_set_client", "utf8mb4", "both", None),
            ("character_set_connection", "utf8mb4", "both", None),
            ("character_set_results", "utf8mb4", "both", None),
            ("character_set_server", "utf8mb4", "both", None),
            ("character_set_database", "utf8mb4", "both", None),
            ("character_set_system", "utf8mb3", "readonly", None),
            ("character_set_filesystem", "binary", "both", None),
            ("collation_connection", "utf8mb4_bin", "both", None),
            ("collation_database", "utf8mb4_bin", "both", None),
            ("collation_server", "utf8mb4_bin", "both", None),
            ("init_connect", "", "global", None),
            ("interactive_timeout", 28800, "both", _int_range(1, 31536000)),
            ("wait_timeout", 28800, "both", _int_range(0, 31536000)),
            ("net_read_timeout", 30, "both", _int_range(1, 31536000)),
            ("net_write_timeout", 60, "both", _int_range(1, 31536000)),
            ("net_buffer_length", 16384, "readonly", None),
            ("max_allowed_packet", 67108864, "both", _int_range(1024, 1 << 30)),
            ("sql_mode", "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES,"
             "NO_ZERO_IN_DATE,NO_ZERO_DATE,ERROR_FOR_DIVISION_BY_ZERO,"
             "NO_ENGINE_SUBSTITUTION", "both", None),
            ("sql_select_limit", 18446744073709551615, "both", None),
            ("sql_safe_updates", False, "both", _bool),
            ("sql_notes", True, "both", _bool),
            ("sql_warnings", False, "both", _bool),
            ("sql_log_bin", True, "session", _bool),
            ("sql_buffer_result", False, "both", _bool),
            ("sql_quote_show_create", True, "both", _bool),
            ("sql_auto_is_null", False, "both", _bool),
            ("sql_big_selects", True, "both", _bool),
            ("sql_require_primary_key", False, "both", _bool),
            ("autocommit", True, "both", _bool),
            ("auto_increment_increment", 1, "both", _int_range(1, 65535)),
            ("auto_increment_offset", 1, "both", _int_range(1, 65535)),
            ("tx_isolation", "REPEATABLE-READ", "both", None),
            ("transaction_isolation", "REPEATABLE-READ", "both", None),
            ("tx_read_only", False, "both", _bool),
            ("transaction_read_only", False, "both", _bool),
            ("default_storage_engine", "InnoDB", "both", None),
            ("default_tmp_storage_engine", "InnoDB", "both", None),
            ("storage_engine", "InnoDB", "both", None),
            ("lower_case_table_names", 2, "readonly", None),
            ("system_time_zone", "UTC", "readonly", None),
            ("explicit_defaults_for_timestamp", True, "both", _bool),
            ("group_concat_max_len", 1048576, "both", _int_range(4, 1 << 34)),
            ("max_connections", 0, "global", _int_range(0, 100000)),
            ("max_user_connections", 0, "both", _int_range(0, 100000)),
            ("max_prepared_stmt_count", -1, "global", None),
            ("max_sort_length", 1024, "both", _int_range(4, 8388608)),
            ("max_sp_recursion_depth", 0, "both", _int_range(0, 255)),
            ("thread_pool_size", 16, "readonly", None),
            ("performance_schema", False, "readonly", _bool),
            ("query_cache_type", "OFF", "readonly", None),
            ("query_cache_size", 0, "readonly", None),
            ("have_openssl", "YES", "readonly", None),
            ("have_ssl", "YES", "readonly", None),
            ("have_query_cache", "NO", "readonly", None),
            ("have_profiling", "NO", "readonly", None),
            ("hostname", "tidb-tpu", "readonly", None),
            ("port", 4000, "readonly", None),
            ("socket", "", "readonly", None),
            ("datadir", "/tmp/tidb-tpu", "readonly", None),
            ("license", "Apache License 2.0", "readonly", None),
            ("protocol_version", 10, "readonly", None),
            ("version_comment", "TiDB-on-TPU Server (Apache License 2.0)",
             "readonly", None),
            ("version_compile_machine", "x86_64", "readonly", None),
            ("version_compile_os", "Linux", "readonly", None),
            ("innodb_buffer_pool_size", 134217728, "readonly", None),
            ("innodb_flush_log_at_trx_commit", 1, "both", None),
            ("innodb_file_per_table", True, "readonly", _bool),
            ("innodb_read_only", False, "readonly", _bool),
            ("innodb_strict_mode", True, "both", _bool),
            ("foreign_key_checks", True, "both", _bool),
            ("unique_checks", True, "both", _bool),
            ("old_passwords", 0, "both", None),
            ("default_password_lifetime", 0, "global", None),
            ("default_authentication_plugin", "mysql_native_password",
             "readonly", None),
            ("validate_password.enable", False, "global", _bool),
            ("secure_auth", True, "readonly", _bool),
            ("local_infile", False, "global", _bool),
            ("log_bin", False, "readonly", _bool),
            ("binlog_format", "ROW", "both", None),
            ("binlog_row_image", "FULL", "both", None),
            ("block_encryption_mode", "aes-128-ecb", "both", None),
            ("div_precision_increment", 4, "both", _int_range(0, 30)),
            ("lc_time_names", "en_US", "both", None),
            ("lc_messages", "en_US", "both", None),
            ("timestamp", 0, "session", None),
            ("rand_seed1", 0, "session", None),
            ("rand_seed2", 0, "session", None),
            ("pseudo_thread_id", 0, "session", None),
            ("warning_count", 0, "readonly", None),
            ("error_count", 0, "readonly", None),
            ("last_insert_id", 0, "session", None),
            ("identity", 0, "session", None),
            ("insert_id", 0, "session", None),
            ("profiling", False, "both", _bool),
            ("profiling_history_size", 15, "both", None),
            ("optimizer_switch", "index_merge=on", "both", None),
            ("optimizer_trace", "enabled=off,one_line=off", "both", None),
            ("max_heap_table_size", 16777216, "both", None),
            ("tmp_table_size", 16777216, "both", None),
            ("table_definition_cache", -1, "global", None),
            ("table_open_cache", 2000, "global", None),
            ("open_files_limit", 5000, "readonly", None),
            ("read_buffer_size", 131072, "both", None),
            ("read_rnd_buffer_size", 262144, "both", None),
            ("sort_buffer_size", 262144, "both", None),
            ("join_buffer_size", 262144, "both", None),
            ("bulk_insert_buffer_size", 8388608, "both", None),
            ("long_query_time", 10.0, "both", _float_range(0.0, 31536000.0)),
            ("log_queries_not_using_indexes", False, "global", _bool),
            ("event_scheduler", "OFF", "global", None),
            ("low_priority_updates", False, "both", _bool),
            ("completion_type", "NO_CHAIN", "both", None),
            ("concurrent_insert", "AUTO", "global", None),
            ("delay_key_write", "ON", "global", None),
            ("flush", False, "global", _bool),
            ("keep_files_on_create", False, "both", _bool),
            ("new", False, "both", _bool),
            ("old", False, "readonly", _bool),
            ("big_tables", False, "both", _bool),
            ("check_proxy_users", False, "global", _bool),
            # -- TiDB compatibility set -----------------------------
            ("tidb_current_ts", 0, "readonly", None),
            ("tidb_last_txn_info", "", "readonly", None),
            ("tidb_last_query_info", "", "readonly", None),
            ("tidb_config", "", "readonly", None),
            ("tidb_general_log", False, "global", _bool),
            ("tidb_pprof_sql_cpu", False, "global", _bool),
            ("tidb_record_plan_in_slow_log", True, "both", _bool),
            ("tidb_enable_slow_log", True, "global", _bool),
            ("tidb_check_mb4_value_in_utf8", True, "global", _bool),
            ("tidb_opt_write_row_id", False, "session", _bool),
            ("tidb_batch_insert", False, "session", _bool),
            ("tidb_batch_delete", False, "session", _bool),
            ("tidb_batch_commit", False, "session", _bool),
            ("tidb_dml_batch_size", 0, "both", _int_range(0, 1 << 31)),
            ("tidb_backoff_lock_fast", 10, "both", None),
            ("tidb_backoff_weight", 2, "both", None),
            ("tidb_ddl_reorg_worker_cnt", 4, "both", _int_range(1, 256)),
            ("tidb_ddl_reorg_batch_size", 256, "both", _int_range(32, 10240)),
            ("tidb_ddl_reorg_priority", "PRIORITY_LOW", "both", None),
            ("tidb_enable_ddl", True, "global", _bool),
            ("tidb_scatter_region", "", "global", None),
            ("tidb_disable_txn_auto_retry", True, "both", _bool),
            ("tidb_enable_streaming", False, "session", _bool),
            ("tidb_enable_rate_limit_action", False, "both", _bool),
            ("tidb_allow_batch_cop", 1, "both", _int_range(0, 2)),
            ("tidb_allow_fallback_to_tikv", "", "both", None),
            ("tidb_enable_tiflash_read_for_write_stmt", True, "both", _bool),
            ("tidb_isolation_read_engines", "tikv,tiflash,tidb", "both", None),
            ("tidb_metric_scheme_ttl", 60, "global", None),
            ("tidb_enable_telemetry", False, "global", _bool),
            ("tidb_enable_extended_stats", False, "both", _bool),
            ("tidb_stats_load_sync_wait", 100, "both", None),
            ("tidb_analyze_version", 2, "both", _int_range(1, 2)),
            ("tidb_stats_cache_mem_quota", 0, "global", None),
            ("tidb_mem_quota_analyze", -1, "global", None),
            ("tidb_enable_fast_analyze", False, "both", _bool),
            ("tidb_persist_analyze_options", True, "global", _bool),
            ("tidb_opt_prefer_range_scan", False, "both", _bool),
            ("tidb_opt_limit_push_down_threshold", 100, "both", None),
            ("tidb_opt_enable_correlation_adjustment", True, "both", _bool),
            ("tidb_opt_correlation_threshold", 0.9, "both",
             _float_range(0.0, 1.0)),
            ("tidb_opt_correlation_exp_factor", 1, "both", None),
            ("tidb_opt_cpu_factor", 3.0, "both", None),
            ("tidb_opt_copcpu_factor", 3.0, "both", None),
            ("tidb_opt_network_factor", 1.0, "both", None),
            ("tidb_opt_scan_factor", 1.5, "both", None),
            ("tidb_opt_desc_factor", 3.0, "both", None),
            ("tidb_opt_seek_factor", 20.0, "both", None),
            ("tidb_opt_memory_factor", 0.001, "both", None),
            ("tidb_opt_disk_factor", 1.5, "both", None),
            ("tidb_opt_concurrency_factor", 3.0, "both", None),
            ("tidb_opt_insubq_to_join_and_agg", True, "both", _bool),
            ("tidb_enable_cascades_planner", False, "both", _bool),
            ("tidb_enable_outer_join_reorder", True, "both", _bool),
            ("tidb_enable_null_aware_anti_join", True, "both", _bool),
            ("tidb_opt_join_reorder_threshold", 0, "both",
             _int_range(0, 63)),
            ("tidb_enable_noop_functions", "OFF", "both", None),
            ("tidb_enable_noop_variables", True, "global", _bool),
            ("tidb_enable_list_partition", True, "both", _bool),
            ("tidb_enable_table_partition", "ON", "both", None),
            ("tidb_partition_prune_mode", "dynamic", "both", None),
            ("tidb_enable_global_index", False, "global", _bool),
            ("tidb_enable_foreign_key", True, "global", _bool),
            ("foreign_key_checks_tidb", True, "both", _bool),
            ("tidb_super_read_only", False, "global", _bool),
            ("tidb_restricted_read_only", False, "global", _bool),
            ("tidb_gc_enable", True, "global", _bool),
            ("tidb_gc_run_interval", "10m0s", "global", None),
            ("tidb_gc_max_wait_time", 86400, "global", None),
            ("tidb_gc_scan_lock_mode", "LEGACY", "global", None),
            ("tidb_gc_concurrency", -1, "global", None),
            ("tidb_enable_gogc_tuner", True, "global", _bool),
            ("tidb_server_memory_limit", "80%", "global", None),
            ("tidb_server_memory_limit_gc_trigger", 0.7, "global", None),
            ("tidb_server_memory_limit_sess_min_size", 134217728,
             "global", None),
            ("tidb_enable_tmp_storage_on_oom", True, "global", _bool),
            ("tidb_tmp_table_max_size", 67108864, "both", None),
            ("tidb_mem_oom_action", "CANCEL", "global", None),
            ("tidb_nontransactional_ignore_error", False, "both", _bool),
            ("tidb_max_delta_schema_count", 1024, "global", None),
            ("tidb_enable_point_get_cache", False, "both", _bool),
            ("tidb_enable_ordered_result_mode", False, "both", _bool),
            ("tidb_enable_pseudo_for_outdated_stats", False, "both", _bool),
            ("tidb_enable_prepared_plan_cache", True, "both", _bool),
            ("tidb_prepared_plan_cache_size", 100, "both",
             _int_range(1, 100000)),
            ("tidb_enable_non_prepared_plan_cache", False, "both", _bool),
            ("tidb_plan_cache_max_plan_size", 2097152, "global", None),
            ("tidb_ignore_prepared_cache_close_stmt", False, "both", _bool),
            ("tidb_enable_new_cost_interface", True, "both", _bool),
            ("tidb_cost_model_version", 2, "both", _int_range(1, 2)),
            ("tidb_index_join_double_read_penalty_cost_rate", 0.0,
             "both", None),
            ("tidb_opt_force_inline_cte", False, "both", _bool),
            ("tidb_enable_reuse_chunk", True, "both", _bool),
            ("tidb_store_batch_size", 4, "both", None),
            ("tidb_committer_concurrency", 128, "global", None),
            ("tidb_enable_batch_dml", False, "global", _bool),
            ("tidb_mem_quota_binding_cache", 67108864, "global", None),
            ("tidb_enable_mutation_checker", True, "both", _bool),
            ("tidb_txn_assertion_level", "FAST", "both", None),
            ("tidb_rc_read_check_ts", False, "both", _bool),
            ("tidb_rc_write_check_ts", False, "both", _bool),
            ("tidb_sysdate_is_now", False, "both", _bool),
            ("tidb_table_cache_lease", 3, "global", None),
            ("tidb_enable_historical_stats", True, "global", _bool),
            ("tidb_enable_plan_replayer_capture", True, "global", _bool),
            ("tidb_enable_resource_control", True, "global", _bool),
            ("tidb_resource_control_strict_mode", True, "global", _bool),
            ("tidb_load_based_replica_read_threshold", "1s", "both", None),
            ("tidb_low_resolution_tso", False, "both", _bool),
            ("tidb_replica_read", "leader", "both", None),
            ("tidb_adaptive_closest_read_threshold", 4096, "both", None),
            ("tidb_use_plan_baselines", True, "both", _bool),
            ("tidb_evolve_plan_baselines", False, "both", _bool),
            ("tidb_capture_plan_baselines", "OFF", "global", None),
            ("tidb_auto_analyze_start_time", "00:00 +0000", "global", None),
            ("tidb_auto_analyze_end_time", "23:59 +0000", "global", None),
            ("tidb_auto_analyze_partition_batch_size", 128, "global", None),
            ("tidb_max_auto_analyze_time", 43200, "global", None),
            ("tidb_read_staleness", 0, "session", None),
            ("tidb_expensive_query_time_threshold", 60, "global",
             _int_range(0, 1 << 31)),
            ("tidb_memory_usage_alarm_ratio", 0.7, "global",
             _float_range(0.0, 1.0)),
            ("tidb_memory_usage_alarm_keep_record_num", 5, "global", None),
            ("tidb_memory_debug_mode_min_heap_inuse", 0, "both", None),
            ("tidb_memory_debug_mode_alarm_ratio", 0, "both", None),
            ("tidb_opt_range_max_size", 67108864, "both", None),
            ("tidb_opt_advanced_join_hint", True, "both", _bool),
            ("tidb_opt_use_invisible_indexes", False, "session", _bool),
            ("tidb_shard_allocate_step", 9223372036854775807, "both", None),
            ("tidb_generate_binary_plan", True, "global", _bool),
            ("tidb_external_ts", 0, "global", None),
            ("tidb_enable_external_ts_read", False, "both", _bool),
            ("tidb_ttl_job_enable", True, "global", _bool),
            ("tidb_ttl_scan_batch_size", 500, "global", None),
            ("tidb_ttl_delete_batch_size", 100, "global", None),
            ("tidb_ttl_delete_rate_limit", 0, "global", None),
            ("tidb_ttl_running_tasks", -1, "global", None),
            ("tidb_stmt_summary_max_stmt_count", 3000, "global", None),
            ("tidb_stmt_summary_max_sql_length", 4096, "global", None),
            ("tidb_stmt_summary_refresh_interval", 1800, "global", None),
            ("tidb_stmt_summary_history_size", 24, "global", None),
            ("tidb_stmt_summary_internal_query", False, "global", _bool),
            ("tidb_enable_column_tracking", True, "global", _bool),
            ("tidb_track_aggregate_memory_usage", True, "both", _bool),
            ("tidb_tso_client_batch_max_wait_time", 0.0, "global", None),
            ("tidb_enable_tso_follower_proxy", False, "global", _bool),
            ("tidb_query_log_max_len", 4096, "global", None),
            ("tidb_hashagg_partial_concurrency", -1, "both", None),
            ("tidb_hashagg_final_concurrency", -1, "both", None),
            ("tidb_streamagg_concurrency", 1, "both", None),
            ("tidb_merge_join_concurrency", 1, "both", None),
            ("tidb_index_lookup_join_concurrency", -1, "both", None),
            ("tidb_index_merge_intersection_concurrency", -1, "both", None),
            ("tidb_enable_index_merge_join", False, "both", _bool),
            ("tidb_mpp_store_fail_ttl", "60s", "both", None),
            ("tidb_enforce_mpp", False, "session", _bool),
            ("tidb_opt_broadcast_cartesian_join", 1, "both", None),
            ("tidb_mpp_version", -1, "both", None),
            ("tidb_max_tiflash_threads", -1, "both", None),
            ("tidb_min_paging_size", 128, "both", None),
            ("tidb_max_paging_size", 50000, "both", None),
            # -- round-5 completion: every remaining reference sysvar
            # (sysvar.go + tidb_vars.go + noop.go name census) —
            # validate + persist only, like the reference's noop tier
            ("allow_auto_random_explicit_insert", False, "both", _bool),
            ("authentication_ldap_sasl_auth_method_name", "", "both", None),
            ("authentication_ldap_sasl_bind_base_dn", "", "both", None),
            ("authentication_ldap_sasl_bind_root_dn", "", "both", None),
            ("authentication_ldap_sasl_bind_root_pwd", "", "both", None),
            ("authentication_ldap_sasl_ca_path", "", "both", None),
            ("authentication_ldap_sasl_init_pool_size", 0, "both", None),
            ("authentication_ldap_sasl_max_pool_size", 0, "both", None),
            ("authentication_ldap_sasl_referral", "", "both", None),
            ("authentication_ldap_sasl_server_host", "", "both", None),
            ("authentication_ldap_sasl_server_port", 0, "both", None),
            ("authentication_ldap_sasl_tls", "", "both", None),
            ("authentication_ldap_sasl_user_search_attr", False, "both", _bool),
            ("authentication_ldap_simple_auth_method_name", "", "both", None),
            ("authentication_ldap_simple_bind_base_dn", "", "both", None),
            ("authentication_ldap_simple_bind_root_dn", "", "both", None),
            ("authentication_ldap_simple_bind_root_pwd", "", "both", None),
            ("authentication_ldap_simple_ca_path", "", "both", None),
            ("authentication_ldap_simple_init_pool_size", 0, "both", None),
            ("authentication_ldap_simple_max_pool_size", 0, "both", None),
            ("authentication_ldap_simple_referral", "", "both", None),
            ("authentication_ldap_simple_server_host", "", "both", None),
            ("authentication_ldap_simple_server_port", 0, "both", None),
            ("authentication_ldap_simple_tls", "", "both", None),
            ("authentication_ldap_simple_user_search_attr", False, "both", _bool),
            ("automatic_sp_privileges", "", "both", None),
            ("avoid_temporal_upgrade", "", "both", None),
            ("binlog_direct_non_transactional_updates", "", "both", None),
            ("binlog_order_commits", "", "both", None),
            ("binlog_rows_query_log_events", "", "both", None),
            ("core_file", "", "both", None),
            ("cte_max_recursion_depth", 1000, "both", None),
            ("ddl_slow_threshold", 0, "both", None),
            ("disconnect_on_expired_password", "", "both", None),
            ("end_markers_in_json", "", "both", None),
            ("enforce_gtid_consistency", "", "both", None),
            ("flush_time", 0, "both", None),
            ("general_log", False, "both", _bool),
            ("innodb_adaptive_flushing", False, "both", _bool),
            ("innodb_adaptive_hash_index", False, "both", _bool),
            ("innodb_buffer_pool_dump_at_shutdown", "", "both", None),
            ("innodb_buffer_pool_dump_now", "", "both", None),
            ("innodb_buffer_pool_load_abort", "", "both", None),
            ("innodb_buffer_pool_load_now", "", "both", None),
            ("innodb_cmp_per_index_enabled", False, "both", _bool),
            ("innodb_commit_concurrency", 0, "both", None),
            ("innodb_disable_sort_file_cache", False, "both", _bool),
            ("innodb_fast_shutdown", "", "both", None),
            ("innodb_ft_enable_stopword", False, "both", _bool),
            ("innodb_log_compressed_pages", False, "both", _bool),
            ("innodb_optimize_fulltext_only", False, "both", _bool),
            ("innodb_print_all_deadlocks", "", "both", None),
            ("innodb_random_read_ahead", "", "both", None),
            ("innodb_stats_auto_recalc", "", "both", None),
            ("innodb_stats_on_metadata", "", "both", None),
            ("innodb_stats_persistent", False, "both", _bool),
            ("innodb_status_output", "", "both", None),
            ("innodb_status_output_locks", "", "both", None),
            ("innodb_support_xa", "", "both", None),
            ("innodb_table_locks", "", "both", None),
            ("last_plan_from_binding", "", "readonly", None),
            ("last_plan_from_cache", "", "readonly", None),
            ("last_sql_use_alloc", False, "readonly", _bool),
            ("log_bin_trust_function_creators", False, "both", _bool),
            ("log_slow_admin_statements", "", "both", None),
            ("log_slow_slave_statements", "", "both", None),
            ("master_verify_checksum", False, "both", _bool),
            ("max_connect_errors", 100, "both", None),
            ("mpp_exchange_compression_mode", "UNSPECIFIED", "both", None),
            ("mpp_version", "-1", "both", None),
            ("myisam_use_mmap", False, "both", _bool),
            ("offline_mode", "", "both", None),
            ("old_alter_table", "", "both", None),
            ("password_history", 0, "both", None),
            ("password_reuse_interval", 0, "both", None),
            ("pd_enable_follower_handle_region", False, "both", _bool),
            ("plugin_dir", "", "both", None),
            ("plugin_load", "", "both", None),
            ("pseudo_slave_mode", "", "both", None),
            ("query_cache_wlock_invalidate", "", "both", None),
            ("read_only", False, "both", _bool),
            ("relay_log_purge", False, "both", _bool),
            ("require_secure_transport", False, "both", _bool),
            ("session_track_gtids", False, "both", _bool),
            ("show_old_temporals", "", "both", None),
            ("skip_name_resolve", False, "both", _bool),
            ("slave_allow_batching", False, "both", _bool),
            ("slave_compressed_protocol", False, "both", _bool),
            ("slow_query_log", True, "both", _bool),
            ("super_read_only", False, "both", _bool),
            ("sync_binlog", 0, "both", None),
            ("tidb_allow_function_for_expression_index", False, "both", _bool),
            ("tidb_allow_remove_auto_inc", False, "both", _bool),
            ("tidb_allow_tiflash_cop", False, "both", _bool),
            ("tidb_analyze_distsql_scan_concurrency", 0, "both", None),
            ("tidb_analyze_partition_concurrency", 0, "both", None),
            ("tidb_analyze_skip_column_types", False, "both", _bool),
            ("tidb_auto_build_stats_concurrency", 0, "both", None),
            ("tidb_batch_pending_tiflash_count", 0, "both", None),
            ("tidb_broadcast_join_threshold_count", 0, "both", None),
            ("tidb_build_sampling_stats_concurrency", 0, "both", None),
            ("tidb_cdc_write_source", "", "both", None),
            ("tidb_checksum_table_concurrency", 0, "both", None),
            ("tidb_cloud_storage_uri", "", "both", None),
            ("tidb_constraint_check_in_place_pessimistic", "", "both", None),
            ("tidb_ddl_disk_quota", 107374182400, "both", None),
            ("tidb_ddl_enable_fast_reorg", False, "both", _bool),
            ("tidb_ddl_flashback_concurrency", 0, "both", None),
            ("tidb_default_string_match_selectivity", "", "both", None),
            ("tidb_disable_column_tracking_time", False, "both", _bool),
            ("tidb_dml_type", "standard", "both", None),
            ("tidb_enable_analyze_snapshot", False, "both", _bool),
            ("tidb_enable_async_merge_global_stats", False, "both", _bool),
            ("tidb_enable_auto_analyze_priority_queue", False, "both", _bool),
            ("tidb_enable_auto_increment_in_generated", False, "both", _bool),
            ("tidb_enable_check_constraint", True, "both", _bool),
            ("tidb_enable_dist_task", True, "both", _bool),
            ("tidb_enable_enhanced_security", False, "both", _bool),
            ("tidb_enable_exchange_partition", False, "both", _bool),
            ("tidb_enable_fast_create_table", False, "both", _bool),
            ("tidb_enable_fast_table_check", False, "both", _bool),
            ("tidb_enable_gc_aware_memory_track", False, "both", _bool),
            ("tidb_enable_historical_stats_for_capture", False, "both", _bool),
            ("tidb_enable_inl_join_inner_multi_pattern", False, "both", _bool),
            ("tidb_enable_legacy_instance_scope", False, "both", _bool),
            ("tidb_enable_local_txn", False, "both", _bool),
            ("tidb_enable_metadata_lock", True, "both", _bool),
            ("tidb_enable_new_only_full_group_by_check", False, "both", _bool),
            ("tidb_enable_non_prepared_plan_cache_for_dml", False, "both", _bool),
            ("tidb_enable_paging", True, "both", _bool),
            ("tidb_enable_parallel_hashagg_spill", False, "both", _bool),
            ("tidb_enable_pipelined_window_function", False, "both", _bool),
            ("tidb_enable_plan_cache_for_param_limit", False, "both", _bool),
            ("tidb_enable_plan_cache_for_subquery", False, "both", _bool),
            ("tidb_enable_plan_replayer_continuous_capture", False, "both", _bool),
            ("tidb_enable_prepared_plan_cache_memory_monitor", False, "both", _bool),
            ("tidb_enable_row_level_checksum", False, "both", _bool),
            ("tidb_enable_strict_double_type_check", False, "both", _bool),
            ("tidb_enable_tiflash_pipeline_model", False, "both", _bool),
            ("tidb_enable_unsafe_substitute", False, "both", _bool),
            ("tidb_evolve_plan_task_end_time", "", "both", None),
            ("tidb_evolve_plan_task_max_time", "", "both", None),
            ("tidb_evolve_plan_task_start_time", "", "both", None),
            ("tidb_expensive_txn_time_threshold", 0, "both", None),
            ("tidb_gogc_tuner_max_value", "", "both", None),
            ("tidb_gogc_tuner_min_value", "", "both", None),
            ("tidb_gogc_tuner_threshold", 0, "both", None),
            ("tidb_guarantee_linearizability", "", "both", None),
            ("tidb_hash_exchange_with_new_collation", "", "both", None),
            ("tidb_historical_stats_duration", 0, "both", None),
            ("tidb_idle_transaction_timeout", 0, "both", None),
            ("tidb_ignore_inlist_plan_digest", "", "both", None),
            ("tidb_index_lookup_size", 20000, "both", None),
            ("tidb_last_ddl_info", "", "readonly", None),
            ("tidb_last_plan_replayer_token", "", "readonly", None),
            ("tidb_load_binding_timeout", 0, "both", None),
            ("tidb_lock_unchanged_keys", "", "both", None),
            ("tidb_log_file_max_days", 0, "both", None),
            ("tidb_low_resolution_tso_update_interval", 2000, "both", None),
            ("tidb_max_bytes_before_tiflash_external_group_by", "", "both", None),
            ("tidb_max_bytes_before_tiflash_external_join", "", "both", None),
            ("tidb_max_bytes_before_tiflash_external_sort", "", "both", None),
            ("tidb_mem_quota_apply_cache", "", "both", None),
            ("tidb_merge_partition_stats_concurrency", 0, "both", None),
            ("tidb_metric_query_range_duration", 0, "both", None),
            ("tidb_metric_query_step", 0, "both", None),
            ("tidb_multi_statement_mode", "OFF", "both", None),
            ("tidb_non_prepared_plan_cache_size", 0, "both", None),
            ("tidb_opt_derive_topn", "", "both", None),
            ("tidb_opt_enable_fuzzy_binding", False, "both", _bool),
            ("tidb_opt_enable_hash_join", False, "both", _bool),
            ("tidb_opt_enable_late_materialization", False, "both", _bool),
            ("tidb_opt_enable_mpp_shared_cte_execution", False, "both", _bool),
            ("tidb_opt_enable_non_eval_scalar_subquery", False, "both", _bool),
            ("tidb_opt_enable_three_stage_multi_distinct_agg", False, "both", _bool),
            ("tidb_opt_fix_control", "", "both", None),
            ("tidb_opt_mpp_outer_join_fixed_build_side", "", "both", None),
            ("tidb_opt_objective", "moderate", "both", None),
            ("tidb_opt_ordering_index_selectivity_ratio", 0.0, "both", None),
            ("tidb_opt_ordering_index_selectivity_threshold", 0, "both", None),
            ("tidb_opt_prefix_index_single_scan", "", "both", None),
            ("tidb_opt_projection_push_down", "", "both", None),
            ("tidb_opt_skew_distinct_agg", "", "both", None),
            ("tidb_opt_three_stage_distinct_agg", "", "both", None),
            ("tidb_opt_tiflash_concurrency_factor", "", "both", None),
            ("tidb_optimizer_selectivity_level", "", "both", None),
            ("tidb_pessimistic_txn_fair_locking", "", "both", None),
            ("tidb_placement_mode", "STRICT", "both", None),
            ("tidb_plan_cache_invalidation_on_fresh_stats", "", "both", None),
            ("tidb_prefer_broadcast_join_by_exchange_data_size", 0, "both", None),
            ("tidb_prepared_plan_cache_memory_guard_ratio", 0.0, "both", None),
            ("tidb_read_consistency", "strict", "both", None),
            ("tidb_redact_log", "", "both", None),
            ("tidb_regard_null_as_point", "", "both", None),
            ("tidb_remove_orderby_in_subquery", "", "both", None),
            ("tidb_request_source_type", "", "both", None),
            ("tidb_runtime_filter_mode", "OFF", "both", None),
            ("tidb_runtime_filter_type", "IN", "both", None),
            ("tidb_schema_cache_size", 536870912, "both", None),
            ("tidb_schema_version_cache_limit", 0, "both", None),
            ("tidb_service_scope", "", "both", None),
            ("tidb_session_alias", "", "both", None),
            ("tidb_session_plan_cache_size", 0, "both", None),
            ("tidb_simplified_metrics", "", "both", None),
            ("tidb_skip_ascii_check", False, "both", _bool),
            ("tidb_skip_isolation_level_check", False, "both", _bool),
            ("tidb_skip_missing_partition_stats", False, "both", _bool),
            ("tidb_slow_query_file", "tidb-slow.log", "both", None),
            ("tidb_slow_txn_log_threshold", 0, "both", None),
            ("tidb_source_id", "", "readonly", None),
            ("tidb_stats_load_pseudo_timeout", 0, "both", None),
            ("tidb_stmt_summary_enable_persistent", False, "both", _bool),
            ("tidb_stmt_summary_file_max_backups", 0, "both", None),
            ("tidb_stmt_summary_file_max_days", 0, "both", None),
            ("tidb_stmt_summary_file_max_size", 0, "both", None),
            ("tidb_stmt_summary_filename", "tidb-statements.log", "both", None),
            ("tidb_store_limit", 0, "both", None),
            ("tidb_sysproc_scan_concurrency", 0, "both", None),
            ("tidb_ttl_delete_worker_count", 0, "both", None),
            ("tidb_ttl_job_schedule_window_end_time", "", "both", None),
            ("tidb_ttl_job_schedule_window_start_time", "", "both", None),
            ("tidb_ttl_scan_worker_count", 0, "both", None),
            ("tidb_txn_commit_batch_size", 16384, "both", None),
            ("tidb_txn_entry_size_limit", 0, "both", None),
            ("tidb_wait_split_region_timeout", 0, "both", None),
            ("tiflash_compute_dispatch_policy", "", "both", None),
            ("tiflash_fastscan", False, "both", _bool),
            ("tiflash_fine_grained_shuffle_batch_size", 0, "both", None),
            ("tiflash_fine_grained_shuffle_stream_count", 0, "both", None),
            ("tiflash_mem_quota_query_per_node", "", "both", None),
            ("tiflash_query_spill_ratio", 0.0, "both", None),
            ("tiflash_replica_read", "", "both", None),
            ("tikv_client_read_timeout", 0, "both", None),
            ("tx_isolation_one_shot", "", "both", None),
            ("tx_read_ts", "", "both", None),
            ("txn_scope", "", "both", None),
            ("windowing_use_high_precision", True, "both", _bool),
]

for _n, _d, _sc, _v in _COMPAT_VARS:
    SYSVAR_DEFS.setdefault(_n, SysVarDef(_n, _d, _sc, _v))


class SysVars:
    """Session view over globals; SET GLOBAL updates the shared store."""

    def __init__(self, globals_store: Optional[Dict[str, object]] = None):
        self._globals = globals_store if globals_store is not None else {}
        self._session: Dict[str, object] = {}

    def get(self, name: str):
        name = name.lower()
        if name in self._session:
            return self._session[name]
        if name in self._globals:
            return self._globals[name]
        d = SYSVAR_DEFS.get(name)
        if d is None:
            raise KeyError(f"unknown system variable {name!r}")
        return d.default

    def set(self, name: str, value, scope: str = "session"):
        name = name.lower()
        d = SYSVAR_DEFS.get(name)
        if d is None:
            raise KeyError(f"unknown system variable {name!r}")
        if d.scope == "readonly":
            raise ValueError(f"variable {name} is read-only")
        if d.validate is not None:
            value = d.validate(value)
        # MySQL keeps the legacy alias and the canonical name in sync
        _ALIASES = (
            ("tx_isolation", "transaction_isolation"),
            ("tx_read_only", "transaction_read_only"),
        )
        names = next(
            (pair for pair in _ALIASES if name in pair), (name,)
        )
        if scope == "global":
            if d.scope == "session":
                raise ValueError(f"variable {name} is session-scoped")
            for n in names:
                self._globals[n] = value
        else:
            if d.scope == "global":
                raise ValueError(f"variable {name} is global-scoped; use SET GLOBAL")
            for n in names:
                self._session[n] = value

    def all(self) -> Dict[str, object]:
        out = {}
        for name in sorted(SYSVAR_DEFS):
            out[name] = self.get(name)
        return out
