"""Resource groups: RU-based statement governance.

Reference: TiDB resource control (pkg/domain/resourcegroup,
pkg/executor/internal/calibrateresource) — named groups with an RU/sec
fill rate; every statement consumes Request Units and is throttled when
its group's token bucket runs dry. The single-process analog keeps one
token bucket per group; statements debit RU after execution (1 RU per
millisecond of engine time + 1 RU per KiB of result, a deliberately
simple documented model standing in for the reference's calibrated
CPU/IO cost vectors) and BLOCK before execution while the bucket is
negative (a burstable group never blocks, mirroring BURSTABLE).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from tidb_tpu.utils import racecheck

class ResourceGroup:
    def __init__(self, name: str, ru_per_sec: Optional[int], burstable: bool):
        self.name = name
        self.ru_per_sec = ru_per_sec  # None = unlimited (default group)
        self.burstable = burstable
        self.tokens = float(ru_per_sec or 0)
        self.last_refill = time.monotonic()
        self.consumed_ru = 0.0
        self.queries = 0

    def _refill(self) -> None:
        now = time.monotonic()
        if self.ru_per_sec:
            self.tokens = min(
                float(self.ru_per_sec),  # burst capacity = 1s of fill
                self.tokens + (now - self.last_refill) * self.ru_per_sec,
            )
        self.last_refill = now


class ResourceGroupManager:
    """All groups of one catalog. `default` always exists, unlimited —
    matching the reference's built-in default group."""

    def __init__(self):
        self._lock = racecheck.make_lock("resgroup")
        self.groups: Dict[str, ResourceGroup] = {
            "default": ResourceGroup("default", None, True)
        }

    @staticmethod
    def _check_rate(ru_per_sec):
        # 0 would alias the unlimited sentinel's falsy checks — and a
        # zero fill rate means "never run", which is a DROP, not a group
        if ru_per_sec is not None and ru_per_sec < 1:
            raise ValueError("RU_PER_SEC must be >= 1")

    def create(self, name, ru_per_sec, burstable, if_not_exists=False):
        name = name.lower()
        self._check_rate(ru_per_sec)
        with self._lock:
            if name in self.groups:
                if if_not_exists:
                    return
                raise ValueError(f"resource group {name!r} already exists")
            self.groups[name] = ResourceGroup(name, ru_per_sec, burstable)

    def alter(self, name, ru_per_sec=None, burstable=None):
        self._check_rate(ru_per_sec)
        with self._lock:
            g = self.groups.get(name.lower())
            if g is None:
                raise ValueError(f"unknown resource group {name!r}")
            if ru_per_sec is not None:
                g.ru_per_sec = ru_per_sec
                g.tokens = min(g.tokens, float(ru_per_sec))
            if burstable is not None:
                g.burstable = burstable

    def drop(self, name, if_exists=False):
        name = name.lower()
        if name == "default":
            raise ValueError("cannot drop the default resource group")
        with self._lock:
            if name not in self.groups:
                if if_exists:
                    return
                raise ValueError(f"unknown resource group {name!r}")
            del self.groups[name]

    def get(self, name: str) -> ResourceGroup:
        g = self.groups.get(name.lower())
        if g is None:
            raise ValueError(f"unknown resource group {name!r}")
        return g

    def acquire(self, name: str, kill_check=None, max_wait_s: float = 60.0):
        """Block while the group's bucket is negative (prior statements
        overdrew it). Returns the seconds waited — surfaced in the slow
        log the way the reference reports RU wait time. A group dropped
        while sessions were still bound to it degrades to no-throttle
        (the session can then SET RESOURCE GROUP to rebind) rather than
        wedging every subsequent statement."""
        g = self.groups.get(name.lower())
        if g is None:
            return 0.0
        t0 = time.monotonic()
        while True:
            with self._lock:
                g._refill()
                if g.burstable or not g.ru_per_sec or g.tokens >= 0:
                    return time.monotonic() - t0
            if kill_check is not None:
                kill_check()
            if time.monotonic() - t0 > max_wait_s:
                raise RuntimeError(
                    f"resource group {g.name!r} RU wait exceeded "
                    f"{max_wait_s:.0f}s"
                )
            time.sleep(0.01)

    def debit(
        self, name: str, elapsed_s: float, result_bytes: int = 0,
        count_query: bool = True,
    ):
        """Post-statement RU consumption: the bucket may go negative —
        the NEXT statement in the group then waits it out.
        ``count_query=False`` bills RU without bumping the group's
        query counter — for supplemental charges within one statement
        (the DCN dispatch site's result-bytes debit) that would
        otherwise double-count it."""
        from tidb_tpu.utils.failpoint import inject

        inject("resgroup/debit")
        g = self.groups.get(name.lower())
        if g is None:  # group dropped mid-statement: nothing to bill
            return 0.0
        ru = elapsed_s * 1000.0 + result_bytes / 1024.0
        with self._lock:
            g._refill()
            if g.ru_per_sec:
                g.tokens -= ru
            g.consumed_ru += ru
            if count_query:
                g.queries += 1
        return ru

    def rows(self):
        with self._lock:
            return [
                (
                    g.name,
                    -1 if g.ru_per_sec is None else int(g.ru_per_sec),
                    "YES" if g.burstable else "NO",
                    round(g.consumed_ru, 3),
                    g.queries,
                )
                for g in sorted(self.groups.values(), key=lambda x: x.name)
            ]
