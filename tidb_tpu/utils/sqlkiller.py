"""Query kill switch.

Reference: pkg/util/sqlkiller/sqlkiller.go:41 — an atomic flag the
executor polls at safepoints; KILL QUERY sets it and the running
statement aborts with ErrQueryInterrupted. Here the safepoints are the
host-side control points of the engine (statement start, each capacity-
discovery iteration, result materialization) — device programs
themselves are short-lived single XLA launches.
"""

from __future__ import annotations

import threading
import time


class QueryKilled(RuntimeError):
    pass


class SQLKiller:
    def __init__(self) -> None:
        self._killed = threading.Event()
        # wall-clock deadline for the current statement (runaway-query
        # control, reference max_execution_time +
        # pkg/domain/resourcegroup/runaway.go); None = no limit
        self.deadline: float = 0.0

    def kill(self) -> None:
        """Signal the running statement to abort (thread-safe)."""
        self._killed.set()

    def clear(self, deadline: float = 0.0) -> None:
        self._killed.clear()
        self.deadline = deadline

    def check(self) -> None:
        if self._killed.is_set():
            raise QueryKilled("query interrupted (killed)")
        if self.deadline and time.monotonic() > self.deadline:
            raise QueryKilled(
                "query interrupted (max_execution_time exceeded)"
            )


# The killer of the statement currently executing on THIS thread
# (set by Session._execute_stmt): host-side blocking builtins (SLEEP,
# GET_LOCK waits) poll it so KILL and the instance watchdogs can abort
# them — the reference's sqlkiller is likewise reachable from any
# executor goroutine.
_current = threading.local()


def set_current(killer) -> None:
    _current.killer = killer


def current_check() -> None:
    k = getattr(_current, "killer", None)
    if k is not None:
        k.check()


def interruptible_sleep(seconds: float) -> None:
    """time.sleep in 50ms slices, polling the current killer."""
    deadline = time.monotonic() + seconds
    while True:
        current_check()
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        time.sleep(min(remaining, 0.05))
