"""Privilege store: users, grants, and mysql_native_password auth.

Reference: pkg/privilege/privileges/cache.go (MySQLPrivilege — the
in-memory cache of mysql.user / mysql.db / mysql.tables_priv) and the
auth check at connection time (pkg/server handshake + pkg/parser/auth).
The TPU engine keeps the same three grant scopes — global (*.*),
database (db.*), table (db.t) — in a plain dict on the catalog; the
wire-auth math is the standard mysql_native_password scramble.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Set, Tuple

from tidb_tpu.utils import racecheck
#: grantable privileges (subset of the reference's Priv bitmask,
#: pkg/parser/mysql/privs.go)
PRIVS = {
    "select", "insert", "update", "delete", "create", "drop",
    "index", "alter",
}


def password_hash(password: str) -> bytes:
    """SHA1(SHA1(password)) — what mysql.user stores for
    mysql_native_password (authentication_string)."""
    return hashlib.sha1(hashlib.sha1(password.encode()).digest()).digest()


def check_native_password(
    scramble: bytes, auth_response: bytes, stored: Optional[bytes]
) -> bool:
    """Verify a mysql_native_password handshake response.

    Client sends SHA1(pw) XOR SHA1(scramble + SHA1(SHA1(pw))); the server
    holds H2 = SHA1(SHA1(pw)) and checks SHA1(response XOR SHA1(scramble
    + H2)) == H2."""
    if stored is None:  # empty password account
        return len(auth_response) == 0
    if len(auth_response) != 20:
        return False
    mask = hashlib.sha1(scramble + stored).digest()
    sha1_pw = bytes(a ^ b for a, b in zip(auth_response, mask))
    return hashlib.sha1(sha1_pw).digest() == stored


class UserStore:
    """Users + grants. Thread-safe (the server authenticates concurrent
    connections against it)."""

    def __init__(self):
        self._lock = racecheck.make_lock("privilege")
        # user -> {"password": sha1sha1 bytes | None, "grants":
        #          {(db|'*', table|'*'): set of privs | {'all'}}}
        self.users: Dict[str, Dict] = {
            "root": {"password": None, "grants": {("*", "*"): {"all"}}}
        }

    # -- administration ------------------------------------------------
    def create_user(
        self, name: str, password: str = "", if_not_exists: bool = False
    ) -> None:
        name = name.lower()
        with self._lock:
            if name in self.users:
                if if_not_exists:
                    return
                raise ValueError(f"user {name!r} already exists")
            self.users[name] = {
                "password": password_hash(password) if password else None,
                "grants": {},
            }

    def drop_user(self, name: str, if_exists: bool = False) -> None:
        name = name.lower()
        with self._lock:
            if name not in self.users:
                if if_exists:
                    return
                raise ValueError(f"unknown user {name!r}")
            if name == "root":
                raise ValueError("cannot drop root")
            del self.users[name]

    def grant(
        self, privs: Set[str], db: str, table: str, user: str
    ) -> None:
        user = user.lower()
        bad = {p for p in privs if p not in PRIVS and p != "all"}
        if bad:
            raise ValueError(f"unknown privileges {sorted(bad)}")
        with self._lock:
            if user not in self.users:
                raise ValueError(f"unknown user {user!r}")
            scope = (db.lower(), table.lower())
            g = self.users[user]["grants"].setdefault(scope, set())
            g |= privs

    def revoke(
        self, privs: Set[str], db: str, table: str, user: str
    ) -> None:
        user = user.lower()
        bad = {p for p in privs if p not in PRIVS and p != "all"}
        if bad:
            raise ValueError(f"unknown privileges {sorted(bad)}")
        with self._lock:
            if user not in self.users:
                raise ValueError(f"unknown user {user!r}")
            scope = (db.lower(), table.lower())
            g = self.users[user]["grants"].get(scope)
            if g:
                if "all" in privs:
                    g.clear()
                else:
                    if "all" in g:
                        # expand ALL so revoking one privilege actually
                        # removes it (not a silent no-op)
                        g.discard("all")
                        g |= PRIVS
                    g -= privs

    # -- checks --------------------------------------------------------
    def authenticate(
        self, user: str, scramble: bytes, auth_response: bytes
    ) -> bool:
        with self._lock:
            u = self.users.get(user.lower())
        if u is None:
            return False
        return check_native_password(scramble, auth_response, u["password"])

    def check(self, user: str, priv: str, db: str, table: str = "*") -> bool:
        """Does `user` hold `priv` on db.table (via table, db, or global
        scope)? information_schema is readable by everyone (reference:
        virtual memtables skip privilege checks for basic reads)."""
        if db.lower() == "information_schema" and priv == "select":
            return True
        with self._lock:
            u = self.users.get(user.lower())
            if u is None:
                return False
            for scope in (
                ("*", "*"),
                (db.lower(), "*"),
                (db.lower(), table.lower()),
            ):
                g = u["grants"].get(scope)
                if g and ("all" in g or priv in g):
                    return True
        return False

    def is_super(self, user: str) -> bool:
        with self._lock:
            u = self.users.get(user.lower())
            return bool(u and "all" in u["grants"].get(("*", "*"), set()))

    def show_grants(self, user: str) -> List[str]:
        user = user.lower()
        with self._lock:
            u = self.users.get(user)
            if u is None:
                raise ValueError(f"unknown user {user!r}")
            out = []
            for (db, tbl), privs in sorted(u["grants"].items()):
                if not privs:
                    continue
                pl = (
                    "ALL PRIVILEGES"
                    if "all" in privs
                    else ", ".join(sorted(p.upper() for p in privs))
                )
                out.append(f"GRANT {pl} ON {db}.{tbl} TO '{user}'@'%'")
            if not out:
                out.append(f"GRANT USAGE ON *.* TO '{user}'@'%'")
            return out

    # -- persistence ---------------------------------------------------
    def to_manifest(self) -> Dict:
        with self._lock:
            return {
                name: {
                    "password": (
                        u["password"].hex() if u["password"] else None
                    ),
                    "grants": [
                        [db, tbl, sorted(privs)]
                        for (db, tbl), privs in u["grants"].items()
                    ],
                }
                for name, u in self.users.items()
            }

    @classmethod
    def from_manifest(cls, m: Dict) -> "UserStore":
        st = cls()
        st.users = {}
        for name, u in m.items():
            st.users[name] = {
                "password": bytes.fromhex(u["password"]) if u["password"] else None,
                "grants": {
                    (db, tbl): set(privs) for db, tbl, privs in u["grants"]
                },
            }
        if "root" not in st.users:
            st.users["root"] = {"password": None, "grants": {("*", "*"): {"all"}}}
        return st
