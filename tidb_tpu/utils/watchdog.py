"""Instance-level watchdogs: memory-usage alarm, expensive-query log,
server memory limit.

Reference: pkg/util/memoryusagealarm/memoryusagealarm.go (record alarm
when instance memory passes a ratio of total), pkg/util/expensivequery/
expensivequery.go (log statements running past a threshold), and
pkg/util/servermemorylimit/servermemorylimit.go:51 (kill the top memory
consumer when the instance limit is breached).

One daemon per catalog samples host RSS and walks the session registry
(the same WeakValueDictionary PROCESSLIST uses). The "top consumer" is
the active session with the largest admitted device/host working set
(PhysicalExecutor.last_working_set, the byte total the quota-admission
tracker computes per execution), falling back to the longest-running
statement. Events surface through information_schema.memory_usage /
memory_usage_alarm_records and the metrics registry.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional


def host_memory() -> tuple:
    """(rss bytes, total bytes) from /proc (Linux)."""
    rss = total = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                    break
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    return rss, total


def parse_mem_limit(v, total: int) -> int:
    """tidb_server_memory_limit: '80%' | bytes | '0' (off) -> bytes."""
    s = str(v).strip()
    if not s or s == "0":
        return 0
    if s.endswith("%"):
        try:
            return int(total * float(s[:-1]) / 100.0)
        except ValueError:
            return 0
    try:
        return int(float(s))
    except ValueError:
        return 0


def gvar(catalog, name, default):
    """A GLOBAL sysvar as the watchdog sees it: explicit SET GLOBAL
    value, else the registered SysVarDef default (so e.g. the
    reference's tidb_server_memory_limit='80%' default is ENFORCED,
    not just displayed), else `default`."""
    v = catalog.global_sysvars.get(name)
    if v is not None:
        return v
    from tidb_tpu.utils.sysvar import SYSVAR_DEFS

    d = SYSVAR_DEFS.get(name)
    return d.default if d is not None else default


class InstanceWatchdog(threading.Thread):
    """Daemon sampler over one catalog's sessions."""

    def __init__(self, catalog, interval: float = 2.0):
        super().__init__(daemon=True, name="watchdog-instance")
        self.catalog = catalog
        self.interval = interval
        self.stop_flag = threading.Event()
        self.alarm_records: List[dict] = []
        self.kill_records: List[dict] = []
        self.expensive_seen: set = set()
        self.last_rss = 0
        self.samples = 0

    def _gvar(self, name, default):
        return gvar(self.catalog, name, default)

    def run(self) -> None:  # pragma: no cover - loop plumbing
        from tidb_tpu.utils.failpoint import FailpointError

        while not self.stop_flag.wait(self.interval):
            try:
                self.sample()
            except FailpointError:
                raise  # injected faults must be observable in tests
            except Exception:
                pass  # the watchdog must never take the engine down

    def sessions(self):
        reg = getattr(self.catalog, "_session_registry", None) or {}
        return [s for s in list(reg.values()) if s is not None]

    def sample(self) -> None:
        from tidb_tpu.utils.failpoint import inject
        from tidb_tpu.utils.metrics import REGISTRY

        inject("watchdog/sample")

        self.samples += 1
        now = time.time()
        rss, total = host_memory()
        self.last_rss = rss

        # ---- expensive-query log (expensivequery.go) ------------------
        thr = float(self._gvar("tidb_expensive_query_time_threshold", 60))
        for s in self.sessions():
            cur = s._current_stmt
            if cur is None:
                continue
            elapsed = now - cur[1]
            key = (s.conn_id, cur[1])
            if elapsed >= thr and key not in self.expensive_seen:
                self.expensive_seen.add(key)
                REGISTRY.counter(
                    "tidbtpu_watchdog_expensive_queries_total",
                    "statements running past the expensive threshold",
                ).inc()
                # the expensive-query entry rides the slow log, so it
                # honors the slow_query_log on/off switch like the
                # session call site. Its admission bar is its OWN
                # sysvar (tidb_expensive_query_time_threshold, checked
                # above) — the statement is still RUNNING here, so
                # comparing the in-flight elapsed against
                # tidb_slow_log_threshold would suppress entries whose
                # final elapsed crosses it moments later
                if bool(self._gvar("slow_query_log", True)):
                    from tidb_tpu.utils.metrics import SLOW_LOG

                    SLOW_LOG.record(
                        f"[expensive_query] conn={s.conn_id} "
                        f"elapsed={elapsed:.1f}s sql={str(cur[0])[:200]}",
                        elapsed,
                        conn_id=s.conn_id,
                    )
        if len(self.expensive_seen) > 4096:
            self.expensive_seen.clear()

        # ---- memory usage alarm (memoryusagealarm.go) -----------------
        ratio = float(self._gvar("tidb_memory_usage_alarm_ratio", 0.7))
        if total and rss > ratio * total:
            keep = int(self._gvar(
                "tidb_memory_usage_alarm_keep_record_num", 5
            ))
            self.alarm_records.append(
                {"time": now, "rss": rss, "total": total, "ratio": ratio}
            )
            del self.alarm_records[:-max(keep, 1)]
            REGISTRY.counter(
                "tidbtpu_watchdog_memory_usage_alarms_total",
                "instance memory passed the alarm ratio",
            ).inc()

        # ---- server memory limit (servermemorylimit.go:51) ------------
        limit = parse_mem_limit(
            self._gvar("tidb_server_memory_limit", "0"), total
        )
        if limit and rss > limit:
            victim = self.top_consumer()
            if victim is not None:
                victim.killer.kill()
                self.kill_records.append(
                    {
                        "time": now,
                        "conn_id": victim.conn_id,
                        "sql": str(victim._current_stmt[0])[:200]
                        if victim._current_stmt
                        else "",
                        "rss": rss,
                        "limit": limit,
                        "working_set": getattr(
                            victim.executor, "last_working_set", 0
                        ),
                    }
                )
                del self.kill_records[:-64]
                REGISTRY.counter(
                    "tidbtpu_watchdog_server_memory_limit_kills_total",
                    "statements killed at the instance memory limit",
                ).inc()

    def top_consumer(self) -> Optional[object]:
        """The active session with the largest admitted working set
        (falls back to the longest-running statement)."""
        best, best_key = None, (-1, -1.0)
        now = time.time()
        for s in self.sessions():
            cur = s._current_stmt
            if cur is None:
                continue
            ws = int(getattr(s.executor, "last_working_set", 0) or 0)
            key = (ws, now - cur[1])
            if key > best_key:
                best, best_key = s, key
        return best


def ensure_watchdog(catalog, interval: float = 2.0) -> InstanceWatchdog:
    """One watchdog per base catalog, started lazily (the TTL/auto-
    analyze daemon pattern)."""
    base = getattr(catalog, "_base", catalog)
    wd = getattr(base, "_watchdog", None)
    if wd is None or not wd.is_alive():
        wd = base._watchdog = InstanceWatchdog(base, interval=interval)
        wd.start()
    return wd
