"""Metrics, slow-query log, and statement summary.

Reference: pkg/metrics (Prometheus collectors per subsystem, registered
at cmd/tidb-server/main.go:282), pkg/executor/slow_query.go (the slow
log read back as INFORMATION_SCHEMA.SLOW_QUERY), and
pkg/util/stmtsummary/statement_summary.go:73 (per-digest aggregated
statement stats). Single-process rendering: a plain in-memory registry
with Prometheus text exposition, a ring-buffer slow log, and a
digest-keyed summary map — all queryable through information_schema
virtual tables so the SQL surface matches the reference's.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from typing import Dict, List, Optional, Tuple


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket latency histogram (seconds)."""

    BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0)

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.BUCKETS):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name, help_)
            return m

    def histogram(self, name: str, help_: str = "") -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(name, help_)
            return m

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out.append(f"# TYPE {name} counter")
                out.append(f"{name} {m.value:g}")
            else:
                out.append(f"# TYPE {name} histogram")
                acc = 0
                for b, c in zip(m.BUCKETS, m.counts):
                    acc += c
                    out.append(f'{name}_bucket{{le="{b:g}"}} {acc}')
                out.append(f'{name}_bucket{{le="+Inf"}} {m.total}')
                out.append(f"{name}_sum {m.sum:g}")
                out.append(f"{name}_count {m.total}")
        return "\n".join(out) + "\n"

    def rows(self) -> List[Tuple[str, str, float]]:
        with self._lock:
            items = sorted(self._metrics.items())
        out = []
        for name, m in items:
            if isinstance(m, Counter):
                out.append((name, "counter", float(m.value)))
            else:
                out.append((name + "_count", "histogram", float(m.total)))
                out.append((name + "_sum", "histogram", float(m.sum)))
        return out


REGISTRY = Registry()


def sql_digest(sql: str) -> str:
    """Normalize a statement for summary grouping: literals -> '?',
    whitespace collapsed, lowercased keywords (reference: parser
    digester.go)."""
    try:
        from tidb_tpu.parser.sqlparse import tokenize

        parts = []
        for t in tokenize(sql):
            if t.kind in ("num", "str"):
                parts.append("?")
            elif t.kind == "hint" or (t.kind == "op" and t.text == ";"):
                # hints and statement separators are not semantic: the
                # hinted and unhinted forms of a query share one digest
                # (reference digester strips hints)
                continue
            elif t.kind == "eof":
                break
            else:
                parts.append(t.text.lower() if t.kind == "kw" else t.text)
        return " ".join(parts)
    except Exception:
        return re.sub(r"\s+", " ", sql.strip())[:512]


class SlowLog:
    """Ring buffer of statements slower than the threshold (reference:
    slow-query log + INFORMATION_SCHEMA.SLOW_QUERY round trip)."""

    def __init__(self, capacity: int = 256):
        self._buf = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, sql: str, seconds: float) -> None:
        with self._lock:
            self._buf.append((time.time(), sql[:2048], seconds))

    def rows(self) -> List[Tuple[float, str, float]]:
        with self._lock:
            return list(self._buf)


class StmtSummary:
    """Per-digest aggregated statement stats (reference:
    statement_summary.go:73)."""

    def __init__(self, capacity: int = 512):
        self._capacity = capacity
        self._map: Dict[str, list] = {}
        self._lock = threading.Lock()

    def record(self, sql: str, seconds: float) -> None:
        d = sql_digest(sql)
        with self._lock:
            ent = self._map.get(d)
            if ent is None:
                if len(self._map) >= self._capacity:
                    # evict the least-executed digest
                    victim = min(self._map, key=lambda k: self._map[k][0])
                    del self._map[victim]
                ent = self._map[d] = [0, 0.0, 0.0, sql[:256]]
            ent[0] += 1
            ent[1] += seconds
            ent[2] = max(ent[2], seconds)

    def rows(self) -> List[Tuple[str, int, float, float, str]]:
        with self._lock:
            return [
                (d, n, s, mx, sample)
                for d, (n, s, mx, sample) in sorted(self._map.items())
            ]

    def reset(self) -> None:
        """Clear all digests (the statements_summary clear analog,
        reference: stmtsummary Clear)."""
        with self._lock:
            self._map.clear()


SLOW_LOG = SlowLog()
STMT_SUMMARY = StmtSummary()
