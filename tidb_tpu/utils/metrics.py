"""Metrics, slow-query log, and statement summary.

Reference: pkg/metrics (Prometheus collectors per subsystem, registered
at cmd/tidb-server/main.go:282), pkg/executor/slow_query.go (the slow
log read back as INFORMATION_SCHEMA.SLOW_QUERY), and
pkg/util/stmtsummary/statement_summary.go:73 (per-digest aggregated
statement stats). Single-process rendering: a plain in-memory registry
with Prometheus text exposition, a ring-buffer slow log, and a
digest-keyed summary map — all queryable through information_schema
virtual tables so the SQL surface matches the reference's.

Metric naming convention (enforced by scripts/check_metric_names.py):
``tidbtpu_<subsystem>_<name>`` — e.g. tidbtpu_engine_jit_compilations,
tidbtpu_dcn_dispatches, tidbtpu_session_statements_total. Counters,
gauges (set/inc/dec) and fixed-bucket histograms, all optionally
labeled: ``REGISTRY.counter("tidbtpu_dcn_dispatches", "…",
labels=("host",)).labels(host=addr).inc()``.
"""

from __future__ import annotations

import collections
import re
import time
from typing import Dict, List, Optional, Tuple

from tidb_tpu.utils import racecheck


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label value escaping (backslash, quote,
    newline — exposition format spec)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_block(labelnames, labelvalues) -> str:
    """'{k="v",…}' or '' for the unlabeled case."""
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"'
        for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


class Counter:
    kind = "counter"

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = racecheck.make_lock("metrics.metric")

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """A value that can go up and down (reference: prometheus Gauge —
    connection counts, quarantined hosts, memory high-water)."""

    kind = "gauge"

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._lock = racecheck.make_lock("metrics.metric")

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def set_max(self, v: float) -> None:
        """High-water helper: keep the maximum of the current value and v."""
        with self._lock:
            if v > self.value:
                self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """Fixed-bucket latency histogram (seconds)."""

    kind = "histogram"

    BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0)

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = racecheck.make_lock("metrics.metric")

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.BUCKETS):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1


class StreamingHistogram(Histogram):
    """A Histogram that additionally answers quantile queries — the
    statements_summary p50/p95/p99 estimator (reference: stmtsummary
    keeps a percentile sketch per digest; Prometheus histogram_quantile
    does the same interpolation server-side). Same fixed buckets as the
    exposition Histogram so one latency vocabulary serves both
    surfaces. O(1) observe, O(buckets) quantile; estimates are
    monotone in q (p99 >= p95 >= p50 by construction)."""

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1]: linear
        interpolation inside the covering bucket (lower bound = the
        previous bucket's upper edge, 0 for the first). The overflow
        bucket has no upper edge; it answers with max(sum/total, last
        edge) — bounded, and exact for the single-observation case."""
        q = min(max(float(q), 0.0), 1.0)
        with self._lock:
            total = self.total
            if total == 0:
                return 0.0
            rank = q * total
            acc = 0
            lo = 0.0
            for edge, c in zip(self.BUCKETS, self.counts):
                if acc + c >= rank and c > 0:
                    frac = (rank - acc) / c
                    return lo + (edge - lo) * min(max(frac, 0.0), 1.0)
                acc += c
                lo = edge
            # overflow bucket: the mean is the best bounded point
            # estimate available without per-sample storage
            return max(self.sum / total, float(self.BUCKETS[-1]))


class MetricFamily:
    """A labeled metric: one (name, labelnames) family whose children
    are plain Counter/Gauge/Histogram instances keyed by label values
    (reference: prometheus client_golang *Vec collectors)."""

    def __init__(self, cls, name: str, help_: str, labelnames: Tuple[str, ...]):
        self.cls = cls
        self.kind = cls.kind
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = racecheck.make_lock("metrics.family")

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            extra = set(kv) - set(self.labelnames)
            if extra:
                raise ValueError(
                    f"{self.name}: unknown label(s) {sorted(extra)} "
                    f"(labelnames={self.labelnames})"
                )
            try:
                values = tuple(kv[k] for k in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e} "
                    f"(labelnames={self.labelnames})"
                ) from None
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"values, got {len(values)}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self.cls(self.name, self.help)
            return child

    def remove_matching(self, predicate) -> int:
        """Drop children whose labelvalues tuple satisfies
        ``predicate``; returns how many were removed. The eviction
        half of cap-bounded label cardinality (Top SQL folds an
        evicted digest's per-digest children out, obs/profiler.py) —
        safe for the worker counter-delta shipping because
        counter_delta carries the post-removal snapshot forward: a
        removed child simply stops shipping, and a re-created one
        counts from zero with no negative delta."""
        with self._lock:
            gone = [
                k for k in self._children if predicate(k)
            ]
            for k in gone:
                del self._children[k]
        return len(gone)

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


def _fmt_value(v: float) -> str:
    """Full-precision sample rendering: %g truncates to 6 significant
    digits, which makes byte-scale counters (h2d/d2h bytes) step in
    ~1e5 increments once they pass 1e10 — rate() over scrapes then
    reads zero between jumps. Integral values render as integers, the
    rest via repr (shortest round-trip float), like the official
    Prometheus clients."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _render_one(out: List[str], name: str, m, labelnames=(), labelvalues=()):
    lb = _label_block(labelnames, labelvalues)
    if isinstance(m, (Counter, Gauge)):
        out.append(f"{name}{lb} {_fmt_value(m.value)}")
    else:  # Histogram: cumulative le buckets per the exposition format
        acc = 0
        for b, c in zip(m.BUCKETS, m.counts):
            acc += c
            blb = _label_block(
                tuple(labelnames) + ("le",), tuple(labelvalues) + (f"{b:g}",)
            )
            out.append(f"{name}_bucket{blb} {acc}")
        blb = _label_block(
            tuple(labelnames) + ("le",), tuple(labelvalues) + ("+Inf",)
        )
        out.append(f"{name}_bucket{blb} {m.total}")
        out.append(f"{name}_sum{lb} {_fmt_value(m.sum)}")
        out.append(f"{name}_count{lb} {m.total}")


class Registry:
    def __init__(self):
        self._lock = racecheck.make_lock("metrics.registry")
        self._metrics: Dict[str, object] = {}

    def _get(self, cls, name: str, help_: str, labels):
        labels = tuple(labels or ())
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                if labels:
                    m = MetricFamily(cls, name, help_, labels)
                else:
                    m = cls(name, help_)
                self._metrics[name] = m
                return m
        # consistency: a name is one kind + one label set, forever
        existing_kind = getattr(m, "kind", None)
        if existing_kind != cls.kind:
            raise ValueError(
                f"metric {name} already registered as {existing_kind}"
            )
        if isinstance(m, MetricFamily) != bool(labels) or (
            isinstance(m, MetricFamily) and m.labelnames != labels
        ):
            raise ValueError(
                f"metric {name} already registered with different labels"
            )
        return m

    def counter(self, name: str, help_: str = "", labels=()) -> Counter:
        return self._get(Counter, name, help_, labels)

    def gauge(self, name: str, help_: str = "", labels=()) -> Gauge:
        return self._get(Gauge, name, help_, labels)

    def histogram(self, name: str, help_: str = "", labels=()) -> Histogram:
        return self._get(Histogram, name, help_, labels)

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: List[str] = []
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            out.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, MetricFamily):
                for values, child in m.children():
                    _render_one(out, name, child, m.labelnames, values)
            else:
                _render_one(out, name, m)
        return "\n".join(out) + "\n"

    def rows(self) -> List[Tuple[str, str, float]]:
        """(name, kind, value) triplets for the information_schema
        METRICS virtual table; labeled children carry their label block
        in the name column."""
        with self._lock:
            items = sorted(self._metrics.items())
        out: List[Tuple[str, str, float]] = []
        for name, m in items:
            if isinstance(m, MetricFamily):
                for values, child in m.children():
                    lb = _label_block(m.labelnames, values)
                    if isinstance(child, Histogram):
                        out.append((name + "_count" + lb, "histogram",
                                    float(child.total)))
                        out.append((name + "_sum" + lb, "histogram",
                                    float(child.sum)))
                    else:
                        out.append((name + lb, child.kind, float(child.value)))
            elif isinstance(m, Histogram):
                out.append((name + "_count", "histogram", float(m.total)))
                out.append((name + "_sum", "histogram", float(m.sum)))
            else:
                out.append((name, m.kind, float(m.value)))
        return out


REGISTRY = Registry()


# -- fleet merge (worker -> coordinator registry shipping) ------------------
#
# Worker processes export their own /metrics, which leaves the
# coordinator's registry blind to fleet-wide engine activity (ROADMAP
# PR 2 open item b). Counter deltas piggyback on fragment/shuffle
# replies over the engine-RPC seam and merge here: the worker snapshots
# its counters per reply and ships only the positive deltas. Delivery
# is AT-MOST-ONCE: the ledger fence guarantees a delta never merges
# twice, but a reply lost in transit (or fenced as a late duplicate
# after re-dispatch) drops its delta — the worker advanced its
# snapshot when it built the reply. Fleet counters may therefore
# UNDER-count around worker deaths/retries; they never over-count.


def sample_rows(registry: Registry = REGISTRY) -> List[tuple]:
    """One full-registry sample for the time-series store (obs/tsdb.py):
    ``(name, labelnames, labelvalues, value, kind)`` per counter/gauge
    child. Histograms sample as TWO cumulative series distinguished by
    an appended ``stat`` label (``count`` and ``sum``) — the same
    decomposition Prometheus scrapes, so rate/mean math over the stored
    history works without per-bucket storage."""
    with registry._lock:
        items = sorted(registry._metrics.items())
    out: List[tuple] = []

    def one(name, lnames, lvalues, m):
        if isinstance(m, Histogram):
            hl = tuple(lnames) + ("stat",)
            out.append(
                (name, hl, tuple(lvalues) + ("count",),
                 float(m.total), "histogram")
            )
            out.append(
                (name, hl, tuple(lvalues) + ("sum",),
                 float(m.sum), "histogram")
            )
        else:
            out.append(
                (name, tuple(lnames), tuple(lvalues),
                 float(m.value), m.kind)
            )

    for name, m in items:
        if isinstance(m, MetricFamily):
            for values, child in m.children():
                one(name, m.labelnames, values, child)
        else:
            one(name, (), (), m)
    return out


def counter_snapshot(registry: Registry = REGISTRY) -> Dict[tuple, float]:
    """(name, labelnames, labelvalues) -> value for every counter."""
    with registry._lock:
        items = list(registry._metrics.items())
    out: Dict[tuple, float] = {}
    for name, m in items:
        if isinstance(m, MetricFamily):
            if m.kind != "counter":
                continue
            for values, child in m.children():
                out[(name, m.labelnames, values)] = float(child.value)
        elif isinstance(m, Counter):
            out[(name, (), ())] = float(m.value)
    return out


def counter_delta(
    prev: Dict[tuple, float], registry: Registry = REGISTRY
) -> Tuple[List[list], Dict[tuple, float]]:
    """Positive counter movement since `prev` as JSON-stable rows
    [[name, [labelnames], [labelvalues], delta], ...] plus the new
    snapshot to carry forward."""
    cur = counter_snapshot(registry)
    delta = [
        [name, list(lnames), list(lvalues), v - prev.get(key, 0.0)]
        for key, v in cur.items()
        for name, lnames, lvalues in (key,)
        if v - prev.get(key, 0.0) > 0
    ]
    return delta, cur


def merge_counter_delta(delta, registry: Registry = REGISTRY) -> None:
    """Fold a shipped counter delta into this process's registry. Only
    tidbtpu_* names are accepted; a name already registered with a
    different kind/label set is skipped rather than poisoning the
    registry (the worker may run newer code than the coordinator)."""
    for row in delta or ():
        try:
            name, lnames, lvalues, d = row
        except Exception:
            continue
        if not isinstance(name, str) or not name.startswith("tidbtpu_"):
            continue
        try:
            c = registry.counter(
                name, "merged from worker replies", labels=tuple(lnames)
            )
            (c.labels(*lvalues) if lnames else c).inc(float(d))
        except ValueError:
            continue


def _collapse_in_lists(parts: List[str]) -> List[str]:
    """Collapse ``in ( ? , ? , ? )`` to ``in ( ... )`` so a statement's
    digest does not fragment per IN-list literal count (reference:
    digester.go reduces value lists to one `...` element — without
    this, `a IN (1,2)` and `a IN (1,2,3)` land in different
    statements_summary rows and the summary store fills with
    cardinality noise)."""
    out: List[str] = []
    i = 0
    n = len(parts)
    while i < n:
        if (
            parts[i] == "in"
            and i + 2 < n
            and parts[i + 1] == "("
            and parts[i + 2] == "?"
        ):
            # only a pure placeholder list collapses; `in (select …)`
            # and mixed-expression lists keep their structure
            j = i + 3
            while j + 1 < n and parts[j] == "," and parts[j + 1] == "?":
                j += 2
            if j < n and parts[j] == ")":
                out.extend(("in", "(", "...", ")"))
                i = j + 1
                continue
        out.append(parts[i])
        i += 1
    return out


def sql_digest(sql: str) -> str:
    """Normalize a statement for summary grouping: literals -> '?',
    IN-lists of literals -> '(...)', whitespace collapsed, lowercased
    keywords (reference: parser digester.go)."""
    try:
        from tidb_tpu.parser.sqlparse import tokenize

        parts = []
        for t in tokenize(sql):
            if t.kind in ("num", "str"):
                parts.append("?")
            elif t.kind == "hint" or (t.kind == "op" and t.text == ";"):
                # hints and statement separators are not semantic: the
                # hinted and unhinted forms of a query share one digest
                # (reference digester strips hints)
                continue
            elif t.kind == "eof":
                break
            else:
                parts.append(t.text.lower() if t.kind == "kw" else t.text)
        return " ".join(_collapse_in_lists(parts))
    except Exception:
        return re.sub(r"\s+", " ", sql.strip())[:512]


class SlowLog:
    """Ring buffer of statements slower than the threshold (reference:
    slow-query log + INFORMATION_SCHEMA.SLOW_QUERY round trip). Each
    entry may carry the query's flight-recorder phase timeline and the
    captured plan text (PR 6); legacy 3-field callers keep working —
    the extras default empty."""

    def __init__(self, capacity: int = 256):
        self._buf = collections.deque(maxlen=capacity)
        self._lock = racecheck.make_lock("metrics.slowlog")
        self._file_lock = racecheck.make_lock("metrics.slowlog_file")

    def record(
        self,
        sql: str,
        seconds: float,
        digest: str = "",
        conn_id: int = 0,
        phases: str = "",
        plan: str = "",
        log_file: Optional[str] = None,
    ) -> None:
        ts = time.time()
        with self._lock:
            self._buf.append(
                (ts, sql[:2048], seconds, digest[:512], int(conn_id),
                 phases[:4096], plan[:16384])
            )
        if log_file:
            self._append_file(log_file, ts, sql, seconds, phases, plan)

    def _append_file(self, path, ts, sql, seconds, phases, plan) -> None:
        """The tidb_slow_query_file sink: reference slow-log entry
        shape (`# Time` / `# Query_time` headers, `# Plan` block, the
        statement terminated by `;`). Write failures are swallowed —
        the log file must never fail the statement."""
        lines = [
            f"# Time: {time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(ts))}Z",
            f"# Query_time: {seconds:.6f}",
        ]
        if phases:
            lines.append(f"# Phases: {phases}")
        if plan:
            lines.extend("# Plan: " + ln for ln in plan.splitlines())
        lines.append(sql.rstrip(";") + ";")
        try:
            with self._file_lock, open(path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            pass

    def rows(self) -> List[tuple]:
        """(time, query, query_time, digest, conn_id, phases, plan),
        oldest first. The first three fields are the pre-PR 6 contract
        (existing consumers index positionally)."""
        with self._lock:
            return list(self._buf)


class _StmtEntry:
    """One digest's aggregates: the legacy count/sum/max/sample plus
    the PR 6 flight-derived columns (latency percentiles via a
    streaming histogram, per-phase sums, plan-cache and engine-watch
    attribution)."""

    __slots__ = (
        "n", "sum_s", "max_s", "sample", "hist", "phases", "rows_sent",
        "plan_digest", "plan_cache_hits", "plan_cache_misses",
        "jit_compilations", "retraces", "h2d_bytes", "d2h_bytes",
        "device_mem_peak_bytes", "compile_flops",
        "compile_bytes_accessed", "compile_output_bytes",
        "card_n", "card_est_sum", "card_act_sum", "card_div_sum",
    )

    def __init__(self, sample: str):
        self.n = 0
        self.sum_s = 0.0
        self.max_s = 0.0
        self.sample = sample
        self.hist = StreamingHistogram("stmt_latency")
        #: phase name -> [sum seconds, bytes, retries]
        self.phases: Dict[str, list] = {}
        self.rows_sent = 0
        self.plan_digest = ""
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.jit_compilations = 0
        self.retraces = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.device_mem_peak_bytes = 0
        # per-digest XLA compile cost analysis (obs/engine_watch.py):
        # flops / bytes accessed / output bytes summed over the
        # digest's compiles — which statement shapes are compile-heavy
        self.compile_flops = 0.0
        self.compile_bytes_accessed = 0.0
        self.compile_output_bytes = 0.0
        # AQE cardinality accuracy (PR 15): planner-estimated vs
        # observed output rows of routed statements — the feedback
        # loop's own accuracy, queryable per digest
        self.card_n = 0
        self.card_est_sum = 0.0
        self.card_act_sum = 0.0
        self.card_div_sum = 0.0

    def absorb_flight(self, flight) -> None:
        """Fold one finished QueryFlight (obs/flight.py) in."""
        for name, (s, b, r) in flight.phases.items():
            row = self.phases.setdefault(name, [0.0, 0, 0])
            row[0] += s
            row[1] += b
            row[2] += r
        self.rows_sent += int(flight.rows_sent)
        if getattr(flight, "plan_digest", ""):
            self.plan_digest = flight.plan_digest
        if flight.plan_cache == "hit":
            self.plan_cache_hits += 1
        elif flight.plan_cache == "miss":
            self.plan_cache_misses += 1
        self.jit_compilations += int(flight.jit_compilations)
        self.retraces += int(flight.retraces)
        self.h2d_bytes += int(flight.h2d_bytes)
        self.d2h_bytes += int(flight.d2h_bytes)
        self.device_mem_peak_bytes = max(
            self.device_mem_peak_bytes, int(flight.device_mem_peak_bytes)
        )
        self.compile_flops += float(
            getattr(flight, "compile_flops", 0.0)
        )
        self.compile_bytes_accessed += float(
            getattr(flight, "compile_bytes_accessed", 0.0)
        )
        self.compile_output_bytes += float(
            getattr(flight, "compile_output_bytes", 0.0)
        )
        est = float(getattr(flight, "est_rows", 0.0) or 0.0)
        act = float(getattr(flight, "act_rows", 0.0) or 0.0)
        if est > 0 or act > 0:
            self.card_n += 1
            self.card_est_sum += est
            self.card_act_sum += act
            # symmetric divergence >= 1.0 (1.0 = perfect estimate):
            # over- and under-estimates both count
            r = max(act, 1.0) / max(est, 1.0)
            self.card_div_sum += max(r, 1.0 / r)


def _entry_dict(digest: str, e: "_StmtEntry") -> dict:
    """One digest's full statements_summary row as a plain dict —
    shared by rows_full() and the eviction snapshot the history store
    keeps (an evicted digest's aggregates must survive into
    statements_summary_history or the AQE feedback loop loses exactly
    the digests that churned out of the live map)."""
    return {
        "digest_text": digest,
        "exec_count": e.n,
        "sum_latency": e.sum_s,
        "max_latency": e.max_s,
        "p50_latency": e.hist.quantile(0.50),
        "p95_latency": e.hist.quantile(0.95),
        "p99_latency": e.hist.quantile(0.99),
        "plan_digest": e.plan_digest,
        "phases": {p: list(v) for p, v in e.phases.items()},
        "rows_sent": e.rows_sent,
        "plan_cache_hits": e.plan_cache_hits,
        "plan_cache_misses": e.plan_cache_misses,
        "jit_compilations": e.jit_compilations,
        "retraces": e.retraces,
        "h2d_bytes": e.h2d_bytes,
        "d2h_bytes": e.d2h_bytes,
        "device_mem_peak_bytes": e.device_mem_peak_bytes,
        "compile_flops": e.compile_flops,
        "compile_bytes_accessed": e.compile_bytes_accessed,
        "compile_output_bytes": e.compile_output_bytes,
        # AQE cardinality accuracy: mean estimated vs observed output
        # rows and the mean symmetric divergence ratio (>= 1.0; 1.0 =
        # perfect) over this digest's routed executions
        "est_rows": (
            e.card_est_sum / e.card_n if e.card_n else 0.0
        ),
        "act_rows": (
            e.card_act_sum / e.card_n if e.card_n else 0.0
        ),
        "card_divergence": (
            e.card_div_sum / e.card_n if e.card_n else 0.0
        ),
        "sample_text": e.sample,
    }


class StmtSummary:
    """Per-digest aggregated statement stats (reference:
    statement_summary.go:73). ``record`` optionally takes the finished
    flight record; without one, only the legacy latency aggregates
    move (worker-internal sessions, tests)."""

    def __init__(self, capacity: int = 512):
        self._capacity = capacity
        self._map: Dict[str, _StmtEntry] = {}
        self._lock = racecheck.make_lock("metrics.stmt_summary")
        #: optional StmtHistory absorbing evicted digests (wired to the
        #: module global below; separable for tests)
        self.history: Optional["StmtHistory"] = None

    def record(
        self, sql: str, seconds: float, flight=None,
        digest: Optional[str] = None,
    ) -> None:
        # callers that already digested the text pass it in (the slow
        # log shares one digest with the summary per statement)
        d = digest if digest is not None else sql_digest(sql)
        evicted = None
        with self._lock:
            ent = self._map.get(d)
            if ent is None:
                if len(self._map) >= self._capacity:
                    # evict the least-executed digest
                    victim = min(self._map, key=lambda k: self._map[k].n)
                    # snapshot the victim BEFORE it is forgotten; the
                    # history append runs after this lock releases
                    # (stmt_summary -> stmt_history is the declared
                    # order — rotate() reads the summary lock first)
                    evicted = _entry_dict(victim, self._map[victim])
                    del self._map[victim]
                ent = self._map[d] = _StmtEntry(sql[:256])
            ent.n += 1
            ent.sum_s += seconds
            ent.max_s = max(ent.max_s, seconds)
            ent.hist.observe(seconds)
            if flight is not None:
                ent.absorb_flight(flight)
        if evicted is not None and self.history is not None:
            self.history.absorb_evicted(evicted)

    def rows(self) -> List[Tuple[str, int, float, float, str]]:
        """The pre-PR 6 contract: (digest, count, sum, max, sample) —
        kept for positional consumers (top_sql ranking, digest
        decode). The full surface is rows_full()."""
        with self._lock:
            return [
                (d, e.n, e.sum_s, e.max_s, e.sample)
                for d, e in sorted(self._map.items())
            ]

    def rows_full(self) -> List[dict]:
        """Extended per-digest dicts for information_schema.
        statements_summary and the bench --flight-out snapshot:
        percentiles, mean per-phase seconds, plan-cache and engine
        columns."""
        with self._lock:
            return [
                _entry_dict(d, e) for d, e in sorted(self._map.items())
            ]

    def reset(self) -> None:
        """Clear all digests (the statements_summary clear analog,
        reference: stmtsummary Clear)."""
        with self._lock:
            self._map.clear()


class StmtHistory:
    """Windowed statements_summary snapshots (reference:
    stmtsummary's history ring — tidb_stmt_summary_refresh_interval
    rotates the live map into a bounded window list read back as
    information_schema.statements_summary_history). This is the AQE
    prerequisite: per-digest runtime TRAJECTORIES, not just the
    current aggregate, survive here — including digests the live
    summary evicted (absorb_evicted folds the victim's final
    aggregates into the window that closes next).

    Rotation is driven by the tsdb sampler tick (obs/tsdb.py) and by
    explicit rotate() calls; ``refresh_interval_s`` and the window
    capacity are live-retuned by the session's SET hooks for the
    tidb_stmt_summary_refresh_interval / _history_size sysvars."""

    def __init__(self, max_windows: int = 24,
                 refresh_interval_s: float = 1800.0):
        self._lock = racecheck.make_lock("metrics.stmt_history")
        #: closed windows, oldest first: (begin_ts, end_ts, [row dicts])
        self._windows: "collections.deque" = collections.deque(
            maxlen=max(int(max_windows), 1)
        )
        #: digests evicted from the live summary since the last rotate
        self._pending_evicted: List[dict] = []
        self._open_t0 = time.time()
        self.refresh_interval_s = float(refresh_interval_s)

    def set_capacity(self, n: int) -> None:
        with self._lock:
            self._windows = collections.deque(
                self._windows, maxlen=max(int(n), 1)
            )

    def absorb_evicted(self, row: dict) -> None:
        """A digest the live summary just evicted: its final
        aggregates land in the window that closes next (bounded — a
        capacity-thrashing workload must not grow this without limit;
        beyond the cap the oldest pending eviction drops)."""
        with self._lock:
            self._pending_evicted.append(dict(row))
            if len(self._pending_evicted) > 4096:
                self._pending_evicted.pop(0)

    def rotate(self, summary: "StmtSummary", now: Optional[float] = None
               ) -> None:
        """Close the open window: snapshot every live digest plus the
        pending evictions. The summary is read BEFORE this store's
        lock is taken — stmt_summary and stmt_history never nest."""
        rows = summary.rows_full()
        now = time.time() if now is None else float(now)
        with self._lock:
            rows = rows + self._pending_evicted
            self._pending_evicted = []
            self._windows.append((self._open_t0, now, rows))
            self._open_t0 = now

    def maybe_rotate(self, summary: "StmtSummary",
                     now: Optional[float] = None) -> bool:
        """rotate() iff the refresh interval elapsed. The due-check
        and the window append share one critical section (with the
        summary snapshot speculatively pre-read outside it, keeping
        the no-nesting lock contract): two statement-close ticks
        racing past the interval must not both append — the loser's
        window would span ~0s and duplicate every digest."""
        now = time.time() if now is None else float(now)
        with self._lock:
            if now - self._open_t0 < self.refresh_interval_s:
                return False
        rows = summary.rows_full()
        with self._lock:
            if now - self._open_t0 < self.refresh_interval_s:
                return False  # another tick rotated meanwhile
            rows = rows + self._pending_evicted
            self._pending_evicted = []
            self._windows.append((self._open_t0, now, rows))
            self._open_t0 = now
        return True

    def rows(self) -> List[tuple]:
        """(begin_ts, end_ts, row_dict) per digest per closed window,
        oldest window first — the statements_summary_history virtual
        table's source."""
        with self._lock:
            windows = list(self._windows)
        return [
            (b, e, dict(r)) for b, e, rows in windows for r in rows
        ]

    def windows_for(self, digest: str) -> int:
        """How many closed windows contain this digest (tests; the
        eviction-boundary retention assertion)."""
        return sum(
            1 for _b, _e, r in self.rows()
            if r.get("digest_text") == digest
        )

    def reset(self) -> None:
        with self._lock:
            self._windows.clear()
            self._pending_evicted = []
            self._open_t0 = time.time()


SLOW_LOG = SlowLog()
STMT_SUMMARY = StmtSummary()
STMT_HISTORY = StmtHistory()
STMT_SUMMARY.history = STMT_HISTORY
