"""Per-query memory accounting with a quota.

Reference: pkg/util/memory — Tracker tree (tracker.go:74) with an
ActionOnExceed escalation chain (action.go:30) that spills or cancels.
On TPU all intermediate sizes are STATIC at compile time (capacity tiles
x dtype widths), so instead of runtime tracking we *pre-account* every
node's output bytes during plan compilation and reject/shrink before
launching — an admission-control formulation of the same contract. The
escalation chain maps to: (1) try smaller capacity tiles, (2) fail the
query with a quota error (the reference's cancel action); host-RAM
staging (the spill analog) is the planned escape hatch for oversized
sorts.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


class QuotaExceeded(RuntimeError):
    pass


@dataclasses.dataclass
class MemoryTracker:
    label: str
    quota_bytes: Optional[int] = None
    consumed: int = 0
    peak: int = 0
    children: List["MemoryTracker"] = dataclasses.field(default_factory=list)
    parent: Optional["MemoryTracker"] = None

    def child(self, label: str) -> "MemoryTracker":
        c = MemoryTracker(label, parent=self)
        self.children.append(c)
        return c

    def consume(self, nbytes: int) -> None:
        t = self
        while t is not None:
            t.consumed += nbytes
            t.peak = max(t.peak, t.consumed)
            if t.quota_bytes is not None and t.consumed > t.quota_bytes:
                raise QuotaExceeded(
                    f"memory quota exceeded at {t.label}: "
                    f"{t.consumed} > {t.quota_bytes} bytes"
                )
            t = t.parent

    def release(self, nbytes: int) -> None:
        t = self
        while t is not None:
            t.consumed -= nbytes
            t = t.parent

    def report(self, depth: int = 0) -> List[str]:
        lines = [
            "  " * depth
            + f"{self.label}: peak={self.peak} consumed={self.consumed}"
            + (f" quota={self.quota_bytes}" if self.quota_bytes else "")
        ]
        for c in self.children:
            lines.extend(c.report(depth + 1))
        return lines


def batch_bytes(capacity: int, col_dtypes: Dict[str, object]) -> int:
    """Static size of a Batch: data + validity per column + row mask."""
    total = capacity  # row_valid
    for dt in col_dtypes.values():
        total += capacity * (getattr(dt, "itemsize", 8) + 1)
    return total
