from tidb_tpu.utils.sysvar import SysVars, SYSVAR_DEFS  # noqa: F401
from tidb_tpu.utils.memtrack import MemoryTracker, QuotaExceeded  # noqa: F401
from tidb_tpu.utils.tracing import Tracer, span  # noqa: F401
from tidb_tpu.utils import failpoint  # noqa: F401
