"""Host-side 3-valued row evaluator for CHECK constraint expressions.

CHECK constraints run on the WRITE path over small Python row batches
(INSERT VALUES / UPDATE rewrites), before values are encoded into
device columns — a jitted kernel would pay a compile per insert shape
for work that is O(rows) host arithmetic. SQL semantics: a CHECK passes
when the predicate is TRUE or UNKNOWN (NULL) and fails only on FALSE
(reference: CHECK enforcement in the write path, pkg/table/tables.go
CheckRowConstraint + pkg/expression evaluation).
"""

from __future__ import annotations

import fnmatch


def sql_like_match(value: str, pattern: str, ci: bool = False) -> bool:
    """SQL LIKE semantics over fnmatch: % -> *, _ -> ? with fnmatch
    metacharacters escaped; ci=True folds case (SHOW ... LIKE is
    case-insensitive in MySQL). The ONE LIKE->fnmatch translation —
    CHECK evaluation and every SHOW filter share it."""
    pat = (
        pattern.replace("[", "[[]").replace("*", "[*]").replace("?", "[?]")
        .replace("%", "*").replace("_", "?")
    )
    if ci:
        return fnmatch.fnmatchcase(value.lower(), pat.lower())
    return fnmatch.fnmatchcase(value, pat)
from typing import Optional

from tidb_tpu.parser import ast


class CheckEvalError(ValueError):
    """The expression uses a construct CHECK does not allow."""


_CMP = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
}

def _bigint(v):
    """MySQL bit-op operand coercion: round half away from zero."""
    import math

    if isinstance(v, bool):
        return int(v)
    if isinstance(v, float):
        return int(math.floor(abs(v) + 0.5)) * (1 if v >= 0 else -1)
    return int(v)


_I64_MASK = (1 << 64) - 1


def _shift(a, b, left: bool):
    if b < 0 or b >= 64:
        return 0  # MySQL: out-of-range shift counts yield 0
    u = _bigint(a) & _I64_MASK
    u = (u << b) if left else (u >> b)
    u &= _I64_MASK
    return u - (1 << 64) if u >= (1 << 63) else u


_ARITH = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b if b != 0 else None,  # SQL: x/0 is NULL
    "mod": lambda a, b: a % b if b != 0 else None,
    # bitwise family (the flags = flags | 1 upsert idiom and
    # CHECK (a & 1 = 1) constraints run through this host evaluator)
    "bit_and": lambda a, b: _bigint(a) & _bigint(b),
    "bit_or": lambda a, b: _bigint(a) | _bigint(b),
    "bit_xor": lambda a, b: _bigint(a) ^ _bigint(b),
    "shl": lambda a, b: _shift(a, _bigint(b), True),
    "shr": lambda a, b: _shift(a, _bigint(b), False),
}


def _truth(v) -> Optional[bool]:
    """SQL boolean coercion: NULL -> UNKNOWN, 0/0.0/'' -> FALSE."""
    return None if v is None else bool(v)


def eval_check(e, row: dict) -> Optional[bool]:
    """Evaluate a parsed CHECK expression against one row (column name ->
    Python value, None = NULL). Returns True/False/None (UNKNOWN)."""
    if isinstance(e, ast.Const):
        return e.value
    if isinstance(e, ast.Name):
        col = e.column.lower()
        if col not in row:
            raise CheckEvalError(f"unknown column {col!r} in CHECK")
        return row[col]
    if not isinstance(e, ast.Call):
        raise CheckEvalError(
            f"unsupported construct in CHECK: {type(e).__name__}"
        )
    op = e.op
    if op == "and":
        a, b = (_truth(eval_check(x, row)) for x in e.args)
        if a is False or b is False:
            return False
        if a is None or b is None:
            return None
        return True
    if op == "or":
        a, b = (_truth(eval_check(x, row)) for x in e.args)
        if a is True or b is True:
            return True
        if a is None or b is None:
            return None
        return False
    if op == "not":
        v = _truth(eval_check(e.args[0], row))
        return None if v is None else not v
    if op == "isnull":
        return eval_check(e.args[0], row) is None
    if op == "isnotnull":
        return eval_check(e.args[0], row) is not None
    if op == "neg":
        v = eval_check(e.args[0], row)
        return None if v is None else -v
    if op == "bit_neg":
        v = eval_check(e.args[0], row)
        return None if v is None else ~_bigint(v)
    if op == "in":
        lhs = eval_check(e.args[0], row)
        if lhs is None:
            return None
        vals = [eval_check(a, row) for a in e.args[1:]]
        if lhs in [v for v in vals if v is not None]:
            return True
        return None if any(v is None for v in vals) else False
    if op == "like":
        a, p = (eval_check(x, row) for x in e.args)
        if a is None or p is None:
            return None
        return sql_like_match(str(a), str(p))
    if op == "coalesce":
        for a in e.args:
            v = eval_check(a, row)
            if v is not None:
                return v
        return None
    if op in _CMP:
        a, b = (eval_check(x, row) for x in e.args)
        if a is None or b is None:
            return None
        if isinstance(a, bool):
            a = int(a)
        if isinstance(b, bool):
            b = int(b)
        try:
            return _CMP[op](a, b)
        except TypeError:
            raise CheckEvalError(
                f"CHECK comparison between incompatible values {a!r}, {b!r}"
            )
    if op in _ARITH:
        a, b = (eval_check(x, row) for x in e.args)
        if a is None or b is None:
            return None
        try:
            return _ARITH[op](a, b)
        except TypeError:
            raise CheckEvalError(
                f"CHECK arithmetic on incompatible values {a!r}, {b!r}"
            )
    if op == "case":
        # [c1, v1, c2, v2, ..., else?] (kernels.py CASE layout)
        args = list(e.args)
        else_e = args.pop() if len(args) % 2 == 1 else None
        for i in range(0, len(args), 2):
            if _truth(eval_check(args[i], row)) is True:
                return eval_check(args[i + 1], row)
        return eval_check(else_e, row) if else_e is not None else None
    if op == "if":
        c = _truth(eval_check(e.args[0], row))
        return eval_check(e.args[1] if c is True else e.args[2], row)
    if op == "ifnull":
        v = eval_check(e.args[0], row)
        return eval_check(e.args[1], row) if v is None else v
    if op == "nullif":
        a, b = (eval_check(x, row) for x in e.args)
        return None if a == b else a
    if op in _SCALAR:
        vals = [eval_check(a, row) for a in e.args]
        return _SCALAR[op](vals)
    raise CheckEvalError(f"unsupported function {op!r} in CHECK")


def _s_concat(vals):
    if any(v is None for v in vals):
        return None
    return "".join(_sqlstr(v) for v in vals)


def _sqlstr(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return str(v)


def _null_in(f):
    """Wrap an all-args scalar: any NULL argument yields NULL."""

    def g(vals):
        if any(v is None for v in vals):
            return None
        return f(vals)

    return g


def _substr(vals):
    s, pos = _sqlstr(vals[0]), int(vals[1])
    ln = int(vals[2]) if len(vals) > 2 else None
    if pos == 0:
        return ""
    i = pos - 1 if pos > 0 else len(s) + pos
    if i < 0:
        return ""
    out = s[i:]
    if ln is not None:
        out = out[: max(ln, 0)]
    return out


# Scalar functions shared by CHECK constraints and generated-column
# evaluation (reference: the deterministic builtin subset allowed in
# generated column expressions, pkg/ddl/generated_column.go:125 +
# pkg/expression/util.go IsAllowedInGeneratedColumn). All NULL-in ->
# NULL-out except where noted.
_SCALAR = {
    "concat": _s_concat,
    "upper": _null_in(lambda v: _sqlstr(v[0]).upper()),
    "ucase": _null_in(lambda v: _sqlstr(v[0]).upper()),
    "lower": _null_in(lambda v: _sqlstr(v[0]).lower()),
    "lcase": _null_in(lambda v: _sqlstr(v[0]).lower()),
    "length": _null_in(lambda v: len(_sqlstr(v[0]).encode())),
    "char_length": _null_in(lambda v: len(_sqlstr(v[0]))),
    "character_length": _null_in(lambda v: len(_sqlstr(v[0]))),
    "substr": _null_in(_substr),
    "substring": _null_in(_substr),
    "left": _null_in(lambda v: _sqlstr(v[0])[: max(int(v[1]), 0)]),
    "right": _null_in(
        lambda v: _sqlstr(v[0])[-max(int(v[1]), 0):] if int(v[1]) > 0 else ""
    ),
    "trim": _null_in(lambda v: _sqlstr(v[0]).strip(" ")),
    "abs": _null_in(lambda v: abs(v[0])),
    "round": _null_in(
        lambda v: _mysql_round(v[0], int(v[1]) if len(v) > 1 else 0)
    ),
    "floor": _null_in(lambda v: int(__import__("math").floor(v[0]))),
    "ceil": _null_in(lambda v: int(__import__("math").ceil(v[0]))),
    "ceiling": _null_in(lambda v: int(__import__("math").ceil(v[0]))),
    "least": _null_in(lambda v: min(v)),
    "greatest": _null_in(lambda v: max(v)),
}


def _mysql_round(x, d: int):
    """Round half away from zero (MySQL), not banker's rounding."""
    import math

    m = 10.0**d
    r = math.floor(abs(x) * m + 0.5) / m * (1 if x >= 0 else -1)
    return int(r) if d <= 0 and not isinstance(x, float) else r


def check_columns(e, out=None) -> set:
    """Column names referenced by a CHECK expression."""
    if out is None:
        out = set()
    if isinstance(e, ast.Name):
        out.add(e.column.lower())
    elif isinstance(e, ast.Call):
        for a in e.args:
            check_columns(a, out)
    elif not isinstance(e, ast.Const):
        raise CheckEvalError(
            f"unsupported construct in CHECK: {type(e).__name__}"
        )
    return out


_STRUCT_OPS = frozenset(
    {
        "and", "or", "not", "isnull", "isnotnull", "neg", "bit_neg",
        "in", "like", "coalesce", "case", "if", "ifnull", "nullif",
    }
)


def validate_expr_ops(e) -> None:
    """Statically verify every node of an expression is evaluable by
    eval_check — used at DDL time so a generated column / CHECK with an
    unsupported function is rejected at CREATE, not at first INSERT
    (the reference whitelists generated-column builtins the same way,
    pkg/expression/util.go IsAllowedInGeneratedColumn). Raises
    CheckEvalError on the first unsupported construct."""
    if isinstance(e, (ast.Const, ast.Name)):
        return
    if not isinstance(e, ast.Call):
        raise CheckEvalError(
            f"unsupported construct: {type(e).__name__}"
        )
    op = e.op
    if (
        op not in _CMP
        and op not in _ARITH
        and op not in _SCALAR
        and op not in _STRUCT_OPS
    ):
        raise CheckEvalError(f"unsupported function {op!r}")
    for a in e.args:
        validate_expr_ops(a)
