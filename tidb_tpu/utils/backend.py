"""JAX backend fallback for the flaky TPU tunnel.

The environment force-registers an 'axon' PJRT plugin (the TPU tunnel)
whose init can fail OR hang for hours. Every CPU-forcing site must do
the same three things, in this order, each independently best-effort:
set JAX_PLATFORMS=cpu, drop the tunnel env var (pallas paths consult
it), update live jax config, and deregister non-cpu backend factories
(the force-registered plugin otherwise wins even with
JAX_PLATFORMS=cpu). tests/conftest.py and tests/_multihost_worker.py
inline the same sequence because they run before tidb_tpu is
importable — keep them in sync with this helper.
"""

from __future__ import annotations

import os
import subprocess
import sys


_IS_TPU: bool | None = None


def is_tpu() -> bool:
    """True when the default JAX backend is a TPU device.

    `jax.default_backend()` returns the PJRT *plugin's* platform name —
    'axon' for this environment's TPU tunnel — so string-comparing it to
    "tpu" silently disables every TPU-only engine path on the real
    hardware (round-5 captures: Q18 SF10 ran the serial dense scatter
    for 9.27s with the sorted path sitting behind exactly this check).
    `Device.platform` normalizes to "tpu" and is the check proven to
    work through the tunnel (streamed._device_budget's HBM fallback).
    Cached: the backend never changes after first use inside a process;
    force_cpu() resets the cache for interpreters that flip early."""
    global _IS_TPU
    if _IS_TPU is None:
        try:
            import jax

            _IS_TPU = jax.default_backend() == "tpu" or any(
                d.platform == "tpu" for d in jax.local_devices()
            )
        except Exception:
            return False  # don't cache a failed probe
    return _IS_TPU


def backend_label() -> str:
    """Human-readable backend line for benches/profilers:
    default_backend() reports the PJRT plugin name ('axon' through the
    TPU tunnel); is_tpu() (Device.platform) tells the truth on
    hardware, so hardware runs label as "tpu (pjrt=axon)"."""
    import jax

    b = jax.default_backend()
    return f"tpu (pjrt={b})" if is_tpu() and b != "tpu" else b


def sort_path_preference() -> str:
    """One switch for every sort-vs-scatter formulation gate:
    TIDB_TPU_SORT_AGG=1 -> 'force' (CPU tests cover the TPU lowering),
    =0 -> 'avoid' (TPU opt-out escape hatch), unset -> 'auto' (backend
    decides). Gates combine this with is_tpu() and their own size
    thresholds, but the env-var policy lives here only."""
    v = os.environ.get("TIDB_TPU_SORT_AGG")
    return "force" if v == "1" else "avoid" if v == "0" else "auto"


def force_cpu() -> None:
    """Make this interpreter CPU-only regardless of registered plugins."""
    global _IS_TPU
    _IS_TPU = False
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:  # separate block: a config failure must not skip deregistration
        from jax._src import xla_bridge as xb

        for name in list(getattr(xb, "_backend_factories", {})):
            if name != "cpu":
                xb._backend_factories.pop(name, None)
    except Exception:
        pass


def probe_accelerator(timeout_s: int = 120) -> bool:
    """Can a fresh process initialize the configured JAX backend?
    Probed in a throwaway subprocess (its own session, output to
    devnull) so a hung tunnel cannot hang US — the child's whole
    process group is killed on timeout."""
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        try:
            return proc.wait(timeout=timeout_s) == 0
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except Exception:
                proc.kill()
            proc.wait(timeout=10)
            return False
    except Exception:
        return False


def ensure_live_backend(timeout_s: int = 120) -> None:
    """Fall back to CPU iff the configured accelerator backend cannot
    initialize (fail or hang). A healthy accelerator — explicit or
    autodetected — is left alone."""
    try:
        from jax._src import xla_bridge as xb

        if xb.backends_are_initialized():
            return
    except Exception:
        pass
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return
    if probe_accelerator(timeout_s):
        return
    force_cpu()
