"""Failpoint-style fault injection.

Reference: pingcap/failpoint with 587 inject sites enabled by code
rewrite (Makefile failpoint-enable) + kv.FaultInjectedStore
(pkg/kv/fault_injection.go). Python needs no rewrite step: `inject(name)`
is a no-op unless a test enabled the failpoint, in which case it raises,
returns a value, or calls a hook — the same three actions the reference's
`failpoint.Inject` callbacks implement.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_active: Dict[str, object] = {}


class FailpointError(RuntimeError):
    pass


def enable(name: str, action: object) -> None:
    """action: an Exception instance/class to raise, a callable hook, or
    a value to return from inject()."""
    with _lock:
        _active[name] = action


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def disable_all() -> None:
    with _lock:
        _active.clear()


def _run_action(action, msg: str):
    """The four action kinds a site applies: raise an exception class,
    raise an instance, call a hook, or return a value (shared by
    inject() and after_n() so the dispatch never drifts)."""
    if isinstance(action, type) and issubclass(action, BaseException):
        raise action(msg)
    if isinstance(action, BaseException):
        raise action
    if callable(action):
        return action()
    return action


def inject(name: str, default=None):
    """Call at a site. Returns `default` (or the enabled value)."""
    action = _active.get(name)
    if action is None:
        return default
    return _run_action(action, f"failpoint {name}")


def is_enabled(name: str) -> bool:
    return name in _active


def after_n(n: int, action: object):
    """An action that fires EXACTLY on the n-th invocation of its site
    (dormant before and after) — 'die on the K-th fragment' style
    schedules, the analog of the reference's `Nx`/`xN` failpoint term
    syntax (pingcap/failpoint terms.go). One-shot so a retry of the
    failed operation observes a healthy site. Thread-safe."""
    state = {"count": 0}
    slock = threading.Lock()

    def fire():
        with slock:
            state["count"] += 1
            due = state["count"] == int(n)
        if not due:
            return None
        return _run_action(action, "failpoint after_n")

    return fire
