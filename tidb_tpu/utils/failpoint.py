"""Failpoint-style fault injection.

Reference: pingcap/failpoint with 587 inject sites enabled by code
rewrite (Makefile failpoint-enable) + kv.FaultInjectedStore
(pkg/kv/fault_injection.go). Python needs no rewrite step: `inject(name)`
is a no-op unless a test enabled the failpoint, in which case it raises,
returns a value, or calls a hook — the same three actions the reference's
`failpoint.Inject` callbacks implement.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from tidb_tpu.utils import racecheck

_lock = racecheck.make_lock("failpoint.registry")
_active: Dict[str, object] = {}

#: Every failpoint site the engine defines. A site must be declared here
#: to be enable()-able, and scripts/check_failpoints.py (tier-1 via
#: tests/test_failpoint_sites.py) cross-checks this set against the
#: actual `inject(...)` call sites — a typo'd name in a test can no
#: longer silently arm nothing (the reference generates its site list
#: from the failpoint.Inject rewrite step; we lint instead).
SITES = frozenset({
    "aqe/probe",
    "aqe/probe-lost",
    "aqe/replan",
    "aqe/switched-stage",
    "br/statement",
    "catalog/create-table",
    "catalog/drop-table",
    "cdc/sink-write",
    "collate/rank-lut",
    "cte/iterate",
    "dcn/cancel",
    "dcn/dispatch",
    "dcn/dispatch-lost",
    "dcn/duplicate-redelivery",
    "dcn/final-stage",
    "dcn/fragment-execute",
    "dcn/heartbeat-timeout",
    "dcn/redispatch",
    "dcn/result-send",
    "ddl/alter-table",
    "ddl/create-index",
    "ddl/generated-recompute",
    "ddl/index-before-public",
    "ddl/index-write-only",
    "ddl/index-write-reorg",
    "ddl/modify-column-delta-retry",
    "ddl/modify-column-reorg",
    "ddl/rename-table",
    "delta/apply",
    "delta/capture",
    "delta/compact-apply",
    "delta/ship",
    "delta/sync-loss",
    "dml/delete",
    "dml/insert",
    "dml/load",
    "dml/update",
    "dxf/heartbeat",
    "dxf/submit",
    "engine/clock-skew",
    "engine/dispatch",
    "engine/execute",
    "engine/probe-fail",
    "exchange/gather",
    "exchange/range-repartition",
    "exchange/repartition",
    "executor/admission",
    "executor/aggregate",
    "executor/before-discover",
    "executor/cap-overflow",
    "executor/join",
    "executor/partition-feed",
    "executor/partition-start",
    "executor/sort",
    "executor/stream-chunk",
    "executor/stream-chunk-device",
    "executor/stream-sort",
    "executor/stream-start",
    "extsort/merge-round",
    "extsort/merge-views",
    "fk/cascade-delete",
    "fk/cascade-update",
    "locks/acquire",
    "locks/deadlock-detected",
    "logbackup/write-segment",
    "persist/backup-table",
    "persist/before-manifest",
    "persist/restore-start",
    "resgroup/debit",
    "sequence/nextval",
    "server/dispatch-query",
    "shuffle/consume",
    "shuffle/decode",
    "shuffle/filter",
    "shuffle/filter-lost",
    "shuffle/open",
    "shuffle/produce",
    "shuffle/push",
    "serving/admit",
    "shuffle/push-lost",
    "shuffle/recv",
    "shuffle/recv-ack-lost",
    "shuffle/sample",
    "shuffle/sample-lost",
    "shuffle/stage",
    "shuffle/stage-input",
    "shuffle/stage-retry",
    "shuffle/wait",
    "session/before-commit",
    "session/begin-txn",
    "session/commit-apply",
    "session/commit-conflict-check",
    "session/execute-prepared",
    "session/stmt-start",
    "stats/analyze",
    "storage/append-skip-unique",
    "storage/gc-drop-version",
    "storage/gc-versions",
    "storage/install-commit",
    "storage/scan",
    "watchdog/sample",
})

#: sites declared at runtime (tests exercising the lint itself or
#: prototyping a new site before it lands in SITES)
_extra_sites: set = set()


class FailpointError(RuntimeError):
    pass


def declare(name: str) -> None:
    """Declare an out-of-tree site (tests/prototypes). Engine sites
    belong in SITES."""
    with _lock:
        _extra_sites.add(name)


def is_declared(name: str) -> bool:
    return name in SITES or name in _extra_sites


def enable(name: str, action: object) -> None:
    """action: an Exception instance/class to raise, a callable hook, or
    a value to return from inject(). Rejects undeclared site names — a
    typo here would otherwise arm nothing and the test would silently
    pass."""
    if not is_declared(name):
        raise ValueError(
            f"unknown failpoint site {name!r}: declare it in "
            "utils/failpoint.py SITES (engine sites) or via declare() "
            "(test-local sites)"
        )
    with _lock:
        _active[name] = action


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def disable_all() -> None:
    with _lock:
        _active.clear()


def _run_action(action, msg: str):
    """The four action kinds a site applies: raise an exception class,
    raise an instance, call a hook, or return a value (shared by
    inject() and after_n() so the dispatch never drifts)."""
    if isinstance(action, type) and issubclass(action, BaseException):
        raise action(msg)
    if isinstance(action, BaseException):
        raise action
    if callable(action):
        return action()
    return action


def inject(name: str, default=None):
    """Call at a site. Returns `default` (or the enabled value)."""
    action = _active.get(name)
    if action is None:
        return default
    return _run_action(action, f"failpoint {name}")


def is_enabled(name: str) -> bool:
    return name in _active


def _gated(action: object, msg: str, due):
    """The shared shell of every stateful action term: serialize hits
    on a private lock, ask ``due()`` (which owns/mutates the term's
    state) whether THIS hit fires, and run the action if so — the
    thread-safety and dispatch live once for seeded/times/after_n."""
    slock = racecheck.make_lock("failpoint.site")

    def fire():
        with slock:
            hit = due()
        if not hit:
            return None
        return _run_action(action, msg)

    return fire


def seeded(seed: int, p: float, action: object):
    """A PROBABILISTIC action driven by a private seeded PRNG: each
    invocation of the site draws once and fires `action` when the draw
    lands under `p`. The draw SEQUENCE is fully determined by the seed
    — the chaos harness (tidb_tpu/chaos) replays a fault schedule by
    re-arming the same (seed, p) pair, the analog of the reference's
    `K%` failpoint term (pingcap/failpoint terms.go) made
    deterministic. Thread-safe: concurrent hits serialize so every
    hit consumes exactly one draw."""
    import random

    rng = random.Random(int(seed))
    return _gated(
        action, f"failpoint seeded({seed}, {p})",
        lambda: rng.random() < float(p),
    )


def _counter(n: int, cmp):
    state = {"count": 0}

    def due():
        state["count"] += 1
        return cmp(state["count"], int(n))

    return due


def times(n: int, action: object):
    """An action that fires on the FIRST n invocations of its site and
    then goes dormant — a bounded fault WINDOW (the reference's `Nx`
    term): a tunnel partition that heals after k frames, a crash storm
    that ends. Thread-safe."""
    return _gated(
        action, "failpoint times", _counter(n, lambda c, n: c <= n)
    )


def after_n(n: int, action: object):
    """An action that fires EXACTLY on the n-th invocation of its site
    (dormant before and after) — 'die on the K-th fragment' style
    schedules, the analog of the reference's `Nx`/`xN` failpoint term
    syntax (pingcap/failpoint terms.go). One-shot so a retry of the
    failed operation observes a healthy site. Thread-safe."""
    return _gated(
        action, "failpoint after_n", _counter(n, lambda c, n: c == n)
    )
