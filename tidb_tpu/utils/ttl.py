"""TTL table expiry worker.

Reference: pkg/ttl — scan/delete job manager over TTL-attributed tables
(ttlworker/job_manager.go, scan.go, del.go) driven by the timer
framework. Here a catalog sweep compares the TTL column against
NOW() - INTERVAL host-side (numpy over the columnar blocks — expiry is
a data-management chore, not a device-compute problem) and drops the
expired rows through the table's versioned delete path.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from tidb_tpu.dtypes import Kind, US_PER_SECOND

_UNIT_SECONDS = {
    "second": 1,
    "minute": 60,
    "hour": 3600,
    "day": 86400,
    "week": 7 * 86400,
    "month": 30 * 86400,  # TTL cutoffs are approximate by design
}


def expire_table(table, now_unix: Optional[float] = None) -> int:
    """Delete rows whose TTL column is older than now - interval.
    Returns the number of rows removed."""
    if table.ttl is None:
        return 0
    col, iv, unit = table.ttl
    now_unix = time.time() if now_unix is None else now_unix
    cutoff_s = now_unix - iv * _UNIT_SECONDS[unit]
    typ = table.schema.types.get(col)
    if typ is None:
        return 0
    if typ.kind == Kind.DATE:
        cutoff = int(cutoff_s // 86400)
    elif typ.kind == Kind.DATETIME:
        cutoff = int(cutoff_s * US_PER_SECOND)
    else:
        return 0
    # snapshot+mask+swap happen inside ONE table-lock hold so the sweep
    # can't race a concurrent INSERT (NULL TTL values never expire)
    removed = table.purge_expired(col, cutoff)
    if removed:
        from tidb_tpu.storage.scan import clear_scan_cache

        clear_scan_cache()
        from tidb_tpu.utils.metrics import REGISTRY

        REGISTRY.counter(
            "tidbtpu_ttl_expired_rows_total", "rows purged by TTL"
        ).inc(removed)
    return removed


class TTLWorker:
    """Background expiry sweep over a catalog (pkg/ttl job manager)."""

    def __init__(self, catalog, interval_s: float = 60.0):
        self.catalog = catalog
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self, now_unix: Optional[float] = None) -> int:
        n = 0
        for db in list(self.catalog.databases()):
            if db.startswith("_") or db == "information_schema":
                continue
            for name in list(self.catalog.tables(db)):
                try:
                    n += expire_table(self.catalog.table(db, name), now_unix)
                except Exception:
                    # a broken TTL config must be visible, not silent
                    from tidb_tpu.utils.metrics import REGISTRY

                    REGISTRY.counter(
                        "tidbtpu_ttl_errors_total", "failed TTL sweeps"
                    ).inc()
                    continue
        return n

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.tick()

        self._thread = threading.Thread(
            target=loop, name="ttl-worker", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
