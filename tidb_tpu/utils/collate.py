"""Collation engine: sort keys for MySQL collations.

Reference: pkg/util/collate/collate.go:66 — the Collator interface
(Compare / Key / KeyWithoutTrimRightSpace) with per-collation
implementations (binCollator, generalCICollator, unicodeCICollator...).
The columnar analog: a collation is a SORT-KEY function over strings;
the engine compares/sorts dictionary-coded columns through dense rank
LUTs built from these keys at compile time (one host pass over the
dictionary, zero per-row device cost beyond a gather).

Semantics implemented:
- *_bin / binary: identity (code order IS binary order — native).
- *_general_ci: per-character simple uppercase mapping (MySQL's
  general_ci compares by uppercasing each character) + PAD SPACE
  (trailing spaces ignored, like the reference's Key()).
- *_unicode_ci / *_0900_ai_ci: accent- and case-insensitive via NFKD
  decomposition with combining marks stripped, then casefold + PAD
  SPACE ('é' == 'e', 'ß' == 'ss' per casefold).
"""

from __future__ import annotations

import unicodedata
from typing import Callable, Optional

# collation name -> key function; None = binary (identity fast path)
_REGISTRY: dict = {}


def _pad(s: str) -> str:
    """PAD SPACE attribute: trailing spaces are insignificant."""
    return s.rstrip(" ")


def _general_ci_key(s: str) -> str:
    return _pad(s).upper()


def _unicode_ci_key(s: str) -> str:
    d = unicodedata.normalize("NFKD", _pad(s))
    return "".join(
        c for c in d if not unicodedata.combining(c)
    ).casefold()


def _bin_key(s: str) -> str:
    return s


for _name in (
    "utf8mb4_general_ci", "utf8_general_ci", "utf8mb3_general_ci",
    "latin1_general_ci", "latin1_swedish_ci", "ascii_general_ci",
):
    _REGISTRY[_name] = _general_ci_key
for _name in (
    "utf8mb4_unicode_ci", "utf8_unicode_ci", "utf8mb4_0900_ai_ci",
    "utf8mb4_unicode_520_ci",
):
    _REGISTRY[_name] = _unicode_ci_key
for _name in (
    "binary", "utf8mb4_bin", "utf8_bin", "utf8mb3_bin", "latin1_bin",
    "ascii_bin", "utf8mb4_0900_bin",
):
    _REGISTRY[_name] = None

#: charset -> its default collation. These are the REFERENCE's (TiDB)
#: defaults — new_collations_enabled_on_first_bootstrap=false ships
#: *_bin for every charset (pkg/parser/charset; MySQL 8.0 would pick
#: utf8mb4_0900_ai_ci) — so dumps restore with identical comparison
#: semantics.
CHARSET_DEFAULTS = {
    "utf8mb4": "utf8mb4_bin",
    "utf8": "utf8_bin",
    "utf8mb3": "utf8mb3_bin",
    "latin1": "latin1_bin",
    "ascii": "ascii_bin",
    "binary": "binary",
}


def known(name: str) -> bool:
    return name.lower() in _REGISTRY


def is_binary(name: Optional[str]) -> bool:
    return name is None or _REGISTRY.get(name.lower(), _bin_key) is None


def key_fn(name: Optional[str]) -> Callable[[str], str]:
    """Sort-key function for a collation name (identity for binary /
    unknown names — unknown should be rejected at DDL time)."""
    if name is None:
        return _bin_key
    f = _REGISTRY.get(name.lower())
    return _bin_key if f is None else f


def validate(name: str) -> str:
    n = name.lower()
    if n not in _REGISTRY:
        raise ValueError(f"Unknown collation: {name!r}")
    return n


def merge_rank_luts(da, db, coll):
    """Merge two dictionaries in collation-KEY space: returns
    (merged sorted key array, lut_a, lut_b) where lut_x[code] is the
    merged rank of dictionary x's entry — equal-under-collation values
    land on equal ranks. The ONE implementation behind string compare
    kernels and join-key alignment."""
    import numpy as np

    kf = key_fn(coll)
    ka = [kf(str(s)) for s in (da.tolist() if da is not None else [])]
    kb = [kf(str(s)) for s in (db.tolist() if db is not None else [])]
    merged = np.array(sorted(set(ka) | set(kb)), dtype=object)
    lut_a = (
        np.searchsorted(merged, np.array(ka, dtype=object)).astype(np.int64)
        if ka else np.zeros(1, np.int64)
    )
    lut_b = (
        np.searchsorted(merged, np.array(kb, dtype=object)).astype(np.int64)
        if kb else np.zeros(1, np.int64)
    )
    return merged, lut_a, lut_b
