"""Collation engine: sort keys for MySQL collations.

Reference: pkg/util/collate/collate.go:66 — the Collator interface
(Compare / Key / KeyWithoutTrimRightSpace) with per-collation
implementations (binCollator, generalCICollator, unicodeCICollator...).
The columnar analog: a collation is a SORT-KEY function over strings;
the engine compares/sorts dictionary-coded columns through dense rank
LUTs built from these keys at compile time (one host pass over the
dictionary, zero per-row device cost beyond a gather).

Semantics implemented:
- *_bin / binary: identity (code order IS binary order — native).
- *_general_ci: per-character simple uppercase mapping (MySQL's
  general_ci compares by uppercasing each character) + PAD SPACE
  (trailing spaces ignored, like the reference's Key()).
- *_unicode_ci / *_0900_ai_ci: accent- and case-insensitive via NFKD
  decomposition with combining marks stripped, then casefold + PAD
  SPACE ('é' == 'e', 'ß' == 'ss' per casefold).
"""

from __future__ import annotations

import unicodedata
from typing import Callable, Optional

# collation name -> key function; None = binary (identity fast path)
_REGISTRY: dict = {}


def _pad(s: str) -> str:
    """PAD SPACE attribute: trailing spaces are insignificant."""
    return s.rstrip(" ")


def _general_ci_key(s: str) -> str:
    return _pad(s).upper()


def _unicode_ci_key(s: str) -> str:
    d = unicodedata.normalize("NFKD", _pad(s))
    return "".join(
        c for c in d if not unicodedata.combining(c)
    ).casefold()


def _bin_key(s: str) -> str:
    return s


for _name in (
    "utf8mb4_general_ci", "utf8_general_ci", "utf8mb3_general_ci",
    "latin1_general_ci", "latin1_swedish_ci", "ascii_general_ci",
):
    _REGISTRY[_name] = _general_ci_key
for _name in (
    "utf8mb4_unicode_ci", "utf8_unicode_ci", "utf8mb4_0900_ai_ci",
    "utf8mb4_unicode_520_ci",
):
    _REGISTRY[_name] = _unicode_ci_key
for _name in (
    "binary", "utf8mb4_bin", "utf8_bin", "utf8mb3_bin", "latin1_bin",
    "ascii_bin", "utf8mb4_0900_bin",
):
    _REGISTRY[_name] = None

#: charset -> its default collation. These are the REFERENCE's (TiDB)
#: defaults — new_collations_enabled_on_first_bootstrap=false ships
#: *_bin for every charset (pkg/parser/charset; MySQL 8.0 would pick
#: utf8mb4_0900_ai_ci) — so dumps restore with identical comparison
#: semantics.
CHARSET_DEFAULTS = {
    "utf8mb4": "utf8mb4_bin",
    "utf8": "utf8_bin",
    "utf8mb3": "utf8mb3_bin",
    "latin1": "latin1_bin",
    "ascii": "ascii_bin",
    "binary": "binary",
}


def known(name: str) -> bool:
    return name.lower() in _REGISTRY


def is_binary(name: Optional[str]) -> bool:
    return name is None or _REGISTRY.get(name.lower(), _bin_key) is None


def key_fn(name: Optional[str]) -> Callable[[str], str]:
    """Sort-key function for a collation name (identity for binary /
    unknown names — unknown should be rejected at DDL time)."""
    if name is None:
        return _bin_key
    f = _REGISTRY.get(name.lower())
    return _bin_key if f is None else f


def validate(name: str) -> str:
    n = name.lower()
    if n not in _REGISTRY:
        raise ValueError(f"Unknown collation: {name!r}")
    return n


def rank_lut(d, coll):
    """Group-key LUT over ONE dictionary: returns (lut, rep) where
    equal-under-collation entries share lut[code], and rep is a
    BINARY-SORTED dictionary of one representative per class (the
    binary-least member — MySQL permits any group member as the
    displayed GROUP BY value) with lut[code] its class's position in
    rep. Keeping rep binary-sorted keeps every downstream consumer
    that assumes sorted dictionaries (literal-compare searchsorted,
    binary ORDER BY on codes, nested re-aggregation) sound. Grouping
    by lut[code] instead of code is the columnar analog of hashing on
    Collator.Key() (reference pkg/util/collate/collate.go:66 — Key()
    drives both compare and hash); unlike the *comparison* rank LUTs
    (merge_rank_luts, kernels._collation_rank_lut) the codes here are
    NOT in collation order — only equality structure matters.
    Returns None for binary collations (identity). Memoized by
    (dictionary identity, collation): plan compilation asks for the
    same LUT from several sites (group keys, output dicts, arg
    wraps) and dictionaries are table-global and immutable."""
    import numpy as np

    if is_binary(coll):
        return None
    key = (id(d), (coll or "").lower())
    hit = _RANK_CACHE.get(key)
    if hit is not None and hit[0] is d:
        return hit[1]
    from tidb_tpu.utils.failpoint import inject

    inject("collate/rank-lut")
    f = key_fn(coll)
    entries = [str(s) for s in d.tolist()]
    keys = [f(s) for s in entries]
    rep_of: dict = {}  # collation key -> binary-least member
    for s, k in zip(entries, keys):
        if k not in rep_of or s < rep_of[k]:
            rep_of[k] = s
    rep_sorted = sorted(rep_of.values())
    idx = {s: i for i, s in enumerate(rep_sorted)}
    lut = np.array([idx[rep_of[k]] for k in keys], dtype=np.int64)
    out = (lut, np.array(rep_sorted, dtype=object))
    while len(_RANK_CACHE) >= 32:
        _RANK_CACHE.pop(next(iter(_RANK_CACHE)))
    # the cached strong ref to `d` keeps its id from being reused
    _RANK_CACHE[key] = (d, out)
    return out


# (id(dict), collation) -> (dict strong ref, (lut, rep)); see rank_lut
_RANK_CACHE: dict = {}


def merge_rank_luts(da, db, coll):
    """Merge two dictionaries in collation-KEY space: returns
    (merged sorted key array, lut_a, lut_b) where lut_x[code] is the
    merged rank of dictionary x's entry — equal-under-collation values
    land on equal ranks. The ONE implementation behind string compare
    kernels and join-key alignment."""
    import numpy as np

    kf = key_fn(coll)
    ka = [kf(str(s)) for s in (da.tolist() if da is not None else [])]
    kb = [kf(str(s)) for s in (db.tolist() if db is not None else [])]
    merged = np.array(sorted(set(ka) | set(kb)), dtype=object)
    lut_a = (
        np.searchsorted(merged, np.array(ka, dtype=object)).astype(np.int64)
        if ka else np.zeros(1, np.int64)
    )
    lut_b = (
        np.searchsorted(merged, np.array(kb, dtype=object)).astype(np.int64)
        if kb else np.zeros(1, np.int64)
    )
    return merged, lut_a, lut_b
