"""HTTP status API: /status, /metrics, /schema, /settings, /dcn,
/links, /timeline, /tsdb, /inspection, /profile.

`/profile` (Top SQL, obs/profiler.py) exports the fleet-merged
collapsed-stack profile — one "digest;frame;...;frame <ms>" line per
sampled tower, loadable by flamegraph.pl and speedscope. `?host=`
narrows to one instance (coordinator or a worker address), `?digest=`
to one statement digest.

`/links` (PR 6) serves the per-peer DCN link health registry
(obs/flight.py LINKS): handshake RTT, heartbeat age, and tunnel
bytes/stall seconds/retransmits per link.

`/timeline` (PR 9) drives the fleet timeline tracer (obs/timeline.py):
GET /timeline dumps the captured Chrome trace-event JSON (save it,
open in Perfetto / chrome://tracing); /timeline/start and
/timeline/stop arm/disarm the bounded capture ring on demand.

`/tsdb` (PR 12) introspects the metric time-series store
(obs/tsdb.py): the sampled family vocabulary + ring occupancy, or —
with ``?metric=<family>[&since=<epoch>]`` — the stored points of one
family. `/inspection` (PR 12) runs the declared-rule diagnosis engine
(obs/inspection.py) over the retained history and returns the
findings; ``?since=<epoch>`` bounds the evaluation window. Both are
the HTTP twins of metrics_schema.<family> and
information_schema.inspection_result.

Reference: pkg/server/http_status.go — the side port serving liveness
(`/status`), Prometheus metrics (`/metrics`), schema introspection
(`/schema`, backed by infoschema), and settings. pprof endpoints are
Go-specific; the Python analog exposes the same operational surface
over the same paths, plus `/dcn` — the cross-host fragment scheduler's
operational snapshot (host liveness/quarantine + the last query's
per-fragment stats; parallel/dcn.py `status()`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class StatusServer:
    def __init__(
        self,
        catalog,
        host: str = "127.0.0.1",
        port: int = 10080,
        connections=None,
        dcn=None,
    ):
        self.catalog = catalog
        # live MySQL-protocol connection count provider (zero-arg
        # callable wired by server/server.py; the reference reports
        # Server.ConnectionCount here)
        self.connections = connections
        # DCN scheduler status provider: a zero-arg callable or an
        # object with .status() (parallel/dcn.DCNFragmentScheduler)
        self.dcn = dcn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, body, ctype="application/json"):
                data = body.encode() if isinstance(body, str) else body
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    path = self.path.split("?")[0].rstrip("/") or "/status"
                    if path == "/status":
                        from tidb_tpu import __version__ as ver
                    else:
                        ver = None
                    if path == "/status":
                        try:
                            nconn = int(outer.connections()) if callable(
                                outer.connections
                            ) else 0
                        except Exception:
                            nconn = 0
                        self._send(200, json.dumps(
                            {
                                "connections": nconn,
                                "version": f"8.0.11-tidb-tpu-{ver}",
                                "git_hash": "embedded",
                            }
                        ))
                    elif path == "/dcn":
                        prov = outer.dcn
                        if prov is None:
                            data = {"enabled": False}
                        elif callable(prov):
                            data = prov()
                        else:
                            data = prov.status()
                        self._send(200, json.dumps(data))
                    elif path == "/links":
                        from tidb_tpu.obs.flight import LINKS

                        self._send(
                            200, json.dumps({"links": LINKS.snapshot()})
                        )
                    elif path == "/timeline":
                        from tidb_tpu.obs.timeline import TIMELINE

                        self._send(200, TIMELINE.dump_json())
                    elif path in ("/timeline/start", "/timeline/stop"):
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.obs.timeline import TIMELINE

                        if path.endswith("/start"):
                            qs = parse_qs(urlparse(self.path).query)
                            cap = qs.get("capacity", [None])[0]
                            TIMELINE.start(
                                int(cap) if cap else None
                            )
                        else:
                            TIMELINE.stop()
                        self._send(200, json.dumps(
                            {
                                "active": TIMELINE.active(),
                                "events": len(TIMELINE),
                            }
                        ))
                    elif path == "/tsdb":
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.obs.tsdb import TSDB

                        qs = parse_qs(urlparse(self.path).query)
                        metric = qs.get("metric", [None])[0]
                        since = qs.get("since", [None])[0]
                        if metric:
                            pts = TSDB.query(
                                metric,
                                t_lo=float(since) if since else None,
                            )
                            self._send(200, json.dumps({
                                "metric": metric,
                                "points": [
                                    {"time": t, "instance": h,
                                     "labels": list(lv), "value": v,
                                     "res": res}
                                    for t, h, lv, v, res in pts
                                ],
                            }))
                        else:
                            self._send(200, json.dumps({
                                "families": {
                                    name: {"kind": k,
                                           "labels": list(ln)}
                                    for name, (k, ln)
                                    in sorted(TSDB.families().items())
                                },
                                "series": TSDB.series_count(),
                                "points": TSDB.point_count(),
                            }))
                    elif path == "/inspection":
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.obs.inspection import (
                            run_inspection,
                        )

                        qs = parse_qs(urlparse(self.path).query)
                        since = qs.get("since", [None])[0]
                        findings = run_inspection(
                            t_lo=float(since) if since else None
                        )
                        self._send(200, json.dumps({
                            "findings": [f.to_dict() for f in findings],
                        }))
                    elif path == "/profile":
                        from urllib.parse import parse_qs, urlparse

                        from tidb_tpu.obs.profiler import TOPSQL

                        qs = parse_qs(urlparse(self.path).query)
                        lines = TOPSQL.store.collapsed(
                            instance=qs.get("host", [None])[0],
                            digest=qs.get("digest", [None])[0],
                        )
                        # FlameGraph/speedscope collapsed-stack format:
                        # "frame;frame;frame count" per line, fleet-
                        # merged (?host= for one instance, ?digest=
                        # for one statement); load with flamegraph.pl
                        # or speedscope's "collapsed" importer
                        self._send(
                            200,
                            "\n".join(lines) + ("\n" if lines else ""),
                            "text/plain",
                        )
                    elif path == "/metrics":
                        from tidb_tpu.utils.metrics import REGISTRY

                        self._send(
                            200, REGISTRY.render(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/schema":
                        out = {}
                        for db in outer.catalog.databases():
                            if db.startswith("_"):
                                continue
                            out[db] = outer.catalog.tables(db)
                        self._send(200, json.dumps(out))
                    elif path.startswith("/schema/"):
                        parts = path.split("/")[2:]
                        db = parts[0]
                        if len(parts) == 1:
                            self._send(
                                200, json.dumps(outer.catalog.tables(db))
                            )
                        else:
                            t = outer.catalog.table(db, parts[1])
                            self._send(200, json.dumps(
                                {
                                    "name": t.name,
                                    "columns": [
                                        {"name": n, "type": repr(ty).lower()}
                                        for n, ty in t.schema.columns
                                    ],
                                    "primary_key": t.schema.primary_key,
                                    "indexes": t.indexes,
                                    "rows": t.nrows,
                                }
                            ))
                    elif path == "/settings":
                        from tidb_tpu.utils.sysvar import SysVars

                        sv = SysVars(outer.catalog.global_sysvars)
                        self._send(200, json.dumps(
                            {k: str(v) for k, v in sv.all().items()}
                        ))
                    else:
                        self._send(404, json.dumps({"error": "not found"}))
                except Exception as e:
                    self._send(500, json.dumps({"error": str(e)}))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._started = False

    def attach_dcn(self, provider) -> None:
        """Wire a DCN scheduler (or a zero-arg status callable) after
        construction — the scheduler usually outlives server boot."""
        self.dcn = provider

    def start_background(self) -> threading.Thread:
        self._started = True
        th = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="http-status",
        )
        th.start()
        return th

    def shutdown(self) -> None:
        # BaseServer.shutdown() blocks on an event only serve_forever
        # sets — never call it if serving never started
        if self._started:
            self._httpd.shutdown()
        self._httpd.server_close()
