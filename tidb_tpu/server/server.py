"""Threaded MySQL-protocol server over the embedded engine.

Reference: pkg/server/server.go:429 (Server.Run accept loop) +
conn.go:1009 (clientConn.Run read-dispatch loop), one goroutine per
connection; here one thread per connection, all sharing the catalog (the
device engine serializes on the single jit dispatch path, matching one
TPU chip per process; multi-chip serving shards sessions across hosts).
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Optional

from tidb_tpu.server import protocol as P
from tidb_tpu.session import Result, Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils import racecheck

COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_SEND_LONG_DATA = 0x18
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A
COM_STMT_FETCH = 0x1C


class Server:
    def __init__(
        self,
        catalog: Optional[Catalog] = None,
        host: str = "127.0.0.1",
        port: int = 4000,
        status_port: Optional[int] = None,
        dcn_scheduler=None,
    ):
        self.catalog = catalog or Catalog()
        self.host = host
        self.port = port
        # serving tier (PR 8): with a DCNFragmentScheduler attached,
        # every connection's session routes fragmentable/shuffleable
        # SELECTs across the worker fleet, gated by the scheduler's
        # admission controller — the MySQL front end becomes a
        # multi-tenant entry point to the fleet instead of a funnel
        # into one local engine
        self.dcn_scheduler = dcn_scheduler
        self._next_conn_id = [0]
        self._active_conns = 0
        self._lock = racecheck.make_lock("server.conns")
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                outer._handle_conn(self.request)

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = TCP((host, port), Handler)
        self.port = self._tcp.server_address[1]
        # background stats owner (reference: domain's stats handle loop)
        from tidb_tpu.stats.handle import StatsHandle
        from tidb_tpu.utils.ttl import TTLWorker

        self.stats_handle = StatsHandle(self.catalog, interval_s=30.0)
        self.ttl_worker = TTLWorker(self.catalog, interval_s=60.0)
        # side HTTP port: /status /metrics /schema /settings (reference
        # pkg/server/http_status.go); None disables
        self.status_server = None
        if status_port is not None:
            from tidb_tpu.server.http_status import StatusServer

            self.status_server = StatusServer(
                self.catalog, host=host, port=status_port,
                connections=lambda: self.connections,
            )

    def serve_forever(self) -> None:
        self.stats_handle.start()
        self.ttl_worker.start()
        if self.status_server is not None:
            self.status_server.start_background()
        self._tcp.serve_forever()

    def start_background(self) -> threading.Thread:
        th = threading.Thread(
            target=self.serve_forever, daemon=True,
            name=f"mysql-serve-{self.port}",
        )
        th.start()
        return th

    def shutdown(self) -> None:
        if self.status_server is not None:
            self.status_server.shutdown()
        self.ttl_worker.stop()
        self.stats_handle.stop()
        self._tcp.shutdown()
        self._tcp.server_close()

    @property
    def connections(self) -> int:
        """Live client connection count (reference: Server.
        ConnectionCount feeding the /status handler)."""
        with self._lock:
            return self._active_conns

    # ------------------------------------------------------------------
    def _handle_conn(self, sock: socket.socket) -> None:
        with self._lock:
            self._active_conns += 1
        try:
            self._handle_conn_inner(sock)
        finally:
            with self._lock:
                self._active_conns -= 1

    def _handle_conn_inner(self, sock: socket.socket) -> None:
        io = P.PacketIO(sock)
        with self._lock:
            self._next_conn_id[0] += 1
            conn_id = self._next_conn_id[0]
        sess = Session(self.catalog)
        version = str(sess.vars.get("version"))
        scramble = P.new_scramble()
        io.write_packet(P.handshake_v10(conn_id, version, scramble))
        body = io.read_packet()
        if body is None:
            return
        try:
            user, db, auth = P.parse_handshake_response(body)
        except Exception:
            io.write_packet(P.err_packet(1045, "malformed handshake"))
            return
        # real authentication (reference: pkg/privilege auth at
        # clientConn.openSessionAndDoAuth) — mysql_native_password
        # against the catalog's user store
        if not self.catalog.users.authenticate(user, scramble, auth):
            io.write_packet(
                P.err_packet(
                    1045, f"Access denied for user '{user}'@'%'", "28000"
                )
            )
            return
        sess.user = user.lower()
        if db:
            sess.db = db.lower()
        if self.dcn_scheduler is not None:
            sess.attach_dcn_scheduler(self.dcn_scheduler)
        io.write_packet(P.ok_packet())

        # prepared statements: per-connection registry (reference:
        # conn_stmt.go handleStmtPrepare/Execute at conn.go:1999)
        stmts = {}
        next_stmt_id = [0]

        while True:
            io.reset_seq()
            body = io.read_packet()
            if body is None or not body:
                return
            cmd, payload = body[0], body[1:]
            try:
                if cmd == COM_QUIT:
                    return
                if cmd == COM_PING:
                    io.write_packet(P.ok_packet())
                elif cmd == COM_INIT_DB:
                    sess.execute(f"use `{payload.decode()}`")
                    io.write_packet(P.ok_packet())
                elif cmd == COM_QUERY:
                    from tidb_tpu.utils.failpoint import inject

                    inject("server/dispatch-query")
                    sql = payload.decode("utf-8", "replace")
                    self._run_query(io, sess, sql)
                elif cmd == COM_FIELD_LIST:
                    io.write_packet(P.eof_packet())
                elif cmd == COM_STMT_PREPARE:
                    sql = payload.decode("utf-8", "replace")
                    nparams = P.count_placeholders(sql)
                    next_stmt_id[0] += 1
                    sid = next_stmt_id[0]
                    # session-level parameterized plan (plan_cache.go
                    # analog): EXECUTE binds values as runtime inputs of
                    # the cached compiled plan instead of re-planning
                    # re-rendered SQL text
                    sess.prepare(f"__c{sid}", sql)
                    stmts[sid] = [sql, nparams, None]  # [sql, n, param types]
                    io.write_packet(P.stmt_prepare_ok(sid, 0, nparams))
                    if nparams:
                        for _ in range(nparams):
                            io.write_packet(P.column_def("?", None))
                        io.write_packet(P.eof_packet())
                elif cmd == COM_STMT_EXECUTE:
                    import struct as _st

                    sid = _st.unpack_from("<I", payload, 0)[0]
                    if sid not in stmts:
                        io.write_packet(P.err_packet(1243, "unknown stmt"))
                        continue
                    sql, nparams, ptypes = stmts[sid][:3]
                    _sid, params, ptypes = P.parse_stmt_execute(
                        payload, nparams, ptypes
                    )
                    stmts[sid][2] = ptypes
                    r = sess.execute_prepared(f"__c{sid}", params)
                    # CURSOR_TYPE_READ_ONLY: buffer the resultset and
                    # answer column defs only; rows stream through
                    # COM_STMT_FETCH (reference conn_stmt.go:153
                    # useCursor — JDBC setFetchSize & BI tools)
                    flags = payload[4] if len(payload) > 4 else 0
                    if (flags & P.CURSOR_TYPE_READ_ONLY) and r.columns:
                        types = (
                            getattr(r, "types", None)
                            or [None] * len(r.columns)
                        )
                        while len(stmts[sid]) < 4:
                            stmts[sid].append(None)
                        stmts[sid][3] = [list(r.rows), types, 0]
                        io.write_packet(P.lenenc_int(len(r.columns)))
                        for name, t in zip(r.columns, types):
                            io.write_packet(P.column_def(name, t))
                        io.write_packet(
                            P.eof_packet(P.SERVER_STATUS_CURSOR_EXISTS)
                        )
                    else:
                        self._write_result(io, r, binary=True, sess=sess)
                elif cmd == COM_STMT_FETCH:
                    import struct as _st

                    fsid = _st.unpack_from("<I", payload, 0)[0]
                    nfetch = _st.unpack_from("<I", payload, 4)[0]
                    ent = stmts.get(fsid)
                    cur = ent[3] if ent is not None and len(ent) > 3 else None
                    if cur is None:
                        io.write_packet(
                            P.err_packet(1243, "no open cursor for stmt")
                        )
                        continue
                    rows, types, pos = cur
                    chunk = rows[pos : pos + max(nfetch, 1)]
                    for row in chunk:
                        io.write_packet(P.binary_row(row, types))
                    cur[2] = pos + len(chunk)
                    if cur[2] >= len(rows):
                        ent[3] = None  # drained: close the cursor
                        io.write_packet(
                            P.eof_packet(P.SERVER_STATUS_LAST_ROW_SENT)
                        )
                    else:
                        io.write_packet(
                            P.eof_packet(P.SERVER_STATUS_CURSOR_EXISTS)
                        )
                elif cmd == COM_STMT_CLOSE:
                    import struct as _st

                    csid = _st.unpack_from("<I", payload, 0)[0]
                    if stmts.pop(csid, None) is not None:
                        try:
                            sess.deallocate(f"__c{csid}")
                        except ValueError:
                            pass
                    # no response by protocol
                elif cmd == COM_STMT_RESET:
                    import struct as _st

                    rsid = _st.unpack_from("<I", payload, 0)[0]
                    ent = stmts.get(rsid)
                    if ent is not None and len(ent) > 3:
                        ent[3] = None  # drop any open cursor
                    io.write_packet(P.ok_packet())
                else:
                    io.write_packet(
                        P.err_packet(1047, f"unsupported command {cmd:#x}")
                    )
            except Exception as e:  # error -> ERR packet, connection lives
                try:
                    # serving-tier admission verdicts (and anything
                    # else that declares one) carry their own MySQL
                    # error number — a rejected statement must read as
                    # a deliberate server verdict, not a generic 1105
                    errno = int(getattr(e, "mysql_errno", 0) or 1105)
                    io.write_packet(P.err_packet(errno, str(e)))
                except OSError:
                    return

    def _run_query(
        self, io: P.PacketIO, sess: Session, sql: str, binary: bool = False
    ) -> None:
        r = sess.execute(sql)
        self._write_result(io, r, binary=binary, sess=sess)

    def _write_result(
        self, io: P.PacketIO, r, binary: bool = False, sess=None
    ) -> None:
        if not r.columns:
            io.write_packet(
                P.ok_packet(
                    affected=r.affected,
                    last_insert_id=int(getattr(sess, "last_insert_id", 0)),
                )
            )
            return
        types = getattr(r, "types", None) or [None] * len(r.columns)
        io.write_packet(P.lenenc_int(len(r.columns)))
        for name, t in zip(r.columns, types):
            io.write_packet(P.column_def(name, t))
        io.write_packet(P.eof_packet())
        if binary:
            for row in r.rows:
                io.write_packet(P.binary_row(row, types))
        else:
            for row in r.rows:
                payload = b""
                for v, t in zip(row, types):
                    fv = P.format_value(v, t)
                    payload += b"\xfb" if fv is None else P.lenenc_str(fv)
                io.write_packet(payload)
        io.write_packet(P.eof_packet())
