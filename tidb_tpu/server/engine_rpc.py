"""Frontend <-> device-engine RPC seam over the plan IR.

Reference: the `kv.Client.Send(kv.Request{Data: tipb.DAGRequest})`
contract (pkg/kv/kv.go:523) — the frontend serializes the pushdown plan
and a remote engine executes it, streaming chunks back. unistore proves
the whole SQL stack runs against that seam with an in-process loopback
(`RPCClient.SendRequest`, pkg/store/mockstore/unistore/rpc.go:64).

Here: EngineServer owns the catalog + device engine and serves
length-prefixed frames over TCP; EngineClient serializes a bound
logical plan with planner/ir.py and gets rows back. A frontend process
with no data of its own can plan SQL and execute it on a separate
engine process — the multi-host frontend/engine split.

Two frame types share the stream, discriminated by the first payload
byte: JSON control/plan frames (first byte ``{``) and binary columnar
shuffle frames (parallel/wire.py MAGIC) — the shuffle data plane skips
json.dumps/json.loads entirely. The handshake/ping reply advertises
the server's wire version so peer tunnels negotiate the codec per
connection; JSON row packets remain the mixed-version fallback.

Protocol safety: every request carries a correlation id echoed in the
response (a desynced stream is detected, the connection is poisoned
rather than returning the wrong query's rows); frames are capped; an
optional shared secret authenticates connections (the reference guards
this interior seam with cluster TLS certs — a bearer secret is the
dependency-free analog)."""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time as _time
from typing import List, Optional, Tuple

from tidb_tpu.parallel import wire
from tidb_tpu.planner.ir import IR_VERSION, plan_from_ir, plan_to_ir
from tidb_tpu.utils import racecheck

#: hard frame cap — a bogus length header must not buffer gigabytes
MAX_FRAME = 64 << 20


class QueryCancelled(RuntimeError):
    """Worker-side fragment/shuffle-task abort: the coordinator sent a
    ``cancel_query`` frame for this qid (KILL QUERY / statement
    timeout / admission revoke), or the dispatch's propagated deadline
    expired on this host. The reply carries ``cancelled: true`` so the
    coordinator distinguishes a deliberate abort from an engine error
    (no failover, no quarantine — the worker is healthy)."""


class CancelRegistry:
    """Per-server registry of cancelled query ids — the worker half of
    fleet-wide cancellation (reference: MPPTask cancellation via
    CancelMPPQuery, tiflash MPPTaskManager::abortMPPQuery). The cancel
    frame arrives on a DIFFERENT connection than the running dispatch
    (that stream is busy executing), marks the qid here, and every
    execution safepoint (PhysicalExecutor.kill_check, ShuffleWorker
    loop points, ShuffleStore wait aborts) polls it.

    Entries key on (coordinator instance id, qid): qids restart at 1
    after a coordinator restart (and two coordinators may share a
    fleet), so a bare qid cancelled by one incarnation would wrongly
    kill another's query — the same cross-instance collision the
    shuffle sids fence with their uuid prefix (parallel/dcn.py).
    Bounded: old entries age out, which is safe — an entry only
    matters while that exact query's dispatches are in flight."""

    _CAP = 1024

    def __init__(self):
        self._lock = racecheck.make_lock("engine_rpc.cancel")
        # (coord, qid) -> reason (insertion-ordered)
        self._cancelled: "dict" = {}

    def cancel(self, qid, reason: str = "", coord=None) -> None:
        with self._lock:
            self._cancelled[(str(coord), int(qid))] = str(
                reason or "cancelled"
            )
            while len(self._cancelled) > self._CAP:
                self._cancelled.pop(next(iter(self._cancelled)))

    def reason(self, qid, coord=None) -> Optional[str]:
        if qid is None:
            return None
        with self._lock:
            return self._cancelled.get((str(coord), int(qid)))

    def check(self, qid, coord=None) -> None:
        r = self.reason(qid, coord=coord)
        if r is not None:
            raise QueryCancelled(f"query q{qid} cancelled: {r}")


def make_cancel_check(registry: CancelRegistry, qid,
                      deadline_s: Optional[float] = None,
                      coord=None):
    """The worker-side safepoint check for one dispatched fragment or
    shuffle task: raises QueryCancelled when the coordinator cancelled
    this qid OR the dispatch-propagated deadline (``deadline_s``
    REMAINING seconds at dispatch time, converted to a local monotonic
    deadline here — wall clocks skew across hosts, remaining time does
    not) has expired. Plugged into PhysicalExecutor.kill_check and
    sqlkiller.set_current so blocking builtins and chaos hang hooks
    abort at the same safepoints KILL uses locally."""
    deadline = (
        _time.monotonic() + float(deadline_s)
        if deadline_s is not None else None
    )

    def check():
        if registry is not None:
            registry.check(qid, coord=coord)
        if deadline is not None and _time.monotonic() > deadline:
            raise QueryCancelled(
                f"query q{qid} cancelled: dispatch deadline exceeded"
            )

    return check


class _CheckKiller:
    """Adapter exposing a cancel check as the sqlkiller 'current
    killer' protocol (.check()) so utils/sqlkiller.current_check and
    interruptible_sleep observe fragment cancellation on worker
    threads exactly like KILL on session threads."""

    __slots__ = ("check",)

    def __init__(self, check):
        self.check = check


class SchemaOutOfDateError(RuntimeError):
    """The frontend planned against a schema version the engine has
    moved past (or not yet reached) — the analog of the domain schema
    lease check ('Information schema is out of date',
    pkg/domain/domain.go lease validation). The frontend must reload
    schemas and re-plan."""


class DropConnection(BaseException):
    """Raised by a failpoint to simulate abrupt worker death: the
    handler closes the connection WITHOUT a response frame, so the
    coordinator sees a transport loss (the work may or may not have
    happened — exactly the ambiguity fragment re-dispatch fences
    against). BaseException so the generic error-reply catch cannot
    swallow it into a polite error frame."""


def _send_frame(sock, payload: bytes) -> None:
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame of {len(payload)}B exceeds {MAX_FRAME}B")
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(sock) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        part = sock.recv(4 - len(hdr))
        if not part:
            return None
        hdr += part
    (n,) = struct.unpack("<I", hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n}B exceeds {MAX_FRAME}B")
    out = b""
    while len(out) < n:
        part = sock.recv(min(1 << 20, n - len(out)))
        if not part:
            return None
        out += part
    return out


class EngineServer:
    """Device-engine side: executes serialized plans over its catalog.
    Each connection gets its own PhysicalExecutor (the per-connection
    Session pattern of server.py — executors' plan caches are not
    thread-safe by design)."""

    def __init__(
        self,
        catalog,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: Optional[str] = None,
        mesh_devices: Optional[int] = None,
        ship_registry: bool = False,
        delta_replica: bool = False,
    ):
        self.catalog = catalog
        self.secret = secret
        # delta_replica: this process holds its OWN copy of the base
        # tables (worker processes — parallel/dcn_worker.py), so
        # coordinator DML reaches it only through delta_sync frames:
        # buffered per table, folded on compact barriers, merged into
        # routed reads (storage/delta.py). In-process servers sharing
        # the coordinator's catalog must NOT set this — their base IS
        # the fresh store, and delta frames ack as no-ops.
        self.delta_state = None
        if delta_replica:
            from tidb_tpu.storage.delta import DeltaReplicaState

            self.delta_state = DeltaReplicaState(catalog)
        # mesh_devices: this engine executes plans SPMD over its local
        # device mesh (intra-host ICI exchanges) — the worker-host shape
        # of the hierarchical DCN scheduler (parallel/dcn.py)
        self.mesh_devices = mesh_devices
        # ship_registry: piggyback this process's counter deltas on
        # fragment/shuffle replies so the coordinator's registry sees
        # fleet-wide engine activity. Worker PROCESSES enable this
        # (parallel/dcn_worker.py); in-process servers must not — they
        # share the coordinator's registry, and shipping would feed the
        # merged increments back into the next delta.
        self.ship_registry = ship_registry
        self._reg_lock = racecheck.make_lock("engine_rpc.registry")
        self._reg_snapshot: dict = {}
        # worker-side metric time-series shipping (obs/tsdb.py): this
        # process samples its OWN registry at a bounded cadence and
        # the pending rows piggyback on the next ship_registry reply —
        # or on a heartbeat ping (the idle-flush), so a worker with no
        # dispatches in flight still reports history. Same at-most-
        # once contract as the counter deltas: the buffer drains into
        # exactly one reply; a reply lost in transit (or fenced as a
        # late duplicate) drops its samples.
        self._tsdb_pending: list = []
        self._tsdb_last = 0.0
        #: min seconds between worker-side sample passes (bounds the
        #: piggyback overhead under rapid dispatch streams)
        self.tsdb_min_interval_s = 1.0
        # worker-to-worker shuffle service: the store this server's
        # shuffle_push frames land in plus the task runner
        # (parallel/shuffle.py); built lazily so plain engine servers
        # pay nothing
        self._shuffle = None
        self._shuffle_lock = racecheck.make_lock("engine_rpc.shuffle_init")
        # fleet-wide cancellation: qids cancelled by coordinator
        # cancel_query frames; every dispatch safepoint polls it
        self.cancels = CancelRegistry()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from tidb_tpu.planner.physical import PhysicalExecutor

                executor = PhysicalExecutor(
                    outer.catalog, mesh_devices=outer.mesh_devices
                )
                authed = outer.secret is None
                while True:
                    try:
                        frame = _recv_frame(self.request)
                    except ValueError:
                        return  # oversized frame: drop the connection
                    if frame is None:
                        return
                    req_id = None
                    try:
                        if wire.is_binary_frame(frame):
                            # binary columnar shuffle frame: the data
                            # plane never round-trips through JSON
                            req_id = wire.peek_request_id(frame)
                            if not authed:
                                import hmac

                                try:
                                    frame_auth = wire.peek_auth(frame)
                                except wire.WireFormatError:
                                    frame_auth = None
                                if not hmac.compare_digest(
                                    str(frame_auth or ""), outer.secret
                                ):
                                    _send_frame(
                                        self.request,
                                        json.dumps(
                                            {
                                                "id": req_id, "ok": False,
                                                "error":
                                                    "authentication failed",
                                            }
                                        ).encode(),
                                    )
                                    return
                                authed = True
                            # route off the sid namespace alone: the
                            # delta-sync data plane shares the binary
                            # codec with shuffle but lands in the
                            # replica state, not the shuffle store
                            try:
                                is_delta = wire.peek_sid(
                                    frame
                                ).startswith("delta://")
                            except wire.WireFormatError:
                                is_delta = False
                            if is_delta:
                                resp = outer._delta_sync_binary(frame)
                            else:
                                resp = outer._shuffle_push_binary(frame)
                            _send_frame(self.request, resp)
                            continue
                        t_dec0 = _time.perf_counter()
                        req = json.loads(frame.decode())
                        dec_s = _time.perf_counter() - t_dec0
                        req_id = req.get("id")
                        if not authed:
                            import hmac

                            if not hmac.compare_digest(
                                str(req.get("auth") or ""), outer.secret
                            ):
                                _send_frame(
                                    self.request,
                                    json.dumps(
                                        {
                                            "id": req_id, "ok": False,
                                            "error": "authentication failed",
                                        }
                                    ).encode(),
                                )
                                return
                            authed = True
                        if "shuffle_push" in req:
                            # peer tunnel frame (JSON fallback codec):
                            # a worker pushing one hash partition row
                            # packet of its fragment
                            resp = outer._shuffle_push(req, dec_s)
                        elif "shuffle_task" in req:
                            resp = outer._shuffle_task(req)
                        elif "shuffle_sample" in req:
                            resp = outer._shuffle_sample(req)
                        elif "shuffle_probe" in req:
                            resp = outer._shuffle_probe(req)
                        elif "cancel_query" in req:
                            resp = outer._cancel_query(req)
                        elif "delta_compact" in req:
                            resp = outer._delta_compact(req)
                        elif "delta_status" in req:
                            resp = outer._delta_status(req)
                        elif "engine_status" in req:
                            resp = outer._engine_status(req)
                        elif "plan" not in req:
                            # handshake/ping frame — fine whether or not
                            # this server requires a secret (a secreted
                            # client must interoperate with an open
                            # server). Advertises the binary shuffle
                            # wire version for per-tunnel codec
                            # negotiation.
                            # "ts" is this host's wall clock at reply
                            # build: with the client's send/receive
                            # timestamps it yields the RTT/2-anchored
                            # clock-offset estimate that rebases worker
                            # spans onto the coordinator timeline
                            from tidb_tpu.utils.failpoint import inject

                            ping = {
                                "id": req_id, "ok": True,
                                "wire": wire.WIRE_VERSION,
                                # engine/clock-skew: the chaos
                                # harness shifts this host's
                                # advertised clock so the offset
                                # estimator and span/timeline
                                # rebasing run under skew
                                "ts": _time.time() + float(
                                    inject("engine/clock-skew", 0)
                                    or 0
                                ),
                            }
                            if "topsql" in req:
                                # heartbeat-carried Top SQL profiler
                                # config: workers arm/disarm/re-tune
                                # even with no dispatch in flight
                                outer._apply_topsql(req.get("topsql"))
                            if outer.ship_registry and req.get(
                                "tsdb_flush"
                            ):
                                # idle-flush: a worker with nothing
                                # dispatched still ships its sampled
                                # history on the heartbeat cadence.
                                # Only EXPLICIT flush pings drain the
                                # buffer — every fresh connection
                                # handshakes with this frame shape and
                                # discards the reply, which would
                                # silently eat the pending samples
                                tsdb_rows = outer._tsdb_ship()
                                if tsdb_rows:
                                    ping["tsdb"] = tsdb_rows
                                topsql = outer._topsql_ship()
                                if topsql:
                                    ping["topsql"] = topsql
                            resp = json.dumps(ping).encode()
                        else:
                            resp = outer._execute(executor, req)
                    except DropConnection:
                        # failpoint-simulated worker death: no response
                        # frame — the peer sees the stream close
                        try:
                            self.request.close()
                        except OSError:
                            pass
                        return
                    except Exception as e:
                        err = {
                            "id": req_id, "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                        }
                        if isinstance(e, QueryCancelled):
                            # a deliberate abort, not an engine error:
                            # the coordinator must surface the kill,
                            # never fail over or quarantine
                            err["cancelled"] = True
                        resp = json.dumps(err).encode()
                    try:
                        _send_frame(self.request, resp)
                    except ValueError:
                        # success payload larger than MAX_FRAME: report
                        # instead of dropping the connection silently
                        _send_frame(
                            self.request,
                            json.dumps(
                                {
                                    "id": req_id, "ok": False,
                                    "error": (
                                        f"result exceeds {MAX_FRAME} bytes; "
                                        "narrow the query"
                                    ),
                                }
                            ).encode(),
                        )

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = TCP((host, port), Handler)
        self.port = self._tcp.server_address[1]

    def _execute(self, executor, req) -> bytes:
        from tidb_tpu.utils.failpoint import inject

        inject("engine/execute")
        from tidb_tpu.chunk import materialize_rows

        frag = req.get("frag")
        if frag is not None:
            # DCN fragment dispatch: a site before execution (dispatch
            # received, about to run — death here loses the fragment
            # cleanly) and one after (dcn/result-send below — death
            # there loses only the REPLY, the duplicate-redelivery case)
            inject("dcn/fragment-execute")
        if req.get("v") != IR_VERSION:
            raise ValueError(f"unsupported IR version {req.get('v')}")
        if "schema_v" in req:
            # schema-lease validation: a plan bound against stale
            # schemas must not execute — name/column resolution could
            # silently hit the wrong physical layout
            engine_v = getattr(self.catalog, "schema_version", 0)
            if int(req["schema_v"]) != int(engine_v):
                raise SchemaOutOfDateError(
                    f"schema out of date: engine at version {engine_v}, "
                    f"client planned at {req['schema_v']}; reload schemas"
                )
        plan = plan_from_ir(req["plan"])
        # snapshot isolation for routed dispatches: pin every scanned
        # table's base version for the WHOLE dispatch (version GC can
        # never collect an in-flight routed query's input) and, on a
        # delta replica, merge the snapshot's buffered deltas into the
        # plan as keyed Staged leaves (storage/delta.py)
        pins: list = []
        delta_stats = None
        snap = req.get("snap")
        conn_executor = executor
        if snap:
            from tidb_tpu.storage import delta as _delta

            plan, hook, delta_stats = _delta.prepare_worker_plan(
                self.catalog, self.delta_state, plan, snap, pins
            )
            if hook is not None:
                executor.table_hook = hook
            if delta_stats is not None and executor.mesh is not None:
                # a merged plan mixes sharded scans with replicated
                # Staged leaves; run it on this connection's plain
                # (single-device) executor — the SPMD mesh program is
                # a scan-throughput optimization, not a correctness
                # requirement
                from tidb_tpu.planner.physical import PhysicalExecutor

                plain = getattr(executor, "_delta_plain", None)
                if plain is None:
                    plain = PhysicalExecutor(self.catalog)
                    executor._delta_plain = plain
                plain.table_hook = executor.table_hook
                executor = plain
        try:
            return self._execute_inner(
                executor, req, plan, frag, delta_stats
            )
        finally:
            # clear BOTH executors' hooks: a merged dispatch swaps to
            # the plain executor but the connection executor's hook was
            # set first — a dangling hook would leak this snapshot's
            # resolution into the next request on this connection
            executor.table_hook = None
            conn_executor.table_hook = None
            for t, v in pins:
                t.unpin(v)

    def _execute_inner(
        self, executor, req, plan, frag, delta_stats
    ) -> bytes:
        from tidb_tpu.chunk import materialize_rows
        from tidb_tpu.utils.failpoint import inject

        tracer = None
        if frag is not None:
            # trace context propagated over the RPC seam: the
            # coordinator's (query id, fragment id) labels every span
            # this worker records, and the spans ship back in the reply
            # for host-labeled merge into the coordinator's Tracer.
            # Span collection is opt-in per dispatch (frag["trace"], set
            # from the coordinator tracer's enabled flag) so untraced
            # production queries pay neither the Tracer nor the span
            # payload in every reply; runtime stats always ship.
            from tidb_tpu.utils.tracing import Tracer

            tracer = Tracer()  # disabled by default: span() is a no-op
            if frag.get("trace"):
                tracer.enabled = True
                tracer.reset()
            ctx = f"q{frag.get('qid')}/f{frag.get('fid')}"
            # per-fragment engine-watch record: this worker's OWN
            # device-mem high-water and compile cost for the slice it
            # ran — shipped in the reply stats so admission estimates
            # learn from worker-eyed peaks (the coordinator-side
            # estimate sees a different, usually smaller, shape)
            from tidb_tpu.obs.engine_watch import (
                ENGINE_WATCH,
                set_cost_wanted,
            )

            ENGINE_WATCH.begin_query(f"frag {ctx}")
            # a timeline-captured dispatch asks this worker to harvest
            # XLA cost analysis for whatever it compiles (thread-scoped)
            set_cost_wanted(bool(frag.get("timeline")))
            # fleet cancellation safepoints: the engine's kill_check
            # polls the cancel registry + the dispatch-propagated
            # deadline, and sqlkiller's thread-local current killer
            # makes interruptible waits (and chaos hang hooks) abort
            # on the same signal
            from tidb_tpu.utils import sqlkiller as _sk

            check = make_cancel_check(
                self.cancels, frag.get("qid"), frag.get("deadline_s"),
                coord=frag.get("coord"),
            )
            executor.kill_check = check
            _sk.set_current(_CheckKiller(check))
            # Top SQL (obs/profiler.py): the dispatch carries the
            # profiler config + the statement digest this fragment
            # belongs to — arm/retune the local sampler and register
            # this handler thread so its samples attribute to that
            # digest (no context, no attribution: a finished or
            # foreign qid can never be charged)
            from tidb_tpu.obs import profiler as _topsql

            ts_cfg = frag.get("topsql")
            self._apply_topsql(ts_cfg)
            ts_prev = _topsql.begin_task(
                "fragment",
                digest=(ts_cfg or {}).get("digest"),
                phase="execute",
            )
            t_exec0 = _time.perf_counter()
            t_wall0 = _time.time()
            try:
                check()
                with tracer.span(f"{ctx}/execute"):
                    batch, dicts = executor.run(plan)
                with tracer.span(f"{ctx}/materialize"):
                    rows = materialize_rows(
                        batch, list(plan.schema), dicts
                    )
            except BaseException:
                ENGINE_WATCH.end_query(
                    _time.perf_counter() - t_exec0
                )
                raise
            finally:
                _topsql.end_task(ts_prev)
                set_cost_wanted(False)
                executor.kill_check = None
                _sk.set_current(None)
            exec_s = _time.perf_counter() - t_exec0
            frag_watch = {
                "mem_peak_bytes": ENGINE_WATCH.current_peak_bytes(),
                "compile": ENGINE_WATCH.current_compile_cost() or None,
            }
            frag_events = None
            if frag.get("timeline"):
                from tidb_tpu.obs.timeline import TimelineBuffer

                tb = TimelineBuffer()
                tb.emit_event(
                    "fragment", f"execute {ctx}", t_wall0, exec_s,
                    track=ctx,
                    args={"attempt": frag.get("attempt", 1)},
                )
                frag_events = tb.events
            ENGINE_WATCH.end_query(exec_s)
        else:
            batch, dicts = executor.run(plan)
            rows = materialize_rows(batch, list(plan.schema), dicts)
        if frag is not None:
            # mid-shuffle worker death AFTER the work, BEFORE the reply:
            # the coordinator must re-dispatch, and its ledger must
            # accept the retry's result exactly once
            inject("dcn/result-send")
        resp = {
            "id": req.get("id"),
            "ok": True,
            "columns": [c.name for c in plan.schema],
            "rows": rows,
        }
        if frag is not None:
            resp["frag"] = frag
            if tracer.enabled:
                resp["spans"] = [
                    [s.name, s.start_s, s.dur_s, s.depth]
                    for s in tracer.spans
                ]
                # this worker's wall clock at tracer reset: with the
                # handshake clock-offset sample the coordinator rebases
                # spans onto its own timeline instead of anchoring at
                # reply receipt
                resp["trace_t0"] = tracer.wall_t0
            # no byte count here: the coordinator measures the actual
            # reply frame length (EngineClient stamps _nbytes), which is
            # what really crossed the DCN link — and avoids serializing
            # the row set twice on the reply hot path
            resp["stats"] = {
                "rows": len(rows),
                "exec_s": exec_s,
                "host": f"{socket.gethostname()}:{self.port}",
                # worker-eyed engine accounting for THIS fragment
                "mem_peak_bytes": frag_watch["mem_peak_bytes"],
                "compile": frag_watch["compile"],
            }
            if delta_stats is not None:
                # this fragment merged buffered deltas: depth / rows /
                # delete keys ride the reply for the coordinator's
                # EXPLAIN ANALYZE DeltaMerge row
                resp["stats"]["delta"] = delta_stats
            if frag_events:
                resp["events"] = frag_events
            if self.ship_registry:
                # fleet observability: this process's counter movement
                # rides the reply; the coordinator merges it behind the
                # ledger fence (at-most-once: a lost/fenced reply drops
                # its delta — see utils/metrics.py fleet-merge notes)
                resp["registry"] = self._registry_delta()
                tsdb_rows = self._tsdb_ship()
                if tsdb_rows:
                    resp["tsdb"] = tsdb_rows
                topsql = self._topsql_ship()
                if topsql:
                    resp["topsql"] = topsql
        return json.dumps(resp).encode()

    # -- worker-to-worker shuffle (parallel/shuffle.py) -----------------
    def shuffle_worker(self):
        with self._shuffle_lock:
            if self._shuffle is None:
                from tidb_tpu.parallel.shuffle import ShuffleWorker

                self._shuffle = ShuffleWorker(
                    self.catalog,
                    self_address=f"{socket.gethostname()}:{self.port}",
                    mesh_devices=self.mesh_devices,
                    delta_state=self.delta_state,
                )
            return self._shuffle

    def _shuffle_push(self, req, decode_s: float = 0.0) -> bytes:
        """A peer worker's JSON-fallback tunnel packet: land the rows
        in the local store (attempt-fenced, seq-deduped) and ack."""
        from tidb_tpu.parallel.shuffle import _c_decode_seconds
        from tidb_tpu.utils.failpoint import inject

        inject("shuffle/recv")
        _c_decode_seconds().labels(codec="json").inc(decode_s)
        p = req["shuffle_push"]
        accepted = self.shuffle_worker().store.push(
            p["sid"], int(p["attempt"]), int(p["m"]), int(p["side"]),
            int(p["sender"]), int(p.get("seq", -1)), p.get("rows"),
            nseq=p.get("nseq"),
        )
        if inject("shuffle/recv-ack-lost"):
            # packet stored, ack lost: the sender retransmits and the
            # seq dedupe drops the duplicate — exactly-once on the wire
            raise DropConnection()
        # shuffle-json-fallback: the tiny control-plane ack stays JSON
        return json.dumps(
            {"id": req.get("id"), "ok": True, "accepted": bool(accepted)}
        ).encode()

    def _shuffle_push_binary(self, frame: bytes) -> bytes:
        """A peer worker's binary columnar tunnel frame, decoded ON
        ARRIVAL (the receive half of the shuffle pipeline — decode
        overlaps the producers still in flight, and ShuffleStore waits
        return already-decoded blocks). The exactly-once fences run
        FIRST, off the header alone (wire.decode_header): a
        stale-attempt or duplicate/retransmitted frame is dropped
        before any column decode work is spent on it — and therefore
        can never double-stage. A frame that fails to decode
        (corruption, version skew inside a negotiated stream — the
        shuffle/decode failpoint injects both) is REJECTED with an
        error reply over the live connection: the sender surfaces it
        as a non-retryable engine error, so a corrupt frame aborts the
        stage instead of masquerading as a peer death and triggering a
        pointless stage retry."""
        from tidb_tpu.parallel.shuffle import (
            _c_decode_on_arrival_seconds,
            _c_decode_seconds,
        )
        from tidb_tpu.utils.failpoint import inject

        inject("shuffle/recv")
        store = self.shuffle_worker().store
        t0 = _time.perf_counter()
        try:
            hdr = wire.decode_header(frame)
            if not hdr["eof"] and not store.admits(
                hdr["sid"], hdr["attempt"], hdr["side"], hdr["sender"],
                hdr["seq"],
            ):
                # fenced from the header — no decode work wasted, and
                # a retransmit can never double-stage
                # shuffle-json-fallback: control-plane ack stays JSON
                return json.dumps(
                    {"id": hdr["id"], "ok": True, "accepted": False}
                ).encode()
            inject("shuffle/decode")
            pkt = wire.decode_frame(frame, header=hdr)
        except Exception as e:
            # shuffle-json-fallback: the error REPLY is control-plane
            return json.dumps(
                {
                    "id": wire.peek_request_id(frame), "ok": False,
                    "error": f"ShuffleDecodeError: {e}",
                }
            ).encode()
        dec_s = _time.perf_counter() - t0
        _c_decode_seconds().labels(codec="binary").inc(dec_s)
        _c_decode_on_arrival_seconds().inc(dec_s)
        payload = pkt["block"]
        accepted = store.push(
            pkt["sid"], pkt["attempt"], pkt["m"], pkt["side"],
            pkt["sender"], pkt["seq"], payload, nseq=pkt["nseq"],
        )
        if inject("shuffle/recv-ack-lost"):
            raise DropConnection()
        # shuffle-json-fallback: the tiny control-plane ack stays JSON
        return json.dumps(
            {"id": pkt["id"], "ok": True, "accepted": bool(accepted)}
        ).encode()

    def _shuffle_task(self, req) -> bytes:
        """One dispatched shuffle stage task: produce + push + wait +
        consume (ShuffleWorker.run_task). Retryable stage failures
        (dead peers, missing producers) reply with a suspect list the
        coordinator verifies before re-running the stage on the
        survivor set."""
        from tidb_tpu.parallel.shuffle import ShuffleAbort
        from tidb_tpu.utils.tracing import Tracer

        if req.get("v") != IR_VERSION:
            raise ValueError(f"unsupported IR version {req.get('v')}")
        spec = req["shuffle_task"]
        if "schema_v" in req:
            engine_v = getattr(self.catalog, "schema_version", 0)
            if int(req["schema_v"]) != int(engine_v):
                raise SchemaOutOfDateError(
                    f"schema out of date: engine at version {engine_v}, "
                    f"client planned at {req['schema_v']}; reload schemas"
                )
        tracer = Tracer()
        if spec.get("trace"):
            tracer.enabled = True
            tracer.reset()
        # per-task engine-watch record: worker-eyed device-mem peak +
        # compile cost ride the reply stats (see _execute)
        from tidb_tpu.obs.engine_watch import (
            ENGINE_WATCH,
            set_cost_wanted,
        )

        ENGINE_WATCH.begin_query(
            f"shuffle {spec.get('sid')}/p{spec.get('part')}"
        )
        set_cost_wanted(bool(spec.get("timeline")))
        # fleet cancellation: the task polls this at its loop points
        # (produce chunks, shipper chunks, store waits, consume) and
        # the thread-local current killer covers interruptible sleeps
        from tidb_tpu.utils import sqlkiller as _sk

        check = make_cancel_check(
            self.cancels, spec.get("qid"), spec.get("deadline_s"),
            coord=spec.get("coord"),
        )
        _sk.set_current(_CheckKiller(check))
        # Top SQL: dispatch-carried config + digest; run_task updates
        # the live phase (produce/push/wait/stage) on this context
        from tidb_tpu.obs import profiler as _topsql

        ts_cfg = spec.get("topsql")
        self._apply_topsql(ts_cfg)
        ts_prev = _topsql.begin_task(
            "shuffle",
            digest=(ts_cfg or {}).get("digest"),
            phase="shuffle-produce",
        )
        t0 = _time.perf_counter()
        try:
            result = self.shuffle_worker().run_task(
                spec, tracer=tracer, cancel_check=check
            )
        except ShuffleAbort as e:
            ENGINE_WATCH.end_query(_time.perf_counter() - t0)
            return json.dumps(
                {
                    "id": req.get("id"), "ok": False, "retryable": "shuffle",
                    "suspects": e.suspects, "error": str(e),
                }
            ).encode()
        except BaseException:
            ENGINE_WATCH.end_query(_time.perf_counter() - t0)
            raise
        finally:
            _topsql.end_task(ts_prev)
            set_cost_wanted(False)
            _sk.set_current(None)
        exec_s = _time.perf_counter() - t0
        task_watch = {
            "mem_peak_bytes": ENGINE_WATCH.current_peak_bytes(),
            "compile": ENGINE_WATCH.current_compile_cost() or None,
        }
        ENGINE_WATCH.end_query(exec_s)
        resp = {
            "id": req.get("id"),
            "ok": True,
            "columns": result["columns"],
            "rows": result["rows"],
            "shuffle": result["shuffle"],
            "stats": {
                # a mid-DAG stage HOLDS its output (rows ship nothing
                # back): report the held partition's row count so
                # per-stage ShuffleExchange rows stay informative
                "rows": len(result["rows"]) or int(
                    result["shuffle"].get("held_rows", 0) or 0
                ),
                "exec_s": exec_s,
                "host": f"{socket.gethostname()}:{self.port}",
                "mem_peak_bytes": task_watch["mem_peak_bytes"],
                "compile": task_watch["compile"],
            },
        }
        if result.get("events"):
            resp["events"] = result["events"]
        if tracer.enabled:
            resp["spans"] = [
                [s.name, s.start_s, s.dur_s, s.depth] for s in tracer.spans
            ]
            resp["trace_t0"] = tracer.wall_t0
        if self.ship_registry:
            resp["registry"] = self._registry_delta()
            tsdb_rows = self._tsdb_ship()
            if tsdb_rows:
                resp["tsdb"] = tsdb_rows
            topsql = self._topsql_ship()
            if topsql:
                resp["topsql"] = topsql
        return json.dumps(resp).encode()

    def _shuffle_sample(self, req) -> bytes:
        """Boundary-sampling round of a range exchange stage
        (ShuffleWorker.run_sample): produce-and-cache this worker's
        side, reply a deterministic key sample for the coordinator's
        merged quantile cut. A lost reply (shuffle/sample-lost) is a
        transport suspect the coordinator verifies like any dispatch
        loss; retryable failures (a held StageInput missing after a
        worker restart) reply with the suspect taxonomy of
        _shuffle_task so the whole DAG retries on the survivor set."""
        from tidb_tpu.parallel.shuffle import ShuffleAbort
        from tidb_tpu.utils import sqlkiller as _sk
        from tidb_tpu.utils.failpoint import inject

        if req.get("v") != IR_VERSION:
            raise ValueError(f"unsupported IR version {req.get('v')}")
        spec = req["shuffle_sample"]
        check = make_cancel_check(
            self.cancels, spec.get("qid"), spec.get("deadline_s"),
            coord=spec.get("coord"),
        )
        _sk.set_current(_CheckKiller(check))
        from tidb_tpu.obs import profiler as _topsql

        ts_cfg = spec.get("topsql")
        self._apply_topsql(ts_cfg)
        ts_prev = _topsql.begin_task(
            "sample",
            digest=(ts_cfg or {}).get("digest"),
            phase="shuffle-produce",
        )
        try:
            result = self.shuffle_worker().run_sample(
                spec, cancel_check=check
            )
        except ShuffleAbort as e:
            return json.dumps(
                {
                    "id": req.get("id"), "ok": False,
                    "retryable": "shuffle", "suspects": e.suspects,
                    "error": str(e),
                }
            ).encode()
        finally:
            _topsql.end_task(ts_prev)
            _sk.set_current(None)
        if inject("shuffle/sample-lost"):
            raise DropConnection()
        return json.dumps(
            {
                "id": req.get("id"), "ok": True,
                "samples": result["samples"], "rows": result["rows"],
            }
        ).encode()

    def _shuffle_probe(self, req) -> bytes:
        """AQE skew/cardinality probe round (ShuffleWorker.run_probe,
        parallel/aqe.py): produce-and-cache every side of a hash
        stage, reply each side's exact per-partition row histogram +
        hottest keys. Taxonomy mirrors _shuffle_sample: a lost reply
        (aqe/probe-lost) is a transport suspect the coordinator
        verifies; retryable failures carry the suspect list."""
        from tidb_tpu.parallel.shuffle import ShuffleAbort
        from tidb_tpu.utils import sqlkiller as _sk
        from tidb_tpu.utils.failpoint import inject

        if req.get("v") != IR_VERSION:
            raise ValueError(f"unsupported IR version {req.get('v')}")
        spec = req["shuffle_probe"]
        check = make_cancel_check(
            self.cancels, spec.get("qid"), spec.get("deadline_s"),
            coord=spec.get("coord"),
        )
        _sk.set_current(_CheckKiller(check))
        from tidb_tpu.obs import profiler as _topsql

        ts_cfg = spec.get("topsql")
        self._apply_topsql(ts_cfg)
        ts_prev = _topsql.begin_task(
            "sample",
            digest=(ts_cfg or {}).get("digest"),
            phase="shuffle-produce",
        )
        try:
            result = self.shuffle_worker().run_probe(
                spec, cancel_check=check
            )
        except ShuffleAbort as e:
            return json.dumps(
                {
                    "id": req.get("id"), "ok": False,
                    "retryable": "shuffle", "suspects": e.suspects,
                    "error": str(e),
                }
            ).encode()
        finally:
            _topsql.end_task(ts_prev)
            _sk.set_current(None)
        if inject("aqe/probe-lost"):
            raise DropConnection()
        return json.dumps(
            {
                "id": req.get("id"), "ok": True,
                "sides": result["sides"],
            }
        ).encode()

    def _cancel_query(self, req) -> bytes:
        """Fleet-wide cancellation, worker half: mark the qid in the
        cancel registry (running fragments/shuffle tasks abort at
        their next safepoint) and free the query's staged shuffle
        buffers NOW — the sid is poisoned so in-flight frames from
        still-pushing peers cannot resurrect an orphan stage record
        (``tidbtpu_shuffle_stages_buffered`` returns to 0 without
        waiting for the eviction window). Held shuffle-DAG blocks of
        the qid drop with it."""
        c = req["cancel_query"]
        self.cancels.cancel(
            c.get("qid"), c.get("reason"), coord=c.get("coord")
        )
        sid = c.get("sid")
        if sid is not None and self._shuffle is not None:
            self._shuffle.store.poison(str(sid))
        if self._shuffle is not None:
            self._shuffle._held_prune(c.get("coord"), c.get("qid"))
        return json.dumps({"id": req.get("id"), "ok": True}).encode()

    # -- delta tier (storage/delta.py) ----------------------------------
    def _delta_sync_binary(self, frame: bytes) -> bytes:
        """One delta-sync frame from the coordinator's replicator:
        decode (binary columnar codec — the delta data plane never
        rides JSON) and buffer it in the replica state, seq-fenced so
        a retransmit can never double-buffer. Servers sharing the
        coordinator's catalog (no replica state) ack without applying:
        their base IS the fresh store. The ``delta/sync-loss``
        failpoint drops the ack AFTER the apply — the chaos frame-loss
        shape the seq fence exists for."""
        from tidb_tpu.utils.failpoint import inject

        try:
            pkt = wire.decode_frame(frame)
        except Exception as e:
            # delta-json-control: the error REPLY is control-plane
            return json.dumps(
                {
                    "id": wire.peek_request_id(frame), "ok": False,
                    "error": f"DeltaDecodeError: {e}",
                }
            ).encode()
        if self.delta_state is not None:
            acked = self.delta_state.apply_frame(pkt)
        else:
            acked = int(pkt["seq"])
        if inject("delta/sync-loss"):
            raise DropConnection()
        # delta-json-control: the tiny ack stays JSON
        return json.dumps(
            {"id": pkt["id"], "ok": True, "acked": acked}
        ).encode()

    def _delta_compact(self, req) -> bytes:
        """Fold barrier: fold buffered deltas <= up_to into the local
        base through the existing columnar write path, retaining the
        previous fold's pinned base version for in-flight snapshots.
        No-op ack on shared-catalog servers and on re-shipped
        barriers (idempotent)."""
        c = req["delta_compact"]
        if self.delta_state is not None:
            acked = self.delta_state.apply_compact(
                int(c["up_to"]), int(c["seq"])
            )
        else:
            acked = int(c["seq"])
        return json.dumps(
            {"id": req.get("id"), "ok": True, "acked": acked}
        ).encode()

    def _delta_status(self, req) -> bytes:
        """Replica-state introspection (tests + chaos invariants)."""
        state = (
            self.delta_state.status()
            if self.delta_state is not None else None
        )
        return json.dumps(
            {"id": req.get("id"), "ok": True, "delta": state}
        ).encode()

    def _engine_status(self, req) -> bytes:
        """Worker introspection frame (tests + chaos invariants): the
        shuffle store's buffered-stage count and the live shuffle
        worker threads on this host — both must return to zero after a
        cancelled or failed stage (the abort-path leak check)."""
        stages = 0
        held = 0
        if self._shuffle is not None:
            stages = self._shuffle.store.buffered_stages()
            held = self._shuffle.held_count()
        shuffle_threads = [
            t.name for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("shuffle-")
        ]
        return json.dumps(
            {
                "id": req.get("id"), "ok": True,
                "stages_buffered": stages,
                "held_outputs": held,
                "shuffle_threads": shuffle_threads,
            }
        ).encode()

    def _registry_delta(self):
        from tidb_tpu.utils.metrics import counter_delta

        with self._reg_lock:
            delta, self._reg_snapshot = counter_delta(self._reg_snapshot)
        return delta

    def _apply_topsql(self, cfg) -> None:
        """Apply a dispatch/ping-carried Top SQL profiler config to
        THIS process's sampler (obs/profiler.py). Worker processes
        only (ship_registry): in-process servers share the
        coordinator's profiler, which the SET GLOBAL hook already
        configured — a second applier would fight it."""
        if not self.ship_registry:
            return
        from tidb_tpu.obs.profiler import TOPSQL

        try:
            TOPSQL.apply_config(cfg)
        except Exception:
            pass  # profiler config must never fail a dispatch

    def _topsql_ship(self):
        """Drain this process's pending Top SQL deltas (collapsed
        stacks + per-digest aggregates) into ONE reply — the
        _tsdb_ship contract: at-most-once, a lost reply drops its
        batch, idle replies stay small."""
        from tidb_tpu.obs.profiler import TOPSQL

        return TOPSQL.store.ship()

    def _tsdb_ship(self):
        """Sample this process's registry (bounded cadence) and drain
        the pending rows into ONE reply: ``[name, [labelnames],
        [labelvalues], ts, value, kind]`` in this worker's wall clock
        (the coordinator rebases through the handshake offset at
        merge). Returns None when nothing is pending — idle pings stay
        small."""
        from tidb_tpu.utils.metrics import sample_rows

        now = _time.time()
        with self._reg_lock:
            if now - self._tsdb_last >= self.tsdb_min_interval_s:
                self._tsdb_last = now
                for name, ln, lv, value, kind in sample_rows():
                    self._tsdb_pending.append(
                        [name, list(ln), list(lv), now, value, kind]
                    )
                if len(self._tsdb_pending) > 8192:
                    # bounded buffer: a coordinator that stopped
                    # draining must not grow worker memory — oldest
                    # samples drop first
                    del self._tsdb_pending[:-8192]
            out, self._tsdb_pending = self._tsdb_pending, []
        return out or None

    def start_background(self) -> threading.Thread:
        th = threading.Thread(
            target=self._tcp.serve_forever, daemon=True,
            name=f"engine-rpc-{self.port}",
        )
        th.start()
        return th

    def shutdown(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


class EngineClient:
    """Frontend side: holds only schemas; data lives on the engine."""

    def __init__(
        self,
        host: str,
        port: int,
        secret: Optional[str] = None,
        timeout_s: float = 60.0,
    ):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._secret = secret
        self._next_id = 0
        self._dead = False
        #: filled by the eager handshake below: the server's advertised
        #: shuffle wire version (per-tunnel codec negotiation) and a
        #: clock-offset sample — offset = server_ts - (t0 + t1)/2, the
        #: classic request/reply RTT/2 anchor (error bounded by RTT/2).
        #: The DCN scheduler uses the offset to rebase worker span
        #: clocks onto the coordinator timeline.
        self.server_wire = 0
        self.clock_offset_s: Optional[float] = None
        self.clock_rtt_s: Optional[float] = None
        # one eager handshake per connection: authenticates (bad
        # credentials fail at connect), learns the wire version, and
        # samples the peer clock
        try:
            t0 = _time.time()
            resp = self._call({} if secret is None else {"auth": secret})
            t1 = _time.time()
        except Exception:
            self._sock.close()
            raise
        if not resp.get("ok"):
            self._sock.close()
            raise PermissionError(resp.get("error", "auth failed"))
        self.server_wire = int(resp.get("wire", 0))
        ts = resp.get("ts")
        if ts is not None:
            self.clock_rtt_s = t1 - t0
            self.clock_offset_s = float(ts) - (t0 + t1) / 2.0

    def _call(self, req: dict) -> dict:
        """One correlated request/response. Any transport error or id
        mismatch poisons the connection — a desynced stream must never
        hand one query another query's rows."""
        if self._dead:
            raise ConnectionError("engine connection is poisoned; reconnect")
        self._next_id += 1
        req = dict(req)
        req["id"] = self._next_id
        if self._secret is not None:
            req["auth"] = self._secret
        return self._roundtrip(json.dumps(req).encode())

    def _roundtrip(self, payload: bytes) -> dict:
        """Ship one already-encoded frame (its "id" must be
        self._next_id) and read the correlated response."""
        if len(payload) > MAX_FRAME:
            # nothing was written: the stream is still synchronized, so
            # don't poison the connection over a local size check
            raise ValueError(
                f"request of {len(payload)}B exceeds {MAX_FRAME}B"
            )
        try:
            _send_frame(self._sock, payload)
            frame = _recv_frame(self._sock)
        except Exception:
            self._dead = True
            self._sock.close()
            raise
        if frame is None:
            self._dead = True
            raise ConnectionError("engine closed the connection")
        resp = json.loads(frame.decode())
        if isinstance(resp, dict):
            # wire-level reply size: the DCN exchange volume a fragment
            # actually staged through the coordinator
            resp["_nbytes"] = len(frame)
        if resp.get("id") != self._next_id:
            self._dead = True
            self._sock.close()
            raise ConnectionError(
                f"response id {resp.get('id')} != request id {self._next_id}"
            )
        return resp

    def call(self, req: dict) -> dict:
        """One correlated raw request (shuffle task dispatch and other
        non-plan frames); the caller interprets the response dict."""
        return self._call(req)

    def cancel_query(self, qid, sid=None, reason: str = "",
                     coord=None) -> bool:
        """Fleet-wide cancellation, coordinator half: tell this worker
        to abort everything it runs for ``qid`` under coordinator
        instance ``coord`` (and free the stage ``sid``'s shuffle
        buffers). Control-plane frame on THIS connection — callers use
        a dedicated short-lived connection, never a stream with a
        dispatch in flight."""
        resp = self._call(
            {"cancel_query": {
                "qid": qid, "sid": sid, "reason": reason,
                "coord": coord,
            }}
        )
        return bool(resp.get("ok"))

    def engine_status(self) -> dict:
        """Worker introspection (tests + chaos invariants): buffered
        shuffle stages and live shuffle threads on the peer."""
        return self._call({"engine_status": True})

    def shuffle_push(self, packet: dict) -> bool:
        """Push one shuffle partition packet to this peer; returns the
        receiver's accepted flag (False = fenced/deduped, which is fine
        — the data is already accounted for)."""
        resp = self._call({"shuffle_push": packet})
        if not resp.get("ok"):
            raise RuntimeError(
                f"shuffle push rejected: {resp.get('error', '')}"
            )
        return bool(resp.get("accepted"))

    def shuffle_push_encoded(self, payload: bytes) -> bool:
        """shuffle_push over a PRE-ENCODED packet — a binary columnar
        frame (parallel/wire.py) or a `{"shuffle_push": {...}}` JSON
        object: the data plane serializes each packet exactly once (at
        enqueue, where the flow-control window is sized) and the
        correlation id / auth are spliced in at the byte level by the
        shared wire.splice_id_auth helper instead of re-encoding the
        rows on the tunnel thread."""
        return self.shuffle_push_encoded_many([payload])[0]

    def shuffle_push_encoded_many(self, payloads) -> List[bool]:
        """Pipelined shuffle push: write EVERY payload's frame onto the
        socket back to back, THEN read the acks in order — one wire
        round trip amortized over the batch instead of a synchronous
        request/response per packet (the per-frame ack latency was the
        dominant serial tail of a shuffle push stream; the server's
        per-connection loop replies in order, so request pipelining is
        safe). Any transport loss or id mismatch poisons the
        connection; the caller (PeerTunnel) reconnects and retransmits
        the WHOLE unacked batch — the receiver's seq dedupe makes that
        exactly-once."""
        if self._dead:
            raise ConnectionError("engine connection is poisoned; reconnect")
        ids = []
        out = bytearray()
        for payload in payloads:
            self._next_id += 1
            ids.append(self._next_id)
            frame = wire.splice_id_auth(
                payload, self._next_id, self._secret
            )
            if len(frame) > MAX_FRAME:
                raise ValueError(
                    f"request of {len(frame)}B exceeds {MAX_FRAME}B"
                )
            out += struct.pack("<I", len(frame)) + frame
        accepted: List[bool] = []
        try:
            self._sock.sendall(out)
            for want_id in ids:
                frame = _recv_frame(self._sock)
                if frame is None:
                    raise ConnectionError("engine closed the connection")
                resp = json.loads(frame.decode())
                if resp.get("id") != want_id:
                    raise ConnectionError(
                        f"response id {resp.get('id')} != request id "
                        f"{want_id}"
                    )
                if not resp.get("ok"):
                    raise RuntimeError(
                        f"shuffle push rejected: {resp.get('error', '')}"
                    )
                accepted.append(bool(resp.get("accepted")))
        except Exception:
            # transport loss, id desync, OR an engine-side rejection
            # mid-batch (replies for the rest of the batch are still
            # queued on the stream): poison the connection so stale
            # replies can never correlate to later requests
            self._dead = True
            self._sock.close()
            raise
        return accepted

    def delta_sync_encoded(self, payload: bytes) -> int:
        """Ship one pre-encoded binary delta-sync frame
        (storage/delta.py encode_entry_frames); returns the worker's
        acked seq. The correlation id and auth splice in at the byte
        level — the delta data plane serializes each entry exactly
        once, like the shuffle push path."""
        if self._dead:
            raise ConnectionError("engine connection is poisoned; reconnect")
        self._next_id += 1
        frame = wire.splice_id_auth(payload, self._next_id, self._secret)
        resp = self._roundtrip(frame)
        if not resp.get("ok"):
            raise RuntimeError(
                f"delta sync rejected: {resp.get('error', '')}"
            )
        return int(resp.get("acked", 0))

    def execute_plan(
        self, plan, schema_version: Optional[int] = None, frag=None,
        snap=None,
    ) -> Tuple[List[str], List[tuple]]:
        cols, rows, _resp = self.execute_plan_full(
            plan, schema_version=schema_version, frag=frag, snap=snap
        )
        return cols, rows

    def execute_plan_full(
        self, plan, schema_version: Optional[int] = None, frag=None,
        snap=None,
    ) -> Tuple[List[str], List[tuple], dict]:
        """execute_plan plus the raw response — fragment dispatches read
        the worker's span list and runtime stats out of it. ``snap``
        (the routed snapshot: pinned base versions + delta fold/seq)
        rides every dispatch of one query so all its fragments read
        one consistent base."""
        req = {"v": IR_VERSION, "plan": plan_to_ir(plan)}
        if schema_version is not None:
            req["schema_v"] = int(schema_version)
        if snap is not None:
            req["snap"] = snap
        if frag is not None:
            # fragment metadata (query id / fragment id / attempt): the
            # trace context — echoed in the response for the
            # coordinator's ledger, labels the worker's spans, and is
            # visible to the worker-side dcn/* failpoints
            req["frag"] = frag
        resp = self._call(req)
        if not resp.get("ok"):
            err = str(resp.get("error", ""))
            if resp.get("cancelled"):
                # deliberate worker-side abort (fleet cancel /
                # propagated deadline): typed so the scheduler treats
                # it as a kill, never an engine error or a death
                raise QueryCancelled(err)
            if "SchemaOutOfDateError" in err:
                raise SchemaOutOfDateError(err)
            raise RuntimeError(f"engine error: {err}")
        return resp["columns"], [tuple(r) for r in resp["rows"]], resp

    def close(self) -> None:
        self._dead = True
        self._sock.close()
