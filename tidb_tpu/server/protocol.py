"""MySQL client/server wire protocol (text protocol subset).

Reference: pkg/server — handshake + dispatch (conn.go:1009,1247), result
encoding (conn.go:2228,2286). Implements protocol 4.1 text protocol:
handshake v10, any-password auth (the embedded engine trusts local
clients, like the reference with auth disabled), COM_QUERY/PING/QUIT/
INIT_DB, OK/ERR/EOF and text resultsets. Enough for the mysql CLI,
drivers and BI tools speaking the classic protocol.
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple

from tidb_tpu.dtypes import Kind, SQLType, days_to_date

CLIENT_PROTOCOL_41 = 0x0200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_TRANSACTIONS = 0x2000

SERVER_STATUS_AUTOCOMMIT = 0x0002

MYSQL_TYPE_DOUBLE = 5
MYSQL_TYPE_NULL = 6
MYSQL_TYPE_LONGLONG = 8
MYSQL_TYPE_DATE = 10
MYSQL_TYPE_TIME = 11
MYSQL_TYPE_DATETIME = 12
MYSQL_TYPE_VAR_STRING = 253
MYSQL_TYPE_NEWDECIMAL = 246
MYSQL_TYPE_TINY = 1


def lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


class PacketIO:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def reset_seq(self) -> None:
        self.seq = 0

    def read_packet(self) -> Optional[bytes]:
        hdr = self._read_n(4)
        if hdr is None:
            return None
        length = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
        self.seq = (hdr[3] + 1) & 0xFF
        body = self._read_n(length)
        return body

    def _read_n(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def write_packet(self, payload: bytes) -> None:
        out = b""
        while True:
            part = payload[: 0xFFFFFF]
            payload = payload[0xFFFFFF:]
            out += struct.pack("<I", len(part))[:3] + bytes([self.seq])
            out += part
            self.seq = (self.seq + 1) & 0xFF
            if len(part) < 0xFFFFFF:
                break
        self.sock.sendall(out)


def new_scramble() -> bytes:
    """20 random non-zero bytes — per-connection challenge (a fixed salt
    would make the challenge-response replayable)."""
    import os

    out = bytearray()
    while len(out) < 20:
        out += bytes(b for b in os.urandom(24) if b not in (0, 0x24))
    return bytes(out[:20])


def scramble_from_handshake(pkt: bytes) -> bytes:
    """Client side: extract the 20-byte scramble from a handshake_v10
    packet (salt part 1 + part 2)."""
    i = 1 + pkt.index(b"\x00", 1) + 4  # proto ver, version string, conn id
    part1 = pkt[i : i + 8]
    i += 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10  # salt1, NUL, caps, cs, status, caps2, alen, filler
    part2 = pkt[i : i + 12]
    return part1 + part2


def handshake_v10(
    conn_id: int, server_version: str, scramble: Optional[bytes] = None
) -> bytes:
    caps = (
        CLIENT_PROTOCOL_41
        | CLIENT_SECURE_CONNECTION
        | CLIENT_PLUGIN_AUTH
        | CLIENT_CONNECT_WITH_DB
        | CLIENT_TRANSACTIONS
    )
    scramble = scramble or SCRAMBLE
    salt, salt2 = scramble[:8], scramble[8:20] + b"\x00"
    p = b"\x0a"  # protocol version
    p += server_version.encode() + b"\x00"
    p += struct.pack("<I", conn_id)
    p += salt + b"\x00"
    p += struct.pack("<H", caps & 0xFFFF)
    p += bytes([0xFF])  # charset: utf8mb4
    p += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    p += struct.pack("<H", (caps >> 16) & 0xFFFF)
    p += bytes([21])  # auth data length
    p += b"\x00" * 10
    p += salt2
    p += b"mysql_native_password\x00"
    return p


#: scramble sent in handshake_v10 (salt + salt2 minus trailing NUL)
SCRAMBLE = b"12345678" + b"901234567890"


def parse_handshake_response(
    body: bytes,
) -> Tuple[str, Optional[str], bytes]:
    """Returns (username, database, auth_response bytes)."""
    caps = struct.unpack("<I", body[:4])[0]
    i = 4 + 4 + 1 + 23  # caps, max packet, charset, reserved
    end = body.index(b"\x00", i)
    user = body[i:end].decode("utf-8", "replace")
    i = end + 1
    # auth response
    if caps & CLIENT_SECURE_CONNECTION:
        alen = body[i]
        auth = body[i + 1 : i + 1 + alen]
        i += 1 + alen
    else:
        end = body.index(b"\x00", i)
        auth = body[i:end]
        i = end + 1
    db = None
    if caps & CLIENT_CONNECT_WITH_DB and i < len(body):
        try:
            end = body.index(b"\x00", i)
        except ValueError:
            end = len(body)
        db = body[i:end].decode("utf-8", "replace") or None
    return user, db, auth


def ok_packet(affected: int = 0, last_insert_id: int = 0, info: str = "") -> bytes:
    return (
        b"\x00"
        + lenenc_int(affected)
        + lenenc_int(last_insert_id)
        + struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
        + struct.pack("<H", 0)
        + info.encode()
    )


def err_packet(errno: int, message: str, sqlstate: str = "HY000") -> bytes:
    return (
        b"\xff"
        + struct.pack("<H", errno)
        + b"#"
        + sqlstate.encode()[:5].ljust(5, b"0")
        + message.encode("utf-8", "replace")[:1024]
    )


#: server status flags for cursors (reference: conn_stmt.go useCursor —
#: EXECUTE with CURSOR_TYPE_READ_ONLY answers column defs only, rows
#: stream through COM_STMT_FETCH)
SERVER_STATUS_CURSOR_EXISTS = 0x0040
SERVER_STATUS_LAST_ROW_SENT = 0x0080
CURSOR_TYPE_READ_ONLY = 0x01


def eof_packet(status: int = 0) -> bytes:
    return (
        b"\xfe"
        + struct.pack("<H", 0)
        + struct.pack("<H", SERVER_STATUS_AUTOCOMMIT | status)
    )


def _mysql_type(t: Optional[SQLType]) -> int:
    if t is None:
        return MYSQL_TYPE_VAR_STRING
    return {
        Kind.INT: MYSQL_TYPE_LONGLONG,
        Kind.FLOAT: MYSQL_TYPE_DOUBLE,
        Kind.BOOL: MYSQL_TYPE_TINY,
        Kind.DATE: MYSQL_TYPE_DATE,
        Kind.DATETIME: MYSQL_TYPE_DATETIME,
        Kind.TIME: MYSQL_TYPE_TIME,
        Kind.DECIMAL: MYSQL_TYPE_NEWDECIMAL,
        Kind.STRING: MYSQL_TYPE_VAR_STRING,
        Kind.NULL: MYSQL_TYPE_NULL,
    }.get(t.kind, MYSQL_TYPE_VAR_STRING)


def column_def(name: str, t: Optional[SQLType]) -> bytes:
    p = lenenc_str(b"def")
    p += lenenc_str(b"")  # schema
    p += lenenc_str(b"")  # table
    p += lenenc_str(b"")  # org table
    p += lenenc_str(name.encode())
    p += lenenc_str(name.encode())
    p += bytes([0x0C])
    p += struct.pack("<H", 0xFF)  # charset utf8mb4
    p += struct.pack("<I", 255)  # display length
    p += bytes([_mysql_type(t)])
    p += struct.pack("<H", 0)  # flags
    p += bytes([t.scale if t and t.kind == Kind.DECIMAL else 0x1F])
    p += b"\x00\x00"
    return p


def format_value(v, t: Optional[SQLType]) -> Optional[bytes]:
    if v is None:
        return None
    if t is not None and t.kind == Kind.DATE and isinstance(v, (int,)):
        return days_to_date(v).encode()
    if t is not None and t.kind == Kind.DATETIME and isinstance(v, int):
        from tidb_tpu.dtypes import micros_to_datetime

        return micros_to_datetime(v).encode()
    if t is not None and t.kind == Kind.TIME and isinstance(v, int):
        from tidb_tpu.dtypes import micros_to_time

        return micros_to_time(v).encode()
    if isinstance(v, bool):
        return b"1" if v else b"0"
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e15:
            return str(v).encode()
        return repr(v).encode()
    return str(v).encode()


# ---------------------------------------------------------------------------
# prepared statements (COM_STMT_*) — reference: pkg/server/conn_stmt.go,
# handleStmtPrepare/handleStmtExecute (conn.go:1999); binary row format per
# the MySQL binary protocol resultset row spec
# ---------------------------------------------------------------------------


def count_placeholders(sql: str) -> int:
    """Count '?' parameter markers outside string literals/comments
    (lexer-accurate, not a substring count)."""
    from tidb_tpu.parser.sqlparse import tokenize

    return sum(1 for t in tokenize(sql) if t.kind == "op" and t.text == "?")


def render_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, (bytes, bytearray)):
        v = v.decode("utf-8", "replace")
    s = str(v).replace("\\", "\\\\").replace("'", "''")
    return f"'{s}'"


def bind_placeholders(sql: str, params) -> str:
    """Substitute parameter values for '?' markers (positions from the
    lexer so markers inside string literals are never touched)."""
    from tidb_tpu.parser.sqlparse import tokenize

    spots = [t.pos for t in tokenize(sql) if t.kind == "op" and t.text == "?"]
    if len(spots) != len(params):
        raise ValueError(
            f"statement expects {len(spots)} parameters, got {len(params)}"
        )
    out = []
    prev = 0
    for pos, v in zip(spots, params):
        out.append(sql[prev:pos])
        out.append(render_literal(v))
        prev = pos + 1
    out.append(sql[prev:])
    return "".join(out)


def stmt_prepare_ok(stmt_id: int, ncols: int, nparams: int) -> bytes:
    return (
        b"\x00"
        + struct.pack("<I", stmt_id)
        + struct.pack("<H", ncols)
        + struct.pack("<H", nparams)
        + b"\x00"
        + struct.pack("<H", 0)  # warnings
    )


def _read_lenenc(data: bytes, pos: int):
    v = data[pos]
    if v < 251:
        return v, pos + 1
    if v == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if v == 0xFD:
        return int.from_bytes(data[pos + 1 : pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9


def parse_stmt_execute(payload: bytes, nparams: int, prev_types=None):
    """COM_STMT_EXECUTE payload -> (stmt_id, [param values], types).

    Clients send parameter types only on the FIRST execute
    (new-params-bound flag); re-executes omit them and the server must
    reuse the types it saw before (reference: conn_stmt.go parameter
    type caching on the statement)."""
    stmt_id = struct.unpack_from("<I", payload, 0)[0]
    pos = 4 + 1 + 4  # flags + iteration count
    params = []
    types = list(prev_types or [])
    if nparams:
        nb = (nparams + 7) // 8
        null_bitmap = payload[pos : pos + nb]
        pos += nb
        bound = payload[pos]
        pos += 1
        if bound:
            types = []
            for _ in range(nparams):
                types.append(struct.unpack_from("<H", payload, pos)[0])
                pos += 2
        elif not types:
            types = [MYSQL_TYPE_VAR_STRING] * nparams
        for i in range(nparams):
            if null_bitmap[i // 8] & (1 << (i % 8)):
                params.append(None)
                continue
            t = types[i] & 0xFF
            unsigned = bool(types[i] & 0x8000)
            if t == MYSQL_TYPE_LONGLONG:
                fmt = "<Q" if unsigned else "<q"
                params.append(struct.unpack_from(fmt, payload, pos)[0])
                pos += 8
            elif t == 3:  # LONG
                fmt = "<I" if unsigned else "<i"
                params.append(struct.unpack_from(fmt, payload, pos)[0])
                pos += 4
            elif t == 2:  # SHORT
                fmt = "<H" if unsigned else "<h"
                params.append(struct.unpack_from(fmt, payload, pos)[0])
                pos += 2
            elif t == MYSQL_TYPE_TINY:
                params.append(
                    payload[pos] if unsigned else struct.unpack_from("<b", payload, pos)[0]
                )
                pos += 1
            elif t == MYSQL_TYPE_DOUBLE:
                params.append(struct.unpack_from("<d", payload, pos)[0])
                pos += 8
            elif t == 4:  # FLOAT
                params.append(struct.unpack_from("<f", payload, pos)[0])
                pos += 4
            elif t == MYSQL_TYPE_DATE or t == 7 or t == 12:  # date/timestamp/datetime
                ln = payload[pos]
                pos += 1
                if ln >= 4:
                    y, mo, d = struct.unpack_from("<HBB", payload, pos)
                    params.append(f"{y:04d}-{mo:02d}-{d:02d}")
                else:
                    params.append("0000-00-00")
                pos += ln
            else:  # strings, decimals, blobs: length-encoded bytes
                ln, pos = _read_lenenc(payload, pos)
                raw = payload[pos : pos + ln]
                pos += ln
                try:
                    params.append(raw.decode())
                except UnicodeDecodeError:
                    params.append(raw)
    return stmt_id, params, types


def binary_row(row, types) -> bytes:
    """Encode one resultset row in the binary protocol (types must match
    the column_def types already sent)."""
    import datetime

    ncols = len(row)
    nb = (ncols + 7 + 2) // 8
    bitmap = bytearray(nb)
    vals = b""
    for i, (v, t) in enumerate(zip(row, types)):
        if v is None:
            bitmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
            continue
        kind = t.kind if t is not None else None
        if kind == Kind.INT:
            vals += struct.pack("<q", int(v))
        elif kind == Kind.BOOL:
            vals += struct.pack("<b", 1 if v else 0)
        elif kind == Kind.FLOAT:
            vals += struct.pack("<d", float(v))
        elif kind == Kind.DATE and isinstance(v, str):
            # result materialization presents temporals as strings now
            y, mo, dd = (int(x) for x in v.split("-"))
            vals += bytes([4]) + struct.pack("<HBB", y, mo, dd)
        elif kind == Kind.DATETIME and isinstance(v, str):
            date_part, _, time_part = v.partition(" ")
            y, mo, dd = (int(x) for x in date_part.split("-"))
            hh, mi, sec = (time_part or "0:0:0").split(":")
            fs = float(sec)
            vals += bytes([11]) + struct.pack(
                "<HBBBBBI", y, mo, dd, int(hh), int(mi), int(fs),
                int(round((fs - int(fs)) * 1e6)),
            )
        elif kind == Kind.TIME and isinstance(v, str):
            neg = 1 if v.startswith("-") else 0
            hh, mi, sec = v.lstrip("-").split(":")
            fs = float(sec)
            total_h = int(hh)
            vals += bytes([12]) + struct.pack(
                "<BIBBBI", neg, total_h // 24, total_h % 24, int(mi),
                int(fs), int(round((fs - int(fs)) * 1e6)),
            )
        elif kind == Kind.DATE and isinstance(v, int):
            d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
            vals += bytes([4]) + struct.pack("<HBB", d.year, d.month, d.day)
        elif kind == Kind.DATETIME and isinstance(v, int):
            dt = datetime.datetime(1970, 1, 1) + datetime.timedelta(
                microseconds=int(v)
            )
            vals += bytes([11]) + struct.pack(
                "<HBBBBBI", dt.year, dt.month, dt.day, dt.hour, dt.minute,
                dt.second, dt.microsecond,
            )
        elif kind == Kind.TIME and isinstance(v, int):
            neg, us = (1, -int(v)) if v < 0 else (0, int(v))
            from tidb_tpu.dtypes import US_PER_DAY, US_PER_SECOND

            days, rem = divmod(us, US_PER_DAY)
            h, rem = divmod(rem, 3600 * US_PER_SECOND)
            m, rem = divmod(rem, 60 * US_PER_SECOND)
            s, frac = divmod(rem, US_PER_SECOND)
            vals += bytes([12]) + struct.pack(
                "<BIBBBI", neg, days, h, m, s, frac
            )
        elif kind == Kind.DECIMAL:
            vals += lenenc_str(format_value(v, t) or b"")
        else:
            vals += lenenc_str(format_value(v, t) or b"")
    return b"\x00" + bytes(bitmap) + vals
