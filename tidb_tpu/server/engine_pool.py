"""Engine pool: multi-engine dispatch with failure probing + recovery.

Reference: the MPP resilience triplet —
- `GlobalMPPFailedStoreProber` (pkg/store/copr/mpp_probe.go:33): a
  registry of TiFlash stores that failed dispatch; each is probed
  periodically with backoff and returns to rotation after a successful
  liveness check.
- `ExecutorWithRetry` + `RecoveryHandler`
  (pkg/executor/internal/mpp/recovery_handler.go:26): an MPP run that
  died from a store failure is retried against the surviving stores,
  bounded by a retry budget.
- dispatch itself (`DispatchMPPTask`, pkg/store/copr/mpp.go:93) picks
  among healthy stores.

TPU-native shape: engines are `EngineServer` processes behind the plan
IR seam (server/engine_rpc.py — the kv.Client.Send analog). The pool
round-robins plans over alive engines, a transport failure quarantines
the endpoint into the prober (exponential-backoff pings via the
protocol's handshake frame), and the plan retries on the next alive
engine. `SchemaOutOfDateError` is a *planning* staleness signal, not a
liveness failure — it propagates so the frontend re-plans, matching
the reference where lease expiry never marks a store failed.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from tidb_tpu.server.engine_rpc import (
    EngineClient,
    SchemaOutOfDateError,
)
from tidb_tpu.utils import racecheck
from tidb_tpu.utils.failpoint import inject


class EngineEndpoint:
    """One engine address + its liveness state."""

    def __init__(self, host: str, port: int, secret: Optional[str] = None):
        self.host = host
        self.port = port
        self.secret = secret
        self.alive = True
        self.failed_since: Optional[float] = None
        self.next_probe: float = 0.0
        self.probe_backoff_s: float = 0.0
        self.detect_count = 0
        self.recover_count = 0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def __repr__(self) -> str:
        state = "alive" if self.alive else "failed"
        return f"EngineEndpoint({self.address}, {state})"


def ping_endpoint(ep: "EngineEndpoint", timeout_s: float = 2.0) -> bool:
    """One liveness ping over the protocol's handshake frame. Shared by
    the quarantine prober (recovery detection) and the DCN scheduler's
    heartbeat (failure detection, parallel/dcn.py) so both sides of the
    liveness state machine agree on what 'alive' means.

    Each successful ping additionally refreshes the link registry's
    handshake telemetry (RTT + clock offset — so skew that develops
    AFTER connect is observed at heartbeat cadence, the inspection
    engine's clock-skew signal) and drains the worker's pending metric
    samples (the ``tsdb_flush`` idle-flush: an idle worker's history
    reaches the coordinator store without waiting for a dispatch).
    Telemetry merge failures never fail the liveness verdict."""
    if inject("engine/probe-fail"):
        return False
    try:
        c = EngineClient(
            ep.host, ep.port, secret=ep.secret, timeout_s=timeout_s
        )
    except Exception:
        return False
    try:
        ping = {"tsdb_flush": True}  # handshake/ping frame
        try:
            # Top SQL config rides every liveness ping so workers
            # arm/disarm/re-tune even with no dispatch in flight
            # (SET GLOBAL tidb_enable_top_sql reaches an idle fleet
            # at heartbeat cadence)
            from tidb_tpu.obs.profiler import TOPSQL

            ping["topsql"] = TOPSQL.dispatch_config()
        except Exception:
            pass
        resp = c._call(ping)
        ok = bool(resp.get("ok"))
        if ok:
            # two INDEPENDENT try blocks: the worker already drained
            # its pending samples into this reply (at-most-once), so a
            # link-registry hiccup must not also discard the batch
            try:
                from tidb_tpu.obs.flight import LINKS

                LINKS.note_handshake(
                    ep.address, c.clock_rtt_s, c.clock_offset_s
                )
            except Exception:
                pass
            try:
                from tidb_tpu.obs.tsdb import TSDB

                TSDB.merge_remote(
                    resp.get("tsdb"), host=ep.address,
                    offset_s=c.clock_offset_s,
                )
            except Exception:
                pass
            try:
                from tidb_tpu.obs.profiler import TOPSQL

                TOPSQL.store.merge_remote(
                    resp.get("topsql"), instance=ep.address
                )
            except Exception:
                pass
        return ok
    except Exception:
        return False
    finally:
        c.close()


class FailedEngineProber:
    """Quarantine + recovery detection for failed engines.

    `detect()` moves an endpoint out of rotation; `probe_once()` pings
    every quarantined endpoint whose backoff has elapsed (doubling up
    to `max_backoff_s`) and returns the ones that answered, which are
    already back in rotation when it returns. With `interval_s` > 0 a
    daemon thread probes continuously (the reference's prober
    goroutine; detect/recover semantics of mpp_probe.go:33)."""

    def __init__(
        self,
        initial_backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
        probe_timeout_s: float = 2.0,
        interval_s: float = 0.0,
    ):
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.probe_timeout_s = probe_timeout_s
        self._lock = racecheck.make_lock("engine_pool.prober")
        self._failed: List[EngineEndpoint] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, args=(interval_s,), daemon=True,
                name="engine-prober",
            )
            self._thread.start()

    def detect(self, ep: EngineEndpoint) -> bool:
        """Mark an endpoint failed (idempotent) and schedule its first
        probe after the initial backoff. Returns True iff this call
        performed the alive->failed transition (so callers counting
        quarantine events count each one exactly once)."""
        with self._lock:
            if not ep.alive:
                return False
            ep.alive = False
            ep.failed_since = time.time()
            ep.detect_count += 1
            ep.probe_backoff_s = self.initial_backoff_s
            ep.next_probe = time.time() + ep.probe_backoff_s
            self._failed.append(ep)
            return True

    def failed_endpoints(self) -> List[EngineEndpoint]:
        with self._lock:
            return list(self._failed)

    def probe_once(self, now: Optional[float] = None
                   ) -> List[EngineEndpoint]:
        """Ping due endpoints; recovered ones return to rotation and
        are returned. Failed pings double the endpoint's backoff.
        Recovery is VISIBLE: each re-admission counts under
        tidbtpu_dcn_readmissions_total{host} and lands an
        admission-category timeline event — before this, only the
        quarantine half of the detect/recover pair was observable."""
        now = time.time() if now is None else now
        with self._lock:
            due = [ep for ep in self._failed if ep.next_probe <= now]
        recovered = []
        for ep in due:
            if self._ping(ep):
                with self._lock:
                    down_s = time.time() - (ep.failed_since or now)
                    ep.alive = True
                    ep.failed_since = None
                    ep.recover_count += 1
                    self._failed = [e for e in self._failed if e is not ep]
                recovered.append(ep)
                from tidb_tpu.obs.timeline import TIMELINE
                from tidb_tpu.utils.metrics import REGISTRY

                REGISTRY.counter(
                    "tidbtpu_dcn_readmissions_total",
                    "quarantined worker hosts re-admitted to rotation "
                    "by the prober (the recovery half of quarantine)",
                    labels=("host",),
                ).labels(host=ep.address).inc()
                TIMELINE.emit_event(
                    "admission", f"readmit {ep.address}",
                    time.time(), 0.0, track="admission",
                    args={"host": ep.address,
                          "downtime_s": round(max(down_s, 0.0), 3)},
                )
            else:
                with self._lock:
                    ep.probe_backoff_s = min(
                        ep.probe_backoff_s * 2 or self.initial_backoff_s,
                        self.max_backoff_s,
                    )
                    ep.next_probe = now + ep.probe_backoff_s
        return recovered

    def _ping(self, ep: EngineEndpoint) -> bool:
        return ping_endpoint(ep, timeout_s=self.probe_timeout_s)

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.probe_once()
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class PooledEngineClient:
    """Dispatch plans over a pool of engines with failover.

    A transport failure (connect error, poisoned stream, engine gone)
    quarantines the endpoint via the prober and the SAME plan retries
    on the next alive engine — the ExecutorWithRetry/RecoveryHandler
    loop. Engine-side *execution* errors (bad plan, unknown table) and
    SchemaOutOfDateError propagate without failover: they would fail
    identically everywhere."""

    def __init__(
        self,
        endpoints: List[Tuple[str, int]],
        secret: Optional[str] = None,
        prober: Optional[FailedEngineProber] = None,
        max_retry: int = 3,
    ):
        if not endpoints:
            raise ValueError("engine pool needs at least one endpoint")
        self.endpoints = [
            EngineEndpoint(h, p, secret) for h, p in endpoints
        ]
        self.prober = prober or FailedEngineProber()
        self.max_retry = max_retry
        self._rr = 0
        self._lock = racecheck.make_lock("engine_pool.pool")
        self._conns = {}  # endpoint -> EngineClient
        # one mutex per endpoint: EngineClient's socket protocol is a
        # strict request/response stream — two threads interleaving
        # frames on it would desync ids and poison a healthy engine
        self._conn_locks = {}

    def alive_endpoints(self) -> List[EngineEndpoint]:
        return [ep for ep in self.endpoints if ep.alive]

    def _next_alive(self) -> Optional[EngineEndpoint]:
        with self._lock:
            alive = [ep for ep in self.endpoints if ep.alive]
            if not alive:
                return None
            ep = alive[self._rr % len(alive)]
            self._rr += 1
            return ep

    def _ep_lock(self, ep: EngineEndpoint) -> threading.Lock:
        with self._lock:
            lk = self._conn_locks.get(ep)
            if lk is None:
                lk = self._conn_locks[ep] = racecheck.make_lock(
                    "engine_pool.conn"
                )
            return lk

    def _conn(self, ep: EngineEndpoint) -> EngineClient:
        c = self._conns.get(ep)
        if c is None or c._dead:
            c = EngineClient(ep.host, ep.port, secret=ep.secret)
            self._conns[ep] = c
        return c

    def execute_plan(
        self, plan, schema_version: Optional[int] = None
    ) -> Tuple[List[str], List[tuple]]:
        last_err: Optional[Exception] = None
        for _attempt in range(max(self.max_retry, 1)):
            # give quarantined engines their shot at recovery before
            # declaring the pool exhausted (probe respects backoff)
            if not self.alive_endpoints():
                self.prober.probe_once()
            ep = self._next_alive()
            if ep is None:
                break
            try:
                inject("engine/dispatch")
                # lock-blocking-ok: the per-endpoint lock EXISTS to
                # hold across the RPC — EngineClient's socket protocol
                # is a strict request/response stream; leaf-level lock
                with self._ep_lock(ep):
                    conn = self._conn(ep)
                    return conn.execute_plan(plan, schema_version)
            except SchemaOutOfDateError:
                raise  # re-plan, don't fail over
            except RuntimeError:
                raise  # engine-side execution error: same everywhere
            except (ValueError, PermissionError):
                # client-local and deterministic (oversized request
                # frame, bad credentials): would fail identically on
                # every engine — never quarantine a healthy one for it
                raise
            except Exception as e:  # transport: quarantine + fail over
                last_err = e
                with self._ep_lock(ep):
                    self._conns.pop(ep, None)
                self.prober.detect(ep)
        raise ConnectionError(
            f"no alive engine after {self.max_retry} attempts "
            f"({len(self.endpoints)} endpoints, all quarantined); "
            f"last error: {last_err}"
        )

    def close(self) -> None:
        for c in self._conns.values():
            try:
                c.close()
            except Exception:
                pass
        self._conns.clear()
        self.prober.stop()
