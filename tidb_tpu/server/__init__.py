from tidb_tpu.server.server import Server  # noqa: F401
